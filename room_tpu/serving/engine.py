"""Continuous-batching inference engine.

The in-tree replacement for the reference's out-of-process Ollama daemon
(reference: src/shared/local-model.ts, agent-executor.ts:327-338): all
Queen/Worker turns across every room land in one decode batch on the
mesh.

Shape of the loop (SURVEY.md §7 stage 5):
- admission: queued turns are prefilled (bucketed chunk lengths to bound
  recompiles) into pages from the shared pool, then occupy a decode slot
- decode: one jitted step advances every active slot a token; sampling
  happens on-device so only [B] token ids cross the host boundary
- completion: EOS / im_end / max-tokens / a closed tool-call block ends
  the turn; tool calls *park* the session (pages retained) so the host
  can run the tool and resume with the result appended — preemptible
  decode, the on-TPU equivalent of the reference's mid-turn tool loop
  (reference: src/shared/agent-executor.ts:404-471)
- sessions map 1:1 onto the engine's page table; parked sessions keep
  their KV (the serving-side twin of the reference's agent_sessions
  continuity rules) — resident in HBM, or hibernated to host RAM/disk
  by the tiered offload layer (kv_offload.py) and restored, byte-exact,
  before their next prefill

Everything device-side is static-shaped: fixed decode slots, fixed page
pool, bucketed prefill lengths.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos import invariants as invariants_mod
from ..models import qwen3
from ..models.config import DecoderConfig
from ..ops import spec as spec_ops
from ..utils import knobs, locks
from . import faults
from . import trace as trace_mod
from .faults import FaultError
from .kv_offload import TieredKVStore, offload_enabled_from_env
from .prefix_store import SharedPrefixStore, prefix_store_enabled_from_env
from .kv_pages import (
    PageTable, init_page_cache, kv_quant_mode, make_paged_kv_hook,
    make_ragged_kv_hook, pallas_decode_int8_ok, pallas_prefill_ok,
    pallas_ragged_int8_ok, pallas_ragged_ok, use_pallas_kernel,
)
from .scheduler import (
    CLASS_PRIORITY, CLASS_RANK, RequestScheduler, SpecTuner,
    chunk_pages_from_env, normalize_class,
)
from .sampler import (
    SamplingParams, apply_penalties, sample_batched, spec_verify,
)
from .tokenizer import ByteTokenizer, Tokenizer

PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
                   16384, 32768)


@jax.jit
def _sample_first(logits, key, temps, top_ps, top_ks):
    """Jitted first-token sampling for prefill groups. Calling
    sample_batched eagerly here cost ~50 primitive dispatches plus an
    eagerly-traced lax.cond per prefill — each one a host<->device
    round trip over the TPU tunnel, straight onto queen-turn latency.
    Shapes are bounded by the power-of-two batch padding, so compiles
    stay bounded too."""
    return sample_batched(logits, key, temps, top_ps, top_ks)


@partial(jax.jit, donate_argnums=(0,))
def _reset_count_row(counts, slot, tok):
    """Zero one slot's penalty-count row and count its first sampled
    token (runs at admission; device-side so the [B, V] array never
    round-trips to host). Donates ``counts`` — the caller immediately
    rebinds it, and without donation each admission would copy the full
    [max_batch, vocab] array (~38 MB at the 30B vocab, batch 64)."""
    return counts.at[slot].set(0).at[slot, tok].add(1)


def propose_ngram(seq: list[int], gamma: int) -> list[int]:
    """Prompt-lookup draft: match the sequence's trailing n-gram against
    its own earlier content and propose the tokens that followed the most
    recent previous occurrence (agent turns repeat tool-call JSON, code,
    and prompt fragments constantly). Returns up to ``gamma`` proposals,
    possibly empty. Pure host-side; the device only verifies."""
    arr = np.asarray(seq, np.int32)
    n_total = len(arr)
    for n in (3, 2):
        if n_total <= n:
            continue
        pat = arr[-n:]
        body = arr[:-1]
        if len(body) < n:
            continue
        wins = np.lib.stride_tricks.sliding_window_view(body, n)
        matches = np.nonzero((wins == pat).all(axis=1))[0]
        # a window starting at i proposes tokens from i+n; the suffix
        # itself (start n_total-n) proposes nothing
        matches = matches[matches < n_total - n]
        if len(matches):
            start = int(matches[-1]) + n
            prop = arr[start:start + gamma]
            if len(prop):
                return prop.tolist()
    return []


@dataclass
class Turn:
    """One generation request against a session."""
    session_id: str
    prompt_tokens: list[int]
    sampling: SamplingParams
    on_token: Optional[Callable[[int], None]] = None
    # custom stop sequences matched against the decoded tail (OpenAI
    # `stop`; the reference's Ollama daemon honored these natively)
    stop_strings: list[str] = field(default_factory=list)
    # filled by the engine:
    new_tokens: list[int] = field(default_factory=list)
    finish_reason: Optional[str] = None   # stop | length | tool_call | error
    stop_hit: Optional[str] = None        # which stop string fired
    error: Optional[str] = None
    done: threading.Event = field(default_factory=threading.Event)
    # ---- robustness (chaos layer) ----
    # absolute monotonic deadline; past it the turn fails cleanly with
    # a timeout error instead of occupying a slot forever
    deadline: Optional[float] = None
    # shed ordering under sustained pressure: lowest priority goes
    # first when the degradation ladder reaches the shedding rung
    priority: int = 0
    # stall-watchdog park+requeue budget consumed so far
    requeues: int = 0
    # set when the engine disturbed this turn (requeue, prefill retry):
    # chaos tests exempt disrupted turns from exact-stream assertions
    disrupted: bool = False
    # shed by the degradation ladder (maps to HTTP 503 + Retry-After)
    shed: bool = False
    # requeued mid-generation: prompt KV is already materialized, only
    # the pending token re-enters at re-admission
    _mid_stream: bool = False
    # ---- SLO scheduler (scheduler.py, docs/scheduler.md) ----
    # priority class (queen > worker > background), tagged from the
    # swarm role by providers/tpu.py; orders admission (EDF against
    # the class TTFT target), chunk budgets, and per-class shedding
    turn_class: str = "worker"
    submitted_at: float = field(default_factory=time.monotonic)
    # EDF admission key: submitted_at + class TTFT target (set by
    # submit(); requeues keep the original so a disrupted turn retains
    # its queue position)
    admit_by: float = 0.0
    first_token_at: Optional[float] = None
    # interleaved prefill chunks written for this turn (telemetry)
    prefill_chunks: int = 0
    # tokens durably written by interleaved chunked prefill that have
    # NOT yet led to a slot admission: while nonzero, a turn death
    # rolls the session back to _prefill_snap so a client retry of the
    # full prompt never lands on a half-prefilled session
    _chunk_committed: int = 0
    _prefill_snap: Optional[dict] = None
    # popped by admission but deferred (chunk budget / pool pressure):
    # re-queued at the end of the admission pass, not re-popped within
    # it
    _admit_deferred: bool = False
    # ---- turnscope (trace.py, docs/observability.md) ----
    # per-turn span trace (None when ROOM_TPU_TRACE=0): queue /
    # prefill / window spans, token timestamps for TTFT/TPOT, fault
    # and offload events — pushed into the flight recorder at finish
    trace: Optional[Any] = None

    def wait(self, timeout: Optional[float] = None) -> "Turn":
        self.done.wait(timeout)
        return self


@dataclass
class _Session:
    id: str
    length: int = 0                 # tokens materialized in KV pages
    parked: bool = False
    # last sampled token not yet written to KV (stop/park happens before
    # its decode step); prepended to the next resume prompt
    pending: Optional[int] = None
    # host-side mirror of the KV contents (|history| == length always):
    # the tokens to re-prefill if this session's pages get evicted under
    # pool pressure. Ints only — a 32k-token session costs ~256KB host
    # memory against its pages' HBM footprint.
    history: list[int] = field(default_factory=list)
    last_used: float = field(default_factory=time.monotonic)
    # shared read-only prefix pages referenced (not owned) by this
    # session: its block table is prefix_pages + own pages, and all its
    # KV writes land at positions >= prefix_len
    prefix_key: Optional[tuple] = None
    prefix_pages: list[int] = field(default_factory=list)
    prefix_len: int = 0
    # turns this session has admitted, across warm restarts (rides the
    # drain manifest so operators can see a session's age after N
    # rolling restarts)
    generation: int = 0


@dataclass
class _PrefixEntry:
    """One cached page-aligned prompt prefix (vLLM-style automatic
    prefix caching): the swarm's workers share multi-thousand-token
    system prompts, so repeat prefills become block-table references.
    Pages are owned by a pseudo-session in the page table; `sessions`
    is the live refcount — an entry is evictable only at refcount 0."""
    key: tuple
    owner_id: str
    pages: list[int]
    length: int
    ready: bool = False          # KV written (first prefill completed)
    sessions: set = field(default_factory=set)
    last_used: float = field(default_factory=time.monotonic)


class ServingEngine:
    """Single-model continuous batcher over a paged KV pool."""

    def __init__(
        self,
        cfg: DecoderConfig,
        params: Any,
        tokenizer: Optional[Tokenizer] = None,
        *,
        max_batch: int = 8,
        page_size: int = 16,
        n_pages: int = 512,
        max_seq_len: Optional[int] = None,
        stop_token_ids: Optional[list[int]] = None,
        rng_seed: int = 0,
        mesh: Optional[Any] = None,
        spec_tokens: Optional[int] = None,
        draft: Optional[tuple] = None,
        offload: Optional[bool] = None,
        prefix_store: Optional[bool] = None,
    ) -> None:
        # persistent XLA compile cache (ROOM_TPU_JAX_CACHE): an engine
        # jits dozens of shapes, and each process's in-memory jit cache
        # starts empty — the disk cache makes cold-compile a per-machine
        # cost instead of a per-process one (bench rounds died in the
        # compile watchdog before this was wired)
        from ..utils.compile_cache import enable_compile_cache

        enable_compile_cache()
        # process-lifecycle phase (docs/lifecycle.md): starting ->
        # (warming, during a manifest restore) -> serving -> draining.
        # Plain str writes are atomic; readers (stats, routes) only
        # ever snapshot it.
        self.lifecycle_phase = "starting"
        self.cfg = cfg
        self.params = params
        # multi-chip serving: cache+params live together on the mesh —
        # KV heads shard over tp next to the attention weights, decode
        # batch rows over dp (reference serves through a single-process
        # Ollama daemon; here the mesh is the daemon)
        self.mesh = mesh
        self.tokenizer = tokenizer or ByteTokenizer()
        self.max_batch = max_batch
        self.page_size = page_size
        self.n_pages = n_pages
        self.max_seq_len = max_seq_len or min(
            cfg.max_seq_len, (n_pages - 1) * page_size
        )
        self.max_pages_per_seq = -(-self.max_seq_len // page_size)
        # multi-step decode pipeline (docs/serving.md): tokens decoded
        # per device dispatch. Each dispatch rolls this many steps
        # inside one jitted lax.scan — sampled ids stay on device and
        # feed the next step's embedding lookup — and writes each
        # step's tokens into a device-resident [steps, max_batch] ring
        # the host drains ASYNCHRONOUSLY, double-buffered against the
        # next dispatch: stop-token detection, stream callbacks,
        # admission and offload sweeps overlap the in-flight window.
        # 1 = legacy step-at-a-time behavior (dispatch + blocking
        # drain every iteration). ROOM_TPU_DECODE_CHUNK is honored as
        # a back-compat alias.
        env_steps = (
            knobs.get_raw("ROOM_TPU_DECODE_STEPS_PER_DISPATCH")
            or knobs.get_raw("ROOM_TPU_DECODE_CHUNK")
        )
        self.steps_per_dispatch = max(1, int(env_steps)) if env_steps \
            else 4
        # long prompts prefill in chunks of this width (0 disables):
        # bounds compile widths + prefill activation memory at 32k ctx
        self.prefill_chunk = knobs.get_int("ROOM_TPU_PREFILL_CHUNK")
        # ---- SLO-aware scheduler (scheduler.py, docs/scheduler.md) ----
        # interleaved chunked prefill: long prompts are written
        # ROOM_TPU_PREFILL_CHUNK_PAGES-page chunks ACROSS scheduler
        # steps, a decode window running between chunks, so no single
        # prompt monopolizes a dispatch (0 = monolithic admission-time
        # prefill, the pre-scheduler behavior). The legacy
        # ROOM_TPU_PREFILL_CHUNK width still caps the compile width.
        chunk_pages = chunk_pages_from_env()
        self.sched_chunk_tokens = chunk_pages * page_size
        if self.prefill_chunk:
            self.sched_chunk_tokens = min(
                self.sched_chunk_tokens, self.prefill_chunk
            )
        self.scheduler = RequestScheduler()
        # On-mesh speculative decoding (docs/serving.md): up to
        # spec_tokens prompt-lookup drafts are proposed PER WINDOW STEP
        # from a device-resident recent-token tail, verified by the
        # same step's batched forward, and accepted/rejected inside the
        # jitted lax.scan — a spec round is a normal window step that
        # emits up to 1+gamma tokens per lane, so speculation no longer
        # flushes the multi-step pipeline. Decode streams the full
        # weight set per device call, so every accepted token divides
        # the HBM bill — multiplicatively with the pipeline's
        # host-stall win. 0 disables (the plain scan runs). Greedy rows
        # are token-identical to non-speculative decoding; stochastic
        # rows keep their exact sampling distribution (spec_verify).
        # The library default stays 0; the production deployment path
        # (providers/tpu.ModelHost) defaults to gamma=4 (VERDICT r2 #8).
        self.spec_tokens = spec_tokens if spec_tokens is not None else \
            knobs.get_int("ROOM_TPU_SPEC_TOKENS")
        # device tail length the on-mesh n-gram matcher sees (host
        # drafting read unbounded history; the tail bounds device
        # memory/compute — repeats beyond it stop drafting, which only
        # costs acceptance, never correctness)
        self.spec_tail_len = max(8, knobs.get_int("ROOM_TPU_SPEC_TAIL"))
        # optional tier-2 draft model (ROOM_TPU_DRAFT_MODEL): a tiny
        # on-mesh decoder sharing the serving mesh, proposing where
        # prompt-lookup found nothing; same in-window verify path.
        # ``draft`` is (DecoderConfig, params).
        self._draft = draft
        self.draft_window = max(4, knobs.get_int("ROOM_TPU_DRAFT_WINDOW"))
        if draft is not None:
            if draft[0].vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft model {draft[0].name} vocab "
                    f"{draft[0].vocab_size} != target vocab "
                    f"{cfg.vocab_size}"
                )
        # Per-class gamma auto-tuning (scheduler.SpecTuner): each
        # traffic class adapts its own draft depth from live window
        # acceptance and owns its own spec-off decision — replacing the
        # old engine-global EMA/cost-ratio gate. The off floor defaults
        # to the roofline breakeven for this model/batch/gamma shape on
        # the detected chip (ROOM_TPU_SPEC_MIN_ACCEPT overrides).
        spec_min_accept = knobs.get_float("ROOM_TPU_SPEC_MIN_ACCEPT")
        floor = 0.0
        # when the floor is roofline-derived (no explicit override) it
        # is re-solved at drains against the batch's LIVE mean context:
        # at long context KV reads dominate verify and plain decode
        # alike, the cost ratio falls toward 1, and a floor frozen at
        # the 1024-token default would throttle drafting exactly where
        # it is still profitable.
        self._spec_floor_fn = None
        self._spec_floor_in = 0
        if self.spec_tokens > 0:
            if spec_min_accept is not None:
                floor = spec_min_accept
            else:
                from room_tpu.perf.roofline import (
                    detect_chip_spec, spec_accept_floor,
                )

                chip = detect_chip_spec()
                self._spec_floor_fn = lambda mean_ctx: spec_accept_floor(
                    cfg, max_batch, self.spec_tokens, chip=chip,
                    mean_ctx=mean_ctx,
                )
                floor = self._spec_floor_fn(1024.0)
        self.spec_tuner = SpecTuner(self.spec_tokens, floor=floor)

        # ---- robustness knobs (chaos layer; docs/chaos.md) ----
        # default per-turn deadline in seconds (0 disables); submit()
        # callers can set a per-request deadline_s on top
        self.turn_deadline_s = knobs.get_float("ROOM_TPU_TURN_DEADLINE_S")
        # a decode/verify device round slower than this counts as a
        # stall: its sessions are parked + requeued (KV retained) and
        # the ladder notes pressure. Generous default — first calls pay
        # jit compiles, and a false stall only costs a requeue.
        self.step_stall_s = knobs.get_float("ROOM_TPU_STEP_STALL_S")
        # park+requeue budget per turn before it just rides out slowness
        self.max_requeues = knobs.get_int("ROOM_TPU_MAX_REQUEUES")
        # transient-fault retry-with-backoff bounds (device-call sites)
        self.fault_retries = knobs.get_int("ROOM_TPU_FAULT_RETRIES")
        self.retry_backoff_s = knobs.get_float("ROOM_TPU_RETRY_BACKOFF_S")
        # degradation ladder: pressure events (stalls, pool exhaustion,
        # prefill faults, crashes) within the sliding window map to a
        # level: >=t1 -> 1 (spec decode off), >=t2 -> 2 (cold sessions
        # offloaded to host/disk), >=t3 -> 3 (admission batch halved),
        # >=t4 -> 4 (lowest-priority queued turns shed w/ 503)
        self.degrade_window_s = knobs.get_float("ROOM_TPU_DEGRADE_WINDOW_S")
        thresholds = knobs.get_str("ROOM_TPU_DEGRADE_THRESHOLDS")
        self.degrade_thresholds = tuple(
            int(x) for x in thresholds.split(",")
        )
        if len(self.degrade_thresholds) != 4:
            # fail at construction, not inside degradation_level()
            # where the crash supervisor would loop on a config typo
            raise ValueError(
                "ROOM_TPU_DEGRADE_THRESHOLDS needs exactly 4 "
                f"comma-separated ints, got {thresholds!r}"
            )
        self._pressure: deque = deque(maxlen=1024)
        # degradation_level() is read from HTTP threads (stats(),
        # /api/tpu/health) while the engine thread appends/drains —
        # its own lock, never nested with self._lock
        self._pressure_lock = locks.make_lock("engine_pressure")
        self._forced_degradation: Optional[int] = None
        # engine-thread supervision: crashes within the window beyond
        # this budget mark the engine unhealthy (fail-closed: the
        # provider registry then falls back)
        self.max_crash_restarts = knobs.get_int(
            "ROOM_TPU_ENGINE_MAX_RESTARTS"
        )
        self._crash_times: deque = deque(maxlen=64)
        self.healthy = True

        # ---- tiered KV offload (docs/kv_offload.md) ----
        # hibernate cold sessions' non-prefix pages to host RAM / disk:
        # parked tool-call sessions, watermark pressure, and ladder
        # rung 2 all route through the same store. Library default OFF
        # (ROOM_TPU_OFFLOAD / the ``offload`` arg opt in); the
        # deployment path (providers/tpu.ModelHost) defaults ON.
        self.offload_enabled = offload if offload is not None \
            else offload_enabled_from_env()
        self.offload_low_wm = knobs.get_float("ROOM_TPU_OFFLOAD_LOW_WM")
        self.offload_high_wm = knobs.get_float("ROOM_TPU_OFFLOAD_HIGH_WM")
        self.offload_on_park = knobs.get_bool("ROOM_TPU_OFFLOAD_ON_PARK")
        self.offload_prefetch = knobs.get_int("ROOM_TPU_OFFLOAD_PREFETCH")
        self.offload_store: Optional[TieredKVStore] = \
            TieredKVStore() if self.offload_enabled else None

        if stop_token_ids is not None:
            self.stop_token_ids = set(stop_token_ids)
        else:
            stops = set()
            eos = getattr(self.tokenizer, "eos_id", None)
            if eos is not None:
                stops.add(eos)
            # add <|im_end|> only when the tokenizer maps it to one id
            # (ByteTokenizer always does; a BPE vocab may not)
            im_end_ids = self.tokenizer.encode("<|im_end|>")
            if len(im_end_ids) == 1:
                stops.add(im_end_ids[0])
            self.stop_token_ids = stops

        # tool-call detection: with a real Qwen vocab </tool_call> is one
        # added special token, so the check is an exact id compare; only
        # a vocab without that special falls back to scanning decoded
        # text (reference relies on Ollama doing this internally)
        tool_end = self.tokenizer.encode("</tool_call>")
        self._tool_end_id = tool_end[0] if len(tool_end) == 1 else None

        # page 0 is the scratch page idle decode slots write into
        self.page_table = PageTable(n_pages, page_size)
        self.page_table.ensure_capacity("__null__", page_size)

        # ROOM_TPU_KV_QUANT=int8: int8 pages + per-(token, head) f32
        # scales — ~49% of the bf16 pool's HBM footprint and decode
        # read traffic; bf16 and int8 paths each have their own
        # Pallas kernels behind startup probes.
        self.kv_quant = kv_quant_mode()

        # startup smoke of the S>1 Pallas prefill kernel (ADVICE r3):
        # one tiny compile + numerics check against attention_ref before
        # any production traffic routes through it; a failed probe pins
        # every S>1 path to the bounded XLA gather for this engine
        from .kv_pages import pallas_prefill_int8_ok

        prefill_ok = pallas_prefill_int8_ok if self.kv_quant \
            else pallas_prefill_ok
        self._pallas_prefill = use_pallas_kernel() and prefill_ok(
            cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, page_size
        )
        # whether S=1 decode actually runs a Pallas kernel (bf16 kernel,
        # or the int8 variant IF its startup probe passes) — the
        # active_pages bucket decision must mirror the hook's routing,
        # or a probe-failed int8 engine would take the XLA dequant
        # gather UNBOUNDED (full 32k capacity per step)
        self._pallas_decode = use_pallas_kernel() and (
            self.kv_quant is None or pallas_decode_int8_ok(
                cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, page_size
            )
        )
        # unified ragged kernel (ops/paged_attention.paged_attention_
        # ragged): ONE Pallas dispatch over the mixed [prefill-chunks +
        # decode-lanes] batch of a fused scheduler window. Probe-gated
        # like the split kernels (ROOM_TPU_RAGGED_KERNEL /
        # _INT8_KERNEL); a failed probe keeps the fused dispatch on the
        # XLA gather+einsum reference (the CPU/tier-1 path).
        self.ragged_qblock = max(1, knobs.get_int(
            "ROOM_TPU_RAGGED_QBLOCK"
        ))
        ragged_ok = pallas_ragged_int8_ok if self.kv_quant \
            else pallas_ragged_ok
        ragged_forced = \
            knobs.get_str("ROOM_TPU_PAGED_KERNEL") == "ragged"
        self._pallas_ragged = (
            use_pallas_kernel()
            and self.sched_chunk_tokens > 0
            and self.sched_chunk_tokens % self.ragged_qblock == 0
            and (ragged_forced or ragged_ok(
                cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, page_size,
                self.ragged_qblock,
            ))
        )

        self.cache = init_page_cache(
            cfg, n_pages, page_size, quant=self.kv_quant
        )
        self._cache_specs = None
        self._dp_size = 1
        if mesh is not None:
            from ..parallel.mesh import page_cache_specs, shard_pytree

            self._cache_specs = page_cache_specs(
                cfg, mesh, quant=self.kv_quant
            )
            self.cache = shard_pytree(self.cache, self._cache_specs, mesh)
            dp = mesh.shape.get("dp", 1)
            if dp > 1 and max_batch % dp == 0:
                self._dp_size = dp
        # fused dispatch window (docs/serving.md): the step's admitted
        # interleaved prefill chunks ride the SAME device dispatch as
        # the decode window — one host round trip per scheduler window
        # instead of one per chunk plus one for decode. Under dp
        # sharding the ragged stream becomes per-dp-shard sub-batches
        # ([ndp, T_local], shard-major chunk rows — the dp-sharded
        # fused spec-window), unless ROOM_TPU_FUSED_WINDOW_DP=0
        # restores the legacy split-per-chunk fallback.
        fused_on = knobs.get_bool("ROOM_TPU_FUSED_WINDOW")
        fused_dp_on = knobs.get_bool("ROOM_TPU_FUSED_WINDOW_DP")
        self.fused_window = (
            fused_on
            and self.sched_chunk_tokens > 0
            and (self._dp_size == 1 or fused_dp_on)
        )
        # mode {off, fused, fused-dp} for stats()/health/panel: a
        # fleet of mixed-mesh replicas (some dp-sharded, some not) is
        # otherwise undiagnosable — the dp auto-off was silent
        self.fused_window_mode = (
            "off" if not self.fused_window
            else ("fused-dp" if self._dp_size > 1 else "fused")
        )
        if not fused_on:
            self.fused_window_disabled_reason: Optional[str] = \
                "disabled by ROOM_TPU_FUSED_WINDOW=0"
        elif self.sched_chunk_tokens <= 0:
            self.fused_window_disabled_reason = (
                "interleaved chunked prefill disabled "
                "(ROOM_TPU_PREFILL_CHUNK_PAGES=0)"
            )
        elif self._dp_size > 1 and not fused_dp_on:
            self.fused_window_disabled_reason = (
                f"auto-off under dp sharding (dp={self._dp_size}): "
                "sharded fused window disabled by "
                "ROOM_TPU_FUSED_WINDOW_DP=0"
            )
            import logging

            logging.getLogger(__name__).warning(
                "fused dispatch window %s for %s",
                self.fused_window_disabled_reason, cfg.name,
            )
        elif self._dp_size > 1:
            # not a disablement: the sharded variant IS the fused
            # window here — the reason string flips to a mode marker
            # so mixed-mesh health surfaces show HOW, not just whether
            self.fused_window_disabled_reason = (
                f"sharded variant active (dp={self._dp_size})"
            )
            import logging

            logging.getLogger(__name__).info(
                "fused dispatch window %s for %s",
                self.fused_window_disabled_reason, cfg.name,
            )
        else:
            self.fused_window_disabled_reason = None
        if self.fused_window_mode == "fused-dp":
            # per-shard chunk budgets (docs/scheduler.md): each dp
            # shard absorbs its own chunk rows at the same dispatch
            # cost, so the per-step budget scales with the shard count
            self.scheduler.chunk_shards = self._dp_size
        # per-shard fused-window telemetry (stats()/health/TPU panel):
        # chunk rows landed per dp shard, mutated under _lock by
        # _commit_staged
        self._fused_dp_shard_chunks = [0] * max(1, self._dp_size)
        self.sessions: dict[str, _Session] = {}
        # admission queue: the scheduler's EDF heap (class TTFT target
        # deadlines), drop-in for the old FIFO queue.Queue surface
        self._queue = self.scheduler
        # refcount of queued turns per session (mutated under _lock via
        # _queue_put/_queue_get*): lets release_session defer for a
        # session whose turn is still QUEUED in O(1) instead of
        # scanning the queue — releasing under a queued turn would
        # free the session only for admission to silently recreate it
        self._queued_sids: dict[str, int] = {}
        self._active: list[Optional[Turn]] = [None] * max_batch
        self._slot_tables = np.zeros(
            (max_batch, self.max_pages_per_seq), np.int32
        )
        self._slot_lengths = np.zeros((max_batch,), np.int32)
        # tokens of page headroom _reserve_slot actually secured per slot
        self._reserved_tokens = np.zeros((max_batch,), np.int32)
        # ---- multi-step decode pipeline state (docs/serving.md) ----
        # the one window whose tokens are still on device awaiting the
        # host drain (depth-1 double buffer: window k executes while
        # window k-1's ring materializes + books)
        self._inflight: Optional[dict] = None
        # fused-window chunk staging (docs/serving.md): interleaved
        # prefill chunks admitted THIS step, host-committed but not yet
        # on device — consumed by the step's one fused dispatch (or the
        # chunk-only flush), rolled back to the last durable boundary
        # if that dispatch faults. _staged_sids guards the sessions
        # against eviction/offload in the stage->dispatch gap.
        self._staged_chunks: list[dict] = []
        self._staged_sids: set[str] = set()
        # per-slot count of KV positions dispatched but not yet drained:
        # reservations and block-table lengths must address the DEVICE's
        # view of the sequence, which runs ahead of sess.length by one
        # window while a dispatch is in flight
        self._slot_ahead = np.zeros((max_batch,), np.int32)
        # device-resident [max_batch] feed: the previous window's final
        # sampled token per slot, consumed by the next dispatch without
        # a host hop (rows with no undrained window feed from host)
        self._feed_tokens: Optional[jax.Array] = None
        # ---- on-mesh speculative window state (docs/serving.md) ----
        # with spec enabled a window emits a VARIABLE number of tokens
        # per lane per step, so the device's sequence length (and each
        # lane's remaining generation budget) can no longer be derived
        # host-side while a window is in flight: both ride the scan
        # carry and chain window-to-window on device, host-overridden
        # only for fresh rows (same contract as _feed_tokens). The
        # [max_batch, spec_tail_len] tail is what on-mesh prompt-lookup
        # drafting matches against.
        self._feed_lens: Optional[jax.Array] = None
        self._feed_rem: Optional[jax.Array] = None
        self._spec_tail_dev: Optional[jax.Array] = None
        # slot occupancy generation, bumped at every admission into the
        # slot: the drain's liveness check needs it because a parked+
        # requeued turn can re-admit into the SAME slot while the old
        # incarnation's window is still in flight — object identity
        # alone would then book the stale window's overshoot tokens
        # into the fresh stream
        self._slot_gen = np.zeros((max_batch,), np.int64)
        self._key = jax.random.PRNGKey(rng_seed)
        self._deferred_release: set[str] = set()
        self._admitting: set[str] = set()
        # turns popped from the queue but not yet slotted (mid-_admit):
        # a scheduler crash here would otherwise leave them in neither
        # _active nor _queue, so _recover_from_crash could never fail
        # them and their callers would hang on done.wait() forever
        self._admission_turns: list[Turn] = []
        # concurrency contract: ALL mutation of sessions / page table /
        # slot arrays / prefix cache happens on the engine thread (the
        # thread driving step()). Other threads only enqueue: submit()
        # puts turns on _queue; release_session() puts ids on
        # _release_requests when a loop thread owns the engine, and
        # step() applies them before admission. _lock covers only the
        # small cross-thread handoffs (loop-thread identity, deferred
        # set, stats snapshot).
        self._release_requests: "queue.SimpleQueue[str]" = \
            queue.SimpleQueue()
        # fleet / warm-handoff adoption seam (serving/fleet.py,
        # docs/fleet.md): parked sessions re-homed onto THIS engine
        # from a drained or crashed sibling replica. Cross-thread like
        # _release_requests: the engine thread applies queued adoptions
        # at the top of each step, BEFORE admission, so a turn
        # submitted after its session's adoption was enqueued can never
        # be admitted ahead of it (the turn would otherwise prefill a
        # fresh session missing its history).
        self._adoption_requests: "queue.SimpleQueue[tuple]" = \
            queue.SimpleQueue()
        # disaggregated prefill->decode handoff seam (serving/disagg.py,
        # docs/disagg.md): the router asks THIS engine to export a
        # quiescent session — park + offload + detach its spool — for a
        # decode replica to adopt. Cross-thread like adoptions: queued
        # and applied at the top of each step, refused (not blocked) if
        # the session picked up a live turn in the meantime.
        self._ship_requests: "queue.SimpleQueue[tuple]" = \
            queue.SimpleQueue()
        # best-effort session state preserved past a FATAL engine
        # crash (restart budget exhausted) for a fleet supervisor to
        # re-home; None on a healthy engine. Only collected when a
        # supervisor is attached (fleet_supervised, set by
        # EngineFleet) — a lone engine has no consumer, and detaching
        # spool files for nobody would just leak them
        self.crash_salvage: Optional[dict] = None
        self.fleet_supervised = False
        self._loop_thread: Optional[threading.Thread] = None
        # [max_batch, V] per-request generated-token counts for OpenAI
        # presence/frequency penalties; allocated on first penalized
        # turn (most traffic never pays the HBM)
        self._counts: Optional[jax.Array] = None
        # automatic prefix caching (0 disables; value = min prefix
        # pages worth sharing)
        self.prefix_cache_min_pages = knobs.get_int(
            "ROOM_TPU_PREFIX_CACHE_PAGES"
        )
        self._prefix_cache: dict[tuple, _PrefixEntry] = {}
        # fleet-global shared prefix store (prefix_store.py,
        # docs/disagg.md): a content-addressed spool tier underneath
        # the in-process prefix cache, shared across replicas /
        # processes / hosts. A local-cache miss pulls the prefix KV and
        # scatters it into fresh pages (copy-on-adopt); a locally
        # computed prefix is published when it becomes ready. Library
        # default off (ROOM_TPU_PREFIX_STORE / the ``prefix_store``
        # ctor arg opt in; providers/tpu.ModelHost defaults on).
        # Requires the in-process prefix cache — the store's entries
        # materialize AS local prefix entries.
        store_on = prefix_store if prefix_store is not None \
            else prefix_store_enabled_from_env()
        self.prefix_store: Optional[SharedPrefixStore] = None
        self.prefix_store_publish = knobs.get_bool(
            "ROOM_TPU_PREFIX_STORE_PUBLISH"
        )
        if store_on and self.prefix_cache_min_pages > 0:
            import logging

            try:
                self.prefix_store = SharedPrefixStore(
                    self._lifecycle_fingerprint(),
                    page_size=page_size,
                )
            except Exception:
                # the store is an accelerator, never a dependency: a
                # bad dir/cap config degrades to process-local caching
                logging.getLogger(__name__).exception(
                    "shared prefix store unavailable for %s", cfg.name,
                )
        self._lock = locks.make_lock("engine")
        self._jit_cache: dict[Any, Callable] = {}
        self._stats = {
            "tokens_decoded": 0, "turns_completed": 0, "prefill_tokens": 0,
            "decode_steps": 0, "evictions": 0,
            "prefix_hits": 0, "prefix_tokens_reused": 0,
            "prefix_evictions": 0,
            "spec_rounds": 0, "spec_proposed": 0, "spec_accepted": 0,
            "spec_rows_sequential": 0, "spec_throttles": 0,
            "deadline_timeouts": 0, "stall_events": 0, "requeues": 0,
            "shed_turns": 0, "fault_retries": 0, "engine_crashes": 0,
            "offloads": 0, "offload_pages_out": 0,
            "offload_restores": 0, "offload_pages_in": 0,
            "offload_prefetches": 0, "offload_resident_fallbacks": 0,
            "offload_reprefills": 0,
            # decode-pipeline telemetry (docs/serving.md): cumulative ms
            # the host spent BLOCKED on a device drain, and windows whose
            # dispatch failed under an injected decode_window fault
            "host_stall_ms": 0.0, "decode_windows": 0,
            "window_faults": 0, "overshoot_tokens": 0,
            # SLO scheduler (docs/scheduler.md): interleaved prefill
            # chunks written, admissions deferred by the per-step
            # chunk budget, and chunk faults requeued at a boundary
            "prefill_chunks_interleaved": 0, "prefill_chunk_defers": 0,
            "prefill_chunk_faults": 0,
            # unified ragged fused window (docs/serving.md): device
            # dispatches that carried ONLY chunk writes (split path +
            # chunk-only flushes), windows whose dispatch fused chunk
            # writes with the decode scan, and chunks that rode fused
            "chunk_dispatches": 0, "fused_windows": 0,
            "fused_chunks": 0,
            # dp-sharded fused spec-window (docs/serving.md): fused
            # windows dispatched as per-dp-shard ragged sub-batches
            "fused_dp_windows": 0,
            # shared prefix store (docs/disagg.md): local-cache misses
            # served by a pull from the fleet-global tier, tokens those
            # pulls saved re-prefilling, pulls that degraded to an
            # ordinary miss, and prefixes this engine published
            "prefix_store_hits": 0, "prefix_store_tokens_reused": 0,
            "prefix_store_pull_fallbacks": 0,
            "prefix_store_publishes": 0,
            # disaggregated serving (docs/disagg.md): sessions this
            # engine exported for a prefill->decode handoff
            "sessions_shipped": 0,
        }
        from collections import Counter

        self._prefix_lengths: Counter = Counter()
        from ..utils.profiling import StepTimer

        self.timer = StepTimer()
        # lifecycle telemetry (docs/lifecycle.md), mutated only on the
        # drain/restore caller's thread, snapshotted under _lock by
        # stats(): drain duration + sessions preserved/resumed/
        # fallback counters the health surface and bench read
        self._lifecycle_stats = {
            "drain_ms": 0.0, "sessions_spooled": 0,
            "sessions_fallback": 0, "sessions_abandoned": 0,
            "sessions_resumed": 0, "sessions_reprefill": 0,
            "manifest_errors": 0,
        }
        self.lifecycle_phase = "serving"

    # ---- jitted device functions ----

    def _constrain_cache(self, cache):
        """Pin the page pool's sharding inside jit so donation reuses the
        sharded buffers instead of letting GSPMD re-layout them."""
        if self._cache_specs is None:
            return cache
        from jax.sharding import NamedSharding

        mesh = self.mesh
        return jax.tree.map(
            lambda a, s: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, s)
            ),
            cache, self._cache_specs,
        )

    def _place_batch(
        self, arr: np.ndarray, *, jnp_dtype=None, name: str = "slot_batch"
    ) -> jax.Array:
        """Decode-batch inputs shard per the declarative window rule
        table (parallel.mesh.WINDOW_RULES — regex name -> PartitionSpec,
        leading slot axis over dp by default) when the mesh has a dp
        axis; replicated otherwise."""
        x = jnp.asarray(arr) if jnp_dtype is None else \
            jnp.asarray(arr, jnp_dtype)
        if self._dp_size > 1:
            from ..parallel.mesh import window_sharding

            x = jax.device_put(
                x, window_sharding(self.mesh, name, x.ndim)
            )
        return x

    def _constrain_dp(self, x: jax.Array, name: str) -> jax.Array:
        """In-trace sharding constraint for a dp-sharded fused-window
        intermediate, resolved through the same rule table as
        _place_batch — pins the ragged [ndp, T_local] stream to the dp
        axis so GSPMD never inserts a cross-shard reshuffle on the
        token path."""
        if self._dp_size <= 1 or self.mesh is None:
            return x
        from ..parallel.mesh import window_sharding

        return jax.lax.with_sharding_constraint(
            x, window_sharding(self.mesh, name, x.ndim)
        )

    def _pages_bucket(self, n_tokens: int) -> Optional[int]:
        """Static bound on how many leading block-table pages attention
        must gather for sequences reaching ``n_tokens``: ceil(/page)
        rounded up to a power of two (so compile variants stay
        O(log capacity)), clamped to the table width. None when the
        bound equals capacity (no slicing to do)."""
        need = max(1, -(-n_tokens // self.page_size))
        b = 1
        while b < need:
            b *= 2
        return b if b < self.max_pages_per_seq else None

    def _counts_array(self) -> jax.Array:
        if self._counts is None:
            self._counts = self._place_batch(
                np.zeros(
                    (self.max_batch, self.cfg.vocab_size), np.int32
                )
            )
        return self._counts

    # ---- robustness helpers (chaos layer) ----

    def _bump(self, key: str, n=1) -> None:
        """Counter mutation under the engine lock. stats() snapshots
        under the same lock from HTTP/route threads, so engine-thread
        increments must not race the dict copy — the async drain makes
        the window where a route thread reads mid-update much wider
        than the old synchronous loop did. Never called while holding
        _lock (it would self-deadlock on the non-reentrant lock)."""
        with self._lock:
            self._stats[key] += n

    def _note_pressure(self) -> None:
        with self._pressure_lock:
            self._pressure.append(time.monotonic())

    def degradation_level(self) -> int:
        """Current rung of the degradation ladder, derived from
        pressure events in the sliding window (stateless, so recovery
        is automatic once pressure stops): 0 healthy, 1 spec decode
        off (per class — queens keep drafting until rung 2,
        scheduler.SpecTuner.gamma_for), 2 cold sessions offloaded to
        host/disk, 3 admission batch halved, 4 shedding."""
        if self._forced_degradation is not None:
            return self._forced_degradation
        cutoff = time.monotonic() - self.degrade_window_s
        with self._pressure_lock:
            while self._pressure and self._pressure[0] < cutoff:
                self._pressure.popleft()
            n = len(self._pressure)
        for level in range(len(self.degrade_thresholds), 0, -1):
            if n >= self.degrade_thresholds[level - 1]:
                return level
        return 0

    def set_degradation(self, level: Optional[int]) -> None:
        """Pin the ladder to a rung (operator override / tests);
        None returns control to the pressure window."""
        self._forced_degradation = level

    def _retrying(self, what: str, fn: Callable):
        """Bounded retry-with-backoff around a device-call site for
        *transient* injected faults. Real device errors (and
        non-transient faults) propagate to the crash supervisor. Fault
        points fire BEFORE the jitted call, so no donated buffer is
        ever consumed by a failed attempt."""
        delay = self.retry_backoff_s
        for attempt in range(self.fault_retries + 1):
            try:
                return fn()
            except FaultError as e:
                if not e.transient or attempt >= self.fault_retries:
                    raise
                self._bump("fault_retries")
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    def _park_and_requeue(self, slot: int, turn: Turn) -> None:
        """Stall recovery: take the turn out of its slot with KV
        retained (park) and requeue it to continue later — stuck
        sessions are never dropped. The last sampled token becomes the
        session's pending token, exactly like a tool-call park."""
        sess = self.sessions[turn.session_id]
        sess.last_used = time.monotonic()
        if turn.new_tokens:
            sess.pending = turn.new_tokens[-1]
        sess.parked = True
        turn.requeues += 1
        turn.disrupted = True
        turn._mid_stream = bool(turn.new_tokens)
        if turn.trace is not None:
            turn.trace.ev("park", slot=slot, tokens=len(turn.new_tokens))
        self._active[slot] = None
        self._slot_tables[slot] = 0
        self._slot_lengths[slot] = 0
        self._slot_ahead[slot] = 0
        self._bump("requeues")
        self._queue_put(turn)
        # a stall-watchdog park under pool pressure hibernates the
        # session too — its requeued turn restores via prefetch (or at
        # admission) once the engine digs out
        if self.offload_store is not None and \
                self.page_table.free_fraction < self.offload_low_wm:
            self._offload_session(sess)

    def _handle_stall(self, active_idx: list[int], elapsed: float) -> None:
        """Decode-step watchdog: a device round slower than the stall
        threshold parks + requeues its still-active sessions (bounded
        per-turn) and notes ladder pressure."""
        if self.step_stall_s <= 0 or elapsed <= self.step_stall_s:
            return
        self._bump("stall_events")
        self._note_pressure()
        for i in active_idx:
            turn = self._active[i]
            if turn is not None and turn.requeues < self.max_requeues:
                self._park_and_requeue(i, turn)

    def _enforce_deadlines(self) -> None:
        """Fail active turns past their deadline cleanly (the session's
        KV survives via park semantics; only the request dies)."""
        now = time.monotonic()
        for i, turn in enumerate(self._active):
            if turn is None or turn.deadline is None or \
                    now < turn.deadline:
                continue
            turn.error = "deadline exceeded"
            self._bump("deadline_timeouts")
            self._finish_turn(i, turn, "error")

    def _shed_if_overloaded(self) -> None:
        """Ladder rung 4, per-class (docs/scheduler.md): when the queue
        is deeper than the engine can plausibly serve, shed queued
        turns with an explicit overload error (routes map it to 503 +
        Retry-After) instead of letting every tenant time out —
        background turns first, then workers, then queens; within a
        class, lowest priority first. A queen is dropped only once
        every lower-class turn over the cap already was."""
        if self.degradation_level() < 4:
            return
        keep_n = self.max_batch * 2
        if self._queue.qsize() <= keep_n:
            return
        drained: list[Turn] = []
        while True:
            try:
                drained.append(self._queue_get_nowait())
            except queue.Empty:
                break
        # most-keepable first: queen < worker < background, then
        # higher explicit priority
        drained.sort(key=lambda t: (
            CLASS_RANK.get(t.turn_class, 1), -t.priority
        ))
        for t in drained[:keep_n]:
            self._queue_put(t)
        for t in drained[keep_n:]:
            t.shed = True
            t.error = ("shedding load: engine degraded under sustained "
                       "pressure; retry later")
            t.finish_reason = "error"
            self._bump("shed_turns")
            self.scheduler.note_shed(t.turn_class)
            self._rollback_partial_prefill(t)
            trace_mod.finish(t, self.scheduler.targets)
            t.done.set()

    def _fail_turn_unslotted(self, turn: Turn, msg: str) -> None:
        """Fail a turn that never reached a slot (queued / admitting).
        A turn that died with interleaved prefill chunks committed
        rolls its session back to the pre-turn state first, so a
        client retry of the full prompt never lands on a
        half-prefilled session (docs/scheduler.md)."""
        self._rollback_partial_prefill(turn)
        turn.error = msg
        turn.finish_reason = "error"
        trace_mod.finish(turn, self.scheduler.targets)
        turn.done.set()

    def _rollback_partial_prefill(self, turn: Turn) -> None:
        """Undo a dying turn's committed-but-unadmitted prefill chunks:
        restore the session's pre-turn snapshot (history mirror,
        pending token, prefix refs). The chunk KV already in pages
        sits past the restored length — the standard overrun contract;
        pages stay owned by the session and are reused or released
        normally, so nothing leaks. No-op for turns without chunk
        progress, and engine-thread-only by construction (every death
        path for a queued turn runs there; submit()'s draining refusal
        happens before any chunk can be written)."""
        snap = turn._prefill_snap
        if snap is None or turn._chunk_committed <= 0:
            return
        turn._chunk_committed = 0
        turn._prefill_snap = None
        sess = self.sessions.get(turn.session_id)
        if sess is None:
            return
        try:
            self._restore_session_snapshot(sess, snap)
        except Exception:
            # rollback is best-effort cleanup on a turn that already
            # failed; the history-mirror re-prefill path remains the
            # correctness backstop
            pass

    def _recover_from_crash(self, exc: BaseException) -> bool:
        """Engine-thread supervision: a crashed scheduler iteration
        fails every pending request cleanly, resets host+device state
        to a provably leak-free baseline (fresh page table + page
        cache), and lets the loop continue. Returns False — and marks
        the engine unhealthy, which fail-closes the tpu: provider into
        registry fallback — once crashes exceed the restart budget
        within the pressure window."""
        self._bump("engine_crashes")
        self._note_pressure()
        try:
            from ..core.telemetry import incr_counter

            incr_counter("engine.crash")
        except Exception:
            pass
        msg = f"engine crashed: {type(exc).__name__}: {exc}"
        now = time.monotonic()
        self._crash_times.append(now)
        window = max(self.degrade_window_s, 60.0)
        recent = sum(1 for t in self._crash_times if now - t < window)
        fatal = recent > self.max_crash_restarts
        # the restart budget is spent AND a fleet supervisor will
        # consume the hand-off: preserve what it can re-home onto
        # sibling replicas before the clears below wipe every session
        # — parked sessions' history mirrors, plus spool files
        # detached from the offload store for hibernated ones
        # (byte-exact warm failover). Pure host work — the device is
        # exactly what just crashed and is never touched. A LONE
        # engine skips this: nothing would ever adopt the detached
        # files, so collecting them would only leak spool bytes.
        salvaging = fatal and self.fleet_supervised
        if salvaging:
            try:
                self.crash_salvage = self._collect_crash_salvage()
            except Exception:
                self.crash_salvage = None
        for i, turn in enumerate(self._active):
            if turn is not None:
                self._fail_turn_unslotted(turn, msg)
            self._active[i] = None
        self._fail_all_pending(msg)
        with self._lock:
            self._admitting.clear()
            self._deferred_release.clear()
        self.sessions.clear()
        self._prefix_cache.clear()
        self._prefix_lengths.clear()
        self._slot_tables[:] = 0
        self._slot_lengths[:] = 0
        self._reserved_tokens[:] = 0
        # the in-flight window's futures may hold the crash exception
        # (or a donated-away cache): drop them with the rest of the
        # device state — its turns were failed above. Staged fused-
        # window chunks go with them (their turns were failed+rolled
        # back via _fail_all_pending's partial-prefill rollback).
        self._inflight = None
        self._staged_chunks = []
        self._staged_sids.clear()
        self._slot_ahead[:] = 0
        self._feed_tokens = None
        self._feed_lens = None
        self._feed_rem = None
        self._spec_tail_dev = None
        # host/disk copies reference sessions that no longer exist (and
        # a crash mid-restore may have half-consumed one): drop them
        # all. On a FATAL supervised crash the spool dir itself must
        # survive — crash_salvage just detached spool files in it for
        # a fleet sibling to adopt, and rmtree would delete those
        # bytes out from under the hand-off.
        if self.offload_store is not None:
            self.offload_store.clear(remove_spool_dir=not salvaging)
        # a crash mid-device-call may have consumed a donated cache
        # buffer: rebuild the pool (and allocator) from scratch rather
        # than trust either side of the page accounting
        self.page_table = PageTable(self.n_pages, self.page_size)
        self.page_table.ensure_capacity("__null__", self.page_size)
        self.cache = init_page_cache(
            self.cfg, self.n_pages, self.page_size, quant=self.kv_quant
        )
        if self._cache_specs is not None:
            from ..parallel.mesh import shard_pytree

            self.cache = shard_pytree(
                self.cache, self._cache_specs, self.mesh
            )
        self._counts = None
        if fatal:
            self.healthy = False
            return False
        # backoff before resuming: a hard-failing dependency (device,
        # params) must not spin the supervisor at 100% CPU
        time.sleep(min(0.05 * (2 ** min(recent, 6)), 2.0))
        return True

    def _collect_crash_salvage(self) -> dict:
        """Manifest-style entries for every QUIESCENT session (parked
        for a tool call, or idle between turns — history/pending
        consistent by the park/finish contract). Sessions with an
        active, staged, or queued turn are deliberately excluded:
        their exact streamed-token state lives in the fleet router's
        history mirror (serving/fleet.py), which is authoritative for
        mid-turn failover. Hibernated sessions' offload entries are
        exported (the spool file detached for a sibling to adopt,
        byte-exact); resident-only KV re-prefills — those pages belong
        to the device state that just crashed."""
        out: dict[str, dict] = {}
        for sid, sess in list(self.sessions.items()):
            if self._session_in_flight(sid):
                continue
            if not sess.history and sess.pending is None:
                continue
            entry = self._session_entry(sess)
            if self.offload_store is not None and \
                    self._kv_export_eligible(sess):
                try:
                    entry["kv"] = self.offload_store.export_entry(sid)
                except Exception:
                    entry["kv"] = None
            out[sid] = entry
        return out

    def _session_entry(self, sess: _Session) -> dict:
        """Manifest-style record of one session's host state (the
        crash-salvage / ship-export shape; ``kv`` filled by callers
        that manage a spool export)."""
        return {
            "id": sess.id,
            "history": [int(t) for t in sess.history],
            "pending": int(sess.pending)
            if sess.pending is not None else None,
            "length": len(sess.history),
            "generation": int(sess.generation),
            "kv": None,
        }

    @staticmethod
    def _kv_export_eligible(sess: _Session) -> bool:
        """A session's KV may travel byte-exact only when it is wholly
        its own (shared prefix pages are cache-owned — they travel via
        the prefix STORE, docs/disagg.md) and the history mirror
        covers it exactly. Shared by crash salvage and the disagg ship
        export."""
        return sess.prefix_len == 0 and \
            len(sess.history) == sess.length

    def _prefill_fn(self, bucket: int, fresh: bool,
                    active_pages: Optional[int] = None):
        key = ("prefill", bucket, fresh, active_pages)
        if key not in self._jit_cache:
            cfg = self.cfg

            @partial(jax.jit, donate_argnums=(1,))
            def prefill(params, cache, tokens, block_table, length,
                        last_idx):
                hook = make_paged_kv_hook(
                    block_table, length, self.page_size,
                    fresh_prefill=fresh, active_pages=active_pages,
                    pallas_prefill=self._pallas_prefill,
                )
                positions = length[:, None] + jnp.arange(tokens.shape[1])
                # only each row's last real position gets sampled; at a
                # 151k vocab the full [B, bucket, V] head matmul would
                # dominate prefill FLOPs, so the head runs on [B, 1, D]
                hidden, cache = qwen3.forward(
                    params, cfg, tokens, positions, cache,
                    kv_hook=hook, apply_head=False,
                )
                last_h = jnp.take_along_axis(
                    hidden, last_idx[:, None, None], axis=1
                )
                last_logits = qwen3.lm_head(params, cfg, last_h)[:, 0]
                return last_logits, self._constrain_cache(cache)

            self._jit_cache[key] = prefill
        return self._jit_cache[key]

    def _decode_fn(self, n_steps: int,
                   active_pages: Optional[int] = None,
                   penalized: bool = False):
        """One compiled dispatch window advancing every slot ``n_steps``
        tokens (lax.scan over the fused forward+sample step). Sampled
        ids never leave the device inside the window: each step's token
        feeds the next step's embedding lookup directly, and every
        step writes its sampled row into the [n_steps, max_batch] ring
        (stacked scan output) the host drains asynchronously. Slots
        that hit a stop mid-window keep generating; the host trims —
        their extra KV writes sit beyond the session length and are
        overwritten on resume.

        Inputs are split so the window can chain off the PREVIOUS
        window without a host hop: ``prev_tokens`` is the last ring
        column of the prior dispatch (device-resident), ``fresh_tokens``
        / ``fresh_mask`` override rows whose feed the host owns (new
        admissions, post-flush rows). ``active_mask`` marks live slots:
        finished/parked rows keep their static batch lane but emit pad
        tokens (and never bump penalty counts) instead of forcing an
        early exit or a recompile.

        ``penalized`` compiles the OpenAI presence/frequency-penalty
        variant: a [B, V] per-request generated-token count array rides
        the scan carry, logits are penalized before sampling (greedy
        rows argmax the penalized logits too), each sampled token bumps
        its row's count."""
        key = ("decode", n_steps, active_pages, penalized)
        if key not in self._jit_cache:
            cfg = self.cfg
            pad_id = self.tokenizer.pad_id

            @partial(jax.jit,
                     donate_argnums=(1, 2) if penalized else (1,))
            def decode(params, cache, counts, prev_tokens, fresh_tokens,
                       fresh_mask, active_mask, block_tables, lengths,
                       rng, temperature, top_p, top_k,
                       presence, frequency):
                tokens = jnp.where(fresh_mask, fresh_tokens, prev_tokens)

                def step(carry, step_rng):
                    toks, cache, lens, cnts = carry
                    hook = make_paged_kv_hook(
                        block_tables, lens, self.page_size,
                        active_pages=active_pages,
                    )
                    logits, cache = qwen3.forward(
                        params, cfg, toks[:, None], lens[:, None],
                        cache, kv_hook=hook,
                    )
                    row_logits = logits[:, 0]
                    if penalized:
                        row_logits = apply_penalties(
                            row_logits.astype(jnp.float32), cnts,
                            presence, frequency,
                        )
                    nxt = sample_batched(
                        row_logits, step_rng, temperature, top_p,
                        top_k,
                    )
                    nxt = jnp.where(
                        active_mask, nxt, jnp.int32(pad_id)
                    )
                    if penalized:
                        # masked lanes must not pollute their slot's
                        # count row with pad garbage
                        cnts = cnts.at[
                            jnp.arange(nxt.shape[0]), nxt
                        ].add(active_mask.astype(jnp.int32))
                    return (nxt, cache, lens + 1, cnts), nxt

                (_, cache, _, counts), ring = jax.lax.scan(
                    step, (tokens, cache, lengths, counts),
                    jax.random.split(rng, n_steps),
                )
                return ring.T, counts, \
                    self._constrain_cache(cache)  # [B, n_steps]

            self._jit_cache[key] = decode
        return self._jit_cache[key]

    def _ragged_stream(self, ndp: int, n_chunks: int, tokens0,
                       lengths, block_tables, chunk_tokens,
                       chunk_tables, chunk_lens):
        """Build the fused window's ragged token stream (traced).

        dp=1: the classic [1, B + C*cw] flat stream, decode lanes
        first. dp>1 (the sharded fused window, docs/serving.md): the
        stream is [ndp, B/ndp + Cl*cw] — each dp shard's slice holds
        ITS decode lanes followed by ITS Cl shard-major chunk rows, so
        the forward is a per-shard ragged sub-batch with no cross-shard
        collective on the token path. Returns (flat tokens, positions,
        row-major block tables, row prefix lens)."""
        cw = self.sched_chunk_tokens
        b = self.max_batch
        bl = b // ndp
        cl = n_chunks // ndp
        chunk_pos = (
            chunk_lens[:, None] + jnp.arange(cw)
        ).reshape(ndp, cl * cw)
        flat = jnp.concatenate([
            tokens0.reshape(ndp, bl),
            chunk_tokens.reshape(ndp, cl * cw),
        ], axis=1)                         # [ndp, bl + cl*cw]
        pos = jnp.concatenate(
            [lengths.reshape(ndp, bl), chunk_pos], axis=1
        )
        tables_r = jnp.concatenate([
            block_tables.reshape(ndp, bl, -1),
            chunk_tables.reshape(ndp, cl, -1),
        ], axis=1).reshape(ndp * (bl + cl), -1)
        prefix_r = jnp.concatenate(
            [lengths.reshape(ndp, bl), chunk_lens.reshape(ndp, cl)],
            axis=1,
        ).reshape(-1)
        return (
            self._constrain_dp(flat, "tokens"),
            self._constrain_dp(pos, "positions"),
            tables_r, prefix_r,
        )

    def _fused_fn(self, n_steps: int, n_chunks: int,
                  active_pages: Optional[int] = None,
                  penalized: bool = False, ndp: int = 1):
        """Fused-window variant of _decode_fn: ONE compiled dispatch
        covering the scheduler window's staged prefill chunks AND its
        decode steps. Step 0 is a forward over the ragged
        [decode-lanes + chunk-rows] token stream — per layer, one
        attention dispatch through the unified ragged kernel (TPU) or
        the bounded gather+einsum reference (CPU) writes every row's KV
        and attends; the decode lanes' logits come off that same
        forward. Steps 1..n-1 are the standard decode scan. Chunk
        hidden states are discarded (apply_head=False; chunked prefill
        samples nothing until its tail admission), and the decode
        lanes are token-identical to the split path: the same KV lands
        at the same positions and sampling consumes the same per-step
        rng keys. ``ndp > 1`` shards the stream into per-dp-shard
        ragged sub-batches (_ragged_stream) — same rows, same write
        positions, same sampling keys, so greedy streams stay
        token-identical to the dp=1 window."""
        cw = self.sched_chunk_tokens
        key = ("fused", n_steps, n_chunks, cw, active_pages, penalized,
               ndp)
        if key not in self._jit_cache:
            cfg = self.cfg
            pad_id = self.tokenizer.pad_id
            b = self.max_batch

            @partial(jax.jit,
                     donate_argnums=(1, 2) if penalized else (1,))
            def fused(params, cache, counts, prev_tokens, fresh_tokens,
                      fresh_mask, active_mask, block_tables, lengths,
                      rng, temperature, top_p, top_k,
                      presence, frequency,
                      chunk_tokens, chunk_tables, chunk_lens):
                tokens0 = jnp.where(fresh_mask, fresh_tokens,
                                    prev_tokens)
                flat, pos, tables_r, prefix_r = self._ragged_stream(
                    ndp, n_chunks, tokens0, lengths, block_tables,
                    chunk_tokens, chunk_tables, chunk_lens,
                )
                hook = make_ragged_kv_hook(
                    tables_r, prefix_r, self.page_size,
                    n_decode=b, n_chunks=n_chunks, chunk_width=cw,
                    active_pages=active_pages,
                    pallas_ragged=self._pallas_ragged,
                    q_block=self.ragged_qblock,
                    n_shards=ndp,
                )
                hidden, cache = qwen3.forward(
                    params, cfg, flat, pos, cache, kv_hook=hook,
                    apply_head=False,
                )
                logits0 = qwen3.lm_head(
                    params, cfg,
                    hidden[:, :b // ndp].reshape(b, 1, -1)
                )[:, 0]                                # [B, V]
                keys = jax.random.split(rng, n_steps)
                row_logits = logits0
                if penalized:
                    row_logits = apply_penalties(
                        row_logits.astype(jnp.float32), counts,
                        presence, frequency,
                    )
                nxt0 = sample_batched(
                    row_logits, keys[0], temperature, top_p, top_k
                )
                nxt0 = jnp.where(active_mask, nxt0, jnp.int32(pad_id))
                if penalized:
                    counts = counts.at[
                        jnp.arange(b), nxt0
                    ].add(active_mask.astype(jnp.int32))

                def step(carry, step_rng):
                    toks, cache, lens, cnts = carry
                    hook = make_paged_kv_hook(
                        block_tables, lens, self.page_size,
                        active_pages=active_pages,
                    )
                    logits, cache = qwen3.forward(
                        params, cfg, toks[:, None], lens[:, None],
                        cache, kv_hook=hook,
                    )
                    row_logits = logits[:, 0]
                    if penalized:
                        row_logits = apply_penalties(
                            row_logits.astype(jnp.float32), cnts,
                            presence, frequency,
                        )
                    nxt = sample_batched(
                        row_logits, step_rng, temperature, top_p,
                        top_k,
                    )
                    nxt = jnp.where(
                        active_mask, nxt, jnp.int32(pad_id)
                    )
                    if penalized:
                        cnts = cnts.at[
                            jnp.arange(nxt.shape[0]), nxt
                        ].add(active_mask.astype(jnp.int32))
                    return (nxt, cache, lens + 1, cnts), nxt

                (_, cache, _, counts), ring_rest = jax.lax.scan(
                    step, (nxt0, cache, lengths + 1, counts), keys[1:]
                )
                ring = jnp.concatenate([nxt0[None], ring_rest], axis=0)
                return ring.T, counts, \
                    self._constrain_cache(cache)  # [B, n_steps]

            self._jit_cache[key] = fused
        return self._jit_cache[key]

    def _spec_window_fn(self, n_steps: int, width: int, n_chunks: int,
                        active_pages: Optional[int] = None,
                        penalized: bool = False, ndp: int = 1):
        """The speculative dispatch window (docs/serving.md): one
        compiled window whose every scan step drafts ON-MESH, verifies,
        and emits a VARIABLE 1..width tokens per lane — no host round
        trip, no pipeline flush.

        Each step: (1) prompt-lookup proposals are matched against the
        lane's device-resident recent-token tail (ops/spec.ngram_
        propose — the exact host propose_ngram rule), optionally backed
        by the tiny on-mesh draft model for lanes where no n-gram
        repeats; per-lane draft depth is clamped by the class gamma
        (``gamma_caps``) and the lane's remaining generation budget.
        (2) one [B, width] forward writes KV at positions
        lens..lens+width-1 and yields verify logits. (3) sampler.
        spec_verify accepts the longest draft prefix (greedy rows:
        exact tie-banded argmax equivalence; stochastic rows: exact
        speculative sampling), the bonus/residual token is appended,
        and lens/tail/budget advance by the emitted count. Rejected
        positions' KV sits past the advanced length and is overwritten
        by the next step's writes (lens' + width >= lens + width, so
        nothing stale is ever attended).

        ``width == 1`` compiles the degenerate no-drafting variant
        (every class at gamma 0) that still maintains the device
        lens/tail chain; ``n_chunks > 0`` fuses the scheduler window's
        staged prefill chunks into step 0 exactly like _fused_fn (step
        0 then emits one token per lane — drafting starts at step 1).

        The ring is [n_steps, B, width] (pad-filled past each step's
        emission) with sibling [n_steps, B] emitted/drafted counts the
        host drains asynchronously. ``ndp > 1`` is the dp-sharded
        fused spec-window: step 0's ragged stream becomes per-dp-shard
        sub-batches and every [B]-leading carry (tokens, lens, tails,
        the emission ring) shards its slot axis over dp — spec_step's
        math is row-wise, so drafting/verify/advance are shard-local
        with no cross-shard collective on the token path."""
        use_draft = self._draft is not None and width > 1
        key = ("spec_window", n_steps, width, n_chunks, active_pages,
               penalized, use_draft, ndp)
        if key not in self._jit_cache:
            cfg = self.cfg
            pad_id = self.tokenizer.pad_id
            b = self.max_batch
            gamma = width - 1
            cw = self.sched_chunk_tokens
            dcfg = self._draft[0] if use_draft else None
            dwindow = self.draft_window

            def spec_step(params, cache, cnts, toks, lens, rem, cov,
                          tail, active_mask, gamma_caps, block_tables,
                          step_rng, temperature, top_p, top_k,
                          presence, frequency, draft_params):
                """One in-window speculative step (traced inside the
                scan): draft -> verify -> accept -> advance."""
                if gamma > 0:
                    # clamp draft depth by the remaining generation
                    # budget AND the row's reserved page coverage
                    # (``cov``, absolute): an accepted token must have
                    # real KV, and positions past the reservation
                    # divert to scratch — so never accept into them.
                    # This keeps the device's length advance inside
                    # max(reserved, steps), which is what lets the
                    # host's _slot_ahead bound stay tight under pool
                    # pressure instead of booking gamma-inflated pages
                    # it can never use.
                    depth_cap = jnp.minimum(
                        jnp.maximum(rem - 1, 0),
                        jnp.maximum(cov - lens - 1, 0),
                    )
                    n_raw, prop = spec_ops.ngram_propose(tail, gamma)
                    n_prop = jnp.minimum(
                        jnp.minimum(n_raw, gamma_caps), depth_cap
                    )
                    if use_draft:
                        dm = spec_ops.draft_propose(
                            draft_params, dcfg, tail, gamma, dwindow
                        )
                        use_dm = (n_prop == 0) & (gamma_caps > 0) & \
                            (depth_cap > 0)
                        prop = jnp.where(use_dm[:, None], dm, prop)
                        n_prop = jnp.where(
                            use_dm,
                            jnp.minimum(gamma_caps, depth_cap),
                            n_prop,
                        )
                    n_prop = jnp.where(active_mask, n_prop, 0)
                    jg = jnp.arange(gamma)[None]
                    draft_mask = jg < n_prop[:, None]
                    ver = jnp.concatenate(
                        [toks[:, None],
                         jnp.where(draft_mask, prop, jnp.int32(pad_id))],
                        axis=1,
                    )
                else:
                    n_prop = jnp.zeros((b,), jnp.int32)
                    ver = toks[:, None]
                hook = make_paged_kv_hook(
                    block_tables, lens, self.page_size,
                    active_pages=active_pages,
                    pallas_prefill=self._pallas_prefill
                    if width > 1 else None,
                )
                positions = lens[:, None] + jnp.arange(width)
                logits, cache = qwen3.forward(
                    params, cfg, ver, positions, cache, kv_hook=hook,
                )
                logits = logits.astype(jnp.float32)
                if penalized:
                    # penalties apply to the lane's NEXT-token logits;
                    # penalized lanes never draft (gamma_caps 0 at
                    # dispatch), so position 0 is the only one sampled
                    logits = logits.at[:, 0].set(apply_penalties(
                        logits[:, 0], cnts, presence, frequency,
                    ))
                if gamma > 0:
                    accept, residual, plain = spec_verify(
                        logits, ver[:, 1:], step_rng,
                        temperature, top_p, top_k,
                    )
                    acc = jnp.cumprod(
                        (accept & (jnp.arange(gamma)[None]
                                   < n_prop[:, None])).astype(jnp.int32),
                        axis=1,
                    )
                    n_acc = acc.sum(axis=1)
                    bonus = jnp.where(
                        n_acc < n_prop,
                        jnp.take_along_axis(
                            residual,
                            jnp.minimum(n_acc, gamma - 1)[:, None],
                            axis=1,
                        )[:, 0],
                        jnp.take_along_axis(
                            plain, jnp.minimum(n_prop, gamma)[:, None],
                            axis=1,
                        )[:, 0],
                    )
                    widx = jnp.arange(width)[None]
                    ext = jnp.concatenate(
                        [ver[:, 1:],
                         jnp.full((b, 1), pad_id, jnp.int32)], axis=1,
                    )
                    emitted = jnp.where(
                        widx < n_acc[:, None], ext,
                        jnp.where(widx == n_acc[:, None],
                                  bonus[:, None], jnp.int32(pad_id)),
                    )
                else:
                    n_acc = jnp.zeros((b,), jnp.int32)
                    bonus = sample_batched(
                        logits[:, 0], step_rng,
                        temperature, top_p, top_k,
                    )
                    emitted = bonus[:, None]
                    widx = jnp.arange(width)[None]
                emitted = jnp.where(
                    active_mask[:, None], emitted, jnp.int32(pad_id)
                )
                emit_n = jnp.where(active_mask, n_acc + 1, 1) \
                    .astype(jnp.int32)
                if penalized:
                    upd = (widx < emit_n[:, None]) & active_mask[:, None]
                    cnts = cnts.at[
                        jnp.arange(b)[:, None], emitted
                    ].add(upd.astype(jnp.int32))
                new_toks = jnp.where(
                    active_mask, bonus, jnp.int32(pad_id)
                ).astype(jnp.int32)
                lens = lens + emit_n
                rem = jnp.where(
                    active_mask, jnp.maximum(rem - emit_n, 0), rem
                )
                tail = spec_ops.shift_tail(tail, emitted, emit_n)
                return cache, cnts, new_toks, lens, rem, tail, \
                    emitted, emit_n, n_prop

            @partial(jax.jit,
                     donate_argnums=(1, 2) if penalized else (1,))
            def specwin(params, cache, counts, draft_params,
                        prev_tokens, fresh_tokens, fresh_mask,
                        active_mask, gamma_caps, coverage,
                        block_tables,
                        host_lengths, prev_lens, fresh_rem, prev_rem,
                        fresh_tails, prev_tail, rng,
                        temperature, top_p, top_k, presence, frequency,
                        chunk_tokens, chunk_tables, chunk_lens):
                toks = jnp.where(fresh_mask, fresh_tokens, prev_tokens)
                lens = jnp.where(fresh_mask, host_lengths, prev_lens)
                rem = jnp.where(fresh_mask, fresh_rem, prev_rem)
                tail = jnp.where(
                    fresh_mask[:, None], fresh_tails, prev_tail
                )
                keys = jax.random.split(rng, n_steps)
                rings = []
                if n_chunks > 0:
                    # fused step 0: the ragged [decode-lanes +
                    # chunk-rows] forward, exactly _fused_fn's — one
                    # token per lane, drafting starts at step 1 (dp>1:
                    # per-dp-shard ragged sub-batches, _ragged_stream)
                    flat, pos, tables_r, prefix_r = \
                        self._ragged_stream(
                            ndp, n_chunks, toks, lens, block_tables,
                            chunk_tokens, chunk_tables, chunk_lens,
                        )
                    hook = make_ragged_kv_hook(
                        tables_r, prefix_r, self.page_size,
                        n_decode=b, n_chunks=n_chunks, chunk_width=cw,
                        active_pages=active_pages,
                        pallas_ragged=self._pallas_ragged,
                        q_block=self.ragged_qblock,
                        n_shards=ndp,
                    )
                    hidden, cache = qwen3.forward(
                        params, cfg, flat, pos, cache, kv_hook=hook,
                        apply_head=False,
                    )
                    logits0 = qwen3.lm_head(
                        params, cfg,
                        hidden[:, :b // ndp].reshape(b, 1, -1)
                    )[:, 0].astype(jnp.float32)
                    if penalized:
                        logits0 = apply_penalties(
                            logits0, counts, presence, frequency,
                        )
                    nxt0 = sample_batched(
                        logits0, keys[0], temperature, top_p, top_k
                    )
                    nxt0 = jnp.where(
                        active_mask, nxt0, jnp.int32(pad_id)
                    ).astype(jnp.int32)
                    if penalized:
                        counts = counts.at[
                            jnp.arange(b), nxt0
                        ].add(active_mask.astype(jnp.int32))
                    emitted0 = jnp.concatenate([
                        nxt0[:, None],
                        jnp.full((b, width - 1), pad_id, jnp.int32),
                    ], axis=1) if width > 1 else nxt0[:, None]
                    emit0 = jnp.ones((b,), jnp.int32)
                    toks = nxt0
                    lens = lens + 1
                    rem = jnp.where(
                        active_mask, jnp.maximum(rem - 1, 0), rem
                    )
                    tail = spec_ops.shift_tail(tail, emitted0, emit0)
                    rings.append(
                        (emitted0, emit0, jnp.zeros((b,), jnp.int32))
                    )
                    step_keys = keys[1:]
                else:
                    step_keys = keys

                def step(carry, step_rng):
                    toks, cache, lens, rem, tail, cnts = carry
                    cache, cnts, toks, lens, rem, tail, emitted, \
                        emit_n, n_prop = spec_step(
                            params, cache, cnts, toks, lens, rem,
                            coverage, tail, active_mask, gamma_caps,
                            block_tables, step_rng, temperature,
                            top_p, top_k, presence, frequency,
                            draft_params,
                        )
                    return (toks, cache, lens, rem, tail, cnts), \
                        (emitted, emit_n, n_prop)

                if len(step_keys):
                    (toks, cache, lens, rem, tail, counts), \
                        (ring_s, emits_s, drafted_s) = jax.lax.scan(
                            step,
                            (toks, cache, lens, rem, tail, counts),
                            step_keys,
                        )
                    if rings:
                        e0, n0, d0 = rings[0]
                        ring_s = jnp.concatenate(
                            [e0[None], ring_s], axis=0
                        )
                        emits_s = jnp.concatenate(
                            [n0[None], emits_s], axis=0
                        )
                        drafted_s = jnp.concatenate(
                            [d0[None], drafted_s], axis=0
                        )
                else:
                    e0, n0, d0 = rings[0]
                    ring_s = e0[None]
                    emits_s = n0[None]
                    drafted_s = d0[None]
                return (
                    ring_s.transpose(1, 0, 2),   # [B, steps, width]
                    emits_s.T,                   # [B, steps]
                    drafted_s.T,                 # [B, steps]
                    toks, lens, rem, tail, counts,
                    self._constrain_cache(cache),
                )

            self._jit_cache[key] = specwin
        return self._jit_cache[key]

    @staticmethod
    def _pow2(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def _offload_gather_fn(self, n_pad: int):
        """Gather ``n_pad`` pages of every cache array into contiguous
        [L, n_pad, ...] blocks for the host copy-out. Page counts are
        padded to powers of two (pad ids point at scratch page 0 and
        are sliced off host-side) so compile variants stay
        O(log capacity). No donation — the pool stays live."""
        key = ("offload_gather", n_pad)
        if key not in self._jit_cache:

            @jax.jit
            def gather(cache, ids):
                return {k: v[:, ids] for k, v in cache.items()}

            self._jit_cache[key] = gather
        return self._jit_cache[key]

    def _offload_scatter_fn(self, n_pad: int):
        """Scatter host page blocks back into the pool at fresh page
        ids (restore). Donates the cache like every other cache-writing
        fn; pad rows write zeros into scratch page 0, which is garbage
        by contract."""
        key = ("offload_scatter", n_pad)
        if key not in self._jit_cache:

            @partial(jax.jit, donate_argnums=(0,))
            def scatter(cache, ids, host):
                out = {
                    k: v.at[:, ids].set(host[k])
                    for k, v in cache.items()
                }
                return self._constrain_cache(out)

            self._jit_cache[key] = scatter
        return self._jit_cache[key]

    def _gather_pages_host(
        self, sess: _Session
    ) -> tuple[dict[str, np.ndarray], int]:
        """Copy a session's own (non-prefix) KV pages out to host
        arrays keyed like the cache. Returns (arrays, n_used). Shared
        by the offload path and the drain spooler — callers own fault
        points and retry policy."""
        pages = self.page_table.pages_of(sess.id)
        own_tokens = sess.length - sess.prefix_len
        n_used = -(-own_tokens // self.page_size)
        return self._gather_page_ids_host(pages[:n_used]), n_used

    def _gather_page_ids_host(
        self, used: list
    ) -> dict[str, np.ndarray]:
        """Copy an explicit page-id list out to host arrays keyed like
        the cache (the session offload gather, and the prefix-store
        publish gather — prefix pages belong to a cache-owned
        pseudo-session, not a real one)."""
        n_used = len(used)
        n_pad = self._pow2(max(n_used, 1))
        ids = np.zeros((n_pad,), np.int32)
        ids[:n_used] = used
        out = self._offload_gather_fn(n_pad)(
            self.cache, jnp.asarray(ids)
        )
        # start every device->host copy before materializing any of
        # them, so transfers overlap
        for a in out.values():
            try:
                a.copy_to_host_async()
            except AttributeError:
                pass
        # ascontiguousarray: a plain slice would be a VIEW pinning the
        # whole pow2-padded transfer buffer (~2x the real bytes),
        # silently defeating the host-tier cap
        return {
            k: np.ascontiguousarray(np.asarray(a)[:, :n_used])
            for k, a in out.items()
        }

    # ---- public API ----

    def submit(
        self,
        prompt_tokens: list[int],
        *,
        session_id: Optional[str] = None,
        sampling: Optional[SamplingParams] = None,
        on_token: Optional[Callable[[int], None]] = None,
        stop_strings: Optional[list[str]] = None,
        deadline_s: Optional[float] = None,
        priority: Optional[int] = None,
        turn_class: Optional[str] = None,
    ) -> Turn:
        """Queue a turn. If session_id names a parked session, generation
        resumes on top of its retained KV. ``deadline_s`` bounds the
        request end to end (default ROOM_TPU_TURN_DEADLINE_S; 0 = no
        deadline); ``priority`` orders load shedding under degradation
        (lowest sheds first). ``turn_class`` (queen/worker/background;
        docs/scheduler.md) sets the SLO class: admission is ordered by
        each class's TTFT-target deadline, chunked prefill draws from
        the class's per-window budget, and the degradation ladder
        sheds background before workers before queens. Unset/unknown
        classes run as ``worker``; an explicit ``priority`` (any int,
        including 0) still sets shed ordering within a class — only an
        UNSET priority takes the class default."""
        sid = session_id or f"s{id(object())}-{time.monotonic_ns()}"
        budget = deadline_s if deadline_s is not None \
            else self.turn_deadline_s
        cls = normalize_class(turn_class)
        now = time.monotonic()
        turn = Turn(
            session_id=sid,
            prompt_tokens=list(prompt_tokens),
            sampling=sampling or SamplingParams(),
            on_token=on_token,
            stop_strings=[s for s in (stop_strings or []) if s],
            deadline=(now + budget) if budget > 0 else None,
            priority=priority if priority is not None
            else CLASS_PRIORITY[cls],
            turn_class=cls,
            submitted_at=now,
        )
        turn.admit_by = self.scheduler.admit_deadline(cls, now)
        # turnscope (docs/observability.md): the span trace follows the
        # turn through admission, chunked prefill, decode windows, and
        # every death path; None when tracing is off
        turn.trace = trace_mod.begin(sid, cls, t_submit=now)
        self.scheduler.note_submitted(cls)
        if not self._queue_put(turn, unless_draining=True):
            # graceful drain (docs/lifecycle.md): admission is closed.
            # Same shed contract as ladder rung 4 — routes map it to
            # 503 + Retry-After, and the session (if any) stays parked
            # for the restarted process to resume.
            turn.shed = True
            self._fail_turn_unslotted(
                turn, "draining: engine is restarting; retry shortly"
            )
        return turn

    def release_session(self, session_id: str) -> None:
        """Free a session's pages. If the session is mid-turn, the release
        happens when that turn finishes (freeing live pages would let a
        new session reuse them while the old slot still writes KV).

        Thread-safe: when a loop thread owns the engine (serve_forever),
        the release is routed through the command queue and applied on
        the engine thread before the next admission — so a release can
        never race _admit/_decode_once on the page table. Without a
        loop thread (synchronous step()/run_until_idle use) it applies
        inline."""
        with self._lock:
            loop = self._loop_thread
        if loop is not None and loop.is_alive() and \
                loop is not threading.current_thread():
            self._release_requests.put(session_id)
            # the loop may have exited between the check and the put;
            # if nobody owns the engine anymore, apply the queue now
            with self._lock:
                loop = self._loop_thread
            if loop is None or not loop.is_alive():
                self._drain_releases()
            return
        self._do_release(session_id)

    def _drain_releases(self) -> None:
        while True:
            try:
                sid = self._release_requests.get_nowait()
            except queue.Empty:
                return
            self._do_release(sid)

    def _queue_put(
        self, turn: Turn, *, unless_draining: bool = False
    ) -> bool:
        """Count + enqueue atomically. With ``unless_draining`` the
        lifecycle-phase check shares the same lock hold, closing the
        submit-vs-drain race: begin_drain() flips the phase under this
        lock and drain()'s sweep runs after, so a turn either lands in
        the queue before the sweep (and is shed by it) or is refused
        here and shed by the caller — never stranded in a queue no
        thread will read again."""
        with self._lock:
            if unless_draining and self.lifecycle_phase == "draining":
                return False
            self._queued_sids[turn.session_id] = \
                self._queued_sids.get(turn.session_id, 0) + 1
            self._queue.put(turn)
        return True

    def _queue_uncount(self, turn: Turn) -> None:
        with self._lock:
            n = self._queued_sids.get(turn.session_id, 0) - 1
            if n > 0:
                self._queued_sids[turn.session_id] = n
            else:
                self._queued_sids.pop(turn.session_id, None)

    def _queue_get(self) -> Turn:
        turn = self._queue.get()
        self._queue_uncount(turn)
        trace_mod.note_dequeue(turn.trace)
        return turn

    def _queue_get_nowait(self) -> Turn:
        turn = self._queue.get_nowait()
        self._queue_uncount(turn)
        trace_mod.note_dequeue(turn.trace)
        return turn

    def _fail_all_pending(self, msg: str, *, shed: bool = False) -> None:
        """Fail every not-yet-slotted turn: drain the submit queue,
        then sweep turns caught mid-admission (popped but unslotted —
        anything already failed/slotted has ``done`` set and is
        skipped; the rest would hang their callers forever), and flush
        deferred releases. Shared by crash recovery and graceful drain
        (the latter marks turns ``shed`` so routes answer 503 +
        Retry-After)."""
        while True:
            try:
                turn = self._queue_get_nowait()
            except queue.Empty:
                break
            if shed:
                turn.shed = True
            self._fail_turn_unslotted(turn, msg)
        for turn in self._admission_turns:
            if not turn.done.is_set():
                if shed:
                    turn.shed = True
                self._fail_turn_unslotted(turn, msg)
        self._admission_turns = []
        self._drain_releases()

    def _session_in_flight(self, session_id: str) -> bool:
        """True while any live turn (active in a slot, mid-admission,
        or still QUEUED) references the session. Queued turns count:
        releasing under a queued turn would free the session now only
        for admission to silently recreate it — the chaos suite caught
        exactly that leak with the provider_timeout fault. Callers
        hold self._lock."""
        if any(
            t is not None and t.session_id == session_id
            for t in self._active
        ) or session_id in self._admitting \
                or session_id in self._staged_sids:
            # staged fused-window chunks count too: releasing the
            # session before its staged dispatch lands would free pages
            # the dispatch is about to write into
            return True
        return self._queued_sids.get(session_id, 0) > 0

    def _do_release(self, session_id: str) -> None:
        """Apply a release on the engine thread (or synchronously when
        no loop thread owns the engine)."""
        with self._lock:
            if self._session_in_flight(session_id):
                self._deferred_release.add(session_id)
                return
            sess = self.sessions.pop(session_id, None)
            if sess is not None:
                self._release_session_prefix(sess)
            self.page_table.release(session_id)
            if self.offload_store is not None:
                self.offload_store.discard(session_id)

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
        out["host_stall_ms"] = round(out["host_stall_ms"], 3)
        out["steps_per_dispatch"] = self.steps_per_dispatch
        out["phases"] = self.timer.snapshot()
        out["queued"] = self._queue.qsize()
        # which attention path decode/prefill actually route through
        # (probe-gated): benches must report what they measured
        out["pallas_decode"] = self._pallas_decode
        out["pallas_prefill"] = self._pallas_prefill
        out["kv_quant"] = self.kv_quant
        # fused-window diagnosability (docs/serving.md): a fleet of
        # mixed-mesh replicas (some dp-sharded) must be able to tell
        # WHY a replica fell back to split per-chunk dispatches
        out["fused_window"] = self.fused_window
        out["fused_window_mode"] = self.fused_window_mode
        out["fused_window_disabled_reason"] = \
            self.fused_window_disabled_reason
        if self._dp_size > 1:
            # dp-sharded fused spec-window: per-shard chunk-row
            # placement so a skewed shard (one dp slice absorbing all
            # the chunk traffic) is visible from the health surface
            out["fused_dp"] = {
                "dp": self._dp_size,
                "windows": out.get("fused_dp_windows", 0),
                "chunks_per_shard": list(self._fused_dp_shard_chunks),
            }
        out["active_slots"] = sum(
            1 for t in self._active if t is not None
        )
        out["degradation_level"] = self.degradation_level()
        out["healthy"] = self.healthy
        # SLO scheduler block (docs/scheduler.md): per-class queue
        # depth, TTFT/TPOT vs target, chunk-budget utilization, and
        # the ladder rung each class experiences
        sched = self.scheduler.snapshot(out["degradation_level"])
        sched["chunk_tokens"] = self.sched_chunk_tokens
        out["scheduler"] = sched
        # on-mesh speculative decoding (docs/serving.md): per-class
        # live gamma, acceptance EMA, and off decisions from the tuner
        out["spec"] = {
            "gamma_max": self.spec_tokens,
            "tail_tokens": self.spec_tail_len,
            "accept_floor": round(self.spec_tuner.floor, 4),
            "draft_model": self._draft[0].name
            if self._draft is not None else None,
            "classes": self.spec_tuner.snapshot(
                out["degradation_level"]
            ),
        }
        out["offload"] = self.offload_store.stats() \
            if self.offload_store is not None else None
        out["prefix_store"] = self.prefix_store.stats() \
            if self.prefix_store is not None else None
        with self._lock:
            lc = dict(self._lifecycle_stats)
        lc["phase"] = self.lifecycle_phase
        out["lifecycle"] = lc
        # system-invariant witness block (docs/chaosfuzz.md): the
        # process-global snapshot rides every engine's stats so the
        # health passthrough + TPU panel see it wherever they look
        out["invariants"] = invariants_mod.snapshot() \
            if invariants_mod.enabled() else None
        return out

    # ---- engine loop ----

    def step(self) -> int:
        """One scheduler iteration: apply queued releases, enforce
        deadlines, shed under overload, offload cold sessions under
        watermark pressure, prefetch queued hibernated sessions,
        admit, one decode step. Returns the number of active slots
        (0 = idle)."""
        # chaos fault point: a non-transient scheduler crash — the
        # serve_forever supervisor must fail pending work and recover
        faults.maybe_fail("engine_crash")
        # fresh per-class chunk budgets for this step's admission pass
        # (docs/scheduler.md): one step = one decode window, so the
        # budget is per-window
        self.scheduler.begin_step()
        self._drain_releases()
        self._drain_adoptions()
        self._drain_ships()
        self._enforce_deadlines()
        self._shed_if_overloaded()
        # sweep before prefetch: demotions free the pages restores need
        self._offload_sweep()
        self._prefetch_offloaded()
        self._admit()
        n = self._decode_once()
        # system-invariant witness (docs/chaosfuzz.md): the step
        # boundary is the engine thread's quiescent point — page
        # conservation and slot/session consistency hold exactly
        # here. Disarmed cost: one knob read.
        if invariants_mod.enabled():
            invariants_mod.probe_engine(self)
        return n

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if self.step() == 0 and self._queue.empty():
                return
        raise RuntimeError("run_until_idle exceeded max_steps")

    def serve_forever(self, stop_event: threading.Event, idle_sleep=0.002):
        """Supervised scheduler loop: a crashed iteration fails pending
        requests cleanly, resets to a leak-free baseline, and restarts
        — until the restart budget is spent, at which point the engine
        marks itself unhealthy and exits (the tpu: provider then
        fail-closes into registry fallback)."""
        with self._lock:
            self._loop_thread = threading.current_thread()
        try:
            while not stop_event.is_set():
                try:
                    if self.step() == 0 and self._queue.empty():
                        time.sleep(idle_sleep)
                except Exception as e:   # noqa: BLE001 — supervisor
                    if not self._recover_from_crash(e):
                        return
        finally:
            # a window still on device at shutdown carries real tokens:
            # drain it so waiting callers see their final stream (a
            # window whose computation itself died is just dropped)
            try:
                self._flush_pipeline()
            except Exception:
                self._inflight = None
            with self._lock:
                self._loop_thread = None
            # releases / adoptions / ships enqueued while stopping
            # still apply
            self._drain_releases()
            self._drain_adoptions()
            self._drain_ships()

    # ---- internals ----

    def _free_slots(self) -> list[int]:
        return [i for i, t in enumerate(self._active) if t is None]

    def _ensure_capacity_evicting(
        self, session_id: str, n_tokens: int
    ) -> list[int]:
        """ensure_capacity with LRU eviction under pool pressure: parked
        / idle sessions lose their pages (their context survives in the
        host-side history mirror and re-prefills on resume) instead of
        new work erroring out. The on-TPU analogue of the reference's
        session-rotation bound (agent-loop.ts:462-493)."""
        while True:
            try:
                return self.page_table.ensure_capacity(
                    session_id, n_tokens
                )
            except MemoryError:
                # cheapest relief first: hibernating a cold session
                # frees its pages without losing its KV (the resume is
                # a memcpy); only then drop KV via LRU eviction
                if not self._offload_coldest(exclude=session_id) and \
                        not self._evict_lru(exclude=session_id) and \
                        not self._evict_prefix():
                    raise

    def _evict_lru(self, exclude: str) -> bool:
        active_ids = {
            t.session_id for t in self._active if t is not None
        }
        # sessions prepped earlier in the SAME admission batch hold
        # page reservations but aren't in _active yet — evicting one
        # would hand its pages to a batchmate and the imminent batched
        # prefill would write two sessions' KV into the same pages
        active_ids |= self._admitting
        # sessions with staged (not yet dispatched) fused-window chunks
        # hold page reservations the fused dispatch will write into —
        # evicting one would point those writes at reallocated pages
        active_ids |= self._staged_sids
        candidates = [
            s for s in self.sessions.values()
            if s.id != exclude and s.id not in active_ids
            and self.page_table.pages_of(s.id)
        ]
        if not candidates:
            return False
        victim = min(candidates, key=lambda s: s.last_used)
        # fold the unwritten pending token into history so the restore
        # prompt reproduces the full context in order
        if victim.pending is not None:
            victim.history.append(victim.pending)
            victim.pending = None
        self.page_table.release(victim.id)
        self._release_session_prefix(victim)
        victim.length = 0
        self._bump("evictions")
        return True

    def _evict_prefix(self) -> bool:
        """Drop the least-recently-used cached prefix no live session
        references (its pages return to the pool)."""
        candidates = [
            e for e in self._prefix_cache.values() if not e.sessions
        ]
        if not candidates:
            return False
        victim = min(candidates, key=lambda e: e.last_used)
        self.page_table.release(victim.owner_id)
        del self._prefix_cache[victim.key]
        self._prefix_lengths[victim.length] -= 1
        if self._prefix_lengths[victim.length] <= 0:
            del self._prefix_lengths[victim.length]
        self._bump("prefix_evictions")
        return True

    # ---- tiered KV offload (kv_offload.py, docs/kv_offload.md) ----

    def _session_is_cold(self, sess: _Session) -> bool:
        """Cold = no live turn references the session (active slot,
        mid-admission, or queued). Queued sessions are excluded so the
        pressure sweep never ping-pongs with the prefetcher."""
        with self._lock:
            return not self._session_in_flight(sess.id)

    def _offload_session(self, sess: _Session) -> bool:
        """Copy the session's non-prefix KV pages out to the tiered
        store (async device->host) and release its HBM pages. Returns
        True when pages were freed. An offload_io fault surviving its
        retry budget FAILS BACK TO RESIDENT: the session keeps its
        pages and nothing is lost."""
        store = self.offload_store
        if store is None or sess.length <= sess.prefix_len:
            return False
        if not self.page_table.pages_of(sess.id):
            return False
        own_tokens = sess.length - sess.prefix_len

        def call():
            # fault point fires BEFORE the device call (no donation to
            # protect here, but the contract stays uniform)
            faults.maybe_fail("offload_io")
            return self._gather_pages_host(sess)

        try:
            with self.timer.phase("offload_out"):
                host, n_used = self._retrying("offload_out", call)
        except FaultError:
            self._bump("offload_resident_fallbacks")
            self._note_pressure()
            return False
        entry = store.put(sess.id, host, own_tokens, n_used)
        self.page_table.release(sess.id)
        self._bump("offloads")
        self._bump("offload_pages_out", n_used)
        try:
            from ..core.telemetry import incr_counter

            incr_counter("offload.out")
            incr_counter("offload.bytes_out", entry.nbytes)
        except Exception:
            pass
        return True

    def offload_session(self, session_id: str) -> bool:
        """Operator/test surface: hibernate one cold session now.
        Engine-thread semantics — call it only from the engine thread
        or while no loop thread owns the engine."""
        sess = self.sessions.get(session_id)
        if sess is None or not self._session_is_cold(sess):
            return False
        return self._offload_session(sess)

    def _restore_session(self, sess: _Session, *, evict: bool = True) -> bool:
        """device_put a hibernated session's pages back into the pool
        before its next prefill. Raises MemoryError when the pool can't
        hold it even after eviction (caller requeues; the entry stays
        intact). ``evict=False`` (speculative prefetch) only takes
        genuinely free pages — an opportunistic restore must never
        evict another queued session's live KV to make room. An
        offload_io fault surviving its retry budget — or a
        dropped/unreadable entry — falls back to the history-mirror
        re-prefill path (sess.length = 0), trading compute for
        correctness."""
        store = self.offload_store
        if store is None:
            return False
        got = store.get(sess.id)
        if got is None:
            return False
        entry, host = got
        t0 = time.monotonic()
        # MemoryError propagates with the entry intact; ensure_capacity
        # is all-or-nothing so no pages leak on the raise
        if evict:
            pages = self._ensure_capacity_evicting(
                sess.id, entry.own_tokens
            )
        else:
            pages = self.page_table.ensure_capacity(
                sess.id, entry.own_tokens
            )
        n_used = entry.n_pages
        n_pad = self._pow2(max(n_used, 1))
        ids = np.zeros((n_pad,), np.int32)
        ids[:n_used] = pages[:n_used]
        padded = {}
        for k, a in host.items():
            buf = np.zeros((a.shape[0], n_pad) + a.shape[2:], a.dtype)
            buf[:, :n_used] = a
            padded[k] = buf
        scatter = self._offload_scatter_fn(n_pad)

        def call():
            # fault point fires BEFORE the jitted call so no donated
            # buffer is consumed by a failed attempt
            faults.maybe_fail("offload_io")
            return scatter(self.cache, jnp.asarray(ids), padded)

        try:
            with self.timer.phase("offload_in"):
                self.cache = self._retrying("offload_in", call)
        except FaultError:
            # fail back to re-prefill: release the just-allocated
            # pages, drop the copy, and let the restoring path rebuild
            # the context from the host-side history mirror
            self.page_table.release(sess.id)
            store.discard(sess.id)
            sess.length = 0
            self._bump("offload_reprefills")
            self._note_pressure()
            return False
        store.discard(sess.id)
        elapsed = time.monotonic() - t0
        store.observe_restore(elapsed, entry.nbytes)
        self._bump("offload_restores")
        self._bump("offload_pages_in", n_used)
        try:
            from ..core.telemetry import incr_counter, observe_ms

            incr_counter("offload.in")
            observe_ms("offload.restore", elapsed * 1000.0)
        except Exception:
            pass
        return True

    def _ensure_resident(self, sess: _Session) -> None:
        """Make an offloaded (or copy-lost) session's KV usable before
        turn preparation: restore its pages, or — when the copy is gone
        (disk-cap drop, spool I/O error, restore fault) — reset to the
        history-mirror re-prefill path. Called BEFORE the preparation
        snapshot so rollback can never mix restored and hibernated
        state."""
        if sess.length <= sess.prefix_len:
            return
        if self.page_table.pages_of(sess.id):
            return   # resident
        if self.offload_store is not None and \
                self.offload_store.has(sess.id):
            if self._restore_session(sess):
                return
        if sess.length > 0:
            # no copy to restore: |history| == length always, so the
            # restoring path in _prepare_turn_inner rebuilds the
            # context exactly
            self._bump("offload_reprefills")
            sess.length = 0

    def _offload_coldest(self, exclude: str) -> bool:
        """Pool-pressure fallback, tried before LRU eviction: hibernate
        the coldest cold session instead of dropping its KV — frees the
        same pages but the resume is a memcpy, not a re-prefill."""
        if self.offload_store is None:
            return False
        candidates = [
            s for s in self.sessions.values()
            if s.id != exclude and s.id not in self._staged_sids
            and s.length > s.prefix_len
            and self.page_table.pages_of(s.id)
            and self._session_is_cold(s)
        ]
        for victim in sorted(candidates, key=lambda s: s.last_used):
            if self._offload_session(victim):
                return True
        return False

    def _offload_sweep(self) -> None:
        """Watermark-driven demotion, run every scheduler step: when
        free pages fall under the low watermark (or ladder rung >= 2
        turns the sweep aggressive), hibernate cold sessions coldest-
        first until the high watermark is restored (aggressive: until
        no cold session holds pages)."""
        if self.offload_store is None:
            return
        aggressive = self.degradation_level() >= 2
        if not aggressive and \
                self.page_table.free_fraction >= self.offload_low_wm:
            return
        candidates = [
            s for s in self.sessions.values()
            if s.length > s.prefix_len
            and s.id not in self._staged_sids
            and self.page_table.pages_of(s.id)
            and self._session_is_cold(s)
        ]
        for victim in sorted(candidates, key=lambda s: s.last_used):
            if not aggressive and self.page_table.free_fraction \
                    >= self.offload_high_wm:
                break
            self._offload_session(victim)

    def _prefetch_offloaded(self) -> None:
        """Restore hibernated sessions whose next turn is already
        QUEUED, overlapping the host->device copy with ongoing decode
        instead of paying it inside the admission path. Bounded per
        step; a full pool just defers to admission-time restore."""
        store = self.offload_store
        if store is None or len(store) == 0:
            return
        # never prefetch INTO a pressured pool: below the low watermark
        # the pages are better spent on the active batch (and restoring
        # a stall-parked session the watchdog just hibernated would be
        # a guaranteed wasted round trip) — admission restores when the
        # turn actually lands
        if self.page_table.free_fraction < self.offload_low_wm:
            return
        with self._lock:
            queued = list(self._queued_sids)
        budget = self.offload_prefetch
        for sid in queued:
            if budget <= 0:
                return
            sess = self.sessions.get(sid)
            if sess is None or not store.has(sid):
                continue
            try:
                # evict=False: a speculative restore takes only free
                # pages — admission (which may evict) restores the rest
                if self._restore_session(sess, evict=False):
                    budget -= 1
                    self._bump("offload_prefetches")
                    # turnscope: a prefetch restore OVERLAPS decode —
                    # it never blocks the turn, so it is a global
                    # event, not a span on the turn's latency (the
                    # blocking admission-time restore is)
                    trace_mod.note_event(
                        "offload_prefetch", {"session": sid}
                    )
            except MemoryError:
                return   # pool busy; admission will retry

    def _prefix_lookup(self, prompt: list[int]) -> Optional["_PrefixEntry"]:
        """Longest ready cached prefix of ``prompt`` (only lengths that
        actually exist in the cache are probed)."""
        page = self.page_size
        max_len = ((len(prompt) - 1) // page) * page
        for length in sorted(self._prefix_lengths, reverse=True):
            if length > max_len:
                continue
            entry = self._prefix_cache.get(tuple(prompt[:length]))
            if entry is not None and entry.ready:
                return entry
        return None

    def _prefix_register(
        self, sess: _Session, prompt: list[int]
    ) -> Optional["_PrefixEntry"]:
        """Allocate cache-owned pages for this prompt's aligned prefix;
        the session's own prefill writes the KV, and the entry becomes
        ready (shareable) once that completes. Best-effort: under pool
        pressure the session simply admits uncached."""
        page = self.page_size
        aligned = ((len(prompt) - 1) // page) * page
        if aligned < self.prefix_cache_min_pages * page:
            return None
        if aligned // page >= self.max_pages_per_seq:
            return None
        key = tuple(prompt[:aligned])
        if key in self._prefix_cache:
            return None   # duplicate in the same admission batch
        owner = f"__prefix__{len(self._prefix_cache)}_" \
            f"{time.monotonic_ns()}"
        try:
            pages = self.page_table.ensure_capacity(owner, aligned)
        except MemoryError:
            return None
        entry = _PrefixEntry(
            key=key, owner_id=owner, pages=list(pages), length=aligned,
        )
        entry.sessions.add(sess.id)
        self._prefix_cache[key] = entry
        self._prefix_lengths[aligned] += 1
        sess.prefix_key = key
        sess.prefix_pages = list(pages)
        sess.prefix_len = aligned
        return entry

    def _release_session_prefix(self, sess: _Session) -> None:
        if sess.prefix_key is None:
            return
        entry = self._prefix_cache.get(sess.prefix_key)
        if entry is not None:
            entry.sessions.discard(sess.id)
            entry.last_used = time.monotonic()
        sess.prefix_key = None
        sess.prefix_pages = []
        sess.prefix_len = 0

    # ---- shared prefix store (prefix_store.py, docs/disagg.md) ----

    def _prefix_store_pull(
        self, turn: Turn, prompt: list[int]
    ) -> Optional["_PrefixEntry"]:
        """Local prefix-cache miss: pull the longest stored prefix of
        ``prompt`` from the fleet-global store and COPY-ON-ADOPT it —
        scatter the spooled KV bytes into freshly allocated cache-owned
        pages, materializing a ready local ``_PrefixEntry`` every later
        session shares for free. Degrades to None (the ordinary miss)
        on store miss, prefix_io fault, checksum failure, pool
        pressure, or a scatter error — correctness never depends on
        the store. Engine-thread only (admission path)."""
        store = self.prefix_store
        if store is None:
            return None
        page = self.page_size
        max_len = min(
            ((len(prompt) - 1) // page) * page,
            (self.max_pages_per_seq - 1) * page,
        )
        if max_len < self.prefix_cache_min_pages * page:
            return None
        t0 = time.monotonic()
        got = store.fetch_longest(prompt, max_len)
        if got is None:
            return None
        length, meta, arrays = got
        if length < self.prefix_cache_min_pages * page:
            return None
        key = tuple(prompt[:length])
        cached = self._prefix_cache.get(key)
        if cached is not None:
            # raced our own earlier pull (or a register that became
            # ready between lookup and pull): use the local entry
            return cached if cached.ready else None
        n_used = length // page
        try:
            meta_pages = int(meta.get("n_pages"))
        except (TypeError, ValueError):
            meta_pages = -1
        if meta_pages != n_used:
            self._bump("prefix_store_pull_fallbacks")
            return None
        owner = f"__prefix__{len(self._prefix_cache)}_" \
            f"{time.monotonic_ns()}"
        try:
            pages = self.page_table.ensure_capacity(owner, length)
        except MemoryError:
            self._bump("prefix_store_pull_fallbacks")
            return None
        n_pad = self._pow2(max(n_used, 1))
        ids = np.zeros((n_pad,), np.int32)
        ids[:n_used] = pages[:n_used]
        try:
            padded = {}
            for k, a in arrays.items():
                buf = np.zeros(
                    (a.shape[0], n_pad) + a.shape[2:], a.dtype
                )
                buf[:, :n_used] = a
                padded[k] = buf
            self.cache = self._offload_scatter_fn(n_pad)(
                self.cache, jnp.asarray(ids), padded
            )
        except Exception:
            # shape/dtype surprises or a device-side scatter failure:
            # release the just-allocated pages and take the miss
            self.page_table.release(owner)
            self._bump("prefix_store_pull_fallbacks")
            return None
        entry = _PrefixEntry(
            key=key, owner_id=owner, pages=list(pages),
            length=length, ready=True,
        )
        self._prefix_cache[key] = entry
        self._prefix_lengths[length] += 1
        self._bump("prefix_store_hits")
        self._bump("prefix_store_tokens_reused", length)
        pull_ms = round((time.monotonic() - t0) * 1000.0, 3)
        # turnscope (docs/observability.md): the pull blocks THIS
        # turn's prefill span — event it on the turn, and into the
        # global ring for cross-turn store visibility
        if turn.trace is not None:
            turn.trace.ev("prefix_pull", tokens=length, ms=pull_ms)
        trace_mod.note_event("prefix_pull", {
            "session": turn.session_id, "tokens": length,
            "ms": pull_ms,
        })
        return entry

    def _prefix_store_maybe_publish(self, entry: "_PrefixEntry") -> None:
        """A locally computed prefix just became ready: publish its KV
        pages to the shared store so sibling replicas (and the next
        process/host) pull instead of re-prefilling. Best-effort and
        bounded — one gather of the prefix's own pages; failures count
        and skip. Engine-thread only."""
        store = self.prefix_store
        if store is None or not self.prefix_store_publish:
            return
        if store.has(entry.key):
            return
        try:
            arrays = self._gather_page_ids_host(entry.pages)
        except Exception:
            return
        if store.publish(entry.key, arrays, len(entry.pages)):
            self._bump("prefix_store_publishes")
            trace_mod.note_event("prefix_publish", {
                "tokens": entry.length,
            })

    def _admit(self) -> None:
        """Admission with batched prefill: queued turns that share a
        (bucket, fresh) shape prefill together in one device call —
        multi-tenant rooms submitting simultaneously don't serialize."""
        free = self._free_slots()
        preps: list[dict] = []
        # popped but deferred to the next step (per-class admission
        # halving, chunk budget, pool pressure on a background chunk):
        # re-queued at the end of the pass with their original EDF key
        # (distinct from the deferred-RELEASE session-id set the
        # finally block reads)
        held_turns: list[Turn] = []
        raw_level = self.degradation_level()
        # ladder rung 3, per-class (docs/scheduler.md): halve the
        # admission batch for classes experiencing rung >= 3 so a
        # pressured pool drains instead of thrashing on eviction;
        # queens get one rung of grace
        halved = max(1, self.max_batch // 2)
        attempts = 0
        with self._lock:
            self._admitting.clear()
        try:
            while free and not self._queue.empty() and \
                    len(preps) < len(free) and \
                    attempts < self.max_batch * 2:
                attempts += 1
                turn = self._queue_get()
                self._admission_turns.append(turn)
                if len(preps) >= halved and self.scheduler.class_rung(
                        turn.turn_class, raw_level) >= 3:
                    held_turns.append(turn)
                    continue
                # registered BEFORE pages are reserved so an inline
                # release from another thread can't free a batchmate's
                # reservation mid-admission (it defers instead);
                # mutation under _lock because _do_release reads it
                with self._lock:
                    self._admitting.add(turn.session_id)
                try:
                    prep = self._prepare_turn(turn)
                except MemoryError as e:
                    with self._lock:
                        self._admitting.discard(turn.session_id)
                    self._note_pressure()
                    # pool exhausted: requeue and stop admitting; decode
                    # will drain sessions and free pages
                    if self._free_slots() == \
                            list(range(self.max_batch)) and not preps:
                        self._fail_turn_unslotted(turn, str(e))
                    else:
                        turn.disrupted = True
                        self._queue_put(turn)
                    break
                except FaultError as e:
                    # transient prefill fault survived its retry budget:
                    # requeue (bounded) rather than drop the turn
                    with self._lock:
                        self._admitting.discard(turn.session_id)
                    self._note_pressure()
                    trace_mod.note_fault(
                        turn.trace, getattr(e, "point", None)
                    )
                    turn.requeues += 1
                    turn.disrupted = True
                    if turn.requeues > self.max_requeues:
                        self._fail_turn_unslotted(turn, str(e))
                    else:
                        self._bump("requeues")
                        self._queue_put(turn)
                    continue
                if prep is not None:
                    preps.append(prep)
                else:
                    with self._lock:
                        self._admitting.discard(turn.session_id)
                    if turn._admit_deferred:
                        # chunk budget / pool pressure mid-chunked-
                        # prefill: hold the turn for the next step (a
                        # decode window runs in between)
                        turn._admit_deferred = False
                        held_turns.append(turn)

            # group by identical prefill shape
            groups: dict[tuple, list[dict]] = {}
            for prep in preps:
                groups.setdefault(
                    (prep["bucket"], prep["fresh"], prep["active_pages"]),
                    [],
                ).append(prep)
            for (bucket, fresh, active_pages), group in groups.items():
                slots = [free.pop(0) for _ in group]
                self._prefill_group(
                    bucket, fresh, group, slots,
                    active_pages=active_pages,
                )
            # held turns re-enter the queue with their original EDF
            # key and seq (before the clear below, so a crash in
            # between cannot orphan them in neither structure)
            for t in held_turns:
                self._queue_put(t)
            held_turns = []
            # normal exit: every popped turn is slotted, requeued, or
            # already failed. Cleared HERE (not in finally) so a crash
            # escaping admission leaves the list for the supervisor.
            self._admission_turns.clear()
        finally:
            with self._lock:
                self._admitting.clear()
                deferred = set(self._deferred_release)
            # releases deferred while a session was mid-admission whose
            # turn never reached a slot (prep failed / shed / crashed)
            # would otherwise linger: _finish_turn only sees slotted
            # turns. A still-queued turn keeps its deferral.
            for sid in deferred:
                with self._lock:
                    in_flight = self._session_in_flight(sid)
                    if not in_flight:
                        # consume the deferral atomically with the
                        # in-flight check: release_session defers
                        # under the same lock, so an unlocked discard
                        # here could swallow a deferral booked for a
                        # NEWER turn between check and discard
                        self._deferred_release.discard(sid)
                if not in_flight:
                    self._do_release(sid)

    def _restore_session_snapshot(self, sess: _Session, snap: dict) -> None:
        """Roll a session back to its pre-preparation state after a
        failed admission (pool exhaustion or an injected prefill
        fault), including dropping a prefix-cache entry or reference
        the failed preparation created."""
        if sess.prefix_key is not None and \
                sess.prefix_key != snap["prefix_key"]:
            key = sess.prefix_key
            self._release_session_prefix(sess)
            entry = self._prefix_cache.get(key)
            if entry is not None and not entry.ready and \
                    not entry.sessions:
                self.page_table.release(entry.owner_id)
                del self._prefix_cache[key]
                self._prefix_lengths[entry.length] -= 1
                if self._prefix_lengths[entry.length] <= 0:
                    del self._prefix_lengths[entry.length]
        sess.prefix_key = snap["prefix_key"]
        sess.prefix_pages = list(snap["prefix_pages"])
        sess.prefix_len = snap["prefix_len"]
        sess.pending = snap["pending"]
        sess.length = snap["length"]
        sess.history = list(snap["history"])
        sess.parked = snap["parked"]

    def _prepare_turn(self, turn: Turn) -> Optional[dict]:
        """Validate + reserve pages for a queued turn. Returns the
        prefill prep dict, or None when the turn ended during
        validation. Raises MemoryError (pool can't hold it) or
        FaultError (injected prefill fault past its retry budget) with
        the session rolled back to its pre-preparation state either
        way, so a requeue re-prepares from scratch losing nothing."""
        if turn.done.is_set():
            # already finished while queued (staged-chunk rollback past
            # its requeue budget, shed race): never re-prefill it
            return None
        if turn.deadline is not None and \
                time.monotonic() > turn.deadline:
            self._bump("deadline_timeouts")
            self._fail_turn_unslotted(
                turn, "deadline exceeded while queued"
            )
            return None
        if turn.session_id in self._staged_sids:
            # the session's staged fused-window chunks haven't landed
            # on device yet (a second turn queued on the same session
            # in the same admission pass): admitting on top of them
            # would prefill against unwritten KV — hold one step
            turn._admit_deferred = True
            return None
        sess = self.sessions.get(turn.session_id)
        if sess is None:
            sess = _Session(id=turn.session_id)
            self.sessions[turn.session_id] = sess
        # hibernated sessions come back BEFORE the snapshot: a later
        # rollback then restores a consistent resident (or re-prefill)
        # state, never a half-restored one. MemoryError propagates to
        # _admit (requeue) with the host copy intact.
        tr = turn.trace
        was_hibernated = (
            tr is not None and self.offload_store is not None
            and self.offload_store.has(sess.id)
        )
        if was_hibernated:
            t_restore = time.monotonic()
            pre_len = sess.length
            self._ensure_resident(sess)
            dt_ms = (time.monotonic() - t_restore) * 1000.0
            tr.offload_restore_ms += dt_ms
            if sess.length == 0 and pre_len > 0:
                # the copy was unusable: this turn pays a history
                # re-prefill instead of a restore
                tr.reprefills += 1
                tr.ev("offload_reprefill", ms=round(dt_ms, 3))
            else:
                tr.offload_restores += 1
                tr.ev("offload_restore", ms=round(dt_ms, 3))
        else:
            self._ensure_resident(sess)
        snap = {
            "pending": sess.pending, "length": sess.length,
            "history": list(sess.history), "parked": sess.parked,
            "prefix_key": sess.prefix_key,
            "prefix_pages": list(sess.prefix_pages),
            "prefix_len": sess.prefix_len,
        }
        try:
            prep = self._prepare_turn_inner(turn, sess, snap)
        except (MemoryError, FaultError):
            self._restore_session_snapshot(sess, snap)
            raise
        if prep is not None:
            prep["snap"] = snap
        return prep

    def _prepare_turn_inner(
        self, turn: Turn, sess: _Session, snap: Optional[dict] = None
    ) -> Optional[dict]:
        sess.parked = False
        sess.last_used = time.monotonic()
        sess.generation += 1

        if turn.sampling.max_new_tokens <= 0:
            turn.finish_reason = "length"
            trace_mod.finish(turn, self.scheduler.targets)
            turn.done.set()
            return None
        prompt = turn.prompt_tokens
        if turn._mid_stream:
            # requeued mid-generation (stall watchdog): the prompt's KV
            # is already materialized (or lives in the history mirror);
            # only the pending token re-enters below
            prompt = []
        if sess.pending is not None:
            # re-materialize the sampled-but-unwritten token from the
            # previous turn so its KV lands before the continuation.
            # pending is cleared only after prefill succeeds, so a
            # MemoryError requeue keeps the token.
            prompt = [sess.pending] + prompt
        restoring = sess.length == 0 and bool(sess.history)
        if restoring:
            # pages were evicted under pool pressure: rebuild the whole
            # context from the host-side mirror. history is cleared only
            # after pages are reserved (the prefill bookkeeping re-fills
            # it), so a MemoryError requeue loses nothing.
            prompt = sess.history + prompt
        if not prompt:
            # mid-stream requeue whose session vanished (released while
            # queued): nothing to continue from
            self._fail_turn_unslotted(turn, "session lost while requeued")
            return None
        total = sess.length + len(prompt)
        # remaining (not full) generation budget: a requeued mid-stream
        # turn already spent part of max_new_tokens
        remaining_budget = max(
            turn.sampling.max_new_tokens - len(turn.new_tokens), 1
        )
        if total + remaining_budget > self.max_seq_len:
            if turn._mid_stream:
                # a mid-generation requeue (stall park, degraded
                # reservation) that ran out of context: the stream
                # legitimately ends at the tokens already delivered
                turn.finish_reason = "length"
                trace_mod.finish(turn, self.scheduler.targets)
                turn.done.set()
                return None
            turn.error = (
                f"sequence would exceed max_seq_len {self.max_seq_len}"
            )
            turn.finish_reason = "error"
            trace_mod.finish(turn, self.scheduler.targets)
            turn.done.set()
            return None

        # automatic prefix caching: a fresh session whose prompt starts
        # with a cached page-aligned prefix references those read-only
        # pages instead of re-prefilling them (the swarm's shared
        # system prompts); a fresh long prompt with no hit registers
        # its own prefix for the next session
        register_entry: Optional[_PrefixEntry] = None
        if sess.length == 0 and self.prefix_cache_min_pages > 0:
            hit = self._prefix_lookup(prompt)
            if hit is None:
                # fleet-global shared prefix store (docs/disagg.md): a
                # sibling replica / process / host may already hold
                # this prompt's prefix KV — pull + scatter it into
                # local pages instead of re-prefilling it
                hit = self._prefix_store_pull(turn, prompt)
            if hit is not None:
                hit.sessions.add(sess.id)
                hit.last_used = time.monotonic()
                sess.prefix_key = hit.key
                sess.prefix_pages = list(hit.pages)
                sess.prefix_len = hit.length
                sess.length = hit.length
                sess.history = list(prompt[: hit.length])
                prompt = prompt[hit.length:]
                self._bump("prefix_hits")
                self._bump("prefix_tokens_reused", hit.length)
            else:
                register_entry = self._prefix_register(sess, prompt)

        # interleaved chunked prefill (scheduler.py, docs/scheduler.md):
        # page-chunk writes spread ACROSS scheduler steps under the
        # class's per-window budget — a decode window runs between
        # chunks, so a multi-thousand-token prompt never monopolizes a
        # dispatch. Token-identical to the monolithic path: the same
        # positions get the same KV, only WHEN they are written moves.
        cw = self.sched_chunk_tokens
        if cw and len(prompt) > cw:
            prompt = self._advance_chunked_prefill(
                turn, sess, prompt, restoring, snap
            )
            if prompt is None:
                return None     # deferred / requeued / failed
            restoring = False   # chunk writes re-materialized history

        # long prompts prefill in fixed-width chunks through the
        # KV-continuation path, so compile widths and activation memory
        # are bounded by prefill_chunk regardless of prompt length; only
        # the final chunk samples
        chunk_limit = self.prefill_chunk
        pre_chunks: list[list[int]] = []
        tail = prompt
        if chunk_limit and len(prompt) > chunk_limit:
            n_full = (len(prompt) - 1) // chunk_limit
            pre_chunks = [
                prompt[i * chunk_limit:(i + 1) * chunk_limit]
                for i in range(n_full)
            ]
            tail = prompt[n_full * chunk_limit:]
        pre_total = sum(len(c) for c in pre_chunks)

        bucket = next(
            (b for b in PREFILL_BUCKETS if b >= len(tail)),
            None,
        )
        capacity = self.max_pages_per_seq * self.page_size
        # the padded prefill must also fit the block table: clamp the
        # bucket to the remaining page-aligned capacity (an off-bucket
        # length near capacity costs one extra compile, not a rejection)
        remaining = capacity - sess.length - pre_total
        if bucket is not None and bucket > remaining:
            bucket = (remaining // self.page_size) * self.page_size
        if bucket is None or bucket < len(tail):
            turn.error = (
                f"prompt too long: {len(prompt)} at session length "
                f"{sess.length} (capacity {capacity})"
            )
            turn.finish_reason = "error"
            trace_mod.finish(turn, self.scheduler.targets)
            turn.done.set()
            return None

        own_target = sess.length + pre_total + bucket - sess.prefix_len
        # MemoryError propagates to _prepare_turn, which rolls the
        # session (including any prefix hit/registration) back to its
        # pre-preparation snapshot before requeueing
        pages = self._ensure_capacity_evicting(sess.id, own_target)
        sess.pending = None
        if restoring and sess.length == 0:
            # a prefix HIT already rebuilt history as prompt[:L] (and
            # set length=L); every other restore path starts clean and
            # lets the prefill bookkeeping re-fill the mirror
            sess.history = []
        table = np.zeros((self.max_pages_per_seq,), np.int32)
        all_pages = sess.prefix_pages + pages
        table[: len(all_pages)] = all_pages
        for chunk_toks in pre_chunks:
            self._prefill_write_chunk(sess, chunk_toks, table)
        fresh = sess.length == 0
        # continuation prefill gathers only the pages this turn can
        # reach (bucketed), not the table's full capacity; with the
        # Pallas prefill kernel (S % q-block == 0) there is no gather
        # at all, so no bound to key compiles on
        active_pages = None
        if not fresh and not (self._pallas_prefill and bucket % 8 == 0):
            active_pages = self._pages_bucket(sess.length + bucket)
        return {
            "turn": turn, "sess": sess, "prompt": tail,
            "bucket": bucket, "fresh": fresh,
            "table": table, "base_length": sess.length,
            "active_pages": active_pages,
        }

    def _advance_chunked_prefill(
        self, turn: Turn, sess: _Session, prompt: list[int],
        restoring: bool, snap: Optional[dict],
    ) -> Optional[list[int]]:
        """Write a long prompt's full-width prefill chunks under the
        turn's class budget (docs/scheduler.md), committing progress
        at every chunk boundary. Returns the remaining tail (<= one
        chunk) once the prompt is fully chunk-written and ready for
        the sampling tail admission — or None when the turn was
        deferred to the next step (budget / pool pressure; _admit
        re-queues it), re-queued at a boundary (an injected
        prefill_chunk fault), or failed (requeue budget spent).

        Progress is durable: each committed chunk advances
        sess.length/history, clears the pending token, and rewrites
        turn.prompt_tokens to the unwritten suffix — a later admission
        resumes at the last chunk boundary, and a turn that dies
        mid-prefill rolls the session back to its pre-turn snapshot
        (_rollback_partial_prefill) so a client retry of the full
        prompt is safe.

        Reservations are per-chunk (partial-prefill reservations,
        kv_pages.py), not whole-prompt: a 4k prompt holds pages only
        for the chunks it has actually written. Background-class
        chunks take free pages only (PageTable.try_capacity) — a
        background prefill must never evict live KV to make room."""
        cw = self.sched_chunk_tokens
        cls = turn.turn_class
        # fused window (docs/serving.md): chunks are STAGED instead of
        # dispatched — host bookkeeping commits now, the KV write rides
        # this step's one fused device dispatch, and a faulted dispatch
        # rolls the turn back to the pre-stage boundary via ``undo``.
        fused = self.fused_window and snap is not None
        staged_undo: Optional[dict] = None
        staged_any = False

        def to_boundary() -> None:
            # every early exit rolls the session back to ``snap`` —
            # the last durable chunk boundary (refreshed in place at
            # each commit), or the admission-start state when nothing
            # committed yet. This is what makes a defer/requeue safe
            # after THIS admission's non-durable mutations: a prefix
            # hit taken above (re-admission re-resolves it against the
            # full prompt), or the restoring-path history clear below
            # (the mirror must survive a first-chunk fault).
            if snap is not None:
                self._restore_session_snapshot(sess, snap)

        while len(prompt) > cw:
            if not self.scheduler.take_chunk(cls):
                # per-window budget spent: hold position (the EDF key
                # is unchanged), resume after the next decode window
                self._bump("prefill_chunk_defers")
                if turn.trace is not None:
                    turn.trace.chunk_defers += 1
                    turn.trace.ev("chunk_defer", reason="budget")
                turn._admit_deferred = True
                to_boundary()
                return None
            need = sess.length + cw - sess.prefix_len
            try:
                if cls == "background":
                    pages = self.page_table.try_capacity(sess.id, need)
                else:
                    pages = self._ensure_capacity_evicting(
                        sess.id, need
                    )
            except MemoryError:
                pages = None
            if pages is None:
                # pool pressure: defer rather than fail — decode
                # drains and the offload sweep free pages between
                # steps. The consumed budget unit is refunded: nothing
                # was written, and a same-class sibling with free
                # pages must not be starved for the step.
                self.scheduler.refund_chunk(cls)
                self._note_pressure()
                if turn.trace is not None:
                    turn.trace.chunk_defers += 1
                    turn.trace.ev("chunk_defer", reason="pool")
                turn._admit_deferred = True
                to_boundary()
                return None
            if fused and staged_undo is None:
                # pre-stage boundary for _rollback_staged: the state a
                # faulted fused dispatch restores this turn to (deep
                # copies — ``snap`` mutates at every staged commit)
                staged_undo = {
                    "snap": {
                        k: list(v) if isinstance(v, list) else v
                        for k, v in snap.items()
                    },
                    "prompt_tokens": list(turn.prompt_tokens),
                    "chunk_committed": turn._chunk_committed,
                    "prefill_chunks": turn.prefill_chunks,
                    "prefill_snap": turn._prefill_snap,
                }
            if turn._prefill_snap is None:
                # rollback baseline: a COPY of the session's state
                # before this turn touched it (kept across requeues —
                # ``snap`` itself is refreshed to each durable
                # boundary below, so it must not be aliased)
                turn._prefill_snap = {
                    k: list(v) if isinstance(v, list) else v
                    for k, v in snap.items()
                }
            if restoring and sess.length == 0:
                # the mirror is re-materialized by the chunk writes;
                # ``prompt`` already carries its tokens in order
                sess.history = []
                restoring = False
            chunk = prompt[:cw]
            table = np.zeros((self.max_pages_per_seq,), np.int32)
            all_pages = sess.prefix_pages + pages
            table[: len(all_pages)] = all_pages
            try:
                # chaos fault point (docs/chaos.md): a failed chunk
                # re-queues the turn at its last durable chunk
                # boundary — committed chunks stay, pages stay owned
                # by the session, nothing leaks
                faults.maybe_fail("prefill_chunk")
                if fused:
                    # stage for this step's fused dispatch: host state
                    # advances now, the device write lands with the
                    # decode window (_dispatch_window) or the chunk
                    # flush; _staged_sids bars eviction/offload of the
                    # session until the dispatch settles
                    self._staged_chunks.append({
                        "turn": turn, "sess": sess,
                        "toks": list(chunk), "table": table,
                        "base_len": sess.length, "cls": cls,
                        "undo": staged_undo,
                    })
                    self._staged_sids.add(sess.id)
                    staged_any = True
                    sess.length += cw
                    sess.history.extend(chunk)
                else:
                    self._prefill_write_chunk(sess, chunk, table)
            except FaultError as e:
                self._bump("prefill_chunk_faults")
                self._note_pressure()
                trace_mod.note_fault(
                    turn.trace, getattr(e, "point", None) or
                    "prefill_chunk"
                )
                # the faulted chunk never wrote: refund its budget
                # unit and roll back to the last durable boundary
                # (restores a restoring session's history mirror if
                # the FIRST chunk faulted after the clear above)
                self.scheduler.refund_chunk(cls)
                to_boundary()
                turn.requeues += 1
                turn.disrupted = True
                if turn.requeues > self.max_requeues:
                    self._fail_turn_unslotted(turn, str(e))
                else:
                    self._bump("requeues")
                    self._queue_put(turn)
                return None
            # durable boundary: the chunk (and any pending token it
            # carried) is in KV + history; only the suffix re-enters
            # on a requeue
            sess.pending = None
            prompt = prompt[cw:]
            turn.prompt_tokens = list(prompt)
            turn._chunk_committed += cw
            turn.prefill_chunks += 1
            if not fused:
                # staged chunks count when their dispatch lands
                # (_commit_staged), keeping the counter an honest
                # record of chunks actually on device — same for the
                # trace's chunk accounting
                self._bump("prefill_chunks_interleaved")
                if turn.trace is not None:
                    turn.trace.chunks += 1
                    turn.trace.chunk_tokens += cw
                    turn.trace.ev("chunk_landed", tokens=cw,
                                  fused=False)
            # refresh the caller's rollback snapshot IN PLACE to this
            # durable boundary: chunk progress must survive a later
            # tail-admission failure (which rolls back to ``snap`` and
            # re-queues turn.prompt_tokens — now just the suffix).
            # The pre-turn state lives on in turn._prefill_snap.
            snap.update(
                pending=sess.pending, length=sess.length,
                history=list(sess.history), parked=sess.parked,
                prefix_key=sess.prefix_key,
                prefix_pages=list(sess.prefix_pages),
                prefix_len=sess.prefix_len,
            )
        if staged_any:
            # the tail admits NEXT step, at the durable boundary the
            # staged chunks establish once this step's fused dispatch
            # lands (scheduling-only delay: the token stream is
            # unchanged)
            turn._admit_deferred = True
            return None
        return prompt

    def _chunk_write_fn(self, fresh: bool,
                        active: Optional[int] = None):
        """Jitted KV-write-only chunk prefill (no head, no sampling),
        shared by the split per-chunk path (batch [1, width]) and the
        staged chunk flush (batch [N, width]) — one compiled family
        for both."""
        key = ("chunk_write", fresh, active)
        if key not in self._jit_cache:
            cfg = self.cfg

            @partial(jax.jit, donate_argnums=(1,))
            def write(params, cache, tokens, block_tables, lengths):
                hook = make_paged_kv_hook(
                    block_tables, lengths, self.page_size,
                    fresh_prefill=fresh, active_pages=active,
                    pallas_prefill=self._pallas_prefill,
                )
                positions = lengths[:, None] + \
                    jnp.arange(tokens.shape[1])
                _, cache = qwen3.forward(
                    params, cfg, tokens, positions, cache,
                    kv_hook=hook, apply_head=False,
                )
                return self._constrain_cache(cache)

            self._jit_cache[key] = write
        return self._jit_cache[key]

    def _prefill_write_chunk(
        self, sess: _Session, toks: list[int], table: np.ndarray
    ) -> None:
        """KV-write-only prefill of one full chunk (no head, no
        sampling)."""
        width = len(toks)
        fresh = sess.length == 0
        active = None
        if not fresh and not (self._pallas_prefill and width % 8 == 0):
            active = self._pages_bucket(sess.length + width)
        write = self._chunk_write_fn(fresh, active)

        def call():
            # chaos fault point fires BEFORE the jitted call so no
            # donated buffer is consumed by a failed attempt
            faults.maybe_fail("prefill_oom")
            return write(
                self.params,
                self.cache,
                jnp.asarray([toks], jnp.int32),
                jnp.asarray(table[None, :]),
                jnp.asarray([sess.length], jnp.int32),
            )

        with self.timer.phase(f"prefill_write_{width}"):
            self.cache = self._retrying("prefill_write", call)
        self._bump("chunk_dispatches")
        self._bump("prefill_tokens", width)
        sess.length += width
        sess.history.extend(toks)

    def _prefill_group(
        self, bucket: int, fresh: bool, group: list[dict],
        slots: list[int], active_pages: Optional[int] = None,
    ) -> None:
        n = len(group)
        # pad the batch to a power of two so compiles stay bounded at
        # (buckets x log2(max_batch) x 2); padding rows write into the
        # scratch page and their samples are discarded
        n_pad = 1
        while n_pad < n:
            n_pad *= 2
        toks = np.full((n_pad, bucket), self.tokenizer.pad_id, np.int32)
        tables = np.zeros((n_pad, self.max_pages_per_seq), np.int32)
        lengths = np.zeros((n_pad,), np.int32)
        for r, prep in enumerate(group):
            toks[r, : len(prep["prompt"])] = prep["prompt"]
            tables[r] = prep["table"]
            lengths[r] = prep["base_length"]

        prefill = self._prefill_fn(
            bucket, fresh=fresh, active_pages=active_pages,
        )
        # first generated token per row comes from its last real
        # position (the head runs only there, device-side)
        last_idx = jnp.asarray(
            [len(p["prompt"]) - 1 for p in group]
            + [0] * (n_pad - n),
            jnp.int32,
        )

        def call():
            # chaos fault point fires BEFORE the jitted call so no
            # donated buffer is consumed by a failed attempt
            faults.maybe_fail("prefill_oom")
            return prefill(
                self.params,
                self.cache,
                jnp.asarray(toks),
                jnp.asarray(tables),
                jnp.asarray(lengths),
                last_idx,
            )

        try:
            with self.timer.phase(f"prefill_{bucket}x{n}"):
                last_logits, self.cache = \
                    self._retrying("prefill", call)
        except FaultError as e:
            # prefill fault survived its retry budget: roll every
            # batchmate's session back to its pre-preparation snapshot
            # and requeue (bounded) — nothing admitted, nothing lost
            self._note_pressure()
            for prep in group:
                self._restore_session_snapshot(
                    prep["sess"], prep["snap"]
                )
                turn = prep["turn"]
                turn.requeues += 1
                turn.disrupted = True
                if turn.requeues > self.max_requeues:
                    self._fail_turn_unslotted(turn, str(e))
                else:
                    self._bump("requeues")
                    self._queue_put(turn)
            return
        with self.timer.phase(f"prefill_{bucket}x{n}_sample"):
            self._key, sub = jax.random.split(self._key)
            temps = [p["turn"].sampling.temperature for p in group]
            top_ps = [p["turn"].sampling.top_p for p in group]
            top_ks = [p["turn"].sampling.top_k for p in group]
            firsts = np.asarray(_sample_first(
                last_logits, sub,
                jnp.asarray(temps + [1.0] * (n_pad - n), jnp.float32),
                jnp.asarray(top_ps + [1.0] * (n_pad - n), jnp.float32),
                jnp.asarray(top_ks + [0] * (n_pad - n), jnp.int32),
            ))

        # per-request penalty counts start fresh at admission; the first
        # sampled token is generated text, so it counts. Only penalized
        # turns pay the row reset — non-penalized rows are never read,
        # and a penalized reuse of a slot resets it at its own admission
        pen = [
            (slot, int(firsts[r]))
            for r, (prep, slot) in enumerate(zip(group, slots))
            if prep["turn"].sampling.penalized
        ]
        if pen:
            counts = self._counts_array()
            for slot, tok in pen:
                counts = _reset_count_row(
                    counts, jnp.int32(slot), jnp.int32(tok)
                )
            self._counts = counts

        for r, (prep, slot) in enumerate(zip(group, slots)):
            turn, sess = prep["turn"], prep["sess"]
            self._bump("prefill_tokens", len(prep["prompt"]))
            sess.length += len(prep["prompt"])
            sess.history.extend(prep["prompt"])
            # a prefix this session registered is fully written now
            if sess.prefix_key is not None:
                entry = self._prefix_cache.get(sess.prefix_key)
                if entry is not None:
                    fresh_ready = not entry.ready
                    entry.ready = True
                    if fresh_ready and self.prefix_store is not None:
                        # publish the freshly computed prefix to the
                        # fleet-global store (one bounded page gather;
                        # failures count and skip)
                        self._prefix_store_maybe_publish(entry)
            self._slot_tables[slot] = prep["table"]
            self._slot_lengths[slot] = sess.length
            self._slot_gen[slot] += 1
            self._active[slot] = turn
            # the turn reached a slot: its chunked-prefill progress is
            # now ordinary session state (a death from here on follows
            # the park contract, never the pre-turn rollback)
            turn._chunk_committed = 0
            turn._prefill_snap = None
            self.scheduler.note_admitted(turn.turn_class)
            # prefill span ends here — the first sampled token books
            # in the _append_token below, so TTFT sits at the same
            # host moment the stream callback fires
            trace_mod.note_slotted(turn.trace, sess.generation)
            self._append_token(slot, turn, int(firsts[r]))

    def _slot_arrays_excluding(
        self, active_idx: list[int]
    ) -> tuple[np.ndarray, np.ndarray]:
        """Block tables + lengths for a device call only ``active_idx``
        rows participate in. Any OTHER still-active row is diverted to
        the scratch page: its slot arrays can be stale (the session
        advanced since its last reserve — e.g. a row sitting out a
        window at capacity until its covering drain settles it), so
        letting the forward write its garbage KV at the recorded
        position would corrupt KV that is already valid."""
        tables = self._slot_tables
        lengths = self._slot_lengths
        active = set(active_idx)
        stale = [
            i for i in range(self.max_batch)
            if self._active[i] is not None and i not in active
        ]
        if stale:
            tables = tables.copy()
            lengths = lengths.copy()
            tables[stale] = 0
            lengths[stale] = 0
        return tables, lengths

    def _reserve_slot(self, i: int, want_tokens: int) -> bool:
        """Reserve pages so slot ``i``'s session can hold
        base+want_tokens (clamped to capacity), degrading to a single
        token under pool pressure; device writes past the reservation
        divert to the scratch page and the host trims. Finishes the
        turn with an error only when even one token won't fit. Updates
        the slot's block table + length row.

        ``base`` is the DEVICE's view of the sequence: sess.length plus
        any positions an undrained in-flight window has already been
        dispatched to write (_slot_ahead) — the next window's KV lands
        after those, whether or not the host has drained them yet."""
        turn = self._active[i]
        sess = self.sessions[turn.session_id]
        capacity = self.max_pages_per_seq * self.page_size
        base = sess.length + int(self._slot_ahead[i])
        if base >= capacity:
            if self._slot_ahead[i] > 0:
                # an undrained window still covers this row: its drain
                # settles the turn from REAL state (budget finish, or
                # trim+park at the reservation clamp) — sit the row out
                # of this dispatch rather than finishing on the
                # speculative length
                return False
            # context capacity exhausted with budget remaining: the
            # stream legitimately ends here — dispatching the row would
            # only produce scratch-diverted writes the drain must park
            # away with zero progress
            self._finish_turn(i, turn, "length")
            return False
        target = min(base + want_tokens, capacity)
        try:
            pages = self._ensure_capacity_evicting(
                sess.id, target - sess.prefix_len
            )
        except MemoryError:
            # degrade to single-token pacing before giving up: a turn
            # finishing within its current pages must not die because
            # the full chunk couldn't be reserved
            try:
                target = min(base + 1, capacity)
                pages = self._ensure_capacity_evicting(
                    sess.id, target - sess.prefix_len
                )
            except MemoryError as e:
                turn.error = str(e)
                self._finish_turn(i, turn, "error")
                return False
        all_pages = sess.prefix_pages + pages
        self._slot_tables[i, : len(all_pages)] = all_pages
        # stale entries from a previous occupant of this slot must
        # never receive overrun writes — point them at scratch
        self._slot_tables[i, len(all_pages):] = 0
        self._slot_lengths[i] = base
        self._reserved_tokens[i] = target - base
        return True

    def _decode_once(self) -> int:
        """One decode iteration of the scheduler.

        steps_per_dispatch == 1 (legacy): dispatch one step and drain
        it synchronously, exactly the old loop.

        steps_per_dispatch > 1 (pipeline, docs/serving.md): dispatch
        window k FIRST, then drain window k-1 — so all of k-1's host
        work (stop detection, stream callbacks, detokenization, and
        next iteration's admission/offload scheduling) overlaps k's
        device execution. A stop/park the drain discovers is
        reconciled at the NEXT dispatch boundary: the finished slot is
        masked out of window k+1 and its window-k overshoot tokens are
        trimmed, which keeps greedy output token-identical to the
        step-at-a-time engine."""
        active_idx = [
            i for i, t in enumerate(self._active) if t is not None
        ]
        if not active_idx and self._inflight is None:
            if self._staged_chunks:
                # no decode lanes to fuse with: the staged chunks
                # still land in ONE batched dispatch this step
                self._dispatch_staged_chunks()
                return 1
            return 0
        # speculation rides INSIDE the window (docs/serving.md): each
        # scan step drafts on-mesh from the device-resident tail and
        # verifies in the same batched forward, so a spec round is a
        # normal window step emitting up to 1+gamma tokens per lane —
        # no flush, no host round trip, no sequential split for
        # penalized batchmates (their lanes simply run at gamma 0).
        # Per-class gamma (and the ladder's per-class spec-off rung)
        # is resolved at dispatch time in _dispatch_window.
        if self.steps_per_dispatch == 1:
            # legacy iteration: dispatch + blocking drain
            window = None
            if active_idx:
                try:
                    window = self._dispatch_window(active_idx)
                except FaultError as e:
                    if getattr(e, "point", None) != "decode_window":
                        raise   # decode_step budget: crash supervisor
                    self._fail_window_turns(active_idx, e)
            if window is None:
                return 0
            return self._drain_window(window)

        prev, self._inflight = self._inflight, None
        window_fault: Optional[FaultError] = None
        if not active_idx and self._staged_chunks:
            # no decode lanes this step but a window still in flight:
            # staged chunks must still land THIS step — the next
            # step's admission runs before its _decode_once and may
            # tail-admit on top of them
            self._dispatch_staged_chunks()
        if active_idx:
            try:
                self._inflight = self._dispatch_window(active_idx)
            except FaultError as e:
                if getattr(e, "point", None) != "decode_window":
                    # decode_step past its budget heads for the crash
                    # supervisor — but the previous window's tokens are
                    # real; deliver them before the supervisor fails
                    # everything pending
                    if prev is not None:
                        self._drain_window(prev)
                    raise
                window_fault = e
        n = self._drain_window(prev) if prev is not None else 0
        if window_fault is not None:
            # fail the faulted window's turns only AFTER the previous
            # window drained: its tokens are real, the device computed
            # them, and the fault's contract is to lose ONLY the
            # faulted window (a turn the drain just completed normally
            # isn't failed at all)
            self._fail_window_turns(active_idx, window_fault)
        active_now = sum(1 for t in self._active if t is not None)
        if active_now == 0 and self._inflight is None:
            return 0
        # non-zero while a window is still in flight so serve_forever /
        # run_until_idle never declare idle with tokens on device
        return max(n, active_now, 1)

    def _fail_window_turns(self, active_idx: list[int],
                           err: FaultError) -> None:
        """decode_window fault past its retry budget: fail exactly the
        turns that were in the faulted window and still need tokens.
        Queued work, parked sessions, and the page pool are untouched —
        sessions keep their pages and KV, so nothing leaks."""
        for i in active_idx:
            turn = self._active[i]
            if turn is not None:
                turn.error = str(err)
                trace_mod.note_fault(
                    turn.trace,
                    getattr(err, "point", None) or "decode_window",
                )
                self._finish_turn(i, turn, "error")

    def _flush_pipeline(self) -> int:
        """Drain the in-flight window, if any (spec round boundaries,
        shutdown), after landing any staged chunk writes — a flush must
        leave no host-committed KV still waiting for a device dispatch.
        Returns rows advanced."""
        self._dispatch_staged_chunks()
        prev, self._inflight = self._inflight, None
        return self._drain_window(prev) if prev is not None else 0

    def _dispatch_staged_chunks(self) -> None:
        """Land staged chunk writes in ONE batched device dispatch when
        there is no decode window to fuse them with (idle batch,
        pipeline flush, shutdown). A dispatch
        fault past the retry budget rolls the staged turns back to
        their last durable chunk boundary — committed chunks stay, the
        already-queued turns re-prepare from the boundary, pages stay
        owned (no leak)."""
        staged = self._staged_chunks
        if not staged:
            return
        cw = self.sched_chunk_tokens
        # under the dp-sharded fused window the flush batch keeps the
        # shard-major layout (equal rows per dp shard) so the write
        # batch's leading axis shards over dp like the fused dispatch
        ndp = self._dp_size if self.fused_window_mode == "fused-dp" \
            else 1
        cl = self._pow2(-(-len(staged) // ndp))
        c_pad = cl * ndp
        toks = np.full((c_pad, cw), self.tokenizer.pad_id, np.int32)
        tables = np.zeros((c_pad, self.max_pages_per_seq), np.int32)
        lens = np.zeros((c_pad,), np.int32)
        for i, rec in enumerate(staged):
            shard = i % ndp
            rec["shard"] = shard
            r = shard * cl + i // ndp
            toks[r] = rec["toks"]
            tables[r] = rec["table"]
            lens[r] = rec["base_len"]
        active = None
        if not (self._pallas_prefill and cw % 8 == 0):
            active = self._pages_bucket(
                max(int(r["base_len"]) for r in staged) + cw
            )
        write = self._chunk_write_fn(False, active)

        def call():
            # chaos fault point fires BEFORE the jitted call so no
            # donated buffer is consumed by a failed attempt
            faults.maybe_fail("prefill_oom")
            return write(
                self.params, self.cache,
                self._place_batch(toks, name="chunk_tokens"),
                self._place_batch(tables, name="chunk_tables"),
                self._place_batch(lens, name="chunk_lens"),
            )

        try:
            with self.timer.phase(f"chunk_flush_{cw}x{len(staged)}"):
                self.cache = self._retrying("chunk_flush", call)
        except FaultError as e:
            self._rollback_staged(e)
            return
        self._bump("chunk_dispatches")
        self._commit_staged(staged, fused=False)

    def _commit_staged(self, staged: list[dict], *, fused: bool) -> None:
        """The dispatch carrying the staged chunks landed: their host
        bookkeeping (committed at stage time) is now durable."""
        self._staged_chunks = []
        self._staged_sids.clear()
        self._bump("prefill_chunks_interleaved", len(staged))
        self._bump(
            "prefill_tokens", sum(len(r["toks"]) for r in staged)
        )
        if fused:
            self._bump("fused_windows")
            self._bump("fused_chunks", len(staged))
            if self.fused_window_mode == "fused-dp":
                self._bump("fused_dp_windows")
                with self._lock:
                    for rec in staged:
                        self._fused_dp_shard_chunks[
                            rec.get("shard", 0)
                        ] += 1
        for rec in staged:
            tr = rec["turn"].trace
            if tr is not None:
                tr.chunks += 1
                tr.chunk_tokens += len(rec["toks"])
                tr.ev("chunk_landed", tokens=len(rec["toks"]),
                      fused=fused)

    def _rollback_staged(self, err: FaultError) -> None:
        """A dispatch carrying staged chunks faulted past its retry
        budget: none of the staged KV landed. Restore every staged
        turn's session to its pre-stage state (the last durable chunk
        boundary — chunks committed by EARLIER dispatches stay),
        refund the consumed chunk-budget units, and let the
        already-queued turns re-prepare from the boundary (bounded by
        the requeue budget). Pages stay owned by their sessions, so
        nothing leaks."""
        staged, self._staged_chunks = self._staged_chunks, []
        self._staged_sids.clear()
        first_rec: dict[int, dict] = {}
        for rec in staged:
            first_rec.setdefault(id(rec["turn"]), rec)
            self.scheduler.refund_chunk(rec["cls"])
        self._bump("prefill_chunk_faults")
        self._note_pressure()
        for rec in first_rec.values():
            turn = rec["turn"]
            undo = rec["undo"]
            trace_mod.note_fault(
                turn.trace, getattr(err, "point", None) or
                "decode_window"
            )
            sess = self.sessions.get(turn.session_id)
            if undo is not None:
                if sess is not None:
                    try:
                        self._restore_session_snapshot(
                            sess, undo["snap"]
                        )
                    except Exception:
                        # best-effort: the history-mirror re-prefill
                        # path remains the correctness backstop
                        pass
                turn.prompt_tokens = list(undo["prompt_tokens"])
                turn._chunk_committed = undo["chunk_committed"]
                turn.prefill_chunks = undo["prefill_chunks"]
                turn._prefill_snap = undo["prefill_snap"]
            turn.requeues += 1
            turn.disrupted = True
            if turn.requeues > self.max_requeues:
                # the queued entry remains; _prepare_turn's done-guard
                # skips it when popped
                self._fail_turn_unslotted(turn, str(err))
            else:
                self._bump("requeues")

    # roomlint: region=dispatch-window
    def _dispatch_window(self, active_idx: list[int]) -> Optional[dict]:
        """Reserve pages and launch one decode window (non-blocking:
        the jitted call returns futures). Returns the window record the
        drain consumes, or None when nothing could dispatch. An
        injected decode_window fault past its retry budget raises
        FaultError for the CALLER to handle (it drains the previous
        window first so its real tokens are delivered, then fails this
        window's turns); ``active_idx`` is mutated in place to the rows
        that were actually in the window.

        When the step staged interleaved prefill chunks (fused window,
        docs/serving.md), they ride THIS dispatch: step 0 of the jitted
        call runs the ragged [decode-lanes + chunk-rows] forward — one
        attention dispatch per layer through the unified ragged kernel
        (or the bounded-gather reference on CPU) — so the whole
        scheduler window costs one host round trip."""
        steps = self.steps_per_dispatch
        penalized = any(
            self._active[i].sampling.penalized for i in active_idx
        )
        # on-mesh speculation (docs/serving.md): per-row draft depth is
        # the row's CLASS gamma (scheduler.SpecTuner — live acceptance
        # adaptation + the per-class ladder spec-off rung), zero for
        # penalized rows (their [B, V] counts must advance one exact
        # token per sampled position). The compiled window width is
        # 1 + max over the batch; narrower rows mask their extra draft
        # slots, so heterogeneous classes share one dispatch.
        spec_on = self.spec_tokens > 0
        gammas = np.zeros((self.max_batch,), np.int32)
        if spec_on:
            raw_level = self.degradation_level()
            for i in active_idx:
                t = self._active[i]
                if t.sampling.penalized:
                    continue
                gammas[i] = self.spec_tuner.gamma_for(
                    t.turn_class, raw_level
                )
        # ensure pages only for tokens the turn can actually accept:
        # min(per-step emission ceiling x steps, its remaining budget
        # net of undrained positions), clamped to capacity. The scan
        # still writes its full width of positions; writes past the
        # reservation divert to scratch and the host trims at drain.
        for i in list(active_idx):
            turn = self._active[i]
            remaining = max(
                turn.sampling.max_new_tokens - len(turn.new_tokens)
                - int(self._slot_ahead[i]), 1
            )
            want = min(steps * (1 + int(gammas[i])), remaining) \
                if spec_on else min(steps, remaining)
            if not self._reserve_slot(i, want):
                active_idx.remove(i)
        if not active_idx:
            if self._staged_chunks:
                self._dispatch_staged_chunks()
            return None
        staged = list(self._staged_chunks)

        # rows whose feed token the host owns (no undrained window):
        # new admissions, first window after a flush. Everything else
        # chains off the previous window's on-device ring tail.
        fresh_tokens = np.zeros((self.max_batch,), np.int32)
        fresh_mask = np.zeros((self.max_batch,), bool)
        active_mask = np.zeros((self.max_batch,), bool)
        for i in active_idx:
            t = self._active[i]
            active_mask[i] = True
            if self._slot_ahead[i] == 0 or self._feed_tokens is None:
                fresh_mask[i] = True
                fresh_tokens[i] = t.new_tokens[-1] if t.new_tokens \
                    else t.prompt_tokens[-1]
        if self._feed_tokens is None:
            self._feed_tokens = self._place_batch(
                np.zeros((self.max_batch,), np.int32)
            )

        temps = np.ones((self.max_batch,), np.float32)
        top_ps = np.ones((self.max_batch,), np.float32)
        top_ks = np.zeros((self.max_batch,), np.int32)
        for i in active_idx:
            sp = self._active[i].sampling
            temps[i] = sp.temperature
            top_ps[i] = sp.top_p
            top_ks[i] = sp.top_k

        # bound the XLA fallback's page gather to the batch's actual
        # reach (the Pallas kernel is already length-bounded — passing a
        # varying static bound there would only churn compiles). A
        # fused window taking the gather reference must also cover the
        # staged chunks' reach.
        cw = self.sched_chunk_tokens
        width = 1 + (int(gammas[active_idx].max()) if spec_on else 0)
        ap = None
        # the S>1 verify steps of a drafting window gather unless the
        # Pallas prefill kernel covers their width — same bound rule as
        # chunked prefill
        spec_gather = width > 1 and \
            not (self._pallas_prefill and width % 8 == 0)
        if not self._pallas_decode or spec_gather or \
                (staged and not self._pallas_ragged):
            max_len = max(
                int(self._slot_lengths[i]) for i in active_idx
            )
            reach = max_len + steps * width
            if staged:
                reach = max(reach, max(
                    int(r["base_len"]) for r in staged
                ) + cw)
            ap = self._pages_bucket(reach)
        if penalized:
            presence = np.zeros((self.max_batch,), np.float32)
            frequency = np.zeros((self.max_batch,), np.float32)
            for i in active_idx:
                sp = self._active[i].sampling
                presence[i] = sp.presence_penalty
                frequency[i] = sp.frequency_penalty
            counts = self._counts_array()
            pen_args = (
                self._place_batch(presence),
                self._place_batch(frequency),
            )
        else:
            counts = jnp.int32(0)
            pen_args = (jnp.float32(0), jnp.float32(0))
        chunk_args: tuple = ()
        c_pad = 0
        ndp = self._dp_size if self.fused_window_mode == "fused-dp" \
            else 1
        if staged:
            # fused window: the staged chunk batch rides this dispatch.
            # dp>1 (sharded fused window): chunk rows are dealt
            # round-robin over the dp shards and stored shard-major
            # (row = shard * Cl + index-within-shard) with Cl equal
            # per shard, so the [ndp*Cl, ...] arrays shard over dp in
            # equal contiguous blocks — each shard's ragged sub-batch
            # carries its own chunk rows. Pad rows (pad tokens, zero
            # tables -> scratch page 0) fill each shard's remainder.
            cl = self._pow2(-(-len(staged) // ndp))
            c_pad = cl * ndp
            chunk_tokens = np.full(
                (c_pad, cw), self.tokenizer.pad_id, np.int32
            )
            chunk_tables = np.zeros(
                (c_pad, self.max_pages_per_seq), np.int32
            )
            chunk_lens = np.zeros((c_pad,), np.int32)
            for i, rec in enumerate(staged):
                shard = i % ndp
                rec["shard"] = shard
                r = shard * cl + i // ndp
                chunk_tokens[r] = rec["toks"]
                chunk_tables[r] = rec["table"]
                chunk_lens[r] = rec["base_len"]
            chunk_args = (
                self._place_batch(chunk_tokens, name="chunk_tokens"),
                self._place_batch(chunk_tables, name="chunk_tables"),
                self._place_batch(chunk_lens, name="chunk_lens"),
            )
        scan_tables, scan_lengths = \
            self._slot_arrays_excluding(active_idx)
        self._key, sub = jax.random.split(self._key)

        if spec_on:
            # host-owned seeds for rows whose device chain broke (new
            # admission / first window): sequence length, remaining
            # generation budget, and the recent-token tail drafting
            # matches against. Continuing rows carry all three on
            # device — the host cannot know them while a variable-
            # emission window is in flight, which is exactly why the
            # old spec path had to flush.
            tail_t = self.spec_tail_len
            fresh_rem = np.zeros((self.max_batch,), np.int32)
            fresh_tails = np.full(
                (self.max_batch, tail_t), spec_ops.TAIL_PAD, np.int32
            )
            for i in active_idx:
                if not fresh_mask[i]:
                    continue
                t = self._active[i]
                sess = self.sessions[t.session_id]
                fresh_rem[i] = max(
                    t.sampling.max_new_tokens - len(t.new_tokens), 1
                )
                fresh_tails[i] = spec_ops.seed_tail(
                    sess.history[-tail_t:] + [int(fresh_tokens[i])],
                    tail_t,
                )
            if self._feed_lens is None or self._spec_tail_dev is None:
                zeros = np.zeros((self.max_batch,), np.int32)
                self._feed_lens = self._place_batch(zeros)
                self._feed_rem = self._place_batch(zeros)
                self._spec_tail_dev = self._place_batch(
                    np.full((self.max_batch, tail_t),
                            spec_ops.TAIL_PAD, np.int32)
                )
            # absolute reserved-coverage cap per row: on-device
            # drafting never accepts into a position past it
            coverage = np.zeros((self.max_batch,), np.int32)
            for i in active_idx:
                coverage[i] = int(self._slot_lengths[i]) \
                    + int(self._reserved_tokens[i])
            specwin = self._spec_window_fn(
                steps, width, c_pad, ap, penalized,
                ndp=ndp if staged else 1,
            )
            draft_params = self._draft[1] if self._draft is not None \
                else jnp.int32(0)
            spec_chunk_args = chunk_args if staged else (
                jnp.int32(0), jnp.int32(0), jnp.int32(0)
            )

            def call():
                # chaos fault points: same contract as the plain window
                faults.maybe_fail("decode_window")
                faults.maybe_fail("decode_step")
                faults.maybe_delay("decode_stall")
                return specwin(
                    self.params,
                    self.cache,
                    counts,
                    draft_params,
                    self._feed_tokens,
                    self._place_batch(fresh_tokens),
                    self._place_batch(fresh_mask),
                    self._place_batch(active_mask),
                    self._place_batch(gammas),
                    self._place_batch(coverage),
                    self._place_batch(scan_tables),
                    self._place_batch(scan_lengths),
                    self._feed_lens,
                    self._place_batch(fresh_rem),
                    self._feed_rem,
                    self._place_batch(fresh_tails),
                    self._spec_tail_dev,
                    sub,
                    self._place_batch(temps),
                    self._place_batch(top_ps),
                    self._place_batch(top_ks),
                    *pen_args,
                    *spec_chunk_args,
                )
        else:
            if staged:
                decode = self._fused_fn(
                    steps, c_pad, ap, penalized, ndp=ndp
                )
            else:
                decode = self._decode_fn(steps, ap, penalized)

            def call():
                # chaos fault points: decode_window fails ONLY this
                # window's turns (caught below); decode_step models a
                # transient device error retried with backoff and
                # escalates to the crash supervisor past its budget;
                # decode_stall injects latency that trips the watchdog
                faults.maybe_fail("decode_window")
                faults.maybe_fail("decode_step")
                faults.maybe_delay("decode_stall")
                return decode(
                    self.params,
                    self.cache,
                    counts,
                    self._feed_tokens,
                    self._place_batch(fresh_tokens),
                    self._place_batch(fresh_mask),
                    self._place_batch(active_mask),
                    self._place_batch(scan_tables),
                    self._place_batch(scan_lengths),
                    sub,
                    self._place_batch(temps),
                    self._place_batch(top_ps),
                    self._place_batch(top_ks),
                    *pen_args,
                    *chunk_args,
                )

        t0 = time.monotonic()
        try:
            with self.timer.phase("decode"):
                if spec_on:
                    (ring, emits_d, drafted_d, feed_toks, feed_lens,
                     feed_rem, tail_out, counts_out, self.cache) = \
                        self._retrying("decode", call)
                else:
                    ring, counts_out, self.cache = \
                        self._retrying("decode", call)
        except FaultError as e:
            # a fused window's staged chunk KV never landed: roll the
            # chunk turns back to their last durable boundary (their
            # committed chunks stay; only this step's staging is lost)
            if staged:
                self._rollback_staged(e)
            if getattr(e, "point", None) != "decode_window":
                raise   # decode_step past its budget: crash supervisor
            # window-scoped failure: note it and let the caller fail
            # the turns — AFTER draining any previous window, whose
            # already-computed tokens must still be delivered
            self._note_pressure()
            self._bump("window_faults")
            raise
        if staged:
            self._commit_staged(staged, fused=True)
        if penalized:
            self._counts = counts_out
        if spec_on:
            # device-resident chain for the next dispatch: last emitted
            # token, sequence length, remaining budget, drafting tail
            self._feed_tokens = feed_toks
            self._feed_lens = feed_lens
            self._feed_rem = feed_rem
            self._spec_tail_dev = tail_out
        else:
            # the ring tail feeds the next dispatch without a host hop
            self._feed_tokens = ring[:, -1]
        # start the device->host copy NOW so it overlaps whatever the
        # host does before the drain materializes it
        try:
            ring.copy_to_host_async()
            if spec_on:
                emits_d.copy_to_host_async()
                drafted_d.copy_to_host_async()
        except AttributeError:
            pass
        # the device's view of each row runs ahead by the window's
        # per-row emission CEILING (actual emission is data-dependent;
        # the drain reconciles) — reservations for the next window
        # address this upper bound, so nothing host-side ever lags the
        # device's real write positions. With spec the ceiling is
        # max(reserved, steps): accepted drafts are coverage-clamped on
        # device, and a bonus-only chain past coverage advances one
        # position per step like the plain scan.
        ahead = {
            i: max(int(self._reserved_tokens[i]), steps)
            if spec_on else steps
            for i in active_idx
        }
        for i in active_idx:
            self._slot_ahead[i] += ahead[i]
        self._bump("decode_steps")
        self._bump("decode_windows")
        # turnscope: bill this window's dispatch wall to every turn
        # riding it (pure host bookkeeping — no sync, the ring is
        # still futures)
        dispatch_s = time.monotonic() - t0
        for i in active_idx:
            t = self._active[i]
            if t is not None and t.trace is not None:
                t.trace.note_window(dispatch_s)
        return {
            "ring": ring,
            "spec": spec_on,
            "emits": emits_d if spec_on else None,
            "drafted": drafted_d if spec_on else None,
            "active_idx": list(active_idx),
            "turns": {i: self._active[i] for i in active_idx},
            "gen": {i: int(self._slot_gen[i]) for i in active_idx},
            # headroom actually secured per row at dispatch (the degrade
            # path can grant a single token): the drain accepts at most
            # this many tokens per row — writes past it went to scratch
            "reserved": {
                i: int(self._reserved_tokens[i]) for i in active_idx
            },
            # absolute session position each row's page reservation
            # covers (spec windows start below the host base when a
            # prior window under-emitted, so the durability bound is
            # absolute, not an offset)
            "limit": {
                i: int(self._slot_lengths[i])
                + int(self._reserved_tokens[i])
                for i in active_idx
            },
            "ahead": ahead,
            "steps": steps,
            # time spent inside the decode dispatch itself (injected
            # stalls, retry backoff, this function's own jit compile) —
            # the stall watchdog's input, so host work between dispatch
            # and drain (admission prefill compiles, offload sweeps)
            # can't masquerade as a device stall
            "dispatch_s": dispatch_s,
        }

    def _drain_window(self, window: dict) -> int:
        """Materialize a window's ring buffer and run the host-side
        bookkeeping: history/length advance, stop-token + stop-string
        detection, stream callbacks, finish/park transitions. Rows
        whose turn left its slot since dispatch (stop or park found in
        an earlier drain, deadline, requeue) are overshoot — their
        tokens are trimmed and their KV writes sit past the recorded
        session length, overwritten on resume."""
        if window.get("spec"):
            return self._drain_window_spec(window)
        t0 = time.monotonic()
        with self.timer.phase("decode_drain"):
            ring_host = np.asarray(window["ring"])   # [B, steps]
        wait_s = time.monotonic() - t0
        self._bump("host_stall_ms", wait_s * 1000.0)
        # turnscope: the drain wait is billed to every turn whose
        # tokens this window carries (still-live check happens in the
        # loop below; an overshoot row's turn already finished and its
        # trace is closed)
        for i in window["active_idx"]:
            t = window["turns"][i]
            if t.trace is not None and not t.trace.finished:
                t.trace.note_drain(wait_s)
        steps = window["steps"]
        decoded = 0
        overshoot = 0
        live_idx: list[int] = []
        for i in window["active_idx"]:
            turn = window["turns"][i]
            if self._active[i] is not turn or \
                    int(self._slot_gen[i]) != window["gen"][i]:
                # late reconciliation: the slot was finished/parked (or
                # reused — possibly by a requeued incarnation of the
                # SAME turn, which the generation counter catches)
                # after this window dispatched: every token it produced
                # for the row is overshoot
                overshoot += steps
                continue
            self._slot_ahead[i] = max(
                0, int(self._slot_ahead[i]) - steps
            )
            live_idx.append(i)
            sess = self.sessions[turn.session_id]
            prev_tok = turn.new_tokens[-1] if turn.new_tokens else \
                turn.prompt_tokens[-1]
            reserved = window["reserved"][i]
            for j in range(steps):
                if j >= reserved:
                    # degraded reservation (pool pressure granted fewer
                    # than `steps` positions): this step's input KV went
                    # to the scratch page, so the chain past it attended
                    # garbage. Park on the last durably-written token —
                    # it becomes the session's pending token, exactly
                    # the mid-stream requeue contract — and let
                    # re-admission re-materialize it with a fresh
                    # reservation. Greedy streams stay identical to the
                    # step-at-a-time engine.
                    overshoot += steps - j
                    self._park_and_requeue(i, turn)
                    break
                # step j wrote the previous token's KV at `length` and
                # sampled ring_host[i, j]
                sess.history.append(
                    prev_tok if j == 0 else int(ring_host[i, j - 1])
                )
                sess.length += 1
                decoded += 1
                self._append_token(i, turn, int(ring_host[i, j]))
                if self._active[i] is not turn:
                    # turn finished mid-window: the remaining sampled
                    # tokens (and their KV writes past sess.length) are
                    # discarded
                    overshoot += steps - 1 - j
                    break
        if decoded:
            self._bump("tokens_decoded", decoded)
        if overshoot:
            self._bump("overshoot_tokens", overshoot)
        # after the bookkeeping so parked sessions carry every token
        # the slow window actually produced. Elapsed = time blocked in
        # the dispatch call + time blocked materializing the ring: a
        # stalled device surfaces in one of the two, while host work
        # that merely overlapped a healthy window counts in neither.
        self._handle_stall(live_idx, window["dispatch_s"] + wait_s)
        return len(live_idx)

    def _drain_window_spec(self, window: dict) -> int:
        """Drain a speculative window: variable tokens per step per
        lane. The ring is [B, steps, width] with sibling emitted/
        drafted counts; each consumed token's KV sits at the session's
        running length (accepted drafts were written by the verify
        forward that accepted them; the bonus/residual token is
        pending, written by the next step as its feed — the same
        contract as every other decode path). Tokens whose position
        reaches the row's page-reservation limit attended scratch KV:
        the row parks on the last durable token, exactly the degraded-
        reservation rule of the plain drain.

        Spec telemetry and the per-class gamma tuner feed from here:
        proposed/accepted are counted only for steps the turn actually
        consumed (a stop mid-window discards the rest), mirroring the
        offline replay's accounting (spec_replay.ReplayStats)."""
        t0 = time.monotonic()
        with self.timer.phase("decode_drain"):
            ring_host = np.asarray(window["ring"])     # [B, steps, W]
            emits = np.asarray(window["emits"])        # [B, steps]
            drafted = np.asarray(window["drafted"])    # [B, steps]
        wait_s = time.monotonic() - t0
        self._bump("host_stall_ms", wait_s * 1000.0)
        for i in window["active_idx"]:
            t = window["turns"][i]
            if t.trace is not None and not t.trace.finished:
                t.trace.note_drain(wait_s)
        steps = window["steps"]
        decoded = 0
        accepted_total = 0
        proposed_total = 0
        overshoot = 0
        seq_rows = 0
        live_idx: list[int] = []
        round_steps: set[int] = set()
        # per-class accounting for the gamma tuner, one observe() per
        # (class) per drain so the tune_every window sees whole batches
        cls_acc: dict[str, list[int]] = {}
        for i in window["active_idx"]:
            turn = window["turns"][i]
            total_i = int(emits[i].sum())
            if self._active[i] is not turn or \
                    int(self._slot_gen[i]) != window["gen"][i]:
                # late reconciliation: the slot was finished/parked (or
                # reused) after this window dispatched — every token it
                # produced for the row is overshoot
                overshoot += total_i
                continue
            self._slot_ahead[i] = max(
                0, int(self._slot_ahead[i]) - window["ahead"][i]
            )
            live_idx.append(i)
            sess = self.sessions[turn.session_id]
            limit = window["limit"][i]
            prev = turn.new_tokens[-1] if turn.new_tokens else \
                turn.prompt_tokens[-1]
            consumed_i = 0
            prop_i = 0
            acc_i = 0
            for s in range(steps):
                if self._active[i] is not turn:
                    break
                e = int(emits[i, s])
                d = int(drafted[i, s])
                consumed_step = 0
                for j in range(e):
                    if sess.length >= limit:
                        # degraded reservation: this position's KV went
                        # to the scratch page, so the chain past it
                        # attended garbage. Park on the last durably-
                        # written token (the mid-stream requeue
                        # contract); greedy streams stay identical to
                        # the step-at-a-time engine.
                        self._park_and_requeue(i, turn)
                        break
                    tok = int(ring_host[i, s, j])
                    # token j's KV chain: `prev` was written at
                    # sess.length by the verify forward that emitted it
                    sess.history.append(prev)
                    sess.length += 1
                    decoded += 1
                    consumed_i += 1
                    consumed_step += 1
                    # emitted[j] for j < d is a consumed draft token
                    # (count only drafts the turn actually kept)
                    if j < d and j < e - 1:
                        acc_i += 1
                    self._append_token(i, turn, tok)
                    prev = tok
                    if self._active[i] is not turn:
                        break
                if consumed_step and d:
                    # this step's verify forward carried a live draft
                    prop_i += d
                    round_steps.add(s)
                if self._active[i] is not turn:
                    break
            overshoot += total_i - consumed_i
            if consumed_i:
                row = cls_acc.setdefault(turn.turn_class, [0, 0, 0])
                row[0] += prop_i
                row[1] += acc_i
                row[2] += consumed_i
            proposed_total += prop_i
            accepted_total += acc_i
            if turn.trace is not None and prop_i:
                turn.trace.spec_proposed += prop_i
                turn.trace.spec_accepted += acc_i
        if round_steps:
            # rows that decoded sequentially while a batchmate drafted
            # (penalized lanes, spec-off classes): the mixed batch's
            # split stays diagnosable in stats
            seq_rows = sum(
                1 for i in live_idx if int(drafted[i].sum()) == 0
            )
        if decoded:
            self._bump("tokens_decoded", decoded)
        if overshoot:
            self._bump("overshoot_tokens", overshoot)
        if round_steps:
            self._bump("spec_rounds", len(round_steps))
        if proposed_total:
            self._bump("spec_proposed", proposed_total)
        if accepted_total:
            self._bump("spec_accepted", accepted_total)
        if seq_rows:
            self._bump("spec_rows_sequential", seq_rows)
        throttles = 0
        for cls, (p, a, e) in cls_acc.items():
            throttles += self.spec_tuner.observe(cls, p, a, e)
        if throttles:
            self._bump("spec_throttles", throttles)
        if self._spec_floor_fn is not None and live_idx:
            self._spec_floor_in -= 1
            if self._spec_floor_in <= 0:
                self._spec_floor_in = 32
                mean_ctx = sum(
                    int(self._slot_lengths[i]) for i in live_idx
                ) / len(live_idx)
                self.spec_tuner.floor = \
                    self._spec_floor_fn(max(mean_ctx, 1.0))
        # after the bookkeeping so parked sessions carry every token
        # the slow window actually produced
        self._handle_stall(live_idx, window["dispatch_s"] + wait_s)
        return len(live_idx)

    def _append_token(self, slot: int, turn: Turn, token: int) -> None:
        turn.new_tokens.append(token)
        if turn.trace is not None:
            turn.trace.note_token(time.monotonic())
        if turn.first_token_at is None:
            # TTFT against the class target (docs/scheduler.md) —
            # measured at the host-side booking of the first token,
            # which for pipelined windows is the drain
            turn.first_token_at = time.monotonic()
            self.scheduler.observe_ttft(
                turn.turn_class,
                turn.first_token_at - turn.submitted_at,
            )
        if turn.on_token is not None:
            try:
                turn.on_token(token)
            except Exception:
                pass

        reason = None
        if token in self.stop_token_ids:
            reason = "stop"
        elif self._tool_end_id is not None:
            if token == self._tool_end_id:
                reason = "tool_call"
        else:
            tail = self.tokenizer.decode(turn.new_tokens[-24:])
            if "</tool_call>" in tail:
                reason = "tool_call"

        if reason is None and turn.stop_strings:
            # window sized in UTF-8 BYTES: byte-level tokenizers emit
            # one token per byte, BPE merges only shrink that, so a
            # (bytes+8)-token tail always covers the longest stop
            # string plus boundary slack
            longest = max(
                len(x.encode("utf-8")) for x in turn.stop_strings
            )
            tail = self.tokenizer.decode(
                turn.new_tokens[-(longest + 8):]
            )
            for stop_s in turn.stop_strings:
                if stop_s in tail:
                    turn.stop_hit = stop_s
                    reason = "stop"  # beats "length" on the last token
                    break

        if reason is None and                 len(turn.new_tokens) >= turn.sampling.max_new_tokens:
            reason = "length"

        if reason is not None:
            self._finish_turn(slot, turn, reason)

    def _finish_turn(self, slot: int, turn: Turn, reason: str) -> None:
        sess = self.sessions[turn.session_id]
        sess.last_used = time.monotonic()
        if turn.new_tokens and reason != "error":
            # the final sampled token never got a decode step, so its KV
            # is unwritten; it re-enters via the next resume prompt
            sess.pending = turn.new_tokens[-1]
        if reason == "tool_call":
            sess.parked = True        # KV retained (HBM or hibernated)
        turn.finish_reason = reason
        # per-class latency accounting (docs/scheduler.md): TPOT over
        # the streamed span; ladder-shed / error turns count completed
        # too (the class saw an answer, even a 503)
        self.scheduler.note_completed(turn.turn_class)
        if turn.first_token_at is not None and len(turn.new_tokens) > 1:
            self.scheduler.observe_tpot(
                turn.turn_class,
                (time.monotonic() - turn.first_token_at)
                / (len(turn.new_tokens) - 1),
            )
        self._active[slot] = None
        # point the freed slot at the scratch page so idle rows of the
        # batched decode never write through a stale block table into
        # pages that get reallocated to another session
        self._slot_tables[slot] = 0
        self._slot_lengths[slot] = 0
        # an in-flight window that still covers this slot reconciles at
        # its drain via the turn-identity check; the slot's NEXT
        # occupant starts with no undrained positions
        self._slot_ahead[slot] = 0
        self._bump("turns_completed")
        trace_mod.finish(turn, self.scheduler.targets)
        with self._lock:
            # consume atomically against release_session's deferral
            # add (cross-thread, same lock): an unlocked check-then-
            # discard pair here races the add and can strand a
            # deferral booked for the turn we are finishing
            deferred_now = sess.id in self._deferred_release
            if deferred_now:
                self._deferred_release.discard(sess.id)
        if deferred_now:
            self.sessions.pop(sess.id, None)
            self._release_session_prefix(sess)
            self.page_table.release(sess.id)
            if self.offload_store is not None:
                self.offload_store.discard(sess.id)
        elif reason == "tool_call" and self.offload_store is not None \
                and self.offload_on_park and self._session_is_cold(sess):
            # the tool-call park: the session goes cold for however
            # long the host-side tool runs — hibernate its pages so a
            # parked room stops billing HBM (restore is prefetched the
            # moment the resume turn queues)
            self._offload_session(sess)
        turn.done.set()

    def text_of(self, turn: Turn) -> str:
        return self.tokenizer.decode(turn.new_tokens)

    # ---- durable process lifecycle (lifecycle.py, docs/lifecycle.md) ----

    def begin_drain(self) -> None:
        """Close admission: submit() sheds every new turn with the
        ladder's 503 + Retry-After contract from this point on. The
        flip shares the engine lock with _queue_put, so a racing
        submit either enqueued before it (drain()'s sweep sheds the
        turn) or sees the new phase and sheds at the door; the quiesce
        + spool happens in drain()."""
        with self._lock:
            self.lifecycle_phase = "draining"

    def _lifecycle_fingerprint(self) -> dict:
        """What a spooled KV entry must match to be scattered into THIS
        engine: model, page geometry, quant mode, and the cache's
        per-array dtype/shape (page axis excluded — pool size may
        legitimately differ across a restart). JSON-stable types only,
        so equality survives the manifest round trip."""
        layout = {
            k: [str(v.dtype),
                [int(d) for i, d in enumerate(v.shape) if i != 1]]
            for k, v in self.cache.items()
        }
        return {
            "model": self.cfg.name,
            "page_size": int(self.page_size),
            "kv_quant": self.kv_quant,
            "cache_layout": layout,
        }

    def _lc_bump(self, key: str, n=1) -> None:
        with self._lock:
            self._lifecycle_stats[key] += n

    def _spool_session_kv(
        self, sess: _Session, lifecycle_dir: str
    ) -> Optional[dict]:
        """Write one session's KV to a durable spool file for the next
        process. Source is the live pool (gather) or the offload store
        (whichever holds the pages). Returns the manifest kv record, or
        None — shared prefix pages, injected shutdown_io/offload_io
        faults, and real I/O errors all degrade to a history re-prefill
        entry, never an exception."""
        import hashlib

        from .kv_offload import _copy_spool, _write_spool

        if sess.prefix_len > 0:
            # prefix pages are shared with other sessions and owned by
            # the (process-local) prefix cache: not reconstructible
            # across a restart — re-prefill rebuilds prefix + own KV
            return None
        own_tokens = sess.length
        if own_tokens <= 0:
            return None
        try:
            faults.maybe_fail("shutdown_io")
            host = src_path = None
            if self.page_table.pages_of(sess.id):
                faults.maybe_fail("offload_io")
                host, n_used = self._gather_pages_host(sess)
            elif self.offload_store is not None and \
                    self.offload_store.has(sess.id):
                copy_src = self.offload_store.spool_copy_source(
                    sess.id
                )
                if copy_src is not None:
                    # disk-tier hibernated session: the file is
                    # already in spool format — byte-copy it instead
                    # of parsing the whole KV into RAM to re-serialize
                    src_path, n_used = copy_src
                else:
                    got = self.offload_store.get(sess.id)
                    if got is None:
                        return None
                    entry, host = got
                    n_used = entry.n_pages
            else:
                return None
            fname = hashlib.sha1(
                sess.id.encode()
            ).hexdigest()[:16] + ".kvspool"
            path = os.path.join(lifecycle_dir, fname)
            digest = _write_spool(path, host, want_digest=True) \
                if host is not None else _copy_spool(src_path, path)
            return {
                "file": fname,
                "own_tokens": int(own_tokens),
                "n_pages": int(n_used),
                "nbytes": int(os.path.getsize(path)),
                "sha256": digest,
            }
        except Exception:
            # FaultError/OSError from the spool I/O, but also device-
            # side failures (XlaRuntimeError out of the page gather):
            # the per-session contract is degrade-to-history, and one
            # bad gather must not abort the whole drain before the
            # manifest lands every other session's history
            return None

    def drain(
        self,
        lifecycle_dir: Optional[str] = None,
        *,
        deadline_s: Optional[float] = None,
        flush: bool = True,
    ) -> dict:
        """Graceful quiesce for a process restart (docs/lifecycle.md):
        close admission, flush the in-flight decode window (every
        durably-streamed token reaches its session's history), park all
        active sessions, shed queued turns with 503 semantics, and
        spool every session to ``lifecycle_dir`` under a versioned
        manifest the next boot rehydrates from.

        Bounded: past ``deadline_s`` (ROOM_TPU_DRAIN_DEADLINE_S,
        default 30) remaining sessions skip the KV copy and are
        recorded in the manifest's ``abandoned`` intent list with their
        token history intact — a restart re-prefills them; the exit is
        never blocked. A wedged shutdown_io/offload_io fault costs at
        most one firing per session, then the same fallback.

        Engine-thread semantics: stop and join the serve_forever
        thread first (its shutdown flush already ran then). For the
        drain's duration THIS thread claims loop-thread ownership, so
        a route thread's release_session defers to the command queue
        instead of popping self.sessions/page-table state out from
        under the spool loop (the HTTP server is still answering
        during the drain window — that's where the 503s come from);
        deferred releases are applied on the way out."""
        from . import lifecycle as lc

        if lifecycle_dir is None:
            lifecycle_dir = lc.engine_dir(self.cfg.name)
        if deadline_s is None:
            deadline_s = lc.drain_deadline_s()
        t0 = time.monotonic()
        deadline = t0 + max(deadline_s, 0.0)
        with self._lock:
            self._loop_thread = threading.current_thread()
        try:
            return self._drain_inner(
                lifecycle_dir, deadline, t0, flush
            )
        finally:
            with self._lock:
                self._loop_thread = None
            self._drain_releases()

    def _drain_inner(
        self, lifecycle_dir: str, deadline: float, t0: float,
        flush: bool,
    ) -> dict:
        from . import lifecycle as lc

        self.begin_drain()
        # adoptions enqueued but not yet applied (the serve thread
        # exited before its next step): apply them NOW so a session a
        # sibling just handed over rides THIS manifest instead of
        # vanishing — its donor manifest is already consumed, this is
        # its only record
        self._drain_adoptions()
        if flush:
            try:
                self._flush_pipeline()
            except Exception:
                self._inflight = None
        else:
            # caller could not quiesce the serve thread (it may still
            # own the in-flight window and a wedged device op): drop
            # the window rather than block on — or race — it
            self._inflight = None
        drain_msg = "draining: engine is restarting; retry shortly"
        sampling_of: dict[str, Any] = {}
        for i, turn in enumerate(self._active):
            if turn is None:
                continue
            sess = self.sessions.get(turn.session_id)
            if sess is not None:
                sess.last_used = time.monotonic()
                if turn.new_tokens:
                    # the park contract: the final sampled token's KV
                    # is unwritten — it re-enters via the resume prompt
                    sess.pending = turn.new_tokens[-1]
                sess.parked = True
                try:
                    import dataclasses

                    sampling_of[sess.id] = dataclasses.asdict(
                        turn.sampling
                    )
                except (TypeError, ValueError):
                    pass
            self._active[i] = None
            self._slot_tables[i] = 0
            self._slot_lengths[i] = 0
            self._slot_ahead[i] = 0
            turn.shed = True
            self._fail_turn_unslotted(turn, drain_msg)
        self._fail_all_pending(drain_msg, shed=True)

        entries: list[dict] = []
        abandoned: list[str] = []
        fallback_ids: set[str] = set()
        try:
            os.makedirs(lifecycle_dir, exist_ok=True)
            dir_ok = True
        except OSError:
            dir_ok = False
        # warmest first: the sessions most likely to resume right after
        # the restart make the deadline cut. Snapshot under the lock —
        # a racing submit can still insert a session entry before its
        # turn is refused at the draining gate
        with self._lock:
            drain_order = sorted(
                self.sessions.values(), key=lambda s: -s.last_used
            )
        for sess in drain_order:
            if not sess.history and sess.pending is None:
                continue
            entry = {
                "id": sess.id,
                "history": [int(t) for t in sess.history],
                "pending": sess.pending,
                "length": int(sess.length),
                "generation": int(sess.generation),
                "sampling": sampling_of.get(sess.id),
                "kv": None,
            }
            preservable = sess.length > sess.prefix_len or (
                self.offload_store is not None
                and self.offload_store.has(sess.id)
            )
            if dir_ok and time.monotonic() >= deadline:
                # out of budget: record the abandonment intent (history
                # still rides the manifest, so nothing is LOST — the
                # restart re-prefills) and keep moving toward the exit
                if preservable:
                    abandoned.append(sess.id)
                entries.append(entry)
                continue
            kv = self._spool_session_kv(sess, lifecycle_dir) \
                if dir_ok else None
            if kv is not None:
                entry["kv"] = kv
            elif preservable:
                fallback_ids.add(sess.id)
            entries.append(entry)
        # apply releases that arrived during the spool loop BEFORE the
        # manifest lands: a session the client explicitly released must
        # not be resurrected parked on the next boot with the very
        # history the release discarded (its orphaned spool file is
        # swept by the restore; a release in the post-write window
        # still leaks one boot's worth of parked state — the restore's
        # idle sweep is the backstop)
        released: set[str] = set()
        while True:
            try:
                sid = self._release_requests.get_nowait()
            except queue.Empty:
                break
            released.add(sid)
            self._do_release(sid)
        if released:
            entries = [
                e for e in entries if e["id"] not in released
            ]
            abandoned = [s for s in abandoned if s not in released]
        spooled = sum(1 for e in entries if e.get("kv"))
        fallback = len(fallback_ids - released)
        manifest = {
            "version": lc.MANIFEST_VERSION,
            "generation": lc.next_generation(lifecycle_dir),
            "written_at": time.time(),
            "fingerprint": self._lifecycle_fingerprint(),
            "sessions": entries,
            "abandoned": abandoned,
        }
        wrote = lc.write_manifest(lifecycle_dir, manifest)
        drain_ms = (time.monotonic() - t0) * 1000.0
        with self._lock:
            st = self._lifecycle_stats
            st["drain_ms"] = round(drain_ms, 3)
            st["sessions_spooled"] += spooled
            st["sessions_fallback"] += fallback
            st["sessions_abandoned"] += len(abandoned)
            if not wrote:
                st["manifest_errors"] += 1
        try:
            from ..core.telemetry import incr_counter, observe_ms

            observe_ms("lifecycle.drain", drain_ms)
            incr_counter("lifecycle.sessions_spooled", spooled)
            if abandoned:
                incr_counter("lifecycle.sessions_abandoned",
                             len(abandoned))
        except Exception:
            pass
        return {
            "drain_ms": round(drain_ms, 3),
            "sessions_total": len(entries),
            "sessions_spooled": spooled,
            "sessions_fallback": fallback,
            "sessions_abandoned": len(abandoned),
            "manifest_written": wrote,
            "dir": lifecycle_dir,
        }

    def _adopt_entry(
        self, entry: dict, lifecycle_dir: Optional[str], fp_ok: bool,
        *, require_sha: bool = True,
    ) -> tuple[str, Optional[_Session], Optional[str]]:
        """Validate + register ONE manifest-style session entry — the
        shared per-entry half of restore_from_manifest and the fleet's
        cross-replica adoption seam (docs/fleet.md). Returns (status,
        session, adopted spool basename): 'resumed' (spool adopted
        into the offload disk tier — the next prefill restores
        byte-exact), 'reprefill' (history-mirror fallback), or
        'skipped' (malformed / empty / duplicate id). ``require_sha``
        relaxes the manifest's checksum requirement for same-process
        fleet handoffs, whose spool files were written by a replica
        this process already trusts."""
        try:
            sid = entry["id"]
            history = [int(t) for t in entry.get("history") or []]
            pending = entry.get("pending")
            pending = int(pending) if pending is not None else None
            generation = int(entry.get("generation") or 0)
            if not isinstance(sid, str) or not sid or (
                not history and pending is None
            ) or sid in self.sessions:
                return "skipped", None, None
        except (KeyError, TypeError, ValueError):
            return "skipped", None, None
        sess = _Session(
            id=sid, parked=True, pending=pending,
            history=history, generation=generation,
        )
        kv = entry.get("kv")
        adopted_fname = None
        if isinstance(kv, dict) and fp_ok and \
                self.offload_store is not None:
            raw = str(kv.get("file") or "")
            fname = os.path.basename(raw)
            # fleet handoffs carry absolute spool paths (the donor's
            # own spool dir); manifest entries are basenames resolved
            # against the manifest's dir
            path = raw if os.path.isabs(raw) else os.path.join(
                lifecycle_dir or "", fname
            )
            sha = kv.get("sha256")
            try:
                faults.maybe_fail("shutdown_io")
                own = int(kv["own_tokens"])
                n_pages = int(kv["n_pages"])
                # metadata-only validation — the sha256 (when present)
                # is verified lazily at the session's first spool read
                # (TieredKVStore.get), so adoption never reads the KV
                # bytes; a size mismatch is caught here for free,
                # anything subtler degrades to a re-prefill miss at
                # first use
                good = (
                    fname.endswith(".kvspool")
                    and own == len(history) == int(
                        entry.get("length") or -1
                    )
                    and (bool(sha) or not require_sha)
                    and n_pages == -(-own // self.page_size)
                    and os.path.getsize(path) == int(
                        kv.get("nbytes") or -1
                    )
                )
            except (FaultError, KeyError, TypeError, ValueError,
                    OSError):
                good = False
            if good and self.offload_store.adopt(
                sid, path, own, n_pages, int(kv.get("nbytes") or 0),
                sha256=str(sha) if sha else None,
            ):
                sess.length = own
                adopted_fname = fname
        if adopted_fname is None:
            # history mirror re-prefill (|history| == length holds
            # once the resume prefill rebuilds the pages)
            sess.length = 0
        self.sessions[sid] = sess
        return (
            ("resumed" if adopted_fname else "reprefill"),
            sess, adopted_fname,
        )

    def adopt_parked_session(
        self,
        entry: dict,
        *,
        lifecycle_dir: Optional[str] = None,
        fingerprint: Optional[dict] = None,
        require_sha: bool = False,
    ) -> threading.Event:
        """Re-home a parked session onto this engine (fleet failover /
        blue-green absorb; docs/fleet.md). ``entry`` is a
        manifest-style session record; its ``kv`` spool file (when
        present and valid against this engine's config) is adopted
        into the offload disk tier so the session's next turn restores
        byte-exact — anything else re-prefills from the entry's token
        history. ``fingerprint`` (the donor manifest's) must equal
        this engine's; None means the caller vouches for config
        identity (a same-process sibling replica of the same model).

        Thread-safe: when a loop thread owns the engine the adoption
        is queued and applied at the next step BEFORE admission —
        callers enqueue the adoption, then submit the session's next
        turn, and the step ordering guarantees admission sees the
        adopted session. The returned Event is set once the adoption
        has been applied (immediately when applied inline)."""
        done = threading.Event()
        with self._lock:
            loop = self._loop_thread
        if loop is not None and loop.is_alive() and \
                loop is not threading.current_thread():
            self._adoption_requests.put(
                (entry, lifecycle_dir, fingerprint, require_sha, done)
            )
            # the loop may have exited between the check and the put;
            # if nobody owns the engine anymore, apply the queue now
            with self._lock:
                loop = self._loop_thread
            if loop is None or not loop.is_alive():
                self._drain_adoptions()
            return done
        self._apply_adoption(
            entry, lifecycle_dir, fingerprint, require_sha
        )
        done.set()
        return done

    def _drain_adoptions(self) -> None:
        while True:
            try:
                entry, lc_dir, fp, require_sha, done = \
                    self._adoption_requests.get_nowait()
            except queue.Empty:
                return
            try:
                self._apply_adoption(entry, lc_dir, fp, require_sha)
            finally:
                done.set()

    def _apply_adoption(
        self, entry, lifecycle_dir, fingerprint, require_sha,
    ) -> str:
        fp_ok = fingerprint is None or \
            fingerprint == self._lifecycle_fingerprint()
        status, _, _ = self._adopt_entry(
            entry, lifecycle_dir, fp_ok, require_sha=require_sha
        )
        if status == "resumed":
            self._lc_bump("sessions_resumed")
        elif status == "reprefill":
            self._lc_bump("sessions_reprefill")
        return status

    def export_session(
        self, session_id: str
    ) -> tuple[threading.Event, dict]:
        """Detach a quiescent session for a prefill->decode handoff
        (serving/disagg.py, docs/disagg.md): park + offload its KV,
        detach the spool file (TieredKVStore.export_entry) and remove
        the session from this engine, handing back a manifest-style
        entry the adopting replica consumes. The inverse of
        ``adopt_parked_session`` and the same thread contract: queued
        to the engine thread when a loop owns it, applied inline
        otherwise. Returns ``(done, holder)``; once ``done`` is set,
        ``holder['entry']`` is the exported entry (``kv`` None when
        only the history could travel) or None with
        ``holder['error']`` — a session that picked up a live turn is
        REFUSED, never blocked on."""
        holder: dict = {"entry": None, "error": None}
        done = threading.Event()
        with self._lock:
            loop = self._loop_thread
        if loop is not None and loop.is_alive() and \
                loop is not threading.current_thread():
            self._ship_requests.put((session_id, holder, done))
            # the loop may have exited between the check and the put;
            # if nobody owns the engine anymore, apply the queue now
            with self._lock:
                loop = self._loop_thread
            if loop is None or not loop.is_alive():
                self._drain_ships()
            return done, holder
        self._apply_ship(session_id, holder)
        done.set()
        return done, holder

    def _drain_ships(self) -> None:
        while True:
            try:
                sid, holder, done = self._ship_requests.get_nowait()
            except queue.Empty:
                return
            try:
                self._apply_ship(sid, holder)
            finally:
                done.set()

    def _apply_ship(self, session_id: str, holder: dict) -> None:
        if self.lifecycle_phase == "draining":
            # a queued export applied during the shutdown drain would
            # pop the session AFTER nobody remains to adopt it — the
            # manifest must cover it instead (refusal keeps it here)
            holder["error"] = "draining"
            return
        with self._lock:
            busy = self._session_in_flight(session_id)
        if busy:
            # a turn raced the ship (possibly queued ahead of the
            # session's very first admission): refuse — the router
            # keeps the placement here and retries at the next turn
            # boundary
            holder["error"] = "session busy"
            return
        sess = self.sessions.get(session_id)
        if sess is None:
            holder["error"] = "unknown session"
            return
        if not sess.history and sess.pending is None:
            holder["error"] = "nothing durable to ship"
            return
        entry = self._session_entry(sess)
        # warm shipment under the same eligibility rule as crash
        # salvage; unlike salvage, a HEALTHY engine may actively
        # offload resident pages first (the device state is trusted)
        if self.offload_store is not None and \
                self._kv_export_eligible(sess):
            try:
                if self.page_table.pages_of(sess.id):
                    self._offload_session(sess)
                if self.offload_store.has(sess.id):
                    entry["kv"] = \
                        self.offload_store.export_entry(sess.id)
            except Exception:
                entry["kv"] = None   # degrade to history-only
        # the session now belongs to the adopter: remove it here so a
        # stale affinity submit can't fork it (the router re-points
        # before any such submit can land)
        self.sessions.pop(sess.id, None)
        self._release_session_prefix(sess)
        self.page_table.release(sess.id)
        if self.offload_store is not None:
            self.offload_store.discard(sess.id)
        with self._lock:
            self._deferred_release.discard(sess.id)
        self._bump("sessions_shipped")
        holder["entry"] = entry

    def restore_from_manifest(
        self, lifecycle_dir: Optional[str] = None
    ) -> dict:
        """Warm restart (docs/lifecycle.md): scan the drain manifest,
        validate every entry against THIS engine's config, and
        rehydrate sessions as restorable-parked. Valid KV spool files
        are adopted into the offload store's disk tier — the session's
        next prefill restores them through the ordinary byte-exact
        disk-hit path, so greedy continuations are token-identical
        across the restart. A layout/config/size mismatch, a truncated
        file, or an injected shutdown_io fault falls back to the
        history re-prefill path here; the manifest's sha256 is checked
        lazily at the first spool read (boot stays a metadata scan),
        where a mismatch degrades to the same re-prefill (still
        token-identical, just slower). Never raises; consumes the
        manifest so a later crash
        cannot resurrect stale sessions; sweeps orphaned spool files on
        the way out.

        Also absorbs fleet per-replica sub-manifests (``replica-*/``
        and ``bluegreen-*/`` under the dir, docs/fleet.md): rolling a
        fleet deployment back to ROOM_TPU_FLEET_REPLICAS=1 must not
        silently lose the sessions the fleet's drain spooled."""
        from . import lifecycle as lc

        if lifecycle_dir is None:
            lifecycle_dir = lc.engine_dir(self.cfg.name)
        with self._lock:
            # snapshot + flip atomically: a begin_drain() landing
            # between an unlocked read and the 'warming' write would
            # be clobbered — admission re-opens mid-shutdown and the
            # exit guard below can no longer tell (the same hole the
            # exit re-read closed, on the entry side). An engine
            # already draining stays draining; the restore still runs
            # (adopted sessions land in the manifest the drain
            # writes).
            prev_phase = self.lifecycle_phase
            if prev_phase != "draining":
                self.lifecycle_phase = "warming"
        summary = {"resumed": 0, "reprefill": 0, "skipped": 0,
                   "manifest": False}
        adopted_sess: dict[str, _Session] = {}
        dirs = [lifecycle_dir] + lc.manifest_subdirs(lifecycle_dir)
        for d in dirs:
            self._restore_dir(d, summary, adopted_sess)
        # a later adopt's rebalance may have evicted an earlier one
        # (disk cap overflow): count only entries that SURVIVED the
        # whole restore as resumed, and demote the evicted back to the
        # re-prefill path — health/bench must never claim warmth the
        # store no longer holds
        for sid, sess in adopted_sess.items():
            if self.offload_store is not None and \
                    self.offload_store.has(sid):
                summary["resumed"] += 1
            else:
                sess.length = 0
                summary["reprefill"] += 1
        with self._lock:
            st = self._lifecycle_stats
            st["sessions_resumed"] += summary["resumed"]
            st["sessions_reprefill"] += summary["reprefill"]
        try:
            from ..core.telemetry import incr_counter

            incr_counter("lifecycle.sessions_resumed",
                         summary["resumed"])
            incr_counter("lifecycle.sessions_reprefill",
                         summary["reprefill"])
        except Exception:
            pass
        with self._lock:
            # begin_drain() may have landed mid-restore (SIGTERM during
            # a boot-time warm-up): never clobber a live 'draining'
            # back to serving off the stale entry snapshot — that would
            # reopen admission on an engine the process is quiescing
            if self.lifecycle_phase == "warming":
                # only the entry flip (guarded against a draining
                # prev_phase) writes 'warming', so reaching here means
                # the restore owned the phase throughout
                self.lifecycle_phase = "serving"
        return summary

    def _restore_dir(
        self, lifecycle_dir: str, summary: dict,
        adopted_sess: dict,
    ) -> None:
        """Absorb ONE manifest dir into this engine (the per-dir half
        of restore_from_manifest). Missing manifest → orphan sweep
        only; present one is consumed and its unprotected spool files
        swept."""
        from . import lifecycle as lc

        manifest = lc.read_manifest(lifecycle_dir)
        if manifest is None:
            if os.path.exists(
                os.path.join(lifecycle_dir, lc.MANIFEST_NAME)
            ):
                self._lc_bump("manifest_errors")
            lc.sweep_orphans(lifecycle_dir)
            return
        summary["manifest"] = True
        fp_ok = manifest.get("version") == lc.MANIFEST_VERSION and \
            manifest.get("fingerprint") == self._lifecycle_fingerprint()
        adopted_files: set[str] = set()
        # COLDEST first: adopt() rebalances the disk tier by evicting
        # the lowest last_used entry, and adoption time IS last_used —
        # so when the manifest's bytes exceed this engine's disk cap,
        # iterating the (warmest-first) manifest in reverse makes the
        # overflow evict the coldest sessions, preserving the drain's
        # warmest-first priority instead of inverting it
        for entry in reversed(manifest.get("sessions", [])):
            status, sess, fname = self._adopt_entry(
                entry, lifecycle_dir, fp_ok
            )
            if status == "resumed":
                adopted_sess[sess.id] = sess
                adopted_files.add(fname)
            elif status == "reprefill":
                summary["reprefill"] += 1
            else:
                summary["skipped"] += 1
        lc.consume_manifest(lifecycle_dir)
        # everything the manifest no longer protects: fallback spool
        # files from THIS restore plus any older process's leavings
        lc.sweep_orphans(lifecycle_dir, keep=adopted_files,
                         max_age_s=0.0)
