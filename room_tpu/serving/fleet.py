"""Engine replica fleet: crash failover, KV-affinity routing,
blue/green drains (docs/fleet.md).

One ``ModelHost`` used to mean ONE engine per model — an engine that
crash-looped past its restart budget took every Queen/Worker session
with it, and a rolling deploy was a full outage. ``EngineFleet`` is the
layer above: N ``ServingEngine`` replicas of one model (hetero
submeshes on one host — the pattern MULTICHIP proves; cross-host later
via ``parallel/multihost.py``) behind a KV-affinity router.

**Routing.** Sessions are placed where their prefix/KV already lives: a
session's first turn goes to the healthiest replica (health score =
serving state × degradation rung × queue depth × active slots ×
restart strikes) and every later turn follows the placement — routing a
turn anywhere else would prefill a fresh session missing its history.
The ``router_io`` fault point models the placement lookup failing:
bounded retry, then a clean 503-contract shed — a session is NEVER
misrouted. EDF class priorities (queen > worker > background,
docs/scheduler.md) pass through untouched: each replica runs its own
scheduler, and the router only picks WHICH replica admits the turn.

**Crash failover.** The router keeps a per-session history mirror (the
prompt + streamed tokens — ints, same cost argument as the engine's own
mirror). When a replica dies — engine thread crash past the restart
budget, or the ``replica_crash`` fault — the supervisor re-homes its
sessions onto siblings through the engine's adoption seam
(``ServingEngine.adopt_parked_session``): **warm** via spool files a
drain/hibernate landed (the dying engine's ``crash_salvage`` +
``TieredKVStore.export_entry`` detach byte-exact KV for the sibling to
adopt), **re-prefill from the mirror** otherwise. Zero durably-streamed
tokens are lost either way: the mirror's last streamed token re-enters
as the session's pending token, exactly the park contract, so greedy
continuations are token-identical to an unkilled run.

**Blue/green.** ``drain_replica`` is the deploy primitive: stop routing
to the replica, let its in-flight turns finish streaming (no 503s —
queen turns survive a rolling deploy), drain it to a handoff manifest
(``ServingEngine.drain``), absorb the manifest's sessions into the
siblings, then ``rebuild_replica`` swaps in the new build. The process
level drain/restore (``ModelHost`` SIGTERM path) fans out per replica:
each drains to its own subdir, and the next boot's restore absorbs
every manifest it finds — tolerant of a fleet-size change across the
restart.

**Sharded router tier (docs/podnet.md).** Router state itself is
partitioned by room id across ``ROOM_TPU_ROUTER_SHARDS`` shards: each
``_RouterShard`` owns the ``_SessionRecord``s, fences, and mirror
journal for its rooms (placement = crc32(room) mod N via the
epoch-versioned ``PlacementMap``, replicated to pod peers over control
frames). A shard that dies (the ``router_shard_crash`` fault, or ops)
sheds its rooms until its lease (``ROOM_TPU_ROUTER_LEASE_S``)
expires, then a surviving sibling ADOPTS its mirror journal — replay
with the journal's hole/tombstone discipline, fences minted +1, a new
placement epoch published — while every other shard's rooms keep
streaming untouched. Submits carrying a pre-failover placement epoch
are refused (``stale placement epoch``), so a healed stale router can
never re-install the old ownership: one room, one owner, always.

Env knobs (docs/knobs.md):

    ROOM_TPU_FLEET_REPLICAS   engine replicas per served model (1 =
                              no fleet, the classic single engine)
    ROOM_TPU_FLEET_MESHES     ';'-separated per-replica mesh specs
    ROOM_TPU_FLEET_STRIKES    replica death strikes before the
                              supervisor stops rebuilding it
    ROOM_TPU_FLEET_TICK_S     supervision poll interval
    ROOM_TPU_FLEET_REBUILD    auto-rebuild crashed replicas (within
                              the strike budget)
    ROOM_TPU_ROUTER_SHARDS    room-id partitions of the router tier
                              (1 = the classic single router slice)
    ROOM_TPU_ROUTER_LEASE_S   dead router shard's lease before a
                              sibling adopts its journal
"""

from __future__ import annotations

import logging
import os
from ..utils import locks
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..chaos import invariants as invariants_mod
from . import disagg as disagg_mod
from . import faults
from . import lifecycle as lifecycle_mod
from . import podnet as podnet_mod
from . import trace as trace_mod
from ..utils import knobs
from .engine import Turn
from .faults import FaultError
from .sampler import SamplingParams
from .scheduler import classify_turn

__all__ = [
    "EngineFleet", "ReplicaHandle", "fleet_replicas_from_env",
    "router_shards_from_env",
]

log = logging.getLogger(__name__)


def fleet_replicas_from_env() -> int:
    try:
        return max(1, knobs.get_int(
            "ROOM_TPU_FLEET_REPLICAS", scope="provider"
        ))
    except ValueError:
        return 1


def router_shards_from_env() -> int:
    try:
        return max(1, knobs.get_int(
            "ROOM_TPU_ROUTER_SHARDS", scope="provider"
        ))
    except ValueError:
        return 1


@dataclass
class _SessionRecord:
    """Router-level view of one session: which replica holds its KV,
    and the token stream (prompt + streamed tokens) needed to re-home
    it if that replica dies mid-turn. Ints only — same cost argument
    as the engine's own history mirror."""

    sid: str
    rid: str
    tokens: list = field(default_factory=list)
    generation: int = 0
    last_used: float = field(default_factory=time.monotonic)
    rehomed: int = 0
    # a re-home that found NO serving sibling defers: the manifest
    # entry parks here (rid="") and the next _route adopts it into
    # whichever replica it places the session on. pending_fingerprint
    # rides along for entries from a manifest (None = same-process
    # salvage, config identity vouched)
    pending_entry: Optional[dict] = None
    pending_fingerprint: Optional[dict] = None
    # per-record lock for the token mirror: the hot per-token append
    # must not contend on the fleet-wide lock across replicas (one
    # session has at most one active turn, so this lock only ever
    # serializes the appender against a failover's mirror read)
    lock: threading.Lock = field(
        default_factory=lambda: locks.make_lock("fleet_record")
    )
    # mirror cap (ROOM_TPU_FLEET_MIRROR_TOKENS): set when this
    # record's token mirror was LRU-evicted — the partial tokens that
    # accumulate afterwards must never be mistaken for a full history
    # (failover for a dropped-mirror session is warm-salvage only)
    mirror_dropped: bool = False
    # disaggregated prefill->decode handoff (serving/disagg.py):
    # ship state machine fields, mutated under the fleet lock
    ship_state: Optional[str] = None      # exporting | adopting
    ship_event: Optional[threading.Event] = None
    ship_export: Optional[tuple] = None   # (done, holder, donor_rid)
    ship_adopt: Optional[tuple] = None    # (ev, entry, target_rid)
    ship_t0: Optional[float] = None
    # count of submits between routing and the engine-queue put: the
    # coordinator must not START a ship in that window (the exported
    # session would vanish from under the about-to-enqueue turn,
    # which would then prefill a forked fresh session on the donor)
    routing: int = 0
    # the session's most recent turn (the ship fires at its
    # completion); cleared when the ship lands
    last_turn: Optional[Any] = None
    # pod fencing (docs/podnet.md): monotonic session-ownership
    # generation — the per-slot admission-generation pattern lifted to
    # the router. Every ownership transfer (re-home, ship, absorb)
    # advances it under the fleet lock; exports and wire frames carry
    # the fence they were minted under, and anything presenting an
    # older fence (a host healing from a partition) is REFUSED — a
    # session's history structurally cannot fork
    fence: int = 0
    # fence the in-flight disagg ship was minted under; a mismatch at
    # collect/dispatch means a re-home superseded the export
    ship_fence: int = 0
    # sharded router tier (docs/podnet.md): index of the _RouterShard
    # whose record map and mirror journal own this session; rewritten
    # (under the fleet lock) when a dead shard's journal is adopted
    shard: int = 0


class ReplicaHandle:
    """One engine replica under fleet supervision."""

    def __init__(
        self, rid: str, index: int, engine: Any,
        role: str = "mixed",
    ) -> None:
        self.rid = rid
        self.index = index
        self.engine = engine
        # disaggregated serving role (docs/disagg.md): prefill
        # replicas absorb fresh long-prompt sessions and ship finished
        # KV to decode replicas; mixed is the classic fleet behavior
        self.role = role
        self.thread: Optional[threading.Thread] = None
        self.stop = threading.Event()
        # serving -> draining -> drained (blue/green) | dead (crash)
        self.state = "serving"
        self.strikes = 0
        # set once a dead replica's sessions have been re-homed; stays
        # False while a wedged serve thread could still be streaming
        # (re-homing then would fork the mirror mid-stream)
        self.rehomed_done = False
        # set once a blue/green drain has absorbed this replica's
        # sessions into siblings: affinity-blocked submitters wait on
        # it instead of 503ing
        self.drained = threading.Event()

    def start_thread(self) -> None:
        if self.thread is not None and self.thread.is_alive():
            return
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self.engine.serve_forever,
            args=(self.stop,),
            daemon=True,
            name=f"fleet-replica-{self.rid}",
        )
        self.thread.start()

    def is_serving(self) -> bool:
        return self.state == "serving" and \
            getattr(self.engine, "healthy", True)

    def health_score(self) -> float:
        """Placement score, higher = better home for a new session.
        Dead/draining replicas score 0; among serving replicas the
        score penalizes queue depth, occupied slots, the degradation
        rung, and restart strikes — the router sends new sessions
        where capacity and stability actually are."""
        if not self.is_serving():
            return 0.0
        eng = self.engine
        try:
            queued = eng._queue.qsize()
            active = sum(1 for t in eng._active if t is not None)
            rung = eng.degradation_level()
        except Exception:
            queued = active = rung = 0
        return max(
            1.0,
            100.0 - 2.0 * queued - 1.0 * active - 10.0 * rung
            - 5.0 * self.strikes,
        )


class _FleetSessions:
    """Read-only merged view over the replicas' session dicts.
    ``in`` / ``len`` (the provider's per-execute hot path) are one
    atomic dict op per replica; iteration snapshots with a bounded
    retry against concurrent serve-thread mutation."""

    def __init__(self, fleet: "EngineFleet") -> None:
        self._fleet = fleet

    def _live(self) -> list[dict]:
        return [
            h.engine.sessions for h in self._fleet.replicas
            if h.state != "dead"
        ]

    def __contains__(self, sid) -> bool:
        return any(sid in d for d in self._live())

    def __len__(self) -> int:
        return sum(len(d) for d in self._live())

    def _snapshot(self) -> dict:
        out: dict = {}
        for d in self._live():
            for _ in range(3):
                try:
                    out.update(d)
                    break
                except RuntimeError:
                    continue  # resized mid-copy; retry
        return out

    def __iter__(self):
        return iter(self._snapshot())

    def __getitem__(self, sid):
        for d in self._live():
            try:
                return d[sid]
            except KeyError:
                continue
        raise KeyError(sid)

    def get(self, sid, default=None):
        try:
            return self[sid]
        except KeyError:
            return default

    def keys(self):
        return self._snapshot().keys()

    def items(self):
        return self._snapshot().items()

    def values(self):
        return self._snapshot().values()


class _RouterShard:
    """One room-id partition of the router tier (docs/podnet.md): its
    own ``_SessionRecord`` map and mirror journal. A shard is the
    router-side failure domain — killing one loses exactly its rooms'
    in-memory records (the journal on disk survives for a sibling to
    adopt), never a sibling shard's, and never any engine KV."""

    def __init__(
        self, shard_id: int,
        journal: Optional[podnet_mod.MirrorJournal] = None,
    ) -> None:
        self.shard_id = shard_id
        self.records: dict[str, _SessionRecord] = {}
        self.journal = journal
        # serving -> dead (crashed; lease running) -> retired (journal
        # adopted by a sibling; placement redirected away)
        self.state = "serving"
        self.died_at = 0.0
        self.adoptions = 0


class _ShardedRecords:
    """Dict-shaped facade over the router shards' record maps, so
    every existing ``_records`` call site (and the tests/bench that
    poke it) keeps its semantics — including the ``get(sid) is rec``
    identity checks the disagg coordinator leans on. Reads scan the
    shard maps; writes home the record on its placement-map shard.
    Mutating call sites already hold the fleet lock."""

    def __init__(self, fleet: "EngineFleet") -> None:
        self._fleet = fleet

    def _maps(self) -> list[dict]:
        return [s.records for s in self._fleet._shards]

    def get(self, sid, default=None):
        for m in self._maps():
            rec = m.get(sid)
            if rec is not None:
                return rec
        return default

    def __getitem__(self, sid) -> _SessionRecord:
        rec = self.get(sid)
        if rec is None:
            raise KeyError(sid)
        return rec

    def __setitem__(self, sid, rec: _SessionRecord) -> None:
        shards = self._fleet._shards
        k = self._fleet.placement.shard_of(sid)
        if shards[k].state != "serving":
            # the room's shard is down with its lease still running
            # (a salvage re-home or boot replay minted this record,
            # not a submit — those shed): home it provisionally on
            # the emptiest serving sibling. Lookups scan every map,
            # so the placement redirect that lands at adoption never
            # loses the record.
            live = [s for s in shards if s.state == "serving"]
            if live:
                k = min(live, key=lambda s: len(s.records)).shard_id
        for s in shards:
            if s.shard_id != k:
                s.records.pop(sid, None)
        rec.shard = k
        shards[k].records[sid] = rec

    def pop(self, sid, default=None):
        out = default
        for m in self._maps():
            rec = m.pop(sid, None)
            if rec is not None:
                out = rec
        return out

    def __contains__(self, sid) -> bool:
        return self.get(sid) is not None

    def __len__(self) -> int:
        return sum(len(m) for m in self._maps())

    def _merged(self) -> dict:
        out: dict = {}
        for m in self._maps():
            out.update(m)
        return out

    def __iter__(self):
        return iter(self._merged())

    def keys(self):
        return self._merged().keys()

    def values(self):
        return self._merged().values()

    def items(self):
        return self._merged().items()


class EngineFleet:
    """N engine replicas of one model behind a KV-affinity router.

    Drop-in for a single ``ServingEngine`` on the provider surface:
    ``submit / text_of / release_session / sessions / stats / healthy /
    begin_drain / drain / restore_from_manifest / serve_forever`` all
    exist with fleet-wide semantics, so ``providers/tpu.ModelHost``
    holds either without caring which.
    """

    def __init__(
        self,
        model_name: str,
        build_engine: Callable[[int], Any],
        n_replicas: Optional[int] = None,
        *,
        auto_rebuild: Optional[bool] = None,
        roles: Optional[list[str]] = None,
    ) -> None:
        self.model_name = model_name
        self._build_engine = build_engine
        self.n_replicas = n_replicas or fleet_replicas_from_env()
        self.max_strikes = knobs.get_int("ROOM_TPU_FLEET_STRIKES")
        self.tick_s = knobs.get_float("ROOM_TPU_FLEET_TICK_S")
        self.auto_rebuild = auto_rebuild if auto_rebuild is not None \
            else knobs.get_bool("ROOM_TPU_FLEET_REBUILD")
        self._lock = locks.make_lock("fleet")
        # sharded router tier (docs/podnet.md): room-id-partitioned
        # record maps behind a dict-shaped facade; 1 shard = the
        # classic single router slice
        self.n_router_shards = router_shards_from_env()
        try:
            self.router_lease_s = knobs.get_float(
                "ROOM_TPU_ROUTER_LEASE_S"
            )
        except ValueError:
            self.router_lease_s = 2.0
        self.placement = podnet_mod.PlacementMap(self.n_router_shards)
        self._shards: list[_RouterShard] = [
            _RouterShard(i) for i in range(self.n_router_shards)
        ]
        # ROOM_TPU_ROUTER_SHARD_HEARTBEATS: shard death and lease
        # expiry come from a PodMembership detector fed per-shard wire
        # heartbeats — the same verdict machinery pods use — instead of
        # the in-process died_at timer. The detector's lease is the
        # router lease, so the adoption timing contract is unchanged;
        # what changes is WHO decides a shard is adoptable (heartbeat
        # silence, not the killer's own timestamp).
        self.shard_heartbeats = knobs.get_bool(
            "ROOM_TPU_ROUTER_SHARD_HEARTBEATS"
        )
        self._shard_membership: Optional[podnet_mod.PodMembership] = None
        self._shard_leases_fired: set[int] = set()
        if self.shard_heartbeats and self.n_router_shards > 1:
            self._shard_membership = podnet_mod.PodMembership(
                lease_s=self.router_lease_s,
            )
            for s in self._shards:
                self._shard_membership.register(
                    f"shard-{s.shard_id}"
                )
        self._records = _ShardedRecords(self)
        self._rr = 0   # round-robin cursor for re-home spreading
        self._threads_started = False
        self.lifecycle_phase = "starting"
        self._stats = {
            "failovers": 0, "sessions_rehomed": 0,
            "sessions_rehomed_warm": 0,
            "sessions_rehomed_reprefill": 0,
            "replica_rebuilds": 0, "bluegreen_drains": 0,
            "router_retries": 0, "router_shed": 0,
            "mirror_evictions": 0, "mirror_tokens_evicted": 0,
            "fence_refusals": 0, "mirror_restored": 0,
            "router_shard_crashes": 0, "router_shard_adoptions": 0,
            "sessions_adopted": 0, "placement_refusals": 0,
        }
        # bounded router history mirror (docs/fleet.md): the per-token
        # mirror grows for the life of a room, and disaggregation's
        # re-prefill fallback leans on it harder — past the fleet-wide
        # cap the least-recently-used records drop their mirrors
        # (warm-only failover for those sessions), counted in
        # mirror_evictions. 0 = unbounded.
        try:
            self.mirror_cap_tokens = knobs.get_int(
                "ROOM_TPU_FLEET_MIRROR_TOKENS"
            )
        except ValueError:
            self.mirror_cap_tokens = 0
        self._mirror_tokens = 0
        self._mirror_lock = locks.make_lock("fleet_mirror")
        self._mirror_sweep_at = 0.0
        self._mirror_sweep_futile = False
        role_list = (
            disagg_mod.normalize_roles(roles, self.n_replicas)
            if roles is not None
            else disagg_mod.roles_from_env(self.n_replicas)
        )
        self.replicas: list[ReplicaHandle] = [
            ReplicaHandle(f"r{i}", i, build_engine(i),
                          role=role_list[i])
            for i in range(self.n_replicas)
        ]
        for h in self.replicas:
            # arms fatal-crash salvage: the engine only detaches spool
            # files for a hand-off when a supervisor exists to consume
            # it (engine._recover_from_crash)
            h.engine.fleet_supervised = True
        # disaggregated prefill/decode (serving/disagg.py,
        # docs/disagg.md): role-aware placement + the prefill->decode
        # KV shipment state machine; inert when every role is mixed
        self.disagg = disagg_mod.DisaggCoordinator(self, role_list)
        # pod fault tolerance (docs/podnet.md): membership heartbeats
        # + lease-gated re-home (inert without ROOM_TPU_POD_MEMBERSHIP)
        # and the crash-durable router mirror (ROOM_TPU_POD_MIRROR) —
        # replayed NOW so a router restart re-parks every in-flight
        # session the journal still covers instead of orphaning it
        self.pod = podnet_mod.PodCoordinator(self)
        # journals exist when the pod mirror knob asks for crash
        # durability OR the router tier is sharded — shard failover IS
        # journal adoption, so a multi-shard router always journals. A
        # single shard keeps the flat router-mirror dir (back compat
        # with pre-shard sidecars); shards get one subdir each.
        if knobs.get_bool("ROOM_TPU_POD_MIRROR") or \
                self.n_router_shards > 1:
            root = os.path.join(
                lifecycle_mod.engine_dir(model_name), "router-mirror",
            )
            for shard in self._shards:
                shard.journal = podnet_mod.MirrorJournal(
                    root if self.n_router_shards == 1
                    else os.path.join(root, f"shard-{shard.shard_id}")
                )
            self._replay_mirror_journals()
        self.lifecycle_phase = "serving"

    @property
    def mirror_journal(self) -> Optional[podnet_mod.MirrorJournal]:
        """Shard 0's journal — THE journal for a single-shard router
        (the pre-shard surface tests and ops scripts poke); per-record
        paths resolve their own shard's journal via _journal_for."""
        return self._shards[0].journal

    # ---- small helpers ----

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._stats[key] += n

    def _handle(self, rid: str) -> Optional[ReplicaHandle]:
        for h in self.replicas:
            if h.rid == rid:
                return h
        return None

    def _serving_replicas(
        self, exclude: Optional[str] = None
    ) -> list[ReplicaHandle]:
        return [
            h for h in self.replicas
            if h.is_serving() and h.rid != exclude
        ]

    @property
    def healthy(self) -> bool:
        """The fleet fails closed only when NO replica can serve —
        one crashed sibling is the failover path working, not an
        unhealthy model."""
        return bool(self._serving_replicas())

    @property
    def tokenizer(self):
        return self.replicas[0].engine.tokenizer

    @property
    def max_batch(self) -> int:
        return sum(
            h.engine.max_batch for h in self.replicas
            if h.state != "dead"
        )

    @property
    def sessions(self) -> "_FleetSessions":
        """Merged read-only session view across live replicas
        (provider surface: membership tests and counts, the hot
        paths, are single GIL-atomic dict ops per replica — never an
        iteration over a dict a serve thread is mutating)."""
        return _FleetSessions(self)

    def text_of(self, turn: Turn) -> str:
        return self.tokenizer.decode(turn.new_tokens)

    # ---- routing ----

    def _shed_turn(
        self, sid: str, prompt_tokens, sampling, turn_class, msg: str,
        priority: Optional[int] = None,
    ) -> Turn:
        """Fail a turn at the router with the engine's exact shed
        contract (503 + Retry-After at the routes layer). The class
        comes from the scheduler's classifier — an untagged turn that
        carries a background priority is shed (and accounted) as
        background, never silently promoted to worker."""
        turn = Turn(
            session_id=sid,
            prompt_tokens=list(prompt_tokens),
            sampling=sampling or SamplingParams(),
            turn_class=classify_turn(turn_class, priority),
        )
        turn.shed = True
        turn.error = msg
        turn.finish_reason = "error"
        # turnscope: router-level sheds never reach an engine, so the
        # flight recorder books them here (evidence ring: shed=True)
        turn.trace = trace_mod.begin(sid, turn.turn_class)
        trace_mod.finish(turn)
        turn.done.set()
        self._bump("router_shed")
        return turn

    def _route(
        self, sid: str, wait_s: float = 60.0, prompt_len: int = 0,
    ) -> Optional[ReplicaHandle]:
        """Resolve a session to its replica. Affinity first: a placed
        session ALWAYS goes where its KV/history lives. A placement on
        a draining replica waits for the blue/green absorb to move it
        (bounded), then follows the new placement; a mid-flight
        prefill->decode ship likewise blocks (bounded) until the
        handoff lands, then follows it; a placement on a dead replica
        triggers failover re-homing inline (the supervisor normally
        got there first)."""
        deadline = time.monotonic() + wait_s
        while True:
            with self._lock:
                rec = self._records.get(sid)
                rid = rec.rid if rec else None
                ship_ev = rec.ship_event if rec else None
            if rec is not None and ship_ev is not None:
                # disagg ship mid-flight (docs/disagg.md): the session
                # is between replicas — routing to either side now
                # could fork it. Wait for the handoff (the coordinator
                # bounds every stage), then follow the new placement.
                if not ship_ev.wait(
                    timeout=max(0.0, deadline - time.monotonic())
                ):
                    return None
                continue
            if rid is None:
                return self._pick_replica(prompt_len, fresh=True)
            if rid == "":
                # deferred re-home: a failover found no serving
                # sibling and parked the session's entry on the
                # record — adopt it into the replica we place on now
                handle = self._pick_replica()
                if handle is None:
                    return None
                with self._lock:
                    if rec.rid != "":
                        continue   # a concurrent route placed it
                    entry = rec.pending_entry
                    fp = rec.pending_fingerprint
                    rec.pending_entry = None
                    rec.pending_fingerprint = None
                    rec.rid = handle.rid
                self._journal_place(rec)
                if entry is not None:
                    # enqueued BEFORE the caller submits the turn, so
                    # the engine applies it ahead of admission
                    handle.engine.adopt_parked_session(
                        entry, fingerprint=fp, require_sha=False,
                    )
                return handle
            handle = self._handle(rid)
            if handle is None:
                with self._lock:
                    self._records.pop(sid, None)
                return self._pick_replica()
            if handle.is_serving():
                return handle
            if handle.state in ("draining", "drained"):
                # blue/green: the session is being absorbed by a
                # sibling — wait for the handoff instead of 503ing
                # (queen turns must survive a rolling deploy), then
                # loop to follow the updated placement
                if not handle.drained.wait(
                    timeout=max(0.0, deadline - time.monotonic())
                ):
                    return None
                with self._lock:
                    rec = self._records.get(sid)
                    if rec is not None and rec.rid == handle.rid:
                        # the handoff completed WITHOUT this session
                        # (a record can exist for a turn the replica
                        # shed before any engine session formed):
                        # nothing durable lives there — place fresh
                        # instead of spinning on the stale record
                        self._records.pop(sid, None)
                if time.monotonic() > deadline:
                    return None
                continue
            # dead and not yet re-homed: run the failover now (_bury
            # is idempotent — a concurrent supervisor pass may be
            # mid-re-home, so back off briefly instead of spinning on
            # the fleet lock it needs)
            self._bury(handle, "dead replica found at routing")
            time.sleep(0.01)
            if time.monotonic() > deadline:
                return None

    def _pick_replica(
        self, prompt_len: int = 0, fresh: bool = False,
    ) -> Optional[ReplicaHandle]:
        if self.disagg.enabled:
            # role-aware placement (docs/disagg.md): fresh long
            # prompts to prefill replicas, everything else prefers
            # decode/mixed
            return self.disagg.pick(prompt_len, fresh)
        cands = self._serving_replicas()
        if not cands:
            return None
        return max(cands, key=lambda h: h.health_score())

    def submit(
        self,
        prompt_tokens,
        *,
        session_id: Optional[str] = None,
        sampling: Optional[SamplingParams] = None,
        on_token: Optional[Callable[[int], None]] = None,
        stop_strings: Optional[list] = None,
        deadline_s: Optional[float] = None,
        priority: Optional[int] = None,
        turn_class: Optional[str] = None,
        placement_epoch: Optional[int] = None,
    ) -> Turn:
        """Queue a turn on the session's replica (KV affinity), or the
        healthiest replica for a fresh session. Same signature and
        Turn contract as ``ServingEngine.submit``; the priority class
        rides through to the replica's own EDF scheduler untouched.
        ``placement_epoch`` is the sharded-router fence: a submitter
        that resolved its room's shard under an older placement epoch
        (a healed router re-playing pre-failover traffic) is refused
        and must re-resolve — never silently re-routed."""
        sid = session_id or f"s{id(object())}-{time.monotonic_ns()}"
        # the scheduler's classifier, not a silent `or "worker"`: an
        # untagged turn carrying an explicit background priority stays
        # background through routing, shedding, and the replica's EDF
        turn_class = classify_turn(turn_class, priority)
        if self.lifecycle_phase == "draining":
            return self._shed_turn(
                sid, prompt_tokens, sampling, turn_class,
                "draining: engine is restarting; retry shortly",
                priority,
            )
        # sharded router tier (docs/podnet.md): refuse stale placement
        # epochs (the split-brain fence), and shed rooms whose shard is
        # dead with its lease still running — routing such a room
        # FRESH could pick a different replica than its live engine
        # session and fork its history; the shed costs a bounded retry
        # until a sibling adopts the shard's journal.
        if self.placement.stale_epoch(placement_epoch):
            self._bump("placement_refusals")
            return self._shed_turn(
                sid, prompt_tokens, sampling, turn_class,
                "stale placement epoch: the room's router shard "
                "moved; re-resolve placement and retry", priority,
            )
        if self._shards[self.placement.shard_of(sid)].state \
                != "serving":
            return self._shed_turn(
                sid, prompt_tokens, sampling, turn_class,
                "router shard down; sibling adoption pending — "
                "retry shortly", priority,
            )
        # router_io fault point: the placement lookup fails — bounded
        # retry, then shed cleanly. NEVER fall through to an arbitrary
        # replica: a misrouted session would prefill fresh and fork
        # its history.
        err: Optional[FaultError] = None
        for attempt in range(3):
            try:
                faults.maybe_fail("router_io")
                err = None
                break
            except FaultError as e:
                err = e
                self._bump("router_retries")
                if not e.transient:
                    break
                time.sleep(0.005 * (attempt + 1))
        if err is not None:
            return self._shed_turn(
                sid, prompt_tokens, sampling, turn_class,
                f"fleet router unavailable: {err}", priority,
            )
        while True:
            handle = self._route(sid, prompt_len=len(prompt_tokens))
            if handle is None:
                return self._shed_turn(
                    sid, prompt_tokens, sampling, turn_class,
                    "no healthy replica available; retry shortly",
                    priority,
                )
            with self._lock:
                rec = self._records.get(sid)
                if rec is not None and rec.ship_state is not None:
                    # a ship started in the routing window: loop back
                    # to _route, which waits the handoff out — a turn
                    # enqueued on the donor NOW would land after the
                    # export and fork a fresh session there
                    continue
                if rec is not None and rec.rid and \
                        rec.rid != handle.rid:
                    # the placement MOVED in the routing window (a
                    # ship that started AND landed, or a re-home):
                    # submitting to the stale handle would fork —
                    # re-resolve against the new placement
                    continue
                # TOCTOU vs a router-shard crash in the routing
                # window: the record was just swept — shed instead of
                # enqueueing a turn the adoption machinery can't see
                shard_down = self._shards[
                    self.placement.shard_of(sid)
                ].state != "serving"
                # bar the coordinator from STARTING a ship until this
                # turn is on the engine queue (where export_session's
                # in-flight check takes over)
                if rec is not None and not shard_down:
                    rec.routing += 1
                routing_rec = rec if not shard_down else None
            if shard_down:
                return self._shed_turn(
                    sid, prompt_tokens, sampling, turn_class,
                    "router shard down; sibling adoption pending — "
                    "retry shortly", priority,
                )
            break
        rec = self._record_for(sid, handle)
        wrapped = self._mirror_on_token(
            rec, list(prompt_tokens), on_token
        )
        try:
            turn = handle.engine.submit(
                prompt_tokens,
                session_id=sid,
                sampling=sampling,
                on_token=wrapped,
                stop_strings=stop_strings,
                deadline_s=deadline_s,
                priority=priority,
                turn_class=turn_class,
            )
            # the disagg coordinator ships a prefill-homed session at
            # this turn's completion (docs/disagg.md) — tracked ONLY
            # where a ship can actually fire, so mixed fleets and
            # decode-homed sessions never pin a Turn (with its prompt
            # list and callback closure) on the record
            if self.disagg.enabled and handle.role == "prefill":
                with self._lock:
                    rec.last_turn = turn
        finally:
            if routing_rec is not None:
                with self._lock:
                    routing_rec.routing -= 1
        # turnscope: record the placement on the turn's trace (the
        # engine created it inside submit)
        trace_mod.note_route(turn.trace, handle.rid)
        if not handle.is_serving() and not turn.done.is_set():
            # TOCTOU: the replica died between routing and the
            # enqueue — a turn parked on a dead engine's queue would
            # never be stepped OR failed, hanging its caller for the
            # full wait timeout. The engine skips done-set turns at
            # admission, so failing it here is race-safe; the caller
            # gets the fast shed/503 contract and retries onto the
            # re-homed session.
            turn.shed = True
            turn.error = "replica died during submit; retry shortly"
            turn.finish_reason = "error"
            trace_mod.finish(turn)
            turn.done.set()
            self._bump("router_shed")
        return turn

    def _record_for(
        self, sid: str, handle: ReplicaHandle
    ) -> _SessionRecord:
        with self._lock:
            rec = self._records.get(sid)
            placed = rec is None or rec.rid != handle.rid
            if rec is None:
                rec = _SessionRecord(sid=sid, rid=handle.rid)
                self._records[sid] = rec
            else:
                rec.rid = handle.rid
            rec.last_used = time.monotonic()
        if placed:
            self._journal_place(rec)
        return rec

    def _mirror_on_token(
        self, rec: _SessionRecord, prompt: list, cb,
    ) -> Callable[[int], None]:
        """Wrap a turn's on_token so the router mirror tracks exactly
        the durably-streamed tokens. The turn's prompt is booked at the
        FIRST streamed token: a turn that dies before streaming did
        nothing durable, so its retry against a re-homed session must
        behave as if the turn never ran."""
        state = {"booked": False}

        def wrapped(tok: int) -> None:
            appended: Optional[list] = None
            offset = 0
            # per-call resolution, not captured at wrap time: an
            # adoption may move rec to a sibling shard mid-stream, and
            # the crashed journal's dead handle drops (never forks)
            # the one append that can race the move
            journal = self._journal_for(rec)
            with rec.lock:
                added = 0
                if not rec.mirror_dropped:
                    # a cap-evicted record stops mirroring entirely:
                    # appending a partial suffix would be unusable for
                    # re-prefill AND unevictable — the exact unbounded
                    # growth the cap exists to stop
                    if not state["booked"]:
                        rec.tokens.extend(int(t) for t in prompt)
                        state["booked"] = True
                        added += len(prompt)
                    rec.tokens.append(int(tok))
                    added += 1
                    if journal is not None:
                        offset = len(rec.tokens) - added
                        appended = rec.tokens[-added:]
                rec.last_used = time.monotonic()
            if added:
                self._mirror_account(added)
            if appended is not None:
                # crash-durable mirror (docs/podnet.md): the journal
                # append happens BEFORE the caller's callback — at
                # batch=1 a token is journaled before anything
                # downstream treats it as durably streamed
                journal.append_tokens(rec.sid, appended, offset)
            if cb is not None:
                cb(tok)

        return wrapped

    # ---- bounded history mirror (ROOM_TPU_FLEET_MIRROR_TOKENS) ----

    def _mirror_account(self, delta: int) -> None:
        """Track the fleet-wide mirror footprint; past the cap, LRU
        records drop their mirrors. The hot path pays one small-lock
        increment; the eviction sweep runs only on crossings, and is
        rate-limited so a corner where nothing is evictable (every
        surviving mirror mid-ship or deferred) cannot turn every
        streamed token into a fleet-lock sort."""
        with self._mirror_lock:
            self._mirror_tokens += delta
            over = self.mirror_cap_tokens > 0 and \
                self._mirror_tokens > self.mirror_cap_tokens
            if not (over and delta > 0):
                return
            now = time.monotonic()
            if self._mirror_sweep_futile and \
                    now - self._mirror_sweep_at < 0.2:
                return
            self._mirror_sweep_at = now
        self._mirror_sweep_futile = self._evict_mirrors() == 0

    def _evict_mirrors(self) -> int:
        """Drop least-recently-used records' token mirrors until the
        fleet fits its cap again. A dropped mirror costs failover
        warmth for that session (warm salvage still works; the
        re-prefill fallback does not — `mirror_dropped` stops further
        appends, so an evicted record never accumulates an unusable,
        unevictable partial suffix), never correctness of the live
        placement. Returns mirrors dropped."""
        with self._lock:
            recs = sorted(
                (r for r in self._records.values()
                 if r.tokens and not r.mirror_dropped
                 and r.ship_state is None and r.pending_entry is None),
                key=lambda r: r.last_used,
            )
        evicted = 0
        for rec in recs:
            with self._mirror_lock:
                if self.mirror_cap_tokens <= 0 or \
                        self._mirror_tokens <= self.mirror_cap_tokens:
                    return evicted
            with rec.lock:
                dropped = len(rec.tokens)
                rec.tokens = []
                rec.mirror_dropped = True
            if dropped:
                evicted += 1
                with self._mirror_lock:
                    self._mirror_tokens -= dropped
                journal = self._journal_for(rec)
                if journal is not None:
                    # the journal must stop claiming this mirror: a
                    # router crash replaying the evicted PREFIX as a
                    # complete history would fork the session the
                    # warm-salvage-only rule protects. A TOMBSTONE,
                    # not a rel — an in-flight token append racing
                    # this eviction must not resurrect the prefix
                    journal.record_drop(rec.sid)
                self._bump("mirror_evictions")
                self._bump("mirror_tokens_evicted", dropped)
        return evicted

    def _mirror_release(self, rec: _SessionRecord) -> None:
        with rec.lock:
            n = len(rec.tokens)
            rec.tokens = []
            # a turn may still be streaming into this (released/
            # replaced) record's callback: mark it dropped so the
            # orphaned closure stops booking tokens nobody will ever
            # release from the fleet-wide counter
            rec.mirror_dropped = True
        if n:
            with self._mirror_lock:
                self._mirror_tokens -= n

    def _set_record_tokens(
        self, rec: _SessionRecord, toks: list
    ) -> None:
        """Replace a record's mirror (absorb/re-home paths) with cap
        accounting."""
        with rec.lock:
            old = len(rec.tokens)
            rec.tokens = toks
            rec.mirror_dropped = False
        with self._mirror_lock:
            self._mirror_tokens += len(toks) - old

    # ---- pod fencing + crash-durable mirror (docs/podnet.md) ----

    def fence_stale(self, sid: str, fence) -> bool:
        """Is ``fence`` older than the session's current ownership
        generation? A frame/export carrying no fence predates fencing
        and passes (the in-transit checksum and fingerprint gates
        still apply); an unknown session has no generation to be
        stale against."""
        if fence is None:
            return False
        try:
            fence = int(fence)
        except (TypeError, ValueError):
            return True
        with self._lock:
            rec = self._records.get(sid)
            return rec is not None and fence < rec.fence

    def note_fence_refusal(self, sid: str, fence, origin: str) -> None:
        """The bookkeeping every stale-fence refusal owes, wherever
        the staleness was detected: counted in ``fence_refusals`` and
        booked in the flight recorder."""
        self._bump("fence_refusals")
        trace_mod.note_event("fence_refused", {
            "session": sid, "fence": fence, "origin": origin,
        })
        log.warning(
            "fleet %s: refused stale-fence %s from %s for session %s",
            self.model_name, fence, origin, sid,
        )

    def refuse_stale_fence(self, sid: str, fence, origin: str) -> bool:
        """fence_stale + the refusal bookkeeping."""
        if not self.fence_stale(sid, fence):
            return False
        self.note_fence_refusal(sid, fence, origin)
        return True

    def _journal_for(
        self, rec: _SessionRecord
    ) -> Optional[podnet_mod.MirrorJournal]:
        """The journal owning ``rec``'s shard. Lock-free: the shard
        list has fixed length, ``rec.shard`` only moves under the
        fleet lock at adoption, and an append that races the move
        lands in the crashed journal's dead handle (dropped, counted,
        never forked)."""
        try:
            return self._shards[rec.shard].journal
        except IndexError:
            return None

    def _journal_place(self, rec: _SessionRecord) -> None:
        journal = self._journal_for(rec)
        if journal is not None:
            journal.record_place(
                rec.sid, rec.rid, rec.fence, rec.generation
            )

    def _mirror_snapshot_sessions(
        self, shard_id: Optional[int] = None,
    ) -> list[dict]:
        """Authoritative record view for a journal compaction (tokens
        copied under each record's own lock, never nested inside the
        fleet lock). ``shard_id`` scopes the snapshot to one router
        shard's records — each shard's journal compacts against ITS
        rooms only; None (the pre-shard surface) snapshots them
        all."""
        with self._lock:
            recs = list(
                self._records.values() if shard_id is None
                else self._shards[shard_id].records.values()
            )
        out = []
        for rec in recs:
            with rec.lock:
                toks = list(rec.tokens) if not rec.mirror_dropped \
                    else []
            with self._lock:
                if self._records.get(rec.sid) is not rec:
                    continue
                out.append({
                    "sid": rec.sid, "rid": rec.rid,
                    "fence": rec.fence, "gen": rec.generation,
                    "tokens": toks,
                })
        return out

    def _replay_mirror_journals(self) -> None:
        """Router-restart recovery: rebuild placements + mirrors from
        every journal source under the model's router-mirror dir —
        the flat dir (a previous single-shard incarnation) plus every
        ``shard-*`` subdir (a previous sharded incarnation, ANY shard
        count: a session whose old shard no longer exists re-homes
        onto its hash-current shard, so an N->M change absorbs every
        journal). Every complete session re-parks exactly like a
        deferred re-home (rid="" + pending entry), so its next route
        adopts it into whichever replica serves — the placement the
        journal names may not exist in this incarnation. Incomplete
        mirrors (a hole from a dropped journal line) are NOT resumed:
        re-prefilling a holey history would fork the session. Sessions
        that cross journals re-log into their current shard (place +
        tokens) and release out of the source, so a SECOND restart
        replays one authoritative copy; sources no current shard owns
        are consumed outright."""
        root = os.path.join(
            lifecycle_mod.engine_dir(self.model_name), "router-mirror",
        )
        current = {
            s.journal.dir: s for s in self._shards
            if s.journal is not None
        }
        sources = [root]
        try:
            for name in sorted(os.listdir(root)):
                if name.startswith("shard-") and \
                        os.path.isdir(os.path.join(root, name)):
                    sources.append(os.path.join(root, name))
        except OSError:
            pass
        restored = 0
        for src in sources:
            src_journal = getattr(current.get(src), "journal", None)
            try:
                # a current shard's own sidecar replays through its
                # live journal (stats accounting: replayed_sessions /
                # replay_incomplete); orphaned sources read raw
                state_map = (
                    src_journal.replay() if src_journal is not None
                    else podnet_mod.replay_journal_dir(src)
                )
            except Exception:
                state_map = {}
            for sid, state in state_map.items():
                toks = state.get("tokens") or []
                if state.get("dropped") or \
                        not state.get("complete") or not toks:
                    continue
                with self._lock:
                    known = sid in self._records
                if known:
                    continue
                rec = _SessionRecord(sid=sid, rid="")
                rec.generation = int(state.get("generation") or 0)
                self._set_record_tokens(rec, [int(t) for t in toks])
                # ONE mirror->entry shape for failover and replay; the
                # NEXT ownership transfer (the adopting route) must
                # supersede anything the pre-crash incarnation exported
                fence = int(state.get("fence") or 0) + 1
                entry = self._entry_from_mirror(rec)
                if entry is None:
                    self._mirror_release(rec)
                    continue
                entry["fence"] = fence
                with self._lock:
                    rec.fence = fence
                    rec.pending_entry = entry
                    rec.pending_fingerprint = None
                    self._records[sid] = rec
                self._journal_place(rec)
                journal = self._journal_for(rec)
                if journal is not None and journal.dir != src:
                    # crossed journals (shard-count change, or the
                    # flat pre-shard dir): the current shard's journal
                    # becomes the one authoritative copy
                    journal.append_tokens(sid, list(toks), 0)
                    journal.flush(sid)
                    if src_journal is not None:
                        src_journal.record_release(sid)
                restored += 1
        for src in sources:
            if src not in current:
                podnet_mod.consume_journal_dir(src)
        if restored:
            self._bump("mirror_restored", restored)
            trace_mod.note_event("mirror_restore", {
                "sessions": restored,
            })
            log.info(
                "fleet %s: mirror journal re-parked %d in-flight "
                "session(s) after router restart",
                self.model_name, restored,
            )

    def release_session(self, session_id: str) -> None:
        with self._lock:
            rec = self._records.pop(session_id, None)
            if rec is not None and rec.ship_event is not None:
                # a released session's ship is moot: unblock any
                # waiter; the coordinator's liveness re-checks see the
                # popped record and discard the exported entry instead
                # of adopting a ghost
                rec.ship_event.set()
        if rec is not None:
            self._mirror_release(rec)
            handle = self._handle(rec.rid)
            targets = [handle] if handle is not None else []
        else:
            targets = list(self.replicas)
        if rec is not None:
            journal = self._journal_for(rec)
            if journal is not None:
                journal.record_release(session_id)
        for h in targets:
            if h.state != "dead":
                h.engine.release_session(session_id)

    # ---- supervision / failover ----

    def serve_forever(
        self, stop_event: threading.Event, idle_sleep: Optional[float] = None,
    ) -> None:
        """The fleet's background loop (what ModelHost's engine thread
        runs): start every replica's serve thread, then supervise —
        detect dead replicas, re-home their sessions, rebuild under the
        strike budget."""
        self.start_threads()
        tick = idle_sleep if idle_sleep is not None else \
            max(0.05, self.tick_s)
        try:
            while not stop_event.wait(tick):
                self.supervise()
        finally:
            for h in self.replicas:
                h.stop.set()

    def start_threads(self) -> None:
        self._threads_started = True
        for h in self.replicas:
            if h.state == "serving":
                h.start_thread()

    def supervise(self) -> None:
        """One supervision pass: fire the ``replica_crash`` chaos
        fault (kills the busiest serving replica), bury replicas whose
        engine went unhealthy or whose thread died un-asked, restart
        threads that merely exited, rebuild dead replicas under the
        strike budget."""
        spec = faults.should_fire("replica_crash")
        if spec is not None:
            victim = self._pick_crash_victim()
            if victim is not None:
                self.kill_replica(
                    victim.rid, reason="injected replica_crash"
                )
        # router_shard_crash chaos (docs/podnet.md): kill the busiest
        # serving router shard — the worst case for adoption — when a
        # sibling exists to adopt it
        spec = faults.should_fire("router_shard_crash")
        if spec is not None:
            with self._lock:
                shards = [
                    s for s in self._shards if s.state == "serving"
                ]
            if len(shards) >= 2:
                victim_shard = max(
                    shards, key=lambda s: len(s.records)
                )
                self.kill_router_shard(
                    victim_shard.shard_id,
                    reason="injected router_shard_crash",
                )
        # heartbeat-driven shard leases: every serving shard beats into
        # the membership detector each supervise tick; a dead shard
        # goes silent, and the detector's suspect->dead->lease-expired
        # verdict (not the killer's timestamp) gates adoption below
        if self._shard_membership is not None:
            for s in self._shards:
                if s.state == "serving":
                    self._shard_membership.observe(
                        f"shard-{s.shard_id}"
                    )
            self._shard_membership.tick()
            self._shard_leases_fired.update(
                int(mid.rsplit("-", 1)[1])
                for mid in self._shard_membership.lease_expired()
            )
        self._adopt_dead_shards()
        # disaggregated prefill->decode ships fire at turn boundaries
        # noticed here (docs/disagg.md); inert without roles
        self.disagg.advance()
        # pod membership: heartbeats + lease-expiry re-homes
        # (docs/podnet.md); inert without ROOM_TPU_POD_MEMBERSHIP
        self.pod.tick()
        for shard in self._shards:
            journal = shard.journal
            if journal is None or shard.state != "serving":
                continue
            # push any batched token appends to disk each tick, and
            # compact each shard's journal once it outgrows its
            # threshold — the CALLABLE form: the journal parks
            # concurrent appends before the snapshot is built, so
            # none can be lost to the file swap. The snapshot is
            # scoped to the SHARD's records: compacting against the
            # whole fleet would resurrect siblings' rooms here.
            journal.flush_all()
            if journal.should_compact():
                journal.compact(
                    lambda k=shard.shard_id:
                        self._mirror_snapshot_sessions(k)
                )
        for h in list(self.replicas):
            if h.state != "serving":
                continue
            if not getattr(h.engine, "healthy", True):
                self._bury(h, "engine crash-restart budget exhausted")
                continue
            if h.thread is not None and not h.thread.is_alive() and \
                    not h.stop.is_set():
                # the loop thread died but the engine is serviceable:
                # supervised restart (same contract ModelHost gave a
                # single engine)
                h.start_thread()
        for h in list(self.replicas):
            # a re-home deferred on a wedged serve thread completes
            # the moment the thread actually exits
            if h.state == "dead" and not h.rehomed_done and (
                h.thread is None or not h.thread.is_alive()
            ):
                self._finish_rehome(h)
        if self.auto_rebuild:
            for h in list(self.replicas):
                if h.state == "dead" and h.strikes <= self.max_strikes:
                    self.rebuild_replica(h.rid)
        # system-invariant witness (docs/chaosfuzz.md): the supervise
        # tick is the fleet's quiescent seam — fences, ownership,
        # mirror-buffer contiguity, and thread leaks are probed here
        if invariants_mod.enabled():
            invariants_mod.probe_fleet(self)

    def _pick_crash_victim(self) -> Optional[ReplicaHandle]:
        cands = self._serving_replicas()
        if not cands:
            return None
        # the busiest replica: the worst case a chaos test wants
        return min(cands, key=lambda h: h.health_score())

    def kill_replica(self, rid: str, reason: str = "killed") -> bool:
        """Hard-kill a replica (chaos / ops): stop its thread, mark
        the engine dead, and re-home its sessions. Models a crash past
        the restart budget — the in-flight window is dropped, never
        flushed."""
        h = self._handle(rid)
        if h is None or h.state in ("dead",):
            return False
        h.stop.set()
        if h.thread is not None:
            h.thread.join(timeout=30.0)
        h.engine.healthy = False
        self._bury(h, reason)
        return True

    # ---- sharded router tier: shard crash + journal adoption ----

    def kill_router_shard(
        self, shard_id: int, reason: str = "killed"
    ) -> bool:
        """Chaos/ops: kill one ROUTER shard — not an engine replica.
        Its in-memory records vanish (exactly what a router process
        death loses), its journal handle crashes (buffered tokens
        lost, on-disk files kept for the adopter), and its rooms shed
        at submit until a sibling adopts the journal past the lease.
        Engine KV is untouched — the shard's rooms keep their live
        engine sessions and resume token-identically after adoption.
        Refused for a single-shard router (nobody left to adopt)."""
        if self.n_router_shards < 2:
            return False
        try:
            shard = self._shards[shard_id]
        except IndexError:
            return False
        orphaned: list = []
        with self._lock:
            if shard.state != "serving":
                return False
            shard.state = "dead"
            shard.died_at = time.monotonic()
            recs = list(shard.records.values())
            shard.records.clear()
            # a ship mid-flight for a dying shard's room is moot: the
            # adoption replay owns the session's future — drain it
            # through the coordinator so waiters unblock and a
            # completed export's spool is discarded, not leaked
            for rec in recs:
                entry = self.disagg.abort_ship_locked(rec)
                if entry is not None:
                    orphaned.append(entry)
        self._bump("router_shard_crashes")
        for entry in orphaned:
            self.disagg._discard_entry(entry)
        for rec in recs:
            # releases the cap accounting AND marks the records
            # dropped, so orphaned on_token closures of still-running
            # turns stop booking tokens into dead state
            self._mirror_release(rec)
        if shard.journal is not None:
            shard.journal.crash()
        trace_mod.note_event("router_shard_crash", {
            "shard": shard_id, "rooms": len(recs), "reason": reason,
        })
        log.warning(
            "fleet %s: router shard %d died (%s); %d room(s) shed "
            "until a sibling adopts its journal",
            self.model_name, shard_id, reason, len(recs),
        )
        return True

    def _adopt_dead_shards(self) -> None:
        """Drive journal adoption for every dead shard whose lease
        (``ROOM_TPU_ROUTER_LEASE_S``) has expired. The lease is the
        fencing dance's timing half: in-process the crash seam already
        closed the journal, but the state machine must stay honest for
        the cross-process deploy where 'dead' is a heartbeat verdict —
        adopting a journal a slow owner could still append to would
        split ownership.

        With ``ROOM_TPU_ROUTER_SHARD_HEARTBEATS`` the timing half is
        the membership detector's instead: a shard is adoptable only
        once its member's lease has *fired* (heartbeat silence ran the
        whole suspect -> dead -> lease course), never on the killer's
        own clock."""
        now = time.monotonic()
        with self._lock:
            if self._shard_membership is not None:
                dead = [
                    s for s in self._shards
                    if s.state == "dead"
                    and s.shard_id in self._shard_leases_fired
                ]
            else:
                dead = [
                    s for s in self._shards
                    if s.state == "dead"
                    and now - s.died_at >= self.router_lease_s
                ]
            serving = [
                s for s in self._shards if s.state == "serving"
            ]
        if not serving:
            return
        for shard in dead:
            adopter = min(serving, key=lambda s: len(s.records))
            self._adopt_shard_journal(shard, adopter)
            self._shard_leases_fired.discard(shard.shard_id)

    def _adopt_shard_journal(
        self, dead: _RouterShard, adopter: _RouterShard,
    ) -> None:
        """Replay a dead shard's on-disk journal into ``adopter``:
        fences mint +1 (anything the dead incarnation exported is
        stale from here), offset holes and tombstones degrade exactly
        as the journal contract says, and the placement map re-homes
        the dead shard's rooms under a NEW epoch published to pod
        peers — stale-epoch submits are refused from that instant.

        A room whose engine session is still live adopts WARM-ONLY
        (``mirror_dropped``): tokens its in-flight turn streamed after
        the shard died were never journaled, so the journal's mirror
        may be a stale prefix — restoring it would hand a later
        re-prefill a forked history. The live engine session itself is
        the token-exact resume path. Only a room whose engine side is
        gone too (the double failure) re-parks its journal mirror as a
        deferred re-home entry."""
        state_map: dict = {}
        if dead.journal is not None:
            try:
                state_map = podnet_mod.replay_journal_dir(
                    dead.journal.dir
                )
            except Exception:
                state_map = {}
        adopted = 0
        for sid, state in state_map.items():
            toks = [int(t) for t in state.get("tokens") or []]
            complete = bool(state.get("complete")) and bool(toks)
            dropped = bool(state.get("dropped"))
            handle = self._handle(str(state.get("rid") or ""))
            engine_live = (
                handle is not None and handle.state != "dead"
                and sid in handle.engine.sessions
            )
            fence = int(state.get("fence") or 0) + 1
            rec = _SessionRecord(sid=sid, rid="")
            rec.generation = int(state.get("generation") or 0)
            entry = None
            if engine_live:
                # affinity survives; the mirror does not (see above)
                with rec.lock:
                    rec.mirror_dropped = True
            elif complete and not dropped:
                self._set_record_tokens(rec, toks)
                entry = self._entry_from_mirror(rec)
                if entry is None:
                    self._mirror_release(rec)
                    continue
                entry["fence"] = fence
            else:
                # tombstoned or holey with no live engine session:
                # nothing durable survives — the room starts cold
                continue
            superseded = False
            with self._lock:
                if self._records.get(sid) is not None:
                    # a salvage re-home minted a newer record while
                    # the lease ran; it wins
                    superseded = True
                else:
                    rec.fence = fence
                    rec.shard = adopter.shard_id
                    if engine_live:
                        rec.rid = handle.rid
                    else:
                        rec.pending_entry = entry
                        rec.pending_fingerprint = None
                    adopter.records[sid] = rec
            if superseded:
                self._mirror_release(rec)
                continue
            self._journal_place(rec)
            journal = adopter.journal
            if journal is not None:
                if rec.mirror_dropped:
                    journal.record_drop(sid)
                elif rec.tokens:
                    journal.append_tokens(sid, list(rec.tokens), 0)
                    journal.flush(sid)
            adopted += 1
        with self._lock:
            dead.state = "retired"
            adopter.adoptions += 1
        self._bump("router_shard_adoptions")
        self._bump("sessions_adopted", adopted)
        epoch = self.placement.rehome(
            dead.shard_id, adopter.shard_id
        )
        self.pod.publish_placement()
        trace_mod.note_event("router_shard_adopt", {
            "shard": dead.shard_id, "adopter": adopter.shard_id,
            "sessions": adopted, "epoch": epoch,
        })
        log.warning(
            "fleet %s: router shard %d adopted shard %d's journal "
            "(%d session(s), placement epoch %d)",
            self.model_name, adopter.shard_id, dead.shard_id,
            adopted, epoch,
        )

    def _bury(self, h: ReplicaHandle, reason: str) -> None:
        """Mark a replica dead and re-home everything it held. A
        WEDGED serve thread (kill join timed out) defers the re-home:
        the thread could still be streaming into the session mirrors,
        and a snapshot taken now would fork mid-stream — supervise()
        finishes the job once the thread actually dies (affinity turns
        shed 503 in the meantime)."""
        with self._lock:
            if h.state == "dead":
                return
            h.state = "dead"
            h.strikes += 1
            h.rehomed_done = False
        self._bump("failovers")
        log.warning(
            "fleet %s: replica %s died (%s); re-homing sessions",
            self.model_name, h.rid, reason,
        )
        if h.thread is not None and h.thread.is_alive():
            log.warning(
                "fleet %s: replica %s serve thread still alive; "
                "deferring re-home until it exits",
                self.model_name, h.rid,
            )
            return
        self._finish_rehome(h)

    def _finish_rehome(self, h: ReplicaHandle) -> None:
        try:
            self._rehome_all(h)
        except Exception:
            log.exception(
                "fleet %s: re-homing from %s failed",
                self.model_name, h.rid,
            )
        h.rehomed_done = True

    def _rehome_all(self, h: ReplicaHandle) -> None:
        eng = h.engine
        # 1) what the dying engine preserved: its fatal-crash salvage
        #    (set by _recover_from_crash), or — for a hard kill that
        #    bypassed the crash path — collect it now from the intact
        #    engine object (thread confirmed dead, so host state is
        #    quiescent)
        salvage: dict = getattr(eng, "crash_salvage", None) or {}
        thread_dead = h.thread is None or not h.thread.is_alive()
        if not salvage and thread_dead:
            try:
                salvage = self._salvage_from_engine(eng)
            except Exception:
                salvage = {}
        # 2) fail whatever turns the dead replica still holds, so no
        #    caller hangs on done.wait() (the engine's own crash path
        #    already did this; the hard-kill path did not)
        if thread_dead:
            self._fail_engine_turns(
                eng, "replica crashed; session re-homed — retry shortly"
            )
        # 3) re-home every session the router placed on this replica:
        #    warm via salvaged spool files, mirror re-prefill otherwise
        orphaned_entries: list = []
        with self._lock:
            recs = [
                r for r in self._records.values() if r.rid == h.rid
            ]
            # abort any disagg ship touching the dead replica: the
            # failover below owns these sessions now (waiters on the
            # ship event re-route against the re-homed placement).
            # Routed through the coordinator so its in-flight tracking
            # drains and a completed export's detached spool is
            # discarded, not leaked.
            for r in recs:
                entry = self.disagg.abort_ship_locked(r)
                if entry is not None:
                    orphaned_entries.append(entry)
        for entry in orphaned_entries:
            self.disagg._discard_entry(entry)
        pending: list[tuple] = []
        for rec in recs:
            entry = salvage.pop(rec.sid, None)
            if entry is None:
                entry = self._entry_from_mirror(rec)
            self._rehome_entry(
                rec, entry, exclude=h.rid, pending=pending
            )
        # sessions the engine knew but the router never placed (e.g.
        # restored-then-never-touched): still re-home from salvage
        for sid, entry in list(salvage.items()):
            with self._lock:
                known = sid in self._records
            if known:
                continue
            rec = _SessionRecord(sid=sid, rid=h.rid)
            toks = list(entry.get("history") or [])
            if entry.get("pending") is not None:
                toks.append(int(entry["pending"]))
            self._set_record_tokens(rec, toks)
            rec.generation = int(entry.get("generation") or 0)
            with self._lock:
                self._records[sid] = rec
            self._rehome_entry(
                rec, entry, exclude=h.rid, pending=pending
            )
        deadline = time.monotonic() + 10.0
        for rec, entry, target, ev in pending:
            ev.wait(timeout=max(0.0, deadline - time.monotonic()))
            # warm is an OUTCOME, not an intent: only count it when
            # the sibling's store actually holds the adopted entry (a
            # disk-cap refusal or bad spool degraded to re-prefill)
            store = getattr(target.engine, "offload_store", None)
            warm = entry.get("kv") is not None and \
                store is not None and store.has(rec.sid)
            self._bump(
                "sessions_rehomed_warm" if warm
                else "sessions_rehomed_reprefill"
            )

    def _entry_from_mirror(
        self, rec: _SessionRecord
    ) -> Optional[dict]:
        with rec.lock:
            toks = list(rec.tokens)
            generation = rec.generation
            dropped = rec.mirror_dropped
        if not toks or dropped:
            # a cap-evicted mirror's later appends are a SUFFIX of the
            # history — re-prefilling from them would fork the session
            return None
        # the mirror's last streamed token re-enters as the pending
        # token — exactly the park contract, so the resumed stream
        # continues where the durable stream stopped
        return {
            "id": rec.sid,
            "history": toks[:-1],
            "pending": toks[-1],
            "length": len(toks) - 1,
            "generation": generation,
            "kv": None,
        }

    def _rehome_entry(
        self,
        rec: _SessionRecord,
        entry: Optional[dict],
        exclude: Optional[str],
        pending: list,
    ) -> None:
        if entry is None:
            # nothing durable ever happened on this session (or its
            # mirror was cap-evicted with no warm salvage): drop the
            # placement; its next turn starts fresh wherever the
            # router puts it
            with self._lock:
                self._records.pop(rec.sid, None)
            self._mirror_release(rec)
            return
        target = self._next_target(exclude)
        if target is None:
            # no sibling to absorb it RIGHT NOW (e.g. the only other
            # replica is mid-drain): keep the record, park the entry
            # on it, and mark it unplaced — the next _route for this
            # session adopts the entry into whatever replica serves
            # by then, so the history is never silently dropped
            with self._lock:
                rec.rid = ""
                rec.fence += 1
                entry["fence"] = rec.fence
                rec.pending_entry = entry
            self._journal_place(rec)
            trace_mod.note_event("rehome_deferred", {
                "session": rec.sid, "from": exclude or "",
            })
            return
        # fencing (docs/podnet.md): ownership leaves the dead replica
        # NOW — anything it exported under the old generation (a host
        # healing from a partition replaying its ship) is stale from
        # this point and will be refused
        with self._lock:
            rec.fence += 1
            entry["fence"] = rec.fence
        ev = target.engine.adopt_parked_session(
            entry, fingerprint=None, require_sha=False,
        )
        pending.append((rec, entry, target, ev))
        with self._lock:
            rec.rid = target.rid
            rec.rehomed += 1
        self._journal_place(rec)
        self._bump("sessions_rehomed")
        # turnscope: failover re-homes land in the flight recorder's
        # global event ring — the trace answer to "why did this
        # session's next TTFT spike" (docs/observability.md)
        trace_mod.note_event("rehome", {
            "session": rec.sid, "from": exclude or "",
            "to": target.rid, "warm": entry.get("kv") is not None,
        })

    def _next_target(
        self, exclude: Optional[str]
    ) -> Optional[ReplicaHandle]:
        """Round-robin over serving siblings so a dead replica's
        sessions spread instead of piling onto one survivor."""
        cands = self._serving_replicas(exclude=exclude)
        if not cands:
            return None
        with self._lock:
            self._rr += 1
            return cands[self._rr % len(cands)]

    def _salvage_from_engine(self, eng) -> dict:
        """Hard-kill salvage: the engine object is intact and its
        thread is dead — collect the same parked-session entries the
        fatal-crash path preserves."""
        try:
            return eng._collect_crash_salvage()
        except Exception:
            return {}

    def _fail_engine_turns(self, eng, msg: str) -> None:
        """Fail every turn a dead replica still holds. Safe only with
        the replica's serve thread confirmed dead; claims loop-thread
        ownership (the drain() pattern) so a racing release_session
        defers to the command queue instead of mutating under us."""
        with eng._lock:
            eng._loop_thread = threading.current_thread()
        try:
            for i, turn in enumerate(eng._active):
                if turn is not None and not turn.done.is_set():
                    turn.shed = True
                    eng._fail_turn_unslotted(turn, msg)
                eng._active[i] = None
            eng._fail_all_pending(msg, shed=True)
        except Exception:
            pass
        finally:
            with eng._lock:
                eng._loop_thread = None

    def rebuild_replica(self, rid: str) -> bool:
        """Swap a fresh engine into a dead or drained slot (the
        blue/green re-admit, and the supervisor's crash rebuild)."""
        h = self._handle(rid)
        if h is None or h.state == "serving":
            return False
        if h.state == "dead" and h.strikes > self.max_strikes:
            return False
        if h.state == "dead" and not h.rehomed_done:
            # the old engine still owes its sessions a (deferred)
            # re-home — discarding it now would orphan them
            return False
        try:
            engine = self._build_engine(h.index)
        except Exception:
            log.exception(
                "fleet %s: rebuild of %s failed", self.model_name, rid,
            )
            return False
        h.engine = engine
        h.engine.fleet_supervised = True
        h.thread = None
        h.stop = threading.Event()
        h.drained = threading.Event()
        h.state = "serving"
        self._bump("replica_rebuilds")
        if self._threads_started:
            h.start_thread()
        return True

    # ---- blue/green ----

    def drain_replica(
        self, rid: str, deadline_s: Optional[float] = None,
    ) -> dict:
        """The blue/green primitive: quiesce one replica (in-flight
        turns finish streaming — no 503s), drain it to a handoff
        manifest, absorb its sessions into the siblings. Affinity
        routing blocks (bounded) rather than sheds while this runs, so
        a rolling deploy is invisible to queen-class turns. Call
        ``rebuild_replica`` afterwards to swap in the new build."""
        h = self._handle(rid)
        if h is None or h.state != "serving":
            return {"error": f"replica {rid!r} not serving"}
        if len(self._serving_replicas(exclude=rid)) == 0:
            return {"error": "no sibling to absorb sessions; refusing "
                             "to drain the last serving replica"}
        if deadline_s is None:
            deadline_s = lifecycle_mod.drain_deadline_s()
        t0 = time.monotonic()
        deadline = t0 + max(deadline_s, 1.0)
        self._bump("bluegreen_drains")
        h.state = "draining"
        h.drained.clear()
        eng = h.engine
        try:
            # quiesce: new turns already route elsewhere (or wait on
            # the handoff); let admitted work finish streaming
            threaded = h.thread is not None and h.thread.is_alive()
            while time.monotonic() < deadline - (deadline - t0) * 0.3:
                busy = (
                    any(t is not None for t in eng._active)
                    or not eng._queue.empty()
                    or eng._inflight is not None
                    or bool(eng._staged_chunks)
                )
                if not busy:
                    break
                if threaded:
                    time.sleep(0.005)
                else:
                    try:
                        eng.step()
                    except Exception as e:
                        # same supervision contract as run_until_idle:
                        # a crashed step inside the quiesce fails its
                        # work cleanly; past budget the drain proceeds
                        # history-only on the unhealthy engine
                        if not eng._recover_from_crash(e):
                            break
            h.stop.set()
            if h.thread is not None:
                h.thread.join(
                    timeout=max(0.0, deadline - time.monotonic())
                )
            wedged = h.thread is not None and h.thread.is_alive()
            handoff = os.path.join(
                lifecycle_mod.engine_dir(self.model_name),
                f"bluegreen-{h.rid}",
            )
            summary = eng.drain(
                handoff,
                deadline_s=max(0.0, deadline - time.monotonic()),
                flush=not wedged,
            )
            absorbed = self._absorb_manifest(handoff, exclude=h.rid)
        except Exception as e:
            # a drain that died must not strand the replica in
            # 'draining' with submitters parked on the handoff event
            # forever: bury it — the crash path re-homes whatever the
            # engine salvage + router mirror still cover
            log.exception(
                "fleet %s: blue/green drain of %s failed; falling "
                "back to crash failover", self.model_name, rid,
            )
            eng.healthy = False
            self._bury(h, f"drain failed: {e}")
            h.drained.set()
            return {"error": f"drain failed: {e}", "rid": rid}
        h.state = "drained"
        h.drained.set()
        log.info(
            "fleet %s: blue/green drained %s (%s absorbed warm, %s "
            "re-prefill)", self.model_name, rid,
            absorbed.get("resumed", 0), absorbed.get("reprefill", 0),
        )
        return {**summary, "absorbed": absorbed}

    def _absorb_manifest(
        self, dir_path: str, exclude: Optional[str] = None,
    ) -> dict:
        """Distribute a drain manifest's sessions across the serving
        replicas (blue/green absorb; also the per-subdir worker of the
        boot-time restore). Consumes the manifest and sweeps what it
        no longer protects, mirroring ``restore_from_manifest``."""
        out = {"resumed": 0, "reprefill": 0, "skipped": 0,
               "deferred": 0, "manifest": False}
        manifest = lifecycle_mod.read_manifest(dir_path)
        if manifest is None:
            lifecycle_mod.sweep_orphans(dir_path)
            return out
        out["manifest"] = True
        version_ok = manifest.get("version") == \
            lifecycle_mod.MANIFEST_VERSION
        # NEVER pass fingerprint=None here: None means "the caller
        # vouches for config identity" to adopt_parked_session, and a
        # manifest MISSING its fingerprint is exactly the stale/legacy
        # case the check exists for — a sentinel that can't equal any
        # real fingerprint degrades those entries to re-prefill
        fingerprint = (
            manifest.get("fingerprint") or {"fingerprint": "missing"}
        ) if version_ok else {"version": "mismatch"}
        pending: list[tuple[_SessionRecord, dict,
                            ReplicaHandle, threading.Event]] = []
        # COLDEST first (same guard as engine._restore_dir): adoption
        # time is last_used, so when the manifest's bytes overflow the
        # absorbing stores' disk caps, the rebalance must evict the
        # coldest sessions — iterating the warmest-first manifest in
        # order would invert the drain's priority
        deferred_keep: set[str] = set()
        for entry in reversed(manifest.get("sessions", [])):
            if not isinstance(entry, dict) or not entry.get("id"):
                out["skipped"] += 1
                continue
            sid = str(entry["id"])
            target = self._next_target(exclude)
            if target is None:
                # no serving sibling RIGHT NOW (e.g. the only one
                # crashed mid-absorb): the manifest below gets
                # consumed, so this entry must not be dropped — park
                # it on the router record (absolute spool path; the
                # sweep keeps the file) and the next _route adopts it
                # into whatever replica serves by then
                entry = dict(entry)
                kv = entry.get("kv")
                if isinstance(kv, dict) and kv.get("file"):
                    fname = os.path.basename(str(kv["file"]))
                    kv = dict(kv)
                    kv["file"] = os.path.join(dir_path, fname)
                    entry["kv"] = kv
                    deferred_keep.add(fname)
                rec = _SessionRecord(sid=sid, rid="")
                toks = [int(t) for t in entry.get("history") or []]
                if entry.get("pending") is not None:
                    toks.append(int(entry["pending"]))
                self._set_record_tokens(rec, toks)
                rec.generation = int(entry.get("generation") or 0)
                with self._lock:
                    # the deferral fields flip under the fleet lock
                    # everywhere else (_route consumes them under it);
                    # setting them inside the publish section keeps
                    # the write discipline uniform even though this
                    # record is not yet reachable
                    old = self._records.get(sid)
                    rec.fence = (old.fence if old is not None
                                 else 0) + 1
                    entry["fence"] = rec.fence
                    rec.pending_entry = entry
                    rec.pending_fingerprint = fingerprint
                    if old is not None:
                        rec.rehomed = old.rehomed
                    self._records[sid] = rec
                if old is not None:
                    self._mirror_release(old)
                self._journal_place(rec)
                out["deferred"] += 1
                continue
            ev = target.engine.adopt_parked_session(
                entry,
                lifecycle_dir=dir_path,
                fingerprint=fingerprint,
                require_sha=True,
            )
            # rebuild the router mirror from the manifest so a LATER
            # crash of the absorbing replica can still re-home this
            # session exactly
            rec = _SessionRecord(sid=sid, rid=target.rid)
            toks = [int(t) for t in entry.get("history") or []]
            if entry.get("pending") is not None:
                toks.append(int(entry["pending"]))
            self._set_record_tokens(rec, toks)
            rec.generation = int(entry.get("generation") or 0)
            with self._lock:
                old = self._records.get(sid)
                rec.fence = (old.fence if old is not None else 0) + 1
                if old is not None:
                    rec.rehomed = old.rehomed + 1
                self._records[sid] = rec
            if old is not None:
                self._mirror_release(old)
            self._journal_place(rec)
            pending.append((rec, entry, target, ev))
        wait_until = time.monotonic() + 30.0
        for rec, entry, target, ev in pending:
            ev.wait(timeout=max(0.0, wait_until - time.monotonic()))
            store = getattr(target.engine, "offload_store", None)
            if store is not None and store.has(rec.sid):
                out["resumed"] += 1
            elif rec.sid in target.engine.sessions:
                out["reprefill"] += 1
            else:
                out["skipped"] += 1
        lifecycle_mod.consume_manifest(dir_path)
        # adopted spools were PID-re-tagged in place by adopt(); the
        # live-PID guard protects them from this sweep. Deferred
        # entries' spools are kept explicitly — their adoption happens
        # at the session's next route. Everything else the manifest
        # stopped protecting goes now.
        lifecycle_mod.sweep_orphans(
            dir_path, keep=deferred_keep, max_age_s=0.0
        )
        return out

    # ---- process lifecycle (ModelHost facade) ----

    def _fold_inflight_ships(self) -> None:
        """Process-drain fold for ships caught mid-flight: a COMPLETED
        export's entry exists only in its holder — no engine would
        manifest it — so hand it to a live replica's adoption queue
        (``engine.drain`` applies queued adoptions before writing the
        manifest). A ship whose adoption is already queued on a live
        target is left alone (that engine's drain applies + manifests
        it); a still-queued export is refused by the draining donor, so
        the session stays in the donor's manifest."""
        with self._lock:
            folds = []
            for rec in list(self.disagg._inflight.values()):
                exported = None
                if rec.ship_export is not None:
                    done, holder, _ = rec.ship_export
                    if done.is_set():
                        exported = holder.get("entry")
                queued_adopt = rec.ship_adopt is not None
                rec.ship_state = None
                rec.ship_export = None
                rec.ship_adopt = None
                if rec.ship_event is not None:
                    rec.ship_event.set()
                    rec.ship_event = None
                if exported is not None and not queued_adopt:
                    folds.append((rec, exported))
            self.disagg._inflight.clear()
        for rec, entry in folds:
            target = next(
                (h for h in self.replicas if h.state != "dead"), None,
            )
            if target is None:
                self.disagg._discard_entry(entry)
                continue
            target.engine.adopt_parked_session(
                entry, fingerprint=None, require_sha=False,
            )
            with self._lock:
                if self._records.get(rec.sid) is rec:
                    rec.rid = target.rid

    def begin_drain(self) -> None:
        self.lifecycle_phase = "draining"
        for h in self.replicas:
            if h.state != "dead" and hasattr(h.engine, "begin_drain"):
                h.engine.begin_drain()

    def drain(
        self,
        lifecycle_dir: Optional[str] = None,
        *,
        deadline_s: Optional[float] = None,
        flush: bool = True,
    ) -> dict:
        """Process-shutdown drain: every replica drains to its own
        subdir under the model's lifecycle dir, sharing ONE deadline
        budget. ``manifest_written`` is the AND across replicas — the
        clean-shutdown marker must not paper over one replica's lost
        sessions."""
        if lifecycle_dir is None:
            lifecycle_dir = lifecycle_mod.engine_dir(self.model_name)
        if deadline_s is None:
            deadline_s = lifecycle_mod.drain_deadline_s()
        t0 = time.monotonic()
        budget_end = t0 + max(deadline_s, 0.0)
        self.begin_drain()
        # no ships once the process is draining; the wire listener
        # closes with the fleet, and any ship already mid-flight is
        # folded back so its session reaches SOME replica's manifest
        # (the zero-durable-loss drain contract)
        self.disagg.close()
        self._fold_inflight_ships()
        summaries: dict[str, dict] = {}
        wrote_all = True
        totals = {"sessions_total": 0, "sessions_spooled": 0,
                  "sessions_fallback": 0, "sessions_abandoned": 0}
        for h in self.replicas:
            h.stop.set()
        for h in self.replicas:
            if h.state == "dead":
                continue
            wedged = False
            if h.thread is not None:
                h.thread.join(
                    timeout=max(0.0, budget_end - time.monotonic())
                )
                wedged = h.thread.is_alive()
            sub = os.path.join(lifecycle_dir, f"replica-{h.rid}")
            try:
                s = h.engine.drain(
                    sub,
                    deadline_s=max(
                        0.0, budget_end - time.monotonic()
                    ) if not wedged else 0.0,
                    flush=flush and not wedged,
                )
            except Exception:
                s = {"manifest_written": False, "error": "drain failed"}
            summaries[h.rid] = s
            wrote_all = wrote_all and s.get("manifest_written", False)
            for k in totals:
                totals[k] += int(s.get(k) or 0)
        for shard in self._shards:
            if shard.journal is None:
                continue
            if wrote_all:
                # the manifests are now the authoritative restart
                # state; stale journal entries must not resurrect
                # sessions the drain already handed off
                shard.journal.clear()
            else:
                # a failed manifest write keeps the journals as the
                # fallback recovery source for the next boot
                shard.journal.close()
        return {
            "drain_ms": round((time.monotonic() - t0) * 1000.0, 3),
            "manifest_written": wrote_all,
            "dir": lifecycle_dir,
            "replicas": summaries,
            **totals,
        }

    def restore_from_manifest(
        self, lifecycle_dir: Optional[str] = None
    ) -> dict:
        """Warm restart for the whole fleet: absorb every manifest
        under the model's lifecycle dir — per-replica subdirs from a
        previous fleet's drain, blue/green handoff leftovers, and the
        dir itself (a previous SINGLE-engine incarnation's manifest) —
        distributing sessions across the current replicas. Tolerant of
        a fleet-size change across the restart by construction."""
        if lifecycle_dir is None:
            lifecycle_dir = lifecycle_mod.engine_dir(self.model_name)
        total = {"resumed": 0, "reprefill": 0, "skipped": 0,
                 "deferred": 0, "manifest": False}
        dirs = [lifecycle_dir] + \
            lifecycle_mod.manifest_subdirs(lifecycle_dir)
        for d in dirs:
            got = self._absorb_manifest(d)
            for k in ("resumed", "reprefill", "skipped", "deferred"):
                total[k] += got[k]
            total["manifest"] = total["manifest"] or got["manifest"]
        return total

    # ---- observability ----

    def fleet_stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            placements: dict[str, int] = {}
            for rec in self._records.values():
                placements[rec.rid] = placements.get(rec.rid, 0) + 1
        out["replicas"] = len(self.replicas)
        out["serving"] = sum(
            1 for h in self.replicas if h.is_serving()
        )
        out["placements"] = placements
        out["health"] = {
            h.rid: {
                "state": h.state,
                "role": h.role,
                "healthy": getattr(h.engine, "healthy", True),
                "score": round(h.health_score(), 1),
                "strikes": h.strikes,
            }
            for h in self.replicas
        }
        with self._mirror_lock:
            mirror_tokens = self._mirror_tokens
        out["mirror"] = {
            "tokens": mirror_tokens,
            "cap_tokens": self.mirror_cap_tokens,
            "evictions": out.pop("mirror_evictions"),
            "tokens_evicted": out.pop("mirror_tokens_evicted"),
        }
        if self.mirror_journal is not None:
            out["mirror"]["journal"] = self.mirror_journal.stats()
        # sharded router tier (docs/podnet.md): per-shard health the
        # /api/tpu/health router block and /metrics family read
        out["router_shards"] = {
            "count": self.n_router_shards,
            "serving": sum(
                1 for s in self._shards if s.state == "serving"
            ),
            "epoch": self.placement.epoch,
            "crashes": out.pop("router_shard_crashes"),
            "adoptions": out.pop("router_shard_adoptions"),
            "sessions_adopted": out.pop("sessions_adopted"),
            "placement_refusals": out.pop("placement_refusals"),
            "placement": self.placement.snapshot(),
            "heartbeats": (
                self._shard_membership.snapshot()
                if self._shard_membership is not None else None
            ),
            "shards": {
                str(s.shard_id): {
                    "state": s.state,
                    "rooms": len(s.records),
                    "journal_bytes": (
                        s.journal.size_bytes()
                        if s.journal is not None else 0
                    ),
                    "adoptions": s.adoptions,
                }
                for s in self._shards
            },
        }
        out["disagg"] = self.disagg.stats()
        # pod membership + per-peer wire breakers (docs/podnet.md);
        # pod.stats() takes the fleet lock itself — outside the
        # snapshot section above
        out["pod"] = self.pod.stats()
        return out

    def stats(self) -> dict:
        """Aggregate engine-stats view (numeric counters summed across
        live replicas) + the fleet block. Per-replica blocks are NOT
        nested here — ``providers.tpu.engines_snapshot`` emits them
        under their own ``model#rid`` keys so fleet siblings never
        overwrite each other's scheduler/offload/lifecycle blocks."""
        agg: dict = {}
        for h in self.replicas:
            if h.state == "dead":
                continue
            st = h.engine.stats()
            for k, v in st.items():
                if isinstance(v, bool) or not isinstance(
                    v, (int, float)
                ):
                    continue
                agg[k] = agg.get(k, 0) + v
        ref = self.replicas[0].engine
        agg["steps_per_dispatch"] = ref.steps_per_dispatch
        agg["healthy"] = self.healthy
        agg["queued"] = sum(
            h.engine._queue.qsize() for h in self.replicas
            if h.state != "dead"
        )
        agg["degradation_level"] = max(
            (h.engine.degradation_level() for h in self.replicas
             if h.state != "dead"), default=0,
        )
        lc = {"phase": self.lifecycle_phase}
        agg["lifecycle"] = lc
        agg["fleet"] = self.fleet_stats()
        return agg

    # ---- test / synchronous driving ----

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        """Synchronous driver (tests, notebooks): steps every
        thread-less serving replica round-robin, supervising between
        rounds, until the whole fleet is idle — including the disagg
        coordinator, whose turn-boundary KV ships run synchronously
        inside the supervision pass here (a turn that finished on the
        step right before idle still gets its ship before return)."""
        for _ in range(max_steps):
            self.supervise()
            busy = self.disagg.pending()
            for h in self.replicas:
                if h.state != "serving" or (
                    h.thread is not None and h.thread.is_alive()
                ):
                    continue
                try:
                    busy += h.engine.step()
                except Exception as e:
                    if not h.engine._recover_from_crash(e):
                        continue
                if not h.engine._queue.empty() or \
                        h.engine._inflight is not None:
                    busy += 1
            if busy == 0:
                # one more supervision pass: a turn that completed on
                # this round's final step may owe a disagg ship
                self.supervise()
                if self.disagg.pending() == 0:
                    return
        raise RuntimeError("fleet run_until_idle exceeded max_steps")
