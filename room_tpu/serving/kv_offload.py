"""Tiered KV offload: hibernate parked sessions to host RAM / disk.

The room workload (PAPER.md) is dominated by agent turns that *park*
mid-turn for tool calls: today every parked session keeps all of its KV
pages resident in HBM, so HBM capacity — not compute — caps room size.
This module is the host side of a three-tier page store:

    tier 0  HBM          the engine's paged pool (kv_pages.py)
    tier 1  host RAM     byte-exact page copies, size-capped, LRU
    tier 2  disk spool   LRU demotions from tier 1, size-capped

The engine (serving/engine.py) copies a cold session's non-prefix pages
out with `jax.device_get` (async host copies), releases the HBM pages
back to the pool, and records the copy here. On the session's next turn
(or earlier, via prefetch while other sessions keep decoding) the pages
are re-allocated and `device_put` back before the prefill step — a
memcpy round trip, not a recompute, so greedy continuations are
token-identical to a never-offloaded run (the restore canary in
tests/test_kv_offload.py pins this).

Degradation-safe by construction: an entry that gets dropped (disk cap,
spool I/O error) is not fatal — the engine's host-side history mirror
re-prefills the context, trading compute for correctness. The store
never throws at the engine for I/O problems; it degrades and counts.

Env knobs (docs/kv_offload.md):

    ROOM_TPU_OFFLOAD           enable ("1"/"0"; engines also take an
                               explicit ``offload=`` constructor arg)
    ROOM_TPU_OFFLOAD_HOST_MB   tier-1 cap (default 512)
    ROOM_TPU_OFFLOAD_DISK_MB   tier-2 cap (default 2048; 0 disables
                               the spool — demotions become drops)
    ROOM_TPU_OFFLOAD_DIR       spool directory (default a per-process
                               dir under the system temp dir)
    ROOM_TPU_OFFLOAD_LOW_WM    free-page fraction that starts the
                               pressure sweep (default 0.25)
    ROOM_TPU_OFFLOAD_HIGH_WM   free-page fraction the sweep restores
                               (default 0.5)
    ROOM_TPU_OFFLOAD_ON_PARK   offload immediately on tool-call park
                               (default 1)
    ROOM_TPU_OFFLOAD_PREFETCH  queued-session restores started per
                               scheduler step (default 2)
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..utils import knobs, locks

__all__ = [
    "OffloadEntry", "TieredKVStore", "offload_enabled_from_env",
    "RESTORE_HIST_BUCKETS_MS",
]

# restore-latency histogram buckets (milliseconds, upper bounds; the
# final bucket is unbounded). Shared with /api/tpu/health and the TPU
# panel so every surface renders the same edges.
RESTORE_HIST_BUCKETS_MS = (1.0, 5.0, 20.0, 100.0, 500.0)


def offload_enabled_from_env(default: str = "0") -> bool:
    return knobs.get_bool("ROOM_TPU_OFFLOAD", default=default)


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name saved in a spool header. bfloat16 (and
    friends) are registered by ml_dtypes — imported lazily so a plain
    int8/float32 spool never needs it."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _write_spool(
    path: str, arrays: dict[str, np.ndarray], want_digest: bool = False,
) -> Optional[str]:
    """One entry -> one file: json header (dtype/shape per key) + raw
    buffers in sorted-key order. Raw bytes instead of np.savez because
    bfloat16 is not a savez-portable dtype. Atomic via rename. With
    ``want_digest`` also returns the file's sha256, hashed as the
    bytes stream out — the drain manifest needs it, and re-reading a
    multi-hundred-MB spool to hash it would double the I/O inside the
    drain deadline. The ordinary host->disk demotion path skips the
    hash: it runs under pool pressure and nothing consumes the digest
    there."""
    tmp = path + ".tmp"
    meta = {
        k: {"dtype": a.dtype.name, "shape": list(a.shape)}
        for k, a in arrays.items()
    }
    hdr = json.dumps(meta).encode()
    h = hashlib.sha256() if want_digest else None
    with open(tmp, "wb") as f:
        for chunk in (len(hdr).to_bytes(8, "little"), hdr):
            f.write(chunk)
            if h is not None:
                h.update(chunk)
        for k in sorted(arrays):
            buf = np.ascontiguousarray(arrays[k]).tobytes()
            f.write(buf)
            if h is not None:
                h.update(buf)
    os.replace(tmp, path)
    return h.hexdigest() if h is not None else None


def _copy_spool(src: str, dst: str) -> str:
    """Streaming byte copy of an existing spool file (atomic via
    rename, sha256 hashed in transit) — drain's fast path for
    disk-tier hibernated sessions: the bytes are already in spool
    format, so parsing them into host RAM just to re-serialize would
    double the I/O and transiently hold the whole KV resident inside
    the drain deadline."""
    h = hashlib.sha256()
    tmp = dst + ".tmp"
    with open(src, "rb") as fi, open(tmp, "wb") as fo:
        for chunk in iter(lambda: fi.read(1 << 20), b""):
            fo.write(chunk)
            h.update(chunk)
    os.replace(tmp, dst)
    return h.hexdigest()


def _read_spool(
    path: str, expected_sha: Optional[str] = None
) -> dict[str, np.ndarray]:
    """Parse a spool file; with ``expected_sha`` also verify the file's
    sha256 (hashed incrementally over the same read — adopted
    warm-restart spools defer their integrity check to this first read
    so boot stays a metadata scan) and raise ValueError on mismatch."""
    h = hashlib.sha256() if expected_sha else None
    with open(path, "rb") as f:
        raw = f.read(8)
        hdr_len = int.from_bytes(raw, "little")
        if h is not None:
            h.update(raw)
        raw = f.read(hdr_len)
        meta = json.loads(raw.decode())
        if h is not None:
            h.update(raw)
        out: dict[str, np.ndarray] = {}
        for k in sorted(meta):
            dt = _np_dtype(meta[k]["dtype"])
            shape = tuple(meta[k]["shape"])
            n = int(np.prod(shape)) * dt.itemsize
            buf = f.read(n)
            if len(buf) != n:
                raise OSError(f"truncated spool file {path!r}")
            if h is not None:
                h.update(buf)
            out[k] = np.frombuffer(buf, dtype=dt).reshape(shape)
        if h is not None:
            h.update(f.read())   # any trailing bytes count too
            if h.hexdigest() != expected_sha:
                raise ValueError(
                    f"checksum mismatch for spool {path!r}"
                )
    return out


@dataclass
class OffloadEntry:
    """One hibernated session: byte-exact copies of its non-prefix KV
    pages, resident in host RAM (``arrays``) or spooled to ``path``."""

    session_id: str
    own_tokens: int                 # tokens the pages cover (past prefix)
    n_pages: int
    nbytes: int
    arrays: Optional[dict[str, np.ndarray]] = None   # tier 1
    path: Optional[str] = None                       # tier 2
    # expected file sha256 for ADOPTED warm-restart spools, verified
    # lazily at first read (None for spools this process wrote itself)
    sha256: Optional[str] = None
    created_at: float = field(default_factory=time.monotonic)
    last_used: float = field(default_factory=time.monotonic)

    @property
    def tier(self) -> str:
        return "host" if self.arrays is not None else "disk"


class TieredKVStore:
    """Host RAM + disk spool tiers of the offload hierarchy.

    Pure host-side bookkeeping: the engine owns all device copies and
    all page-table mutation; this class only holds bytes and applies
    the LRU cap policy (host overflow demotes to disk, disk overflow
    drops the oldest entry — the engine re-prefills a dropped session
    from its history mirror, so a drop costs compute, never
    correctness).

    Thread-safe: the engine thread mutates while HTTP threads snapshot
    ``stats()``.
    """

    def __init__(
        self,
        host_bytes_cap: Optional[int] = None,
        disk_bytes_cap: Optional[int] = None,
        spool_dir: Optional[str] = None,
    ) -> None:
        mb = 1024 * 1024
        if host_bytes_cap is None:
            host_bytes_cap = int(
                knobs.get_float("ROOM_TPU_OFFLOAD_HOST_MB") * mb
            )
        if disk_bytes_cap is None:
            disk_bytes_cap = int(
                knobs.get_float("ROOM_TPU_OFFLOAD_DISK_MB") * mb
            )
        self.host_bytes_cap = host_bytes_cap
        self.disk_bytes_cap = disk_bytes_cap
        self._spool_dir = spool_dir or \
            knobs.get_str("ROOM_TPU_OFFLOAD_DIR") or None
        self._own_spool = self._spool_dir is None
        # a SHARED spool dir (env/arg — the durable deployment shape,
        # docs/lifecycle.md) accumulates files from processes that died
        # uncleanly: sweep age-thresholded orphans at construction,
        # never files a live drain manifest still references
        if self._spool_dir and os.path.isdir(self._spool_dir):
            try:
                from .lifecycle import sweep_orphans

                sweep_orphans(self._spool_dir)
            except Exception:
                pass  # hygiene is best-effort; the store must come up
        self._entries: dict[str, OffloadEntry] = {}
        self._lock = locks.make_lock("kv_offload")
        self._stats = {
            "host_hits": 0, "disk_hits": 0, "misses": 0,
            "demotions": 0, "disk_drops": 0, "spool_errors": 0,
            "bytes_out": 0, "bytes_in": 0,
        }
        self._hist = [0] * (len(RESTORE_HIST_BUCKETS_MS) + 1)

    # ---- spool dir ----

    def _ensure_spool_dir(self) -> str:
        if self._spool_dir is None:
            self._spool_dir = tempfile.mkdtemp(prefix="room_tpu_kv_")
        else:
            os.makedirs(self._spool_dir, exist_ok=True)
        return self._spool_dir

    def _spool_path(self, session_id: str) -> str:
        # PID-tagged (lifecycle.spool_owner_pid): in a SHARED durable
        # spool dir, a sibling process's boot sweep must be able to
        # tell "hibernated by a live engine" (skip, whatever the age)
        # from "leaked by a dead one" (sweep past the age threshold)
        slug = hashlib.sha1(session_id.encode()).hexdigest()[:16]
        return os.path.join(self._ensure_spool_dir(),
                            f"pid{os.getpid()}-{slug}.kvspool")

    # ---- tier accounting (callers hold self._lock) ----

    def _bump(self, key: str, n: int = 1) -> None:
        # callers hold self._lock (non-reentrant): the single counter
        # mutation point the stats() reader snapshot relies on, not a
        # lock-taking helper like the engine's (same shape as the
        # mirror journal's _bump)
        self._stats[key] += n

    def _host_bytes_locked(self) -> int:
        return sum(
            e.nbytes for e in self._entries.values()
            if e.arrays is not None
        )

    def _disk_bytes_locked(self) -> int:
        return sum(
            e.nbytes for e in self._entries.values() if e.path
        )

    def _drop_entry_locked(self, entry: OffloadEntry) -> None:
        self._entries.pop(entry.session_id, None)
        if entry.path:
            try:
                os.unlink(entry.path)
            except OSError:
                pass

    def _rebalance(self) -> None:
        """LRU-demote host entries to the spool until tier 1 fits its
        cap, then drop LRU disk entries until tier 2 fits. A failed
        spool write (or a zero disk cap) drops the victim outright —
        the engine's history mirror makes that safe.

        Spool WRITES happen outside the lock (they can be hundreds of
        MB; stats()/has()/get() from HTTP threads must not stall on
        them). Safe because the engine thread is the store's only
        mutator — the lock only protects reader snapshots."""
        while True:
            with self._lock:
                if self._host_bytes_locked() <= self.host_bytes_cap:
                    break
                victims = [
                    e for e in self._entries.values()
                    if e.arrays is not None
                ]
                if not victims:
                    break
                victim = min(victims, key=lambda e: e.last_used)
                if self.disk_bytes_cap <= 0:
                    self._bump("disk_drops")
                    self._drop_entry_locked(victim)
                    continue
                arrays = victim.arrays
                path = self._spool_path(victim.session_id)
            try:
                _write_spool(path, arrays)
            except OSError:
                with self._lock:
                    self._bump("spool_errors")
                    self._drop_entry_locked(victim)
                continue
            with self._lock:
                victim.path = path
                victim.arrays = None
                self._bump("demotions")
        with self._lock:
            while self._disk_bytes_locked() > self.disk_bytes_cap:
                victims = [
                    e for e in self._entries.values() if e.path
                ]
                if not victims:
                    break
                victim = min(victims, key=lambda e: e.last_used)
                self._bump("disk_drops")
                self._drop_entry_locked(victim)

    # ---- public API (engine thread mutates; HTTP threads read) ----

    def put(
        self, session_id: str, arrays: dict[str, np.ndarray],
        own_tokens: int, n_pages: int,
    ) -> OffloadEntry:
        nbytes = sum(int(a.nbytes) for a in arrays.values())
        entry = OffloadEntry(
            session_id=session_id, own_tokens=own_tokens,
            n_pages=n_pages, nbytes=nbytes, arrays=arrays,
        )
        with self._lock:
            old = self._entries.pop(session_id, None)
            if old is not None:
                self._drop_entry_locked(old)
            self._entries[session_id] = entry
            self._bump("bytes_out", nbytes)
        self._rebalance()
        return entry

    def adopt(
        self, session_id: str, path: str, own_tokens: int,
        n_pages: int, nbytes: int, sha256: Optional[str] = None,
    ) -> bool:
        """Register an EXISTING spool file as a disk-tier entry without
        reading it — warm-restart rehydration (serving/lifecycle.py):
        the restored engine's next prefill for the session restores it
        through the ordinary disk-hit path, byte-exact. ``sha256`` (the
        manifest's digest) is verified lazily on that first read, so
        boot stays a metadata scan; a mismatch degrades to the same
        re-prefill miss as a truncated file. The store takes ownership
        of the file (a later discard/drop unlinks it). Returns False
        when the disk cap can't hold the entry — the caller falls back
        to a history re-prefill."""
        if self.disk_bytes_cap <= 0 or nbytes > self.disk_bytes_cap:
            return False
        from .lifecycle import spool_owner_pid

        if spool_owner_pid(path) != os.getpid():
            # re-tag with the adopting PID: drain spools carry untagged
            # names, and in a shared engine dir a sibling boot's sweep
            # only age-protects untagged files — the PID tag is what
            # keeps a live engine's adopted sessions safe past the age
            # threshold (same-dir rename, so the move stays atomic)
            tagged = os.path.join(
                os.path.dirname(path),
                f"pid{os.getpid()}-{os.path.basename(path)}",
            )
            try:
                os.replace(path, tagged)
                path = tagged
            except OSError:
                pass  # keep the untagged name; age still protects it
        entry = OffloadEntry(
            session_id=session_id, own_tokens=own_tokens,
            n_pages=n_pages, nbytes=nbytes, arrays=None, path=path,
            sha256=sha256,
        )
        with self._lock:
            old = self._entries.pop(session_id, None)
            if old is not None:
                self._drop_entry_locked(old)
            self._entries[session_id] = entry
        self._rebalance()
        return self.has(session_id)

    def has(self, session_id: str) -> bool:
        with self._lock:
            return session_id in self._entries

    def export_entry(self, session_id: str) -> Optional[dict]:
        """Detach a session's entry for cross-replica handoff (fleet
        failover, docs/fleet.md): a disk-tier entry gives up its spool
        file — removed from this store WITHOUT unlinking, the adopting
        sibling takes ownership of the file; a host-tier entry is
        spooled to disk first (pure host bytes: safe even when the
        owning engine's device state is suspect). Returns a
        manifest-style kv record (absolute ``file`` path) or None —
        absent entries and spool I/O errors both degrade to the
        caller's history re-prefill path, never an exception."""
        with self._lock:
            entry = self._entries.get(session_id)
            if entry is None:
                return None
            arrays, path, sha = entry.arrays, entry.path, entry.sha256
        if arrays is not None:
            path = self._spool_path(session_id)
            try:
                sha = _write_spool(path, arrays, want_digest=True)
            except OSError:
                with self._lock:
                    self._bump("spool_errors")
                return None
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            return None
        with self._lock:
            # detach, don't drop: the file now belongs to the adopter
            self._entries.pop(session_id, None)
        return {
            "file": path,
            "own_tokens": int(entry.own_tokens),
            "n_pages": int(entry.n_pages),
            "nbytes": int(nbytes),
            "sha256": sha,
        }

    def spool_copy_source(
        self, session_id: str
    ) -> Optional[tuple[str, int]]:
        """(path, n_pages) when a session's KV can be byte-copied
        straight off the disk tier — already in spool format and
        written (hence implicitly trusted) by THIS process. Adopted
        entries, whose sha256 is still pending its lazy first-read
        verification, are excluded: byte-copying them would re-digest
        unverified bytes and launder an earlier corruption through the
        next manifest's checksum. Drain's fast path."""
        with self._lock:
            e = self._entries.get(session_id)
            if e is None or e.arrays is not None or e.path is None \
                    or e.sha256 is not None:
                return None
            return e.path, e.n_pages

    def tier_of(self, session_id: str) -> Optional[str]:
        with self._lock:
            e = self._entries.get(session_id)
            return e.tier if e else None

    def get(
        self, session_id: str
    ) -> Optional[tuple[OffloadEntry, dict[str, np.ndarray]]]:
        """Load an entry's arrays (from RAM or spool) WITHOUT removing
        it — the engine discards only after the device scatter lands,
        so a failed restore leaves the copy intact. A spool read error
        degrades to a miss (entry dropped; history re-prefills)."""
        with self._lock:
            entry = self._entries.get(session_id)
            if entry is None:
                self._bump("misses")
                return None
            entry.last_used = time.monotonic()
            if entry.arrays is not None:
                self._bump("host_hits")
                return entry, entry.arrays
            path = entry.path
        try:
            arrays = _read_spool(path, expected_sha=entry.sha256)
        except (OSError, ValueError, KeyError):
            # truncated file, garbage header, shape/dtype mismatch, or
            # an adopted spool failing its (lazy) checksum
            # all degrade the same way: a miss the engine re-prefills
            with self._lock:
                self._bump("spool_errors")
                self._bump("misses")
                self._drop_entry_locked(entry)
            return None
        with self._lock:
            self._bump("disk_hits")
        return entry, arrays

    def discard(self, session_id: str) -> bool:
        with self._lock:
            entry = self._entries.get(session_id)
            if entry is None:
                return False
            self._drop_entry_locked(entry)
            return True

    def clear(self, remove_spool_dir: bool = True) -> None:
        """Drop every entry (unlinking their files). With
        ``remove_spool_dir=False`` a store-owned spool dir survives —
        the fatal-crash salvage path (engine._collect_crash_salvage)
        has just DETACHED spool files still sitting in that dir for a
        fleet sibling to adopt, and the rmtree would delete the very
        bytes the salvage hand-off points at."""
        with self._lock:
            for entry in list(self._entries.values()):
                self._drop_entry_locked(entry)
            self._entries.clear()
        if remove_spool_dir and self._own_spool and self._spool_dir:
            shutil.rmtree(self._spool_dir, ignore_errors=True)
            self._spool_dir = None

    def observe_restore(self, seconds: float, nbytes: int) -> None:
        ms = seconds * 1000.0
        idx = len(RESTORE_HIST_BUCKETS_MS)
        for i, edge in enumerate(RESTORE_HIST_BUCKETS_MS):
            if ms <= edge:
                idx = i
                break
        with self._lock:
            self._hist[idx] += 1
            self._bump("bytes_in", nbytes)

    def restore_hist(self) -> dict[str, int]:
        with self._lock:
            hist = list(self._hist)
        out = {}
        for i, edge in enumerate(RESTORE_HIST_BUCKETS_MS):
            out[f"le_{edge:g}ms"] = hist[i]
        out[f"gt_{RESTORE_HIST_BUCKETS_MS[-1]:g}ms"] = hist[-1]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        """Tier occupancy + hit/miss/byte counters + restore-latency
        histogram (for engine.stats(), /api/tpu/health, the TPU
        panel)."""
        with self._lock:
            host_entries = sum(
                1 for e in self._entries.values()
                if e.arrays is not None
            )
            disk_entries = sum(
                1 for e in self._entries.values() if e.path
            )
            out = {
                "host_entries": host_entries,
                "disk_entries": disk_entries,
                "host_bytes": self._host_bytes_locked(),
                "disk_bytes": self._disk_bytes_locked(),
                "host_bytes_cap": self.host_bytes_cap,
                "disk_bytes_cap": self.disk_bytes_cap,
                **self._stats,
            }
        out["restore_ms_hist"] = self.restore_hist()
        return out
