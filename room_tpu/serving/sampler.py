"""Token sampling: temperature / top-k / top-p, jit-safe with static
knobs folded into the compiled step."""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.7
    top_k: int = 0          # 0 = off
    top_p: float = 1.0      # 1.0 = off
    max_new_tokens: int = 1024


def sample(
    logits: jax.Array,       # [B, V]
    key: jax.Array,
    params: SamplingParams,
) -> jax.Array:
    """Returns sampled token ids [B]. Greedy when temperature == 0."""
    if params.temperature == 0.0:
        return jnp.argmax(logits, axis=-1)

    logits = logits.astype(jnp.float32) / params.temperature

    if params.top_k > 0:
        kth = jax.lax.top_k(logits, params.top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)

    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p
        cutoff_idx = jnp.sum(cum < params.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)

    return jax.random.categorical(key, logits, axis=-1)


def sample_batched(
    logits: jax.Array,        # [B, V]
    key: jax.Array,
    temperature: jax.Array,   # [B] (0 = greedy for that row)
    top_p: jax.Array,         # [B] (1 = off)
    top_k: jax.Array,         # [B] int32 (0 = off for that row)
) -> jax.Array:
    """Per-row sampling knobs as arrays so one compiled decode step serves
    heterogeneous turns in the same batch. top_k is per-row: a row with
    top_k=0 samples the full vocabulary regardless of its batchmates."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1)

    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t

    # one descending sort serves both top-k (rank threshold) and
    # top-p (mass threshold)
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]

    vocab = logits.shape[-1]
    k_idx = jnp.clip(top_k[:, None] - 1, 0, vocab - 1)
    kth = jnp.take_along_axis(sorted_logits, k_idx, axis=-1)
    apply_k = (top_k > 0)[:, None]
    scaled = jnp.where(apply_k & (scaled < kth), -jnp.inf, scaled)
    # top-p applies to the k-filtered distribution (sequential semantics);
    # masking the sorted copy by the same value threshold avoids a resort
    sorted_logits = jnp.where(
        apply_k & (sorted_logits < kth), -jnp.inf, sorted_logits
    )

    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(
        cum < top_p[:, None], axis=-1, keepdims=True
    )
    cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
    apply_p = (top_p < 1.0)[:, None]
    scaled = jnp.where(apply_p & (scaled < cutoff), -jnp.inf, scaled)

    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy)
