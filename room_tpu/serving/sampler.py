"""Token sampling: temperature / top-k / top-p, jit-safe with static
knobs folded into the compiled step."""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..utils import knobs

# Greedy tie band: logits within this distance of the row max count as
# tied, and the LOWEST index wins. The band is RELATIVE to the max's
# magnitude (floored at 1): reduction-order noise is a few f32 ULPs,
# and a ULP scales with the value — an absolute band tuned on small
# logits (measured ~5e-7 on the 8-device virtual mesh at tiny-moe
# scale) would fall below one ULP once row maxima exceed ~8 and the
# determinism guarantee would silently lapse at realistic magnitudes.
# 1e-6 relative stays ~2x above per-ULP noise at every scale while
# remaining far below any gap that reflects a real model decision.
# Read once at import — it participates in compiled programs.
GREEDY_TIE_EPS = knobs.get_float("ROOM_TPU_GREEDY_TIE_EPS")


def greedy_argmax(logits: jax.Array) -> jax.Array:
    """Index-ordered argmax over stably-banded logits [..., V]: every
    greedy pick in the repo (plain decode, prefill first token,
    speculative verify) routes through here so mesh-vs-single-device
    reduction-order noise can never flip a near-tie differently in two
    places."""
    x = logits.astype(jnp.float32)
    mx = jnp.max(x, axis=-1, keepdims=True)
    band = GREEDY_TIE_EPS * jnp.maximum(1.0, jnp.abs(mx))
    # first index within the tie band of the row max
    return jnp.argmax(x >= mx - band, axis=-1)


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.7
    top_k: int = 0          # 0 = off
    top_p: float = 1.0      # 1.0 = off
    max_new_tokens: int = 1024
    # OpenAI-style repetition controls over THIS request's generated
    # tokens: presence subtracts a flat penalty from every token already
    # emitted, frequency subtracts proportionally to its count
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0

    @property
    def penalized(self) -> bool:
        return bool(self.presence_penalty or self.frequency_penalty)


def apply_penalties(
    logits,          # [B, V] f32
    counts,          # [B, V] int32 — this request's generated-token counts
    presence,        # [B] f32
    frequency,       # [B] f32
):
    """OpenAI penalty semantics: logits[b, v] -= presence[b]*(count>0)
    + frequency[b]*count. Rows with both zero are untouched."""
    import jax.numpy as _jnp

    c = counts.astype(_jnp.float32)
    return logits - presence[:, None] * (c > 0) - frequency[:, None] * c


def sample(
    logits: jax.Array,       # [B, V]
    key: jax.Array,
    params: SamplingParams,
) -> jax.Array:
    """Returns sampled token ids [B]. Greedy when temperature == 0."""
    if params.temperature == 0.0:
        return greedy_argmax(logits)

    logits = logits.astype(jnp.float32) / params.temperature

    if params.top_k > 0:
        kth = jax.lax.top_k(logits, params.top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)

    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p
        cutoff_idx = jnp.sum(cum < params.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)

    return jax.random.categorical(key, logits, axis=-1)


SAMPLE_FAST_K = 128


def masked_scaled_logits(
    logits: jax.Array,        # [B, V] float32
    temperature: jax.Array,   # [B]
    top_p: jax.Array,         # [B]
    top_k: jax.Array,         # [B]
) -> jax.Array:
    """Temperature-scaled, top-k/top-p-masked logits: the categorical
    over a row of these IS that row's sampling distribution (rows with
    temperature 0 are handled by callers via argmax).

    Fast path: LLM next-token distributions are peaked, so the top-p
    cutoff almost always lies within the top ``SAMPLE_FAST_K`` logits —
    `lax.top_k` over those replaces the full-vocab sort (151k entries
    every decode step). A `lax.cond` falls back to the exact full sort
    whenever any row's top-K prefix doesn't cover its top_p mass (or
    requests top_k > K), so the result is bit-identical to the sorted
    reference in all cases."""
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t
    vocab = logits.shape[-1]

    if vocab <= SAMPLE_FAST_K * 2:
        return _mask_sorted(scaled, jnp.sort(scaled, axis=-1)[:, ::-1],
                            top_p, top_k, vocab)

    kk = SAMPLE_FAST_K
    top_vals = jax.lax.top_k(scaled, kk)[0]           # [B, K] descending
    # the top-p cumulative mass needs the k-masked softmax denominator,
    # which is a full-vocab reduction either way (O(V), no sort)
    prefix_ok = _prefix_covers(scaled, top_vals, top_p, top_k, kk)

    def fast(_):
        return _mask_sorted(scaled, top_vals, top_p, top_k, vocab)

    def slow(_):
        return _mask_sorted(
            scaled, jnp.sort(scaled, axis=-1)[:, ::-1], top_p, top_k,
            vocab,
        )

    return jax.lax.cond(prefix_ok, fast, slow, None)


def sample_batched(
    logits: jax.Array,        # [B, V]
    key: jax.Array,
    temperature: jax.Array,   # [B] (0 = greedy for that row)
    top_p: jax.Array,         # [B] (1 = off)
    top_k: jax.Array,         # [B] int32 (0 = off for that row)
) -> jax.Array:
    """Per-row sampling knobs as arrays so one compiled decode step serves
    heterogeneous turns in the same batch. top_k is per-row: a row with
    top_k=0 samples the full vocabulary regardless of its batchmates.
    (`_sample_batched_sorted` is the full-sort test oracle.)"""
    logits = logits.astype(jnp.float32)
    greedy = greedy_argmax(logits)
    masked = masked_scaled_logits(logits, temperature, top_p, top_k)
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy)


def spec_verify(
    logits: jax.Array,        # [B, W, V] at the verify window positions
    drafts: jax.Array,        # [B, W-1] proposed continuation tokens
    key: jax.Array,
    temperature: jax.Array,   # [B]
    top_p: jax.Array,         # [B]
    top_k: jax.Array,         # [B]
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Speculative-sampling verification (Leviathan et al.) with a
    DETERMINISTIC draft distribution (prompt-lookup proposes exactly one
    candidate, so q(d)=1 and the acceptance probability is simply the
    target distribution's p(d)).

    Returns per position:
      accept   [B, W-1] — draft j is kept iff all of 0..j accepted
      residual [B, W-1] — token to emit at the first rejection: a draw
                          from the renormalized target-minus-draft
                          distribution (exactly preserves the target)
      plain    [B, W]   — ordinary sample at each position (used for
                          the bonus token when every draft is accepted,
                          and for rows that proposed nothing)

    Rows with temperature 0 reduce to argmax verification: accept iff
    the draft IS the argmax; residual/plain are the argmax (removing a
    rejected, non-argmax draft cannot change it) — identical to greedy
    decoding."""
    b, w, v = logits.shape
    flat = logits.reshape(b * w, v).astype(jnp.float32)
    rep = lambda x: jnp.repeat(x, w)                    # noqa: E731
    masked = masked_scaled_logits(
        flat, rep(temperature), rep(top_p), rep(top_k)
    )
    argmax_full = greedy_argmax(flat)                   # [B*W]

    k_u, k_resid, k_plain = jax.random.split(key, 3)
    stoch = (rep(temperature) > 0)

    plain_flat = jnp.where(
        stoch,
        jax.random.categorical(k_plain, masked, axis=-1),
        argmax_full,
    )
    plain = plain_flat.reshape(b, w)

    # acceptance of draft j happens against position j's distribution
    d_flat = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), drafts.dtype)], axis=1
    ).reshape(b * w)
    mx = jnp.max(masked, axis=-1, keepdims=True)
    exp_m = jnp.where(jnp.isfinite(masked), jnp.exp(masked - mx), 0.0)
    denom = jnp.sum(exp_m, axis=-1)
    p_draft = jnp.take_along_axis(
        exp_m, d_flat[:, None], axis=-1
    )[:, 0] / jnp.maximum(denom, 1e-30)
    u = jax.random.uniform(k_u, (b * w,))
    accept_flat = jnp.where(
        stoch, u < p_draft, d_flat == argmax_full
    )

    resid_logits = masked.at[jnp.arange(b * w), d_flat].set(-jnp.inf)
    # greedy rows: the residual is only consumed at a rejection, i.e.
    # when the draft is NOT the greedy pick — so the pick itself is the
    # exact sequential-decoding token. Using argmax_full (not an argmax
    # over the draft-masked row) keeps the tie-banded greedy rule
    # identical between the spec path and plain decode.
    residual_flat = jnp.where(
        stoch,
        jax.random.categorical(k_resid, resid_logits, axis=-1),
        argmax_full,
    )
    accept = accept_flat.reshape(b, w)[:, : w - 1]
    residual = residual_flat.reshape(b, w)[:, : w - 1]
    return accept, residual, plain


def _mask_sorted(
    scaled: jax.Array,         # [B, V]
    sorted_desc: jax.Array,    # [B, K>=needed] descending prefix (or full)
    top_p: jax.Array,
    top_k: jax.Array,
    vocab: int,
) -> jax.Array:
    """Shared top-k + top-p masking given a descending (prefix of the)
    sorted logits. Exact when the prefix covers the cutoffs."""
    width = sorted_desc.shape[-1]
    k_idx = jnp.clip(top_k[:, None] - 1, 0, width - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx, axis=-1)
    apply_k = (top_k > 0)[:, None]
    masked = jnp.where(apply_k & (scaled < kth), -jnp.inf, scaled)
    # top-p applies to the k-filtered distribution (sequential
    # semantics); mask the sorted view by the same value threshold
    sorted_m = jnp.where(
        apply_k & (sorted_desc < kth), -jnp.inf, sorted_desc
    )
    # softmax denominator over the FULL masked vocab, not the prefix
    denom = jnp.sum(
        jnp.where(jnp.isfinite(masked), jnp.exp(
            masked - jnp.max(sorted_m, axis=-1, keepdims=True)
        ), 0.0),
        axis=-1, keepdims=True,
    )
    probs = jnp.where(
        jnp.isfinite(sorted_m),
        jnp.exp(sorted_m - jnp.max(sorted_m, axis=-1, keepdims=True)),
        0.0,
    ) / denom
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.clip(
        jnp.sum(cum < top_p[:, None], axis=-1, keepdims=True),
        0, width - 1,
    )
    cutoff = jnp.take_along_axis(sorted_m, cutoff_idx, axis=-1)
    apply_p = (top_p < 1.0)[:, None]
    return jnp.where(apply_p & (masked < cutoff), -jnp.inf, masked)


def _prefix_covers(
    scaled: jax.Array, top_vals: jax.Array, top_p: jax.Array,
    top_k: jax.Array, kk: int,
) -> jax.Array:
    """True iff, for every row, the top-K prefix contains both the
    top_k rank cutoff and >= top_p of the k-masked mass."""
    k_ok = jnp.all(top_k <= kk)
    k_idx = jnp.clip(top_k[:, None] - 1, 0, kk - 1)
    kth = jnp.take_along_axis(top_vals, k_idx, axis=-1)
    apply_k = (top_k > 0)[:, None]
    masked = jnp.where(apply_k & (scaled < kth), -jnp.inf, scaled)
    mx = jnp.max(top_vals, axis=-1, keepdims=True)
    denom = jnp.sum(
        jnp.where(jnp.isfinite(masked), jnp.exp(masked - mx), 0.0),
        axis=-1,
    )
    prefix_vals = jnp.where(
        apply_k & (top_vals < kth), -jnp.inf, top_vals
    )
    prefix_mass = jnp.sum(
        jnp.where(jnp.isfinite(prefix_vals),
                  jnp.exp(prefix_vals - mx), 0.0),
        axis=-1,
    )
    # rows with top_p >= 1 don't apply a mass cutoff at all (idle decode
    # slots are padded with top_p=1), so they never need prefix coverage
    p_ok = jnp.all(
        (top_p >= 1.0) | (prefix_mass >= top_p * denom)
    )
    return k_ok & p_ok


def _sample_batched_sorted(
    logits: jax.Array,
    key: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    top_k: jax.Array,
) -> jax.Array:
    """Reference implementation: one full-vocab sort (the test oracle
    for the fast path)."""
    logits = logits.astype(jnp.float32)
    greedy = greedy_argmax(logits)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t
    masked = _mask_sorted(
        scaled, jnp.sort(scaled, axis=-1)[:, ::-1], top_p, top_k,
        logits.shape[-1],
    )
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy)
