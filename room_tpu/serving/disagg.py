"""Disaggregated prefill/decode serving (docs/disagg.md).

The fleet (serving/fleet.py) made replicas interchangeable; this module
makes them *specialized*. A burst of 2k-token prompts from a thousand
rooms used to land its chunked prefills between every replica's decode
windows — each chunk a dispatch stolen from live sessions' token
cadence. With ``ROOM_TPU_FLEET_ROLES`` the router knows which replicas
are **prefill** (admit fresh long-prompt sessions, run chunked prefill
to completion on wide submeshes), which are **decode** (serve the
steady token streams), and which stay **mixed** (the classic fleet
behavior). The standard disaggregated-serving architecture surveyed in
PAPERS.md ("Inference Optimization of Foundation Models on AI
Accelerators", 2407.09111), built on three seams that already exist:

- **Placement**: the router sends a fresh session whose prompt is at
  least ``ROOM_TPU_DISAGG_PREFILL_TOKENS`` to the healthiest prefill
  replica; everything else prefers decode/mixed replicas. Affinity
  still wins for placed sessions — roles only choose the FIRST home.

- **Shipment**: when a prefill-homed session's turn completes (the
  prompt's KV fully materialized, the stream delivered contiguously
  from one replica — a turn's stream never splices across replicas),
  the coordinator exports the session (``ServingEngine.
  export_session``: park + offload + detached-spool, the exact crash-
  salvage format) and a decode replica adopts it
  (``adopt_parked_session``) so every subsequent turn decodes there.
  Same-process ships hand the detached spool file over directly —
  byte-identical to failover; with ``ROOM_TPU_DISAGG_WIRE=loopback``
  (or a cross-host deployment) the spool bytes travel as
  length-prefixed sha256-checksummed frames through
  ``parallel/multihost.KVWireServer`` — the first concrete cross-host
  pod seam.

- **Degradation**: the router's per-session history mirror is the
  fallback at every failure point. A refused export retries at the
  next turn boundary; a lost/corrupt/refused shipment (the ``kv_wire``
  fault point) adopts history-only — the decode replica re-prefills,
  pulling the shared system-prompt prefix from the prefix store
  (prefix_store.py) when it can. Zero durably-streamed tokens are ever
  lost, a session is never misrouted, and greedy continuations stay
  token-identical through every path (pinned in tests/test_disagg.py).

Role routing composes with the sharded router tier (docs/podnet.md):
roles pick the REPLICA a session computes on, router shards own the
RECORD that tracks it. A router shard crash aborts its records'
in-flight ships (``abort_ship_locked`` — the detached spool is
discarded, never adopted under a dead owner); after the sibling
adopts the shard's journal, the next turn re-ships or re-prefills
through the same degradation ladder as a lost shipment.

Thread model: the coordinator is driven by ``EngineFleet.supervise()``
(the fleet serve thread, or the synchronous ``run_until_idle`` driver)
and mutates ship state only under the fleet lock; engine interaction
happens exclusively through the queued export/adopt seams, which carry
their own engine-thread contracts.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import TYPE_CHECKING, Optional

from . import lifecycle as lifecycle_mod
from . import trace as trace_mod
from ..utils import knobs

if TYPE_CHECKING:   # pragma: no cover - import cycle guard
    from .fleet import EngineFleet, ReplicaHandle, _SessionRecord

__all__ = [
    "ROLES", "normalize_roles", "roles_from_env",
    "prefill_threshold_tokens", "wire_mode", "DisaggCoordinator",
]

log = logging.getLogger(__name__)

ROLES = ("prefill", "decode", "mixed")


def normalize_roles(roles, n_replicas: int) -> list[str]:
    """Pad/validate an explicit per-replica role list: missing
    entries default to ``mixed``, extras are ignored, an unknown role
    raises (a typo must be loud, not silently mixed)."""
    out = ["mixed"] * n_replicas
    for i, part in enumerate(list(roles)[:n_replicas]):
        part = str(part).strip() or "mixed"
        if part not in ROLES:
            raise ValueError(
                f"unknown fleet role {part!r}; known: {ROLES}"
            )
        out[i] = part
    return out


def roles_from_env(
    n_replicas: int, env: Optional[str] = None
) -> list[str]:
    """Parse ``ROOM_TPU_FLEET_ROLES`` — ','/';'-separated
    prefill|decode|mixed entries, replica i taking entry i. Missing
    entries default to ``mixed``; extras are ignored; an unknown role
    raises (a typo'd deployment must be loud, not silently mixed)."""
    spec = env if env is not None else \
        (knobs.get_str("ROOM_TPU_FLEET_ROLES") or "")
    # positions are the contract (replica i takes entry i): empty
    # entries stay IN PLACE and normalize to mixed — filtering them
    # out would silently shift roles onto the wrong replicas
    parts = [p.strip() for p in spec.replace(";", ",").split(",")]
    return normalize_roles(parts, n_replicas)


def prefill_threshold_tokens() -> int:
    try:
        return max(1, knobs.get_int("ROOM_TPU_DISAGG_PREFILL_TOKENS"))
    except ValueError:
        return 512


def wire_mode() -> str:
    mode = knobs.get_str("ROOM_TPU_DISAGG_WIRE") or "0"
    return mode if mode in ("0", "loopback") else "0"


class DisaggCoordinator:
    """Role-aware placement + the prefill->decode KV shipment state
    machine for one fleet.

    Ship states live on the router's ``_SessionRecord``
    (``ship_state``): None -> ``exporting`` (export queued on the
    donor engine) -> ``adopting`` (entry handed to the target's
    adoption queue) -> None. All transitions happen under the fleet
    lock inside ``advance()`` (the supervise tick) or ``cancel()``
    (the routing path when a new turn must land before the ship
    finishes)."""

    def __init__(self, fleet: "EngineFleet", roles: list[str]) -> None:
        self.fleet = fleet
        self.roles = list(roles)
        self.enabled = any(r != "mixed" for r in roles)
        self.threshold = prefill_threshold_tokens()
        self.wire = wire_mode()
        self._wire_server = None
        self._stats = {
            "prefill_placements": 0, "decode_placements": 0,
            "ships": 0, "ships_warm": 0, "ships_reprefill": 0,
            "ships_deferred": 0, "ships_refused": 0,
            "ship_wire": 0, "wire_errors": 0,
        }
        # records with a ship mid-flight (sid -> record), INDEPENDENT
        # of the router's record map: a session released mid-ship is
        # popped from fleet._records, and the coordinator must still
        # revisit it to discard the exported entry / release the
        # adopted ghost — mutated under the fleet lock
        self._inflight: dict = {}
        if self.enabled and self.wire == "loopback":
            self._start_wire_server()

    # ---- observability ----

    def _bump(self, key: str, n: int = 1) -> None:
        with self.fleet._lock:
            self._stats[key] += n

    def stats(self) -> dict:
        with self.fleet._lock:
            out = dict(self._stats)
        out["enabled"] = self.enabled
        out["wire"] = self.wire
        out["prefill_threshold_tokens"] = self.threshold
        if self._wire_server is not None:
            out["wire_address"] = list(self._wire_server.address)
            # receive counters + acceptor liveness incl. the
            # failed-join report (docs/podnet.md)
            out["wire_server"] = self._wire_server.stats()
        return out

    # ---- placement ----

    def pick(
        self, prompt_len: int, fresh: bool
    ) -> Optional["ReplicaHandle"]:
        """Role-aware replacement for the fleet's health-score pick.
        Fresh long prompts go to prefill replicas; everything else
        prefers decode/mixed. A missing role tier falls back to ANY
        serving replica — specialization degrades, availability does
        not."""
        fleet = self.fleet
        serving = fleet._serving_replicas()
        if not serving:
            return None
        best = lambda hs: max(hs, key=lambda h: h.health_score())  # noqa: E731
        if fresh and prompt_len >= self.threshold:
            pre = [h for h in serving if h.role == "prefill"]
            if pre:
                self._bump("prefill_placements")
                return best(pre)
        dec = [h for h in serving if h.role != "prefill"]
        if dec:
            self._bump("decode_placements")
            return best(dec)
        return best(serving)

    # ---- shipment state machine ----

    def pending(self) -> int:
        """Ships mid-flight (exporting/adopting) — the synchronous
        driver counts them as busy so ``run_until_idle`` returns only
        once every started handoff has landed (including ships whose
        record was released mid-flight and still owes cleanup)."""
        with self.fleet._lock:
            return len(self._inflight)

    def advance(self) -> None:
        """One coordinator tick (from EngineFleet.supervise): mark
        ships due at turn boundaries, collect finished exports, hand
        entries to decode replicas, finalize outcomes. ONE pass under
        the fleet lock pre-filters to actionable records (mid-flight
        ships + prefill-homed sessions with a completed turn) so the
        steady state — thousands of decode-homed sessions — costs one
        lock hold per tick, not one per record."""
        if not self.enabled:
            return
        fleet = self.fleet
        if fleet.lifecycle_phase == "draining":
            return
        with fleet._lock:
            # mid-flight ships first — tracked independently of the
            # record map so a release mid-ship can't orphan cleanup
            actionable = list(self._inflight.values())
            for rec in fleet._records.values():
                if rec.ship_state is not None:
                    continue   # already in _inflight
                if rec.routing > 0:
                    continue
                turn = rec.last_turn
                if turn is None or not turn.done.is_set():
                    continue
                donor = fleet._handle(rec.rid)
                if donor is not None and donor.role == "prefill":
                    actionable.append(rec)
        for rec in actionable:
            state = rec.ship_state
            if state is None:
                self._maybe_start(rec)
            elif state == "exporting":
                self._collect_export(rec)
            elif state == "adopting":
                self._finalize(rec)

    def _ship_targets(
        self, exclude: str
    ) -> list["ReplicaHandle"]:
        return [
            h for h in self.fleet._serving_replicas(exclude=exclude)
            if h.role != "prefill"
        ]

    def _maybe_start(self, rec) -> None:
        fleet = self.fleet
        with fleet._lock:
            if rec.ship_state is not None:
                return
            if rec.routing > 0:
                # a submit resolved its route but hasn't enqueued yet:
                # starting a ship now would export the session out
                # from under that turn (fork on the donor) — re-arm at
                # the next tick
                return
            if fleet._records.get(rec.sid) is not rec:
                return   # released/replaced meanwhile
            donor = fleet._handle(rec.rid)
            if donor is None or donor.role != "prefill" or \
                    not donor.is_serving():
                return
            turn = rec.last_turn
            if turn is None or not turn.done.is_set():
                return   # stream still in flight (or never started)
            if not self._ship_targets(donor.rid):
                return   # no decode home right now; retry next tick
            rec.ship_state = "exporting"
            rec.ship_event = threading.Event()
            rec.ship_t0 = time.monotonic()
            # fence the ship (docs/podnet.md): the export is valid for
            # THIS ownership generation only — a re-home while the
            # ship is in flight supersedes it and the dispatch below
            # refuses the stale entry instead of forking the session
            rec.ship_fence = rec.fence
            self._inflight[rec.sid] = rec
        # engine interaction outside the fleet lock: the export is
        # queued to the donor's engine thread (applied inline when no
        # loop owns it — the synchronous test driver)
        done, holder = donor.engine.export_session(rec.sid)
        with fleet._lock:
            rec.ship_export = (done, holder, donor.rid)
        self._collect_export(rec)

    def _collect_export(self, rec) -> None:
        fleet = self.fleet
        with fleet._lock:
            if rec.ship_state != "exporting" or rec.ship_export is None:
                return
            done, holder, donor_rid = rec.ship_export
            donor = fleet._handle(donor_rid)
        if donor is None or donor.state == "dead":
            # the donor died mid-export: failover owns this session
            # now — and a completed export's detached spool belongs to
            # nobody, so drop it rather than leak it
            if done.is_set():
                self._discard_entry(holder.get("entry"))
            self._abort(rec)
            return
        if not done.is_set():
            return   # engine hasn't applied the export yet; next tick
        with fleet._lock:
            released = fleet._records.get(rec.sid) is not rec
        if released:
            # the session was released mid-export: nothing must adopt
            # it anywhere — drop the exported entry (and its detached
            # spool) instead of creating an unreleasable ghost
            self._discard_entry(holder.get("entry"))
            self._abort(rec)
            return
        entry = holder.get("entry")
        if entry is None:
            # refused: back off. A BUSY session re-arms when the
            # racing turn completes (that turn replaced last_turn). An
            # unknown/durably-empty one (e.g. its only turn was shed
            # before any engine session formed) clears last_turn so
            # the ship re-arms at the NEXT completed turn — never a
            # permanent pin to the prefill replica, never a per-tick
            # retry of the same dead turn
            err = str(holder.get("error") or "")
            with fleet._lock:
                self.abort_ship_locked(rec)
                if err != "session busy" and \
                        rec.last_turn is not None and \
                        rec.last_turn.done.is_set():
                    rec.last_turn = None
            self._bump("ships_refused")
            return
        self._dispatch_entry(rec, entry, donor_rid)

    def _dispatch_entry(self, rec, entry: dict, donor_rid: str) -> None:
        """The exported entry is in hand: pick the decode target and
        hand the entry over — detached-spool adopt in-process, framed
        spool bytes over the wire in loopback mode — falling back to a
        history-only adopt on any wire failure (the kv_wire contract:
        degraded warmth, never a misroute or a fork)."""
        fleet = self.fleet
        with fleet._lock:
            released = fleet._records.get(rec.sid) is not rec
            stale = not released and rec.fence != rec.ship_fence
        if released:
            self._discard_entry(entry)
            self._abort(rec)
            return
        if stale:
            # a failover/re-home advanced the fence while the export
            # was in flight: the entry is a stale generation — refuse
            # it (the re-homed placement owns the history now)
            fleet.note_fence_refusal(
                rec.sid, rec.ship_fence,
                f"ship export from {donor_rid}",
            )
            self._discard_entry(entry)
            self._abort(rec)
            return
        entry["fence"] = rec.ship_fence
        targets = self._ship_targets(donor_rid)
        if not targets:
            # every decode sibling vanished between start and now:
            # park the entry on the record exactly like a deferred
            # failover re-home — the next route adopts it wherever
            # the fleet serves by then. Re-verify ownership INSIDE
            # the lock: a re-home racing this branch must not have
            # its newer placement unrouted by a stale park.
            with fleet._lock:
                released = fleet._records.get(rec.sid) is not rec
                stale = not released and rec.fence != rec.ship_fence
                if not released and not stale:
                    rec.rid = ""
                    rec.fence += 1
                    entry["fence"] = rec.fence
                    rec.pending_entry = entry
                    rec.pending_fingerprint = None
                    self._finish_ship_locked(
                        rec, outcome="deferred"
                    )
            if released or stale:
                if stale:
                    fleet.note_fence_refusal(
                        rec.sid, rec.ship_fence,
                        "ship defer superseded",
                    )
                self._discard_entry(entry)
                self._abort(rec)
                return
            fleet._journal_place(rec)
            self._bump_outcome("deferred")
            trace_mod.note_event("kv_ship_deferred", {
                "session": rec.sid, "from": donor_rid,
            })
            return
        target = max(targets, key=lambda h: h.health_score())
        if self.wire == "loopback" and self._wire_server is not None:
            reply, entry = self._ship_over_wire(rec, entry, target)
            if reply is not None and reply.get("adopted"):
                # the wire receiver already adopted into the target —
                # flip the placement and finalize (unless the session
                # was released mid-wire: then release the adopted copy
                # so no ghost survives)
                outcome = "warm" if reply.get("warm") else "reprefill"
                adopted_rid = str(reply.get("rid") or target.rid)
                with fleet._lock:
                    released = fleet._records.get(rec.sid) is not rec
                    # a re-home landing during the wire roundtrip
                    # advanced the fence: the receiver's adopted copy
                    # is an OLDER history and must not supersede the
                    # re-homed placement
                    stale = not released and \
                        rec.fence != rec.ship_fence
                    if not released and not stale:
                        rec.rid = adopted_rid
                        rec.rehomed += 1
                        rec.fence += 1
                    cur_rid = rec.rid
                    self._finish_ship_locked(rec, outcome)
                if released or stale:
                    if stale:
                        fleet.note_fence_refusal(
                            rec.sid, rec.ship_fence,
                            "wire ship superseded",
                        )
                    # same exception as _finalize: when the
                    # superseding placement itself landed on the
                    # adopting replica, the engine's duplicate-sid
                    # guard collapsed the copies — releasing there
                    # would destroy the LIVE session
                    adopter = fleet._handle(adopted_rid)
                    if adopter is not None and (
                        released or cur_rid != adopted_rid
                    ):
                        try:
                            adopter.engine.release_session(rec.sid)
                        except Exception:
                            pass
                    return
                fleet._journal_place(rec)
                self._bump_outcome(outcome)
                self._note_shipped(
                    rec, donor_rid, target,
                    warm=bool(reply.get("warm")), wired=True,
                )
                return
            if reply is not None:
                # the receiver ACCEPTED the frame but its queued
                # adoption hadn't applied by the reply deadline — it
                # may still land. Fall back history-only onto the SAME
                # replica the receiver named, so the engine-level
                # duplicate-sid guard dedupes the two adoptions on one
                # engine instead of registering the session twice
                # (the sender-side spool was consumed by the send)
                named = self.fleet._handle(
                    str(reply.get("rid") or "")
                )
                if named is not None and named.is_serving():
                    target = named
                entry = dict(entry)
                entry["kv"] = None
            # wire refused/failed: ``entry`` is history-only now —
            # adopt locally so the session is never lost
        # last ownership re-check before the adoption is queued: a
        # re-home that landed during the wire roundtrip (the receiver
        # may have refused this very entry as stale) advanced the
        # fence — adopting the older history now would fork the
        # session the fence refusal just protected
        with fleet._lock:
            released = fleet._records.get(rec.sid) is not rec
            stale = not released and rec.fence != rec.ship_fence
        if released or stale:
            if stale:
                fleet.note_fence_refusal(
                    rec.sid, rec.ship_fence,
                    "ship adopt superseded",
                )
            self._discard_entry(entry)
            self._abort(rec)
            return
        ev = target.engine.adopt_parked_session(
            entry, fingerprint=None, require_sha=False,
        )
        with fleet._lock:
            released = fleet._records.get(rec.sid) is not rec
            stale = not released and rec.fence != rec.ship_fence
            if not released and not stale:
                rec.rid = target.rid
                rec.rehomed += 1
                rec.fence += 1
                # re-mint the ship fence to the new generation so
                # _finalize's supersede check tracks LATER re-homes,
                # not this (sanctioned) transfer itself
                rec.ship_fence = rec.fence
            rec.ship_state = "adopting"
            rec.ship_export = None
            rec.ship_adopt = (ev, entry, target.rid)
        if not released and not stale:
            fleet._journal_place(rec)
            self._note_shipped(
                rec, donor_rid, target,
                entry.get("kv") is not None, wired=False,
            )
        self._finalize(rec)

    def _ship_over_wire(
        self, rec, entry: dict, target
    ) -> tuple[Optional[dict], dict]:
        """Frame the entry (+ spool bytes) through the loopback wire.
        Returns (reply, entry): on any failure — kv_wire fault, socket
        error, checksum refusal — the reply is None, the spool file is
        dropped, and the returned entry is history-only: re-prefill
        from the mirror, the documented degradation. The local spool
        file is consumed either way (the receiver persisted its own
        verified copy on success)."""
        from ..parallel.multihost import kv_wire_send

        donor_fp = None
        try:
            donor_fp = self.fleet._handle(
                rec.rid
            ).engine._lifecycle_fingerprint()
        except Exception:
            pass
        kv = entry.get("kv") if isinstance(entry.get("kv"), dict) \
            else None
        src = str(kv["file"]) if kv and kv.get("file") else None
        self._bump("ship_wire")
        try:
            from ..parallel.multihost import wire_timeout_s
            from . import podnet as podnet_mod

            # this runs on the SUPERVISE thread: split the configured
            # shipment timeout across the retry attempts so a
            # partitioned peer costs roughly one old-style timeout in
            # total (plus backoffs), not one per attempt — heartbeats,
            # failover detection, and routed turns wait behind this
            attempts = podnet_mod.wire_retries()
            reply = kv_wire_send(
                self._wire_server.address, entry,
                fingerprint=donor_fp, target_rid=target.rid,
                timeout_s=max(1.0, wire_timeout_s() / attempts),
            )
        except Exception as e:   # KVWireError / FaultError / OSError
            self._bump("wire_errors")
            log.warning(
                "fleet %s: kv wire ship of %s failed (%s); adopting "
                "history-only", self.fleet.model_name, rec.sid, e,
            )
            if src:
                try:
                    os.unlink(src)
                except OSError:
                    pass
            fallback = dict(entry)
            fallback["kv"] = None
            return None, fallback
        if src:
            try:
                os.unlink(src)   # receiver holds its own copy now
            except OSError:
                pass
        return reply, entry

    def _finalize(self, rec) -> None:
        fleet = self.fleet
        with fleet._lock:
            if rec.ship_state != "adopting" or rec.ship_adopt is None:
                return
            ev, entry, target_rid = rec.ship_adopt
            target = fleet._handle(target_rid)
        if target is None or target.state == "dead":
            self._abort(rec)
            return
        if not ev.is_set():
            return   # adoption applies at the target's next step
        with fleet._lock:
            released = fleet._records.get(rec.sid) is not rec
            # ship_fence was re-minted at the dispatch flip, so a
            # mismatch here means a LATER re-home superseded this
            # adoption — the adopted copy is an older history
            stale = not released and rec.fence != rec.ship_fence
            cur_rid = rec.rid
        if released or stale:
            # the target just adopted a session nobody owns (released)
            # or that a newer generation owns elsewhere (stale) —
            # release it there so no ghost holds pages/spool and no
            # fork survives. Exception: when the superseding placement
            # itself landed on this target, the engine's duplicate-sid
            # guard collapsed the two adoptions into the one session
            # that placement owns — releasing would destroy it.
            if stale:
                fleet.note_fence_refusal(
                    rec.sid, rec.ship_fence,
                    "ship finalize superseded",
                )
            if released or cur_rid != target.rid:
                try:
                    target.engine.release_session(rec.sid)
                except Exception:
                    pass
            self._abort(rec)
            return
        warm = False
        if entry.get("kv") is not None:
            store = getattr(target.engine, "offload_store", None)
            warm = store is not None and store.has(rec.sid)
        outcome = "warm" if warm else "reprefill"
        with fleet._lock:
            self._finish_ship_locked(rec, outcome)
        self._bump_outcome(outcome)

    def _finish_ship_locked(self, rec, outcome: str) -> None:
        """Terminal state cleanup; caller holds the fleet lock. The
        outcome counters go through _bump AFTER the caller releases
        it (``_bump_outcome``) — the fleet lock is not reentrant."""
        rec.ship_state = None
        rec.ship_export = None
        rec.ship_adopt = None
        rec.last_turn = None
        if self._inflight.get(rec.sid) is rec:
            del self._inflight[rec.sid]
        if rec.ship_event is not None:
            rec.ship_event.set()
            rec.ship_event = None

    def _bump_outcome(self, outcome: str) -> None:
        self._bump("ships")
        if outcome == "warm":
            self._bump("ships_warm")
        elif outcome == "reprefill":
            self._bump("ships_reprefill")
        elif outcome == "deferred":
            self._bump("ships_deferred")

    @staticmethod
    def _discard_entry(entry: Optional[dict]) -> None:
        """Unlink a no-longer-wanted exported entry's detached spool
        file (the adopter would have taken ownership; nobody will)."""
        if not isinstance(entry, dict):
            return
        kv = entry.get("kv")
        if isinstance(kv, dict) and kv.get("file"):
            try:
                os.unlink(str(kv["file"]))
            except OSError:
                pass

    def _abort(self, rec) -> None:
        with self.fleet._lock:
            entry = self.abort_ship_locked(rec)
        self._discard_entry(entry)

    def abort_ship_locked(self, rec) -> Optional[dict]:
        """Terminal ship cleanup for callers ALREADY HOLDING the fleet
        lock (the failover re-home path). Returns the completed
        export's entry, if any — the caller must ``_discard_entry`` it
        OUTSIDE the lock (its detached spool belongs to nobody once
        the ship dies)."""
        entry = None
        if rec.ship_export is not None:
            done, holder, _ = rec.ship_export
            if done.is_set():
                entry = holder.get("entry")
        if entry is None and rec.ship_adopt is not None:
            # an adoption the target never APPLIED (its thread died
            # before draining the queue) strands the entry's detached
            # spool; an applied one (ev set) moved ownership to the
            # target's store — salvage re-homes it from there
            ev, adopt_entry, _ = rec.ship_adopt
            if not ev.is_set():
                entry = adopt_entry
        rec.ship_state = None
        rec.ship_export = None
        rec.ship_adopt = None
        if self._inflight.get(rec.sid) is rec:
            del self._inflight[rec.sid]
        if rec.ship_event is not None:
            rec.ship_event.set()
            rec.ship_event = None
        return entry

    def _note_shipped(
        self, rec, donor_rid: str, target, warm: bool, wired: bool,
    ) -> None:
        ms = None
        if rec.ship_t0 is not None:
            ms = round((time.monotonic() - rec.ship_t0) * 1000.0, 3)
        # turnscope (docs/observability.md): ships happen BETWEEN
        # turns, so they land in the flight recorder's global event
        # ring — the trace answer to "why did this session move"
        trace_mod.note_event("kv_ship", {
            "session": rec.sid, "from": donor_rid, "to": target.rid,
            "warm": warm, "wire": wired, "ms": ms,
        })

    # ---- wire server (the cross-host receive seam) ----

    def _start_wire_server(self) -> None:
        from ..parallel.multihost import KVWireServer

        spool_dir = os.path.join(
            lifecycle_mod.engine_dir(self.fleet.model_name), "wire-in"
        )
        try:
            self._wire_server = KVWireServer(
                spool_dir, self._on_wire_entry,
                on_control=self._on_wire_control,
            )
        except OSError:
            log.exception(
                "fleet %s: kv wire server failed to start; ships "
                "fall back to in-process handoff",
                self.fleet.model_name,
            )
            self._wire_server = None

    def _on_wire_entry(
        self, entry: dict, fingerprint: Optional[dict],
        target_rid: Optional[str],
    ) -> dict:
        """Receiver side: adopt a wire-shipped entry into the named
        decode replica (or the healthiest one). Runs on the wire
        server's accept thread; adoption rides the engine's queued
        seam. The wire re-checksummed the payload in transit; the
        fingerprint check (against the receiving engine's config) and
        the spool sha verify-at-first-read run in adopt."""
        # fencing (docs/podnet.md): an export minted under an older
        # ownership generation — a sender healing from a partition
        # whose sessions were re-homed off it — is refused before any
        # engine sees it; split-brain cannot fork the history
        if self.fleet.refuse_stale_fence(
            str(entry.get("id") or ""), entry.get("fence"),
            origin="wire entry",
        ):
            return {"ok": False,
                    "error": "stale fence: ownership superseded"}
        # adopt ONLY into the replica the sender named: re-targeting
        # here would let a lost reply leave the session adopted on a
        # replica the sender doesn't know about (a two-engine ghost).
        # A refusal keeps placement authority with the sender, whose
        # history-only fallback never diverges.
        handle = self.fleet._handle(target_rid) if target_rid else None
        if handle is None or not handle.is_serving():
            return {"ok": False,
                    "error": f"target {target_rid!r} not serving"}
        from ..parallel.multihost import wire_timeout_s
        from . import podnet as podnet_mod

        ev = handle.engine.adopt_parked_session(
            entry, fingerprint=fingerprint, require_sha=True,
        )
        # the reply must beat the SENDER's socket timeout or the wait
        # is wasted (the sender would count a wire error and enqueue a
        # redundant history-only adoption a slow-but-alive target then
        # dedupes) — and the sender splits its shipment timeout across
        # its retry attempts (_ship_over_wire), so the margin is
        # against the PER-ATTEMPT timeout, not the whole budget
        sender_attempt_s = max(
            1.0, wire_timeout_s() / podnet_mod.wire_retries()
        )
        ev.wait(timeout=max(0.5, sender_attempt_s * 0.8))
        store = getattr(handle.engine, "offload_store", None)
        warm = entry.get("kv") is not None and store is not None \
            and store.has(str(entry.get("id")))
        return {"adopted": ev.is_set(), "warm": warm,
                "rid": handle.rid}

    def _on_wire_control(self, control: dict) -> dict:
        """Control frames (pod heartbeats over the RTKW wire,
        docs/podnet.md) dispatch to the fleet's pod coordinator."""
        return self.fleet.pod.handle_control(control)

    def close(self) -> None:
        if self._wire_server is not None:
            self._wire_server.close()
            self._wire_server = None
