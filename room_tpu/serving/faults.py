"""Injectable fault layer for the serving stack (chaos engineering).

The engine's concurrency invariants were discipline, not proof (VERDICT
r5 "What's weak" #6): nothing hammered submit/park/resume/release/evict
under induced failure. This module gives every hot-path failure mode a
named *fault point* that tests (and staging deployments) can arm:

    kv_alloc           page allocation fails (MemoryError)
    prefill_oom        prefill device call fails (transient)
    prefill_chunk      one interleaved chunked-prefill write fails
                       (docs/scheduler.md): the turn re-queues at its
                       last durable chunk boundary — committed chunks
                       stay, KV pages stay owned, nothing leaks
    decode_step        decode device call fails (transient)
    decode_window      multi-step dispatch window fails: the engine
                       fails ONLY the turns in that window (queued
                       work, parked sessions, and the page pool are
                       untouched; docs/serving.md)
    decode_stall       decode step sleeps `latency` seconds
    tokenizer          tokenizer encode/decode fails (transient)
    engine_crash       scheduler iteration raises (non-transient)
    client_disconnect  SSE stream aborts mid-generation
    provider_timeout   provider-level turn deadline forced to expire
    offload_io         KV offload copy-out / restore fails (transient;
                       exhaustion fails back to resident pages on the
                       way out, to a history re-prefill on the way in)
    shutdown_io        lifecycle manifest / drain-spool / marker I/O
                       fails (docs/lifecycle.md): a failed write loses
                       warmth (the restart re-prefills), a failed read
                       cold-starts — a drain or boot never hangs or
                       crashes on it
    replica_crash      one engine replica of a fleet dies hard
                       (docs/fleet.md): the supervisor re-homes its
                       sessions onto siblings — warm via adopted spool
                       files, re-prefill from the router's history
                       mirror otherwise — losing zero durably-streamed
                       tokens
    router_io          the fleet router's placement lookup fails
                       (docs/fleet.md): bounded retry; exhaustion sheds
                       the turn with the 503 contract — a session is
                       NEVER misrouted to a replica without its KV
    kv_wire            a prefill->decode KV shipment fails in transit
                       (docs/disagg.md): the decode replica adopts the
                       session history-only and re-prefills from the
                       router mirror — degraded warmth, zero
                       durably-streamed tokens lost, never a misroute
    prefix_io          shared prefix-store publish/pull I/O fails
                       (docs/disagg.md): a failed pull degrades to the
                       ordinary prefill miss, a failed publish skips —
                       correctness never depends on the store
    wire_partition     one KV-wire connection attempt fails in
                       transit (docs/podnet.md): bounded retry with
                       jittered backoff, a per-peer circuit breaker
                       past consecutive failures, and exhaustion
                       still degrades to the mirror re-prefill —
                       zero durably-streamed-token loss
    heartbeat_loss     a pod membership heartbeat is dropped
                       (docs/podnet.md): the member walks alive ->
                       suspect -> dead; past its session lease the
                       re-home machinery moves its sessions; a late
                       heartbeat before the lease expires heals it
    mirror_journal_io  a router-mirror journal read/write fails
                       (docs/podnet.md): the append is dropped (a
                       router crash then loses that much resume
                       warmth, never live correctness) and a corrupt
                       journal line is skipped at replay, never a
                       crash
    placement_io       a placement-map publish or apply is dropped
                       (docs/podnet.md): the epoch-versioned map is
                       re-published every supervise tick, so a lost
                       frame costs staleness (refused submits that
                       retry), never a fork — and a stale APPLY is
                       refused by the epoch check regardless
    router_shard_crash one router shard of N dies hard
                       (docs/podnet.md): its rooms' records and
                       journal freeze, submits for those rooms shed
                       until a surviving sibling adopts the shard's
                       mirror journal past the router lease, mints
                       fences +1, and publishes a new placement
                       epoch — bystander shards' rooms never stall

Swarm-layer points (docs/swarm_recovery.md) thread the same registry
up through the agent runtime above the engine:

    db_io              SQLite statement helper raises OperationalError
    cycle_crash        agent cycle / task run dies before its cleanup
                       handler (arm ``permanent`` to model a hard crash
                       that escapes the loop's handler entirely)
    loop_hang          agent-loop iteration stalls `latency` seconds
                       (stale-heartbeat watchdog territory)
    tool_exec          journaled tool side effect crashes between its
                       intent record and execution
    shard_crash        one swarm-runtime shard of N dies hard
                       (docs/swarmshard.md): its database handle
                       closes mid-flight, its agent loops stop, its
                       rooms shed until a sibling shard reopens the
                       file past the swarm lease, journal-recovers
                       it, and publishes a new placement epoch —
                       cross-shard dispatch redelivered afterwards
                       dedups on the journal's idempotency keys
    shard_proc_kill    SIGKILL one live swarm-shard child process at
                       the supervisor seam (docs/swarmshard.md
                       process mode): its rooms shed until the
                       supervisor restarts it under the
                       ROOM_TPU_SWARM_PROC_RESTARTS/window budget
                       (boot journal recovery abandons the intent a
                       mid-transaction kill left); past budget the
                       shard degrades to sibling adoption and goes
                       unhealthy — either way redelivered dispatch
                       halves dedup on their journal keys
    shard_wire_io      one cross-shard dispatch frame fails in
                       flight (parent→child wire_send_control):
                       the parent retries the frame — safe because
                       every frame carries its content-derived
                       idempotency key and the child journals
                       check-then-act, so a frame that DID land
                       before the failure report dedups on retry

Arming is per-point with probability / latency / one-shot triggers,
via code (`inject`) or env (`ROOM_TPU_FAULTS`), e.g.::

    ROOM_TPU_FAULTS="kv_alloc:p=0.1;decode_stall:latency=0.5,times=3"

The disarmed path costs one module-global bool check — production
traffic with no faults configured pays nothing measurable. All state is
process-global (the engine, providers, and HTTP layer must see one
registry) and thread-safe: tests arm from the driving thread while the
engine thread rolls the dice.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils import knobs, locks

__all__ = [
    "FaultError", "FaultSpec", "FAULT_POINTS", "inject", "clear",
    "configure_from_env", "is_active", "is_armed", "should_fire",
    "maybe_fail", "maybe_delay", "fired", "snapshot",
]

FAULT_POINTS = (
    "kv_alloc", "prefill_oom", "prefill_chunk",
    "decode_step", "decode_window",
    "decode_stall", "tokenizer", "engine_crash", "client_disconnect",
    "provider_timeout", "offload_io", "shutdown_io",
    # engine replica fleet (docs/fleet.md)
    "replica_crash", "router_io",
    # disaggregated prefill/decode + shared prefix store
    # (docs/disagg.md)
    "kv_wire", "prefix_io",
    # pod fault tolerance (docs/podnet.md)
    "wire_partition", "heartbeat_loss", "mirror_journal_io",
    # sharded router tier (docs/podnet.md)
    "placement_io", "router_shard_crash",
    # swarm runtime (docs/swarm_recovery.md)
    "db_io", "cycle_crash", "loop_hang", "tool_exec",
    # swarm shard tier (docs/swarmshard.md)
    "shard_crash",
    # multi-process swarm shards (docs/swarmshard.md "Process mode")
    "shard_proc_kill", "shard_wire_io",
)


class FaultError(RuntimeError):
    """An injected fault. ``transient`` marks faults the caller should
    retry with backoff (allocation races, flaky device calls); a
    non-transient fault models a real crash and must propagate to the
    supervisor. ``point`` names the fault point that fired — recovery
    paths that scope differently per point (decode_window fails only
    the window's turns; decode_step escalates to the crash supervisor)
    must dispatch on it, never on the message text."""

    def __init__(self, message: str, transient: bool = True,
                 point: Optional[str] = None) -> None:
        super().__init__(message)
        self.transient = transient
        self.point = point


@dataclass
class FaultSpec:
    """One armed fault point."""

    name: str
    probability: float = 1.0      # chance each check fires
    latency_s: float = 0.0        # sleep instead of / before raising
    times: Optional[int] = None   # remaining firings (None = unlimited)
    transient: bool = True        # retryable by the caller
    fired: int = 0
    _rng: random.Random = field(
        default_factory=lambda: random.Random(0xC4A05), repr=False
    )


_lock = locks.make_lock("faults")
_active: dict[str, FaultSpec] = {}
# fast-path flag: checked without the lock on every fault point
_armed = False


def _telemetry_count(name: str) -> None:
    # lazy import breaks any serving<->core import cycle; telemetry is
    # strictly best-effort from a fault point
    try:
        from ..core.telemetry import incr_counter

        incr_counter(f"fault.{name}")
    except Exception:
        pass


def _trace_event(name: str) -> None:
    # every firing lands in the flight recorder's global event ring
    # (docs/observability.md) under its trace.FAULT_EVENTS name —
    # roomlint's fault-trace coverage cross-check pins that every
    # FAULT_POINTS entry has one. Lazy + best-effort like telemetry.
    try:
        from . import trace

        trace.note_event(trace.FAULT_EVENTS.get(name, f"fault.{name}"),
                         {"point": name})
    except Exception:
        pass


def inject(
    name: str,
    *,
    probability: float = 1.0,
    latency_s: float = 0.0,
    times: Optional[int] = None,
    transient: bool = True,
    seed: Optional[int] = None,
) -> FaultSpec:
    """Arm a fault point. ``times=1`` is a one-shot trigger."""
    global _armed
    if name not in FAULT_POINTS:
        raise ValueError(
            f"unknown fault point {name!r}; known: {FAULT_POINTS}"
        )
    spec = FaultSpec(
        name=name, probability=probability, latency_s=latency_s,
        times=times, transient=transient,
    )
    if seed is not None:
        spec._rng = random.Random(seed)
    with _lock:
        _active[name] = spec
        _armed = True
    return spec


def clear(name: Optional[str] = None) -> None:
    """Disarm one fault point, or all of them (name=None)."""
    global _armed
    with _lock:
        if name is None:
            _active.clear()
        else:
            _active.pop(name, None)
        _armed = bool(_active)


def configure_from_env(env: Optional[str] = None) -> None:
    """Parse ``ROOM_TPU_FAULTS`` — ``;``-separated points, each
    ``name[:k=v,k=v...]`` with keys p/probability, latency, times,
    permanent (non-transient). Unknown names raise so a typo in a
    chaos-staging deployment is loud, not silently inert."""
    spec_str = env if env is not None else \
        knobs.get_str("ROOM_TPU_FAULTS")
    for part in filter(None, (s.strip() for s in spec_str.split(";"))):
        name, _, args = part.partition(":")
        kw: dict = {}
        for pair in filter(None, (a.strip() for a in args.split(","))):
            k, _, v = pair.partition("=")
            if k in ("p", "probability"):
                kw["probability"] = float(v)
            elif k == "latency":
                kw["latency_s"] = float(v)
            elif k == "times":
                kw["times"] = int(v)
            elif k == "once":
                kw["times"] = 1
            elif k == "permanent":
                kw["transient"] = False
            elif k == "seed":
                kw["seed"] = int(v)
            else:
                raise ValueError(f"unknown fault arg {k!r} in {part!r}")
        inject(name.strip(), **kw)


def is_armed() -> bool:
    """Lock-free fast-path flag: is ANY fault point armed? Layers that
    must not import this module unconditionally (the db layer resolves
    it through sys.modules) use this to skip maybe_fail entirely."""
    return _armed


def is_active(name: str) -> bool:
    if not _armed:
        return False
    with _lock:
        return name in _active


def should_fire(name: str) -> Optional[FaultSpec]:
    """Roll the dice for a fault point; consumes a one-shot budget and
    counts the firing. Returns the spec when the fault fires."""
    if not _armed:
        return None
    with _lock:
        spec = _active.get(name)
        if spec is None:
            return None
        if spec.times is not None and spec.times <= 0:
            return None
        if spec.probability < 1.0 and \
                spec._rng.random() >= spec.probability:
            return None
        if spec.times is not None:
            spec.times -= 1
        spec.fired += 1
    _telemetry_count(name)
    _trace_event(name)
    return spec


def maybe_fail(
    name: str,
    exc_factory: Optional[Callable[[str], BaseException]] = None,
) -> None:
    """Fault point: raise when the named fault fires. The default
    exception is FaultError carrying the spec's transience; pass
    ``exc_factory`` to raise the error class the surrounding recovery
    path actually handles (e.g. MemoryError for allocation)."""
    spec = should_fire(name)
    if spec is None:
        return
    if spec.latency_s > 0:
        import time

        time.sleep(spec.latency_s)
    msg = f"injected fault: {name}"
    if exc_factory is not None:
        raise exc_factory(msg)
    raise FaultError(msg, transient=spec.transient, point=name)


def maybe_delay(name: str) -> float:
    """Fault point: sleep the spec's latency when the fault fires (for
    stall injection). Returns the seconds slept."""
    spec = should_fire(name)
    if spec is None or spec.latency_s <= 0:
        return 0.0
    import time

    time.sleep(spec.latency_s)
    return spec.latency_s


def fired(name: str) -> int:
    with _lock:
        spec = _active.get(name)
        return spec.fired if spec else 0


def snapshot() -> dict[str, dict]:
    """Armed fault points and their firing counts (for /api/tpu/health
    and the TPU panel)."""
    with _lock:
        return {
            n: {
                "probability": s.probability,
                "latency_s": s.latency_s,
                "times_remaining": s.times,
                "transient": s.transient,
                "fired": s.fired,
            }
            for n, s in _active.items()
        }


# a chaos-staging deployment arms faults for the whole process lifetime
if knobs.get_str("ROOM_TPU_FAULTS"):
    configure_from_env()
