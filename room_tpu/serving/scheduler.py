"""SLO-aware request scheduler (docs/scheduler.md).

The subsystem between ``ServingEngine.submit()`` and the decode
pipeline. Three jobs:

1. **Priority classes with TTFT/TPOT targets.** Every turn carries a
   class — ``queen`` > ``worker`` > ``background`` — mapped from the
   swarm role that produced it (providers/tpu.py tags queen cycles,
   worker cycles, and background task runs). Each class has a
   time-to-first-token / time-per-output-token target
   (``ROOM_TPU_CLASS_TARGETS``); the scheduler tracks observed EMAs
   against them for the health surface.

2. **Deadline-aware admission ordering.** The queue is
   earliest-admission-deadline-first: a turn's admission deadline is
   ``submitted_at + its class's TTFT target``. A queen turn (tight
   target) beats a background turn submitted earlier, but a background
   turn can never starve — its deadline eventually becomes the
   earliest. Ties break by class rank, then submission order (so
   same-class traffic stays FIFO, which the engine's tests rely on).

3. **Class-weighted chunk budgets.** Long prompts prefill in
   page-sized chunks interleaved between decode windows (the engine's
   multi-step host-overlap seam). Per scheduler step, each class may
   write at most its chunk budget (``ROOM_TPU_CLASS_CHUNKS``): a
   4k-token background prefill advances one chunk per window instead
   of monopolizing a dispatch — the head-of-line-blocking fix from
   PAPERS.md "Inference Optimization of Foundation Models on AI
   Accelerators" (continuous batching with chunked prefill).

The scheduler also gives the degradation ladder (docs/chaos.md) its
per-class shape: shedding at rung 4 drops background turns before
workers before queens, and queens get one rung of grace on admission
halving. ``class_rung`` reports the rung each class actually
experiences.

Thread-safety: the queue is locked internally (submit() runs on HTTP
threads, pops on the engine thread); the budget/telemetry state shares
that lock.
"""

from __future__ import annotations

import heapq
import queue as queue_mod
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

from ..utils import knobs, locks

__all__ = [
    "TURN_CLASSES", "CLASS_RANK", "DEFAULT_CLASS", "ClassTargets",
    "RequestScheduler", "SpecTuner", "normalize_class", "classify_turn",
    "class_targets_from_env",
    "class_chunks_from_env", "chunk_pages_from_env",
]

# rank orders shed/keep decisions: lower rank is kept longest
TURN_CLASSES = ("queen", "worker", "background")
CLASS_RANK = {"queen": 0, "worker": 1, "background": 2}
DEFAULT_CLASS = "worker"

# rungs of ladder grace on ADMISSION pressure (rungs 3/4): queens keep
# full admission until the raw ladder is one rung deeper. Rungs 1/2
# (spec off, offload) are engine-global and get no grace.
CLASS_GRACE = {"queen": 1, "worker": 0, "background": 0}

# shed-ordering priority when the caller didn't set one explicitly
CLASS_PRIORITY = {"queen": 2, "worker": 1, "background": 0}


@dataclass(frozen=True)
class ClassTargets:
    """Per-class latency targets, in seconds."""

    ttft_s: float   # submit -> first streamed token
    tpot_s: float   # per-token interval once streaming


DEFAULT_TARGETS = {
    # queen turns are the p50 the paper's <4 s v5e-8 target hangs on
    "queen": ClassTargets(ttft_s=2.0, tpot_s=0.10),
    "worker": ClassTargets(ttft_s=8.0, tpot_s=0.25),
    "background": ClassTargets(ttft_s=30.0, tpot_s=1.0),
}

# chunks of interleaved prefill a class may write per scheduler step
DEFAULT_CHUNKS = {"queen": 4, "worker": 2, "background": 1}


def normalize_class(turn_class: Optional[str]) -> str:
    """Map an arbitrary tag to a known class (unknown -> worker: the
    middle class is the safe default for untagged external traffic)."""
    if turn_class in CLASS_RANK:
        return turn_class
    return DEFAULT_CLASS


def classify_turn(
    turn_class: Optional[str], priority: Optional[int] = None,
) -> str:
    """The scheduler's classifier for traffic that reaches a routing
    layer without an explicit class tag. A known tag always wins; an
    UNTAGGED turn that carries an explicit shed priority is classified
    from it through the inverse of CLASS_PRIORITY (0 -> background,
    1 -> worker, >=2 -> queen; negatives are background) — a
    background-priority turn must not be silently promoted to worker
    class just because its submitter forgot the tag. No signal at all
    falls back to the worker default, same as ``normalize_class``."""
    if turn_class in CLASS_RANK:
        return turn_class
    if priority is not None:
        if priority <= CLASS_PRIORITY["background"]:
            return "background"
        if priority >= CLASS_PRIORITY["queen"]:
            return "queen"
        return "worker"
    return DEFAULT_CLASS


def class_targets_from_env(
    env: Optional[str] = None,
) -> dict[str, ClassTargets]:
    """Parse ``ROOM_TPU_CLASS_TARGETS`` — ``;``-separated
    ``class=ttft:tpot`` (seconds), e.g.
    ``queen=2:0.1;worker=8:0.25;background=30:1``. Unknown classes and
    malformed entries raise (a typo'd SLO config must be loud)."""
    spec = env if env is not None else \
        knobs.get_str("ROOM_TPU_CLASS_TARGETS")
    out = dict(DEFAULT_TARGETS)
    for part in filter(None, (s.strip() for s in spec.split(";"))):
        name, _, vals = part.partition("=")
        name = name.strip()
        if name not in CLASS_RANK:
            raise ValueError(
                f"unknown class {name!r} in ROOM_TPU_CLASS_TARGETS; "
                f"known: {TURN_CLASSES}"
            )
        ttft_s, sep, tpot_s = vals.partition(":")
        if not sep:
            raise ValueError(
                f"ROOM_TPU_CLASS_TARGETS entry {part!r} must be "
                "class=ttft:tpot (seconds)"
            )
        out[name] = ClassTargets(
            ttft_s=float(ttft_s), tpot_s=float(tpot_s)
        )
    return out


def class_chunks_from_env(env: Optional[str] = None) -> dict[str, int]:
    """Parse ``ROOM_TPU_CLASS_CHUNKS`` — ``;``-separated
    ``class=n`` per-step chunk budgets. Clamped to >= 1: a zero budget
    would park a class's prefills forever."""
    spec = env if env is not None else \
        knobs.get_str("ROOM_TPU_CLASS_CHUNKS")
    out = dict(DEFAULT_CHUNKS)
    for part in filter(None, (s.strip() for s in spec.split(";"))):
        name, _, val = part.partition("=")
        name = name.strip()
        if name not in CLASS_RANK:
            raise ValueError(
                f"unknown class {name!r} in ROOM_TPU_CLASS_CHUNKS; "
                f"known: {TURN_CLASSES}"
            )
        out[name] = max(1, int(val))
    return out


def chunk_pages_from_env() -> int:
    """``ROOM_TPU_PREFILL_CHUNK_PAGES``: width of an interleaved
    prefill chunk, in KV pages (registry default 16). 0 disables
    interleaving (monolithic admission-time prefill, the
    pre-scheduler behavior)."""
    return max(0, knobs.get_int("ROOM_TPU_PREFILL_CHUNK_PAGES"))


class _SpecClassState:
    """Per-class speculative-drafting state, mutated on the engine
    thread at window drains (read by stats()/health snapshots)."""

    __slots__ = (
        "gamma", "ema", "proposed", "accepted", "emitted",
        "win_prop", "win_acc", "win_dry", "off", "resume_at",
        "throttles", "probes", "probe_pending",
    )

    def __init__(self, gamma: int) -> None:
        self.gamma = gamma
        self.ema: Optional[float] = None
        self.proposed = 0
        self.accepted = 0
        self.emitted = 0
        # acceptance window since the last adjustment
        self.win_prop = 0
        self.win_acc = 0
        # tokens emitted through proposal-less windows since the last
        # proposal/adjustment (nothing draftable in the class's traffic)
        self.win_dry = 0
        self.off = False
        self.resume_at = 0      # emitted-token count the probe re-arms at
        self.throttles = 0
        self.probes = 0
        # one dry drain has already arrived past resume_at: that
        # window was dispatched at gamma 0 BEFORE the cooldown
        # expired (pipelined windows drain one behind the dispatch
        # clock), so only the NEXT dry drain is the probe itself
        # coming back empty
        self.probe_pending = False


class SpecTuner:
    """Per-traffic-class speculative gamma auto-tuner (docs/serving.md).

    Replaces the engine's old GLOBAL acceptance-EMA/cost-ratio gate:
    each class (queen / worker / background) tracks its own running
    draft acceptance from live window drains (the same accounting
    ``spec_replay.ReplayStats`` models offline) and owns its own gamma
    and spec-off decision — queen tool-call echo traffic keeps a deep
    gamma while background prose ratchets down to spec-off, without
    either decision leaking across classes.

    Rules, applied once a class accumulates ``tune_every`` proposals:
    the class acceptance EMA updates; below ``floor`` the class goes
    SPEC-OFF for ``cooldown`` emitted tokens, after which single
    gamma-1 probe rounds refresh the estimate (the old global
    cooldown/probe contract, now per class); at or above the floor,
    gamma tracks ``ceil(ema * gamma_max)`` so a half-accepting class
    drafts half as deep instead of paying full-width verifies.

    The degradation ladder's spec-off rung is per-class too:
    ``gamma_for`` takes the RAW ladder level and applies CLASS_GRACE,
    so rung 1 silences background/worker drafting while queens keep
    theirs until rung 2.

    Single-writer (the engine thread, at drains); snapshots are
    GIL-atomic reads of plain ints/floats.
    """

    def __init__(
        self,
        gamma_max: int,
        *,
        floor: float = 0.0,
        ema_alpha: Optional[float] = None,
        cooldown: Optional[int] = None,
        tune_every: Optional[int] = None,
    ) -> None:
        self.gamma_max = max(0, int(gamma_max))
        self.floor = float(floor)
        self.ema_alpha = ema_alpha if ema_alpha is not None else \
            knobs.get_float("ROOM_TPU_SPEC_EMA")
        self.cooldown = cooldown if cooldown is not None else \
            knobs.get_int("ROOM_TPU_SPEC_COOLDOWN")
        self.tune_every = max(1, tune_every if tune_every is not None
                              else knobs.get_int("ROOM_TPU_SPEC_TUNE_EVERY"))
        self._cls = {c: _SpecClassState(self.gamma_max)
                     for c in TURN_CLASSES}

    def gamma_for(self, turn_class: str, raw_level: int) -> int:
        """Draft depth this class runs at right now: 0 under its
        per-class ladder spec-off rung, 0 while spec-off cooling down,
        1 for a post-cooldown probe round, else the adapted gamma."""
        if self.gamma_max <= 0:
            return 0
        cls = normalize_class(turn_class)
        if raw_level - CLASS_GRACE.get(cls, 0) >= 1:
            return 0
        st = self._cls[cls]
        if st.off:
            if st.emitted >= st.resume_at:
                return 1                      # probe round
            return 0
        return st.gamma

    def observe(
        self, turn_class: str, proposed: int, accepted: int,
        emitted: int,
    ) -> int:
        """Feed one drained turn-window's spec accounting. Returns the
        number of throttle events (off decisions) this observation
        triggered, so the engine can mirror them into
        ``stats()["spec_throttles"]``."""
        st = self._cls[normalize_class(turn_class)]
        st.emitted += emitted
        if proposed <= 0:
            # Dry emission: the window carried no proposals (nothing
            # in the class's traffic matched). While ON that is itself
            # a profitability signal — the acceptance EMA only sees
            # windows that carried drafts, so without this a class
            # serving non-repetitive prose would pin gamma at
            # gamma_max and pay the full-width verify forward forever.
            # A tune_every run of dry tokens decays the EMA toward
            # zero: gamma ratchets down and the floor can engage.
            # While OFF a gamma-0 cooldown window is expected to be
            # dry and only ticks the cooldown clock — but a dry PROBE
            # window (the gamma-1 round drafted nothing) counts as a
            # failed probe and re-arms the cooldown, or an undraftable
            # class would sit at gamma-1 probes forever. The first dry
            # drain past resume_at only marks the probe pending: under
            # pipelining that window was dispatched at gamma 0 before
            # the cooldown expired, and the probe itself drains next.
            if emitted <= 0:
                return 0
            if st.off:
                if st.emitted >= st.resume_at:
                    if st.probe_pending:
                        st.probe_pending = False
                        st.probes += 1
                        st.throttles += 1
                        st.resume_at = st.emitted + self.cooldown
                        return 1
                    st.probe_pending = True
                return 0
            st.win_dry += emitted
            if st.win_dry < self.tune_every:
                return 0
            st.win_dry = 0
            st.ema = 0.0 if st.ema is None else \
                (1 - self.ema_alpha) * st.ema
            st.gamma = self._gamma_from_ema(st.ema)
            if st.ema < self.floor:
                st.off = True
                st.throttles += 1
                st.resume_at = st.emitted + self.cooldown
                return 1
            return 0
        st.win_dry = 0
        st.probe_pending = False   # the probe did draft something
        st.proposed += proposed
        st.accepted += accepted
        st.win_prop += proposed
        st.win_acc += accepted
        # while off, a probe's small sample must be enough to decide —
        # waiting for a full tune_every of gamma-1 probes would pin the
        # class off for far longer than the cooldown promises
        need = max(1, self.tune_every // 4) if st.off else \
            self.tune_every
        if st.win_prop < need:
            return 0
        rate = st.win_acc / st.win_prop
        st.win_prop = st.win_acc = 0
        st.ema = rate if st.ema is None else (
            (1 - self.ema_alpha) * st.ema + self.ema_alpha * rate
        )
        if st.ema < self.floor:
            if st.off:
                st.probes += 1
            st.off = True
            st.throttles += 1
            st.resume_at = st.emitted + self.cooldown
            return 1
        if st.off:
            st.probes += 1
        st.off = False
        st.gamma = self._gamma_from_ema(st.ema)
        return 0

    def _gamma_from_ema(self, ema: float) -> int:
        """ceil(ema * gamma_max) with a 0.01 tolerance (the x100 int
        truncation) so float noise just under a boundary doesn't bump
        the depth, clamped to [1, gamma_max]."""
        return max(1, min(
            self.gamma_max, -(-int(ema * self.gamma_max * 100) // 100)
        ))

    def snapshot(self, raw_level: int = 0) -> dict:
        """Per-class spec state for stats()/health/metrics/panel."""
        out = {}
        for cls in TURN_CLASSES:
            st = self._cls[cls]
            out[cls] = {
                "gamma": self.gamma_for(cls, raw_level),
                "gamma_adapted": st.gamma,
                "accept_ema": round(st.ema, 4)
                if st.ema is not None else None,
                "acceptance": round(st.accepted / st.proposed, 4)
                if st.proposed else None,
                "proposed": st.proposed,
                "accepted": st.accepted,
                "emitted": st.emitted,
                "off": st.off,
                "throttles": st.throttles,
                "probes": st.probes,
            }
        return out


class _ClassStats:
    """Observed latency + throughput accounting for one class.
    Mutated under the scheduler lock."""

    __slots__ = (
        "submitted", "admitted", "completed", "shed",
        "ttft_ema", "tpot_ema", "ttft_worst", "chunks_written",
    )

    def __init__(self) -> None:
        self.submitted = 0
        self.admitted = 0
        self.completed = 0
        self.shed = 0
        self.ttft_ema: Optional[float] = None
        self.tpot_ema: Optional[float] = None
        self.ttft_worst = 0.0
        self.chunks_written = 0


class RequestScheduler:
    """Class-aware admission queue + per-step chunk budgets.

    Exposes the queue.Queue surface the engine already speaks
    (put / get / get_nowait / qsize / empty) so it drops in as the
    engine's ``_queue``; pops are earliest-admission-deadline-first
    instead of FIFO. Budget and telemetry methods are called from the
    engine thread; put() also from submit() threads.
    """

    EMA_ALPHA = 0.2

    def __init__(
        self,
        targets: Optional[dict[str, ClassTargets]] = None,
        chunk_budgets: Optional[dict[str, int]] = None,
    ) -> None:
        self.targets = targets or class_targets_from_env()
        self.chunk_budgets = chunk_budgets or class_chunks_from_env()
        # per-shard chunk budgets (docs/serving.md): under the
        # dp-sharded fused window each dp shard carries its own chunk
        # sub-batch in the same dispatch, so the engine scales the
        # per-step budget by the shard count it sets here (1 = the
        # unsharded window; set once at engine init, before traffic)
        self.chunk_shards = 1
        self._lock = locks.make_lock("scheduler")
        self._heap: list[tuple[float, int, int, Any]] = []
        self._seq = 0
        self._depth = {c: 0 for c in TURN_CLASSES}
        self._stats = {c: _ClassStats() for c in TURN_CLASSES}
        # per-step chunk accounting (begin_step resets)
        self._step_chunks = {c: 0 for c in TURN_CLASSES}
        self._steps = 0
        self._budget_hits = 0   # times a class ran out of step budget

    # ---- class helpers ----

    def admit_deadline(self, turn_class: str, submitted_at: float) -> float:
        """EDF key: the moment this turn's class TTFT target expires."""
        t = self.targets.get(
            normalize_class(turn_class), DEFAULT_TARGETS[DEFAULT_CLASS]
        )
        return submitted_at + t.ttft_s

    @staticmethod
    def class_rung(turn_class: str, raw_level: int) -> int:
        """The degradation rung a class actually experiences: rungs
        1/2 (spec off, offload) are engine-global; rungs 3/4
        (admission halved, shed) reach higher classes one raw rung
        later. Shedding inside rung 4 is additionally class-ordered —
        a queen queued behind the shed cap is dropped only once every
        background and worker turn already was."""
        if raw_level <= 2:
            return raw_level
        return max(2, raw_level - CLASS_GRACE.get(
            normalize_class(turn_class), 0
        ))

    # ---- queue surface (engine._queue drop-in) ----

    def put(self, turn) -> None:
        cls = normalize_class(getattr(turn, "turn_class", None))
        key = getattr(turn, "admit_by", 0.0) or self.admit_deadline(
            cls, getattr(turn, "submitted_at", time.monotonic())
        )
        with self._lock:
            # the seq tiebreak is pinned at FIRST enqueue and kept for
            # the turn's lifetime: a deferral/fault requeue re-enters
            # at its ORIGINAL queue position (same admit_by, same
            # seq), so same-class ordering stays stable — a turn
            # submitted later can never leapfrog a deferred one
            seq = getattr(turn, "_sched_seq", None)
            if seq is None:
                self._seq += 1
                seq = self._seq
                try:
                    turn._sched_seq = seq
                except Exception:
                    pass
            heapq.heappush(
                self._heap, (key, CLASS_RANK[cls], seq, turn)
            )
            self._depth[cls] += 1

    def _pop(self):
        _, _, _, turn = heapq.heappop(self._heap)
        cls = normalize_class(getattr(turn, "turn_class", None))
        self._depth[cls] -= 1
        return turn

    def get_nowait(self):
        with self._lock:
            if not self._heap:
                raise queue_mod.Empty
            return self._pop()

    def get(self):
        # the engine only calls get() after checking non-empty, from
        # the single scheduler thread — blocking semantics are not
        # needed, but keep the contract honest
        return self.get_nowait()

    def qsize(self) -> int:
        with self._lock:
            return len(self._heap)

    def empty(self) -> bool:
        return self.qsize() == 0

    def depth_by_class(self) -> dict[str, int]:
        with self._lock:
            return dict(self._depth)

    # ---- per-step chunk budget ----

    def begin_step(self) -> None:
        """Reset per-step chunk counters; called once per engine
        scheduler step (= once per dispatch window)."""
        with self._lock:
            self._steps += 1
            for c in self._step_chunks:
                self._step_chunks[c] = 0

    def take_chunk(self, turn_class: str) -> bool:
        """Consume one unit of the class's per-step chunk budget.
        False = budget exhausted; the caller defers the prefill to the
        next step (a decode window runs in between)."""
        cls = normalize_class(turn_class)
        budget = max(1, self.chunk_budgets.get(
            cls, DEFAULT_CHUNKS[DEFAULT_CLASS]
        )) * max(1, int(self.chunk_shards))
        with self._lock:
            if self._step_chunks[cls] >= budget:
                self._budget_hits += 1
                return False
            self._step_chunks[cls] += 1
            self._stats[cls].chunks_written += 1
            return True

    def refund_chunk(self, turn_class: str) -> None:
        """Return a consumed budget unit whose chunk never wrote
        (capacity deferral, injected fault): the class keeps its full
        step budget for siblings, and chunks_written stays an honest
        count of chunks actually on device."""
        cls = normalize_class(turn_class)
        with self._lock:
            if self._step_chunks[cls] > 0:
                self._step_chunks[cls] -= 1
            st = self._stats[cls]
            if st.chunks_written > 0:
                st.chunks_written -= 1

    # ---- telemetry ----

    def note_submitted(self, turn_class: str) -> None:
        with self._lock:
            self._stats[normalize_class(turn_class)].submitted += 1

    def note_admitted(self, turn_class: str) -> None:
        with self._lock:
            self._stats[normalize_class(turn_class)].admitted += 1

    def note_shed(self, turn_class: str) -> None:
        with self._lock:
            self._stats[normalize_class(turn_class)].shed += 1

    def observe_ttft(self, turn_class: str, ttft_s: float) -> None:
        with self._lock:
            st = self._stats[normalize_class(turn_class)]
            st.ttft_ema = ttft_s if st.ttft_ema is None else (
                (1 - self.EMA_ALPHA) * st.ttft_ema
                + self.EMA_ALPHA * ttft_s
            )
            st.ttft_worst = max(st.ttft_worst, ttft_s)

    def observe_tpot(self, turn_class: str, tpot_s: float) -> None:
        with self._lock:
            st = self._stats[normalize_class(turn_class)]
            st.tpot_ema = tpot_s if st.tpot_ema is None else (
                (1 - self.EMA_ALPHA) * st.tpot_ema
                + self.EMA_ALPHA * tpot_s
            )

    def note_completed(self, turn_class: str) -> None:
        with self._lock:
            self._stats[normalize_class(turn_class)].completed += 1

    def snapshot(self, raw_level: int = 0) -> dict:
        """Per-class scheduler state for stats()/health/the TPU panel:
        queue depth, observed TTFT/TPOT vs target, shed counts, chunk
        budget + utilization, and the rung each class experiences."""
        with self._lock:
            depth = dict(self._depth)
            steps = self._steps
            budget_hits = self._budget_hits
            rows = {}
            for cls in TURN_CLASSES:
                st = self._stats[cls]
                tgt = self.targets[cls]
                budget = max(1, self.chunk_budgets.get(
                    cls, DEFAULT_CHUNKS[DEFAULT_CLASS]
                )) * max(1, int(self.chunk_shards))
                rows[cls] = {
                    "queued": depth[cls],
                    "rung": self.class_rung(cls, raw_level),
                    "submitted": st.submitted,
                    "admitted": st.admitted,
                    "completed": st.completed,
                    "shed": st.shed,
                    "ttft_target_s": tgt.ttft_s,
                    "ttft_ema_s": round(st.ttft_ema, 4)
                    if st.ttft_ema is not None else None,
                    "ttft_worst_s": round(st.ttft_worst, 4),
                    "ttft_ok": st.ttft_ema is None
                    or st.ttft_ema <= tgt.ttft_s,
                    "tpot_target_s": tgt.tpot_s,
                    "tpot_ema_s": round(st.tpot_ema, 4)
                    if st.tpot_ema is not None else None,
                    "tpot_ok": st.tpot_ema is None
                    or st.tpot_ema <= tgt.tpot_s,
                    "chunk_budget": budget,
                    "chunks_written": st.chunks_written,
                    # mean chunks actually written per step vs budget
                    "chunk_budget_util": round(
                        st.chunks_written / (budget * steps), 4
                    ) if steps else 0.0,
                }
        return {
            "classes": rows,
            "steps": steps,
            "budget_hits": budget_hits,
            "chunk_shards": max(1, int(self.chunk_shards)),
        }
