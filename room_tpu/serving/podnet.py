"""Pod fault tolerance: membership heartbeats, fenced session
ownership, wire retry/backoff + circuit breaking, and the
crash-durable router mirror (docs/podnet.md).

The disaggregated pod (docs/disagg.md) gave the fleet its cross-host
seams — framed-RTKW KV shipments, role replicas, the shared prefix
store — but every seam assumed a polite failure: a socket error was
terminal (one attempt), a silent host was invisible (nothing detected
it), a healed host could replay a stale export into a live session
(split-brain fork), and the router's session records died with its
process. This module is the robustness layer the ROADMAP's multi-host
pod item blocks on, in four pieces:

- **Membership** (``PodMembership`` + ``PodCoordinator``): each pod
  member heartbeats — over the existing framed-RTKW wire when a
  listener exists, in-process otherwise — into a
  deadline-with-suspicion failure detector: ``alive`` -> ``suspect``
  (``ROOM_TPU_POD_SUSPECT_S`` of silence; routing unchanged) ->
  ``dead`` (``ROOM_TPU_POD_DEAD_S``). A dead member's **session
  lease** (``ROOM_TPU_POD_LEASE_S``) then runs out, and only past it
  does the coordinator drive the exact re-home machinery the
  ``replica_crash`` failover uses today — a lagging-but-alive host
  that heartbeats again inside the lease heals without losing a
  session. The ``heartbeat_loss`` fault point drops heartbeats at the
  observe seam so chaos tests walk the whole ladder.

- **Fencing**: session ownership carries a monotonic fence generation
  (``_SessionRecord.fence`` — the same monotonic-counter pattern the
  decode pipeline's per-slot admission generation uses). Every
  ownership transfer (re-home, ship, absorb) advances it; wire frames
  and ship exports carry the fence they were minted under; a host
  returning from a partition presents a stale fence and its
  export/adoption is *refused* — a session's history structurally
  cannot fork. Refusals are counted (``fence_refusals``) and land in
  the flight recorder.

- **Wire hardening** (``CircuitBreaker`` + the retry policy consumed
  by ``parallel/multihost.kv_wire_send``): bounded attempts
  (``ROOM_TPU_WIRE_RETRIES``) with jittered exponential backoff, and
  a per-peer breaker that opens after ``ROOM_TPU_WIRE_BREAKER_FAILS``
  consecutive failures, lets one half-open probe through per cooldown,
  and closes on success — a partitioned peer costs one fast refusal,
  not a timeout per shipment. Exhaustion keeps the existing contract:
  degrade to the router-mirror re-prefill, zero durably-streamed
  tokens lost. The ``wire_partition`` fault point fails individual
  attempts so tests drive retry, breaker, and exhaustion separately.

- **Crash-durable router mirror** (``MirrorJournal``): the router's
  per-session records (placement, fence, token mirror) journal to a
  versioned, checksummed sidecar — a sha256-stamped snapshot plus a
  crc32-per-line append log with batched token appends
  (``ROOM_TPU_POD_MIRROR_BATCH``), the ``lifecycle.py`` manifest
  pattern applied incrementally. A router restart replays the journal
  and re-parks every in-flight session for adoption at its next route
  instead of orphaning it. Token appends carry their mirror offset,
  so a dropped line (``mirror_journal_io``) is detected as a hole at
  replay and that session degrades to a cold start — never a forked
  re-prefill.

- **Sharded router tier** (``PlacementMap`` + the fleet's
  ``_RouterShard`` slices, docs/podnet.md): with
  ``ROOM_TPU_ROUTER_SHARDS`` > 1 the router's session records, fences,
  and mirror journal partition by room-id hash across N independent
  shards, fronted by an epoch-versioned placement map (room-id ->
  shard) replicated to pod peers (``ROOM_TPU_POD_PEERS``) over the
  same ``wire_send_control`` frames heartbeats use. Router failover is
  the lease/fence dance replicas already do: a dead shard's rooms shed
  (retryable 503) for ``ROOM_TPU_ROUTER_LEASE_S``, then a surviving
  sibling adopts the dead shard's journal (``replay_journal_dir`` —
  offset holes refused, tombstones honored), mints every fence +1, and
  publishes a new placement epoch; a healed stale-epoch router's
  submits are refused by the epoch check — one room structurally has
  one owner. ``placement_io`` drops publish/apply frames;
  ``router_shard_crash`` kills the busiest shard in supervise.

Thread model: the membership table, each breaker, the placement map,
and the journal buffers sit behind their own registered locks
(``locks.make_lock`` — lockmap/lockdep cover them); none of them calls
into an engine or the fleet while held. The coordinator runs inside
the fleet's supervise tick and takes the fleet lock only through the
fleet's own seams.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import random
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils import knobs, locks

__all__ = [
    "CircuitBreaker", "breaker_for", "reset_breakers",
    "wire_retries", "wire_backoff_s",
    "MEMBER_ALIVE", "MEMBER_SUSPECT", "MEMBER_DEAD",
    "PodMember", "PodMembership", "PodCoordinator",
    "PlacementMap", "MirrorJournal",
    "replay_journal_dir", "consume_journal_dir",
]

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# wire retry policy + per-peer circuit breaker
# ---------------------------------------------------------------------------

def wire_retries() -> int:
    """Total attempts for one wire send (>= 1)."""
    try:
        return max(1, knobs.get_int("ROOM_TPU_WIRE_RETRIES"))
    except ValueError:
        return 3


def wire_backoff_s(
    attempt: int, rng: Optional[random.Random] = None
) -> float:
    """Jittered exponential backoff before retry ``attempt`` (0-based
    count of failures so far): ``base * 2^attempt`` scaled by a
    uniform 0.5..1.5 jitter, capped at the configured max — retries
    from a healing pod must not arrive in lockstep."""
    try:
        base = max(0.0, knobs.get_float("ROOM_TPU_WIRE_BACKOFF_S"))
    except ValueError:
        base = 0.05
    try:
        cap = max(0.0, knobs.get_float("ROOM_TPU_WIRE_BACKOFF_MAX_S"))
    except ValueError:
        cap = 2.0
    if base <= 0.0:
        return 0.0
    jitter = 0.5 + (rng.random() if rng is not None else
                    random.random())
    return min(cap, base * (2.0 ** attempt) * jitter)


class CircuitBreaker:
    """Per-peer wire circuit breaker: ``closed`` -> ``open`` after N
    consecutive failures -> ``half_open`` after the cooldown (exactly
    one probe allowed through) -> ``closed`` on probe success, back to
    ``open`` on probe failure. Threshold 0 disables the breaker (every
    call allowed)."""

    def __init__(
        self,
        peer: str,
        threshold: Optional[int] = None,
        cooldown_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.peer = peer
        if threshold is None:
            try:
                threshold = max(
                    0, knobs.get_int("ROOM_TPU_WIRE_BREAKER_FAILS")
                )
            except ValueError:
                threshold = 5
        if cooldown_s is None:
            try:
                cooldown_s = max(0.0, knobs.get_float(
                    "ROOM_TPU_WIRE_BREAKER_COOLDOWN_S"
                ))
            except ValueError:
                cooldown_s = 5.0
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = locks.make_lock("podnet_breaker")
        self._state = "closed"
        self._fails = 0
        self._opened_at = 0.0
        self._probing = False
        self._opens = 0
        self._rejections = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a call go to this peer now? Open circuits refuse fast;
        past the cooldown exactly one half-open probe passes until its
        outcome is recorded."""
        if self.threshold <= 0:
            return True
        with self._lock:
            if self._state == "closed":
                return True
            now = self._clock()
            if self._state == "open":
                if now - self._opened_at < self.cooldown_s:
                    self._rejections += 1
                    return False
                self._state = "half_open"
                self._probing = False
            # half_open: one probe in flight at a time
            if self._probing:
                self._rejections += 1
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._state = "closed"
            self._fails = 0
            self._probing = False

    def record_failure(self) -> None:
        if self.threshold <= 0:
            return
        with self._lock:
            self._fails += 1
            if self._state == "half_open":
                # the probe failed: re-open and restart the cooldown
                self._state = "open"
                self._opened_at = self._clock()
                self._opens += 1
                self._probing = False
            elif self._state == "closed" and \
                    self._fails >= self.threshold:
                self._state = "open"
                self._opened_at = self._clock()
                self._opens += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._fails,
                "opens": self._opens,
                "rejections": self._rejections,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
            }


_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = locks.make_lock("podnet_breakers")


def _peer_key(address) -> str:
    if isinstance(address, (tuple, list)) and len(address) >= 2:
        return f"{address[0]}:{address[1]}"
    return str(address)


def breaker_for(address) -> CircuitBreaker:
    """The process-wide breaker for one peer address (every sender to
    a peer shares its failure history — that is what makes the breaker
    a partition detector rather than a per-call retry budget)."""
    key = _peer_key(address)
    with _breakers_lock:
        br = _breakers.get(key)
        if br is None:
            br = _breakers[key] = CircuitBreaker(key)
        return br


def reset_breakers() -> None:
    """Drop all per-peer breaker state (tests; a config reload)."""
    with _breakers_lock:
        _breakers.clear()


def breakers_snapshot() -> dict:
    with _breakers_lock:
        items = list(_breakers.items())
    return {k: b.snapshot() for k, b in items}


# ---------------------------------------------------------------------------
# membership: deadline-with-suspicion failure detector
# ---------------------------------------------------------------------------

MEMBER_ALIVE = "alive"
MEMBER_SUSPECT = "suspect"
MEMBER_DEAD = "dead"


@dataclass
class PodMember:
    """One pod member's detector state (mutated under the membership
    lock only)."""

    member_id: str
    state: str = MEMBER_ALIVE
    last_seen: float = 0.0
    dead_at: Optional[float] = None
    lease_fired: bool = False
    heartbeats: int = 0
    heartbeats_lost: int = 0


class PodMembership:
    """Deadline-with-suspicion membership table: silence past
    ``suspect_s`` suspects a member, past ``dead_s`` declares it dead,
    and ``lease_s`` beyond that expires its session lease (the
    coordinator re-homes only then). A heartbeat at ANY point before
    the lease fires heals the member back to alive with nothing
    lost."""

    def __init__(
        self,
        suspect_s: Optional[float] = None,
        dead_s: Optional[float] = None,
        lease_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        def _knob(name: str, fallback: float) -> float:
            try:
                return max(0.0, knobs.get_float(name))
            except ValueError:
                return fallback

        self.suspect_s = suspect_s if suspect_s is not None else \
            _knob("ROOM_TPU_POD_SUSPECT_S", 3.0)
        self.dead_s = dead_s if dead_s is not None else \
            _knob("ROOM_TPU_POD_DEAD_S", 6.0)
        # a mis-ordered config must not detect dead before suspect
        self.dead_s = max(self.dead_s, self.suspect_s)
        self.lease_s = lease_s if lease_s is not None else \
            _knob("ROOM_TPU_POD_LEASE_S", 2.0)
        self._clock = clock
        self._lock = locks.make_lock("podnet_membership")
        self._members: dict[str, PodMember] = {}

    def register(self, member_id: str) -> None:
        now = self._clock()
        with self._lock:
            if member_id not in self._members:
                self._members[member_id] = PodMember(
                    member_id, last_seen=now
                )

    def forget(self, member_id: str) -> None:
        with self._lock:
            self._members.pop(member_id, None)

    def observe(
        self, member_id: str, now: Optional[float] = None
    ) -> bool:
        """One heartbeat from a member. Rolls the ``heartbeat_loss``
        fault point — a dropped beat is counted, not applied — and
        heals a suspect/dead member whose lease has not yet fired.
        Returns True when the beat was applied."""
        from . import faults

        now = self._clock() if now is None else now
        lost = faults.should_fire("heartbeat_loss") is not None
        with self._lock:
            m = self._members.get(member_id)
            if m is None:
                m = self._members[member_id] = PodMember(
                    member_id, last_seen=now
                )
            if lost:
                m.heartbeats_lost += 1
                return False
            m.heartbeats += 1
            m.last_seen = now
            if m.lease_fired:
                # its sessions were already re-homed: the member comes
                # back as a fresh (fenced-out) peer, alive again
                m.lease_fired = False
            if m.state != MEMBER_ALIVE:
                m.state = MEMBER_ALIVE
                m.dead_at = None
            return True

    def tick(
        self, now: Optional[float] = None
    ) -> list[tuple[str, str, str]]:
        """Advance the detector; returns ``(member_id, old, new)``
        transitions observed this pass."""
        now = self._clock() if now is None else now
        events: list[tuple[str, str, str]] = []
        with self._lock:
            for m in self._members.values():
                silence = now - m.last_seen
                if m.state == MEMBER_ALIVE and \
                        silence >= self.suspect_s:
                    m.state = MEMBER_SUSPECT
                    events.append(
                        (m.member_id, MEMBER_ALIVE, MEMBER_SUSPECT)
                    )
                if m.state == MEMBER_SUSPECT and \
                        silence >= self.dead_s:
                    m.state = MEMBER_DEAD
                    m.dead_at = now
                    events.append(
                        (m.member_id, MEMBER_SUSPECT, MEMBER_DEAD)
                    )
        return events

    def lease_expired(
        self, now: Optional[float] = None
    ) -> list[str]:
        """Dead members whose session lease has run out and has not
        yet been consumed — each id is returned exactly once (the
        caller owns the re-home)."""
        now = self._clock() if now is None else now
        out: list[str] = []
        with self._lock:
            for m in self._members.values():
                if m.state == MEMBER_DEAD and not m.lease_fired and \
                        m.dead_at is not None and \
                        now - m.dead_at >= self.lease_s:
                    m.lease_fired = True
                    out.append(m.member_id)
        return out

    def state_of(self, member_id: str) -> Optional[str]:
        with self._lock:
            m = self._members.get(member_id)
            return m.state if m is not None else None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                m.member_id: {
                    "state": m.state,
                    "heartbeats": m.heartbeats,
                    "heartbeats_lost": m.heartbeats_lost,
                    "lease_fired": m.lease_fired,
                }
                for m in self._members.values()
            }


class PlacementMap:
    """Epoch-versioned room-id -> router-shard map (docs/podnet.md).

    The base placement is a stable content hash (crc32 of the session
    id mod ``n_shards`` — deterministic across processes and restarts,
    so every pod member computes the same home without coordination).
    A shard failover overlays a **redirect** (dead shard -> adopter,
    chains followed) and bumps the **epoch**; the map replicates to
    pod peers as a control frame, and ``apply`` refuses any frame
    whose epoch is not strictly newer — a healed stale router cannot
    re-install the pre-failover ownership, so one room structurally
    has one owner. ``placement_io`` fires at the publish and apply
    seams (a dropped frame costs staleness, never a fork)."""

    def __init__(self, n_shards: int) -> None:
        self.n_shards = max(1, int(n_shards))
        self._lock = locks.make_lock("placement_map")
        self._epoch = 0
        self._redirects: dict[int, int] = {}
        self._stats = {
            "rehomes": 0, "stale_applies_refused": 0,
            "applies": 0, "submit_refusals": 0,
        }

    def _bump(self, key: str, n: int = 1) -> None:
        # callers hold self._lock (non-reentrant): this is the single
        # mutation point the stats()/snapshot() readers rely on, not a
        # lock-taking helper like the engine's
        self._stats[key] += n

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def shard_of(self, sid: str) -> int:
        """Resolve a room/session id to its current owning shard:
        stable hash, then follow failover redirects (cycle-guarded —
        a malformed replicated frame must not hang the router)."""
        k = zlib.crc32(str(sid).encode("utf-8")) % self.n_shards
        with self._lock:
            seen = set()
            while k in self._redirects and k not in seen:
                seen.add(k)
                k = self._redirects[k]
        return k % self.n_shards

    def rehome(self, dead: int, adopter: int) -> int:
        """Record a shard failover (dead -> adopter) and bump the
        epoch. Returns the new epoch; the caller owes a publish."""
        with self._lock:
            self._redirects[int(dead)] = int(adopter)
            # an earlier failover may have redirected INTO the shard
            # that just died: re-point those chains at the adopter so
            # lookups stay one hop deep
            for src, dst in list(self._redirects.items()):
                if dst == int(dead):
                    self._redirects[src] = int(adopter)
            self._epoch += 1
            self._bump("rehomes")
            return self._epoch

    def frame(self) -> dict:
        """The replicated control-frame payload."""
        with self._lock:
            return {
                "kind": "placement",
                "epoch": self._epoch,
                "n_shards": self.n_shards,
                "redirects": {
                    str(k): int(v)
                    for k, v in self._redirects.items()
                },
            }

    def apply(self, frame: dict) -> bool:
        """Install a replicated placement frame. Refused (False) when
        the frame's epoch is not strictly newer than ours — the
        split-brain guard: after a heal, whichever side published last
        wins and the stale side's map (and its submits, via
        ``stale_epoch``) is rejected. The ``placement_io`` fault drops
        the apply the way a lost frame would."""
        from . import faults

        try:
            faults.maybe_fail("placement_io")
            epoch = int(frame.get("epoch"))
            redirects = {
                int(k): int(v)
                for k, v in (frame.get("redirects") or {}).items()
            }
        except Exception:
            return False
        with self._lock:
            if epoch <= self._epoch:
                self._bump("stale_applies_refused")
                return False
            self._epoch = epoch
            self._redirects = redirects
            self._bump("applies")
        return True

    def stale_epoch(self, epoch) -> bool:
        """Is a submitter's captured epoch older than the map's? (A
        healed router re-submitting under the pre-failover epoch must
        be refused and told to re-route.)"""
        if epoch is None:
            return False
        try:
            epoch = int(epoch)
        except (TypeError, ValueError):
            return True
        with self._lock:
            if epoch < self._epoch:
                self._bump("submit_refusals")
                return True
        return False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "epoch": self._epoch,
                "n_shards": self.n_shards,
                "redirects": {
                    str(k): v for k, v in self._redirects.items()
                },
                **self._stats,
            }


def pod_peers() -> list[tuple[str, int]]:
    """Parse ``ROOM_TPU_POD_PEERS`` into control-wire addresses."""
    raw = knobs.get_str("ROOM_TPU_POD_PEERS") or ""
    out: list[tuple[str, int]] = []
    for part in filter(None, (p.strip() for p in raw.split(","))):
        host, _, port = part.rpartition(":")
        try:
            out.append((host or "127.0.0.1", int(port)))
        except ValueError:
            log.warning("ROOM_TPU_POD_PEERS: bad address %r", part)
    return out


class PodCoordinator:
    """Glue between the membership detector and one ``EngineFleet``:
    registers every replica as a pod member, heartbeats them each
    supervise tick (over the fleet's RTKW wire listener when one
    exists, in-process otherwise), and — once a member is dead AND its
    lease has expired — drives the replica_crash re-home machinery
    (``fleet.kill_replica``) so the member's sessions move to
    survivors with zero durably-streamed-token loss.

    Inert (every call a cheap no-op) unless ``ROOM_TPU_POD_MEMBERSHIP``
    is set. ``partition``/``heal`` are the chaos/ops seam: a
    partitioned member's heartbeats stop reaching the detector without
    its process/thread dying — exactly the failure the detector
    exists for."""

    def __init__(self, fleet) -> None:
        self.fleet = fleet
        self.enabled = knobs.get_bool("ROOM_TPU_POD_MEMBERSHIP")
        try:
            self.heartbeat_s = max(
                0.0, knobs.get_float("ROOM_TPU_POD_HEARTBEAT_S")
            )
        except ValueError:
            self.heartbeat_s = 1.0
        self.membership = PodMembership()
        self._partitioned: set[str] = set()
        self._last_beat = 0.0
        self._stats = {
            "heartbeats_sent": 0, "heartbeats_lost": 0,
            "heartbeats_wire": 0, "members_suspected": 0,
            "members_died": 0, "lease_rehomes": 0,
            "placements_published": 0, "placement_publish_drops": 0,
        }
        if self.enabled:
            for h in fleet.replicas:
                self.membership.register(h.rid)

    def _bump(self, key: str, n: int = 1) -> None:
        # the coordinator ticks on the fleet's supervise thread; the
        # fleet lock makes its counters coherent with fleet_stats()
        with self.fleet._lock:
            self._stats[key] += n

    # ---- chaos / ops seam ----

    def partition(self, member_id: str) -> None:
        """Stop delivering this member's heartbeats (the member itself
        keeps running — a network partition, not a crash)."""
        self._partitioned.add(member_id)

    def heal(self, member_id: str) -> None:
        self._partitioned.discard(member_id)

    def partitioned(self, member_id: str) -> bool:
        return member_id in self._partitioned

    # ---- heartbeats ----

    def handle_control(self, control: dict) -> dict:
        """Wire-server control-frame dispatch (the receive side of a
        framed-RTKW heartbeat)."""
        kind = control.get("kind")
        if kind == "heartbeat":
            member = str(control.get("member") or "")
            if not member:
                return {"ok": False, "error": "heartbeat w/o member"}
            applied = self.membership.observe(member)
            return {
                "ok": True, "applied": applied,
                "member_state": self.membership.state_of(member),
            }
        if kind == "placement":
            # replicated placement map (sharded router tier): install
            # iff strictly newer — the receive half of the epoch fence
            placement = getattr(self.fleet, "placement", None)
            if placement is None:
                return {"ok": False, "error": "no placement map"}
            applied = placement.apply(control)
            return {
                "ok": True, "applied": applied,
                "epoch": placement.epoch,
            }
        return {"ok": False, "error": f"unknown control {kind!r}"}

    def publish_placement(self) -> int:
        """Replicate the fleet's placement map to every configured
        pod peer (``ROOM_TPU_POD_PEERS``) as a control frame. Runs on
        the supervise thread after every epoch bump; best-effort per
        peer (the breaker + retry policy bound a partitioned peer's
        cost, and the next bump re-publishes). Returns peers that
        acknowledged. Independent of the membership knob: shard
        failover needs the epoch fence even in a single-member pod,
        where the peer list is simply empty."""
        from . import faults, trace as trace_mod
        from .faults import FaultError

        placement = getattr(self.fleet, "placement", None)
        if placement is None:
            return 0
        frame = placement.frame()
        try:
            faults.maybe_fail("placement_io")
        except FaultError:
            # the publish was dropped in flight: peers stay one epoch
            # behind until the next bump — their stale submits are
            # refused by the epoch check, so staleness never forks
            self._bump("placement_publish_drops")
            return 0
        peers = pod_peers()
        acked = 0
        if peers:
            from ..parallel.multihost import wire_broadcast_control

            replies = wire_broadcast_control(peers, frame)
            acked = sum(
                1 for r in replies.values()
                if isinstance(r, dict) and r.get("ok")
            )
        self._bump("placements_published")
        trace_mod.note_event("placement_published", {
            "epoch": frame["epoch"], "peers": len(peers),
            "acked": acked,
        })
        return acked

    def _beat_one(self, rid: str, wire_address) -> None:
        if wire_address is not None:
            from ..parallel.multihost import (
                KVWireError, wire_send_control, wire_timeout_s,
            )

            try:
                # one attempt, bounded WELL under the detector's own
                # deadlines: the heartbeat cadence is the retry, and
                # the shared per-peer breaker makes a hard-down wire
                # fail fast — a beat must never stall the supervise
                # thread past the suspect/dead windows it enforces
                reply = wire_send_control(
                    tuple(wire_address),
                    {"kind": "heartbeat", "member": rid},
                    timeout_s=min(
                        wire_timeout_s(),
                        max(0.25, self.heartbeat_s),
                    ),
                    retries=1,
                )
                self._bump("heartbeats_wire")
                if reply.get("applied") is False:
                    # delivered but dropped at the observe seam (the
                    # heartbeat_loss fault): the loss counter must
                    # see it just like the in-process path's
                    self._bump("heartbeats_lost")
                return
            except (KVWireError, OSError):
                # the wire channel failed, but this member lives IN
                # THIS PROCESS — its liveness is directly observable,
                # and a dead/saturated LISTENER must not escalate to
                # killing every healthy replica. Count the wire loss
                # (health shows the sick channel) and fall through to
                # the in-process observe. A future cross-host member
                # has no such fallback: there the wire IS liveness.
                self._bump("heartbeats_lost")
        if not self.membership.observe(rid):
            self._bump("heartbeats_lost")

    def tick(self) -> None:
        """One supervise-tick pass: emit due heartbeats, advance the
        detector, re-home members whose lease expired. Never called
        under a lock; all fleet interaction goes through the fleet's
        own public seams."""
        if not self.enabled:
            return
        fleet = self.fleet
        now = time.monotonic()
        if now - self._last_beat >= self.heartbeat_s:
            self._last_beat = now
            wire = getattr(fleet.disagg, "_wire_server", None)
            wire_address = wire.address if wire is not None else None
            for h in fleet.replicas:
                if h.rid in self._partitioned or h.state == "dead":
                    continue
                if not getattr(h.engine, "healthy", True):
                    continue
                self._bump("heartbeats_sent")
                self._beat_one(h.rid, wire_address)
        for member_id, old, new in self.membership.tick(now):
            from . import trace as trace_mod

            if new == MEMBER_SUSPECT:
                self._bump("members_suspected")
            elif new == MEMBER_DEAD:
                self._bump("members_died")
            log.warning(
                "pod %s: member %s %s -> %s",
                fleet.model_name, member_id, old, new,
            )
            trace_mod.note_event("pod_member_state", {
                "member": member_id, "from": old, "to": new,
            })
        for member_id in self.membership.lease_expired(now):
            h = fleet._handle(member_id)
            if h is None or h.state == "dead":
                continue
            self._bump("lease_rehomes")
            log.warning(
                "pod %s: member %s lease expired; re-homing its "
                "sessions", fleet.model_name, member_id,
            )
            fleet.kill_replica(
                member_id,
                reason="pod membership: heartbeat lease expired",
            )

    def stats(self) -> dict:
        out = {"enabled": self.enabled}
        if not self.enabled:
            return out
        with self.fleet._lock:
            out.update(self._stats)
        out["members"] = self.membership.snapshot()
        out["partitioned"] = sorted(self._partitioned)
        return out


# ---------------------------------------------------------------------------
# crash-durable router mirror
# ---------------------------------------------------------------------------

JOURNAL_VERSION = 1
JOURNAL_NAME = "mirror.jsonl"
SNAPSHOT_NAME = "snapshot.json"


def _crc_line(rec: str) -> str:
    return f"{zlib.crc32(rec.encode('utf-8')):08x} {rec}\n"


def _parse_line(line: str) -> Optional[dict]:
    """One ``crc32-hex json`` journal line -> dict, or None for a
    torn/corrupt line (a crash mid-write truncates the tail; the crc
    catches subtler damage)."""
    head, sep, rec = line.rstrip("\n").partition(" ")
    if not sep or len(head) != 8:
        return None
    try:
        if int(head, 16) != zlib.crc32(rec.encode("utf-8")):
            return None
        obj = json.loads(rec)
    except (ValueError, TypeError):
        return None
    return obj if isinstance(obj, dict) else None


class MirrorJournal:
    """Versioned, checksummed sidecar for the fleet router's session
    records: a sha256-stamped ``snapshot.json`` (the ``lifecycle.py``
    manifest pattern) plus an append-only ``mirror.jsonl`` whose lines
    each carry a crc32 — ``place`` (sid -> rid/fence/generation),
    ``tok`` (mirror tokens at an explicit offset, batched by
    ``ROOM_TPU_POD_MIRROR_BATCH``), ``rel`` (release). ``replay``
    rebuilds sid -> record state; an offset gap (a line the
    ``mirror_journal_io`` fault or an I/O error dropped) marks the
    session incomplete so its resume degrades to a cold start instead
    of a forked re-prefill.

    Durability target is a ROUTER PROCESS crash (the restart case):
    every write reaches the OS before the append returns, no fsync —
    host-power-loss durability is the lifecycle volume's problem.
    Every file op degrades on failure (drop the append, count it);
    nothing here may crash or stall the token hot path."""

    def __init__(
        self,
        dir_path: str,
        batch: Optional[int] = None,
        compact_lines: Optional[int] = None,
    ) -> None:
        self.dir = dir_path
        if batch is None:
            try:
                batch = max(
                    1, knobs.get_int("ROOM_TPU_POD_MIRROR_BATCH")
                )
            except ValueError:
                batch = 1
        if compact_lines is None:
            try:
                compact_lines = max(16, knobs.get_int(
                    "ROOM_TPU_POD_MIRROR_COMPACT"
                ))
            except ValueError:
                compact_lines = 4096
        self.batch = batch
        self.compact_lines = compact_lines
        self._lock = locks.make_lock("pod_mirror_journal")
        # sid -> (start_offset, [tokens]) pending one `tok` line
        self._buffers: dict[str, tuple[int, list[int]]] = {}
        self._fh = None
        # compaction window: while True, formatted lines park in
        # _pending_lines instead of the file, then land in the NEW
        # journal after the swap — an append racing the snapshot can
        # duplicate a token the snapshot already covers (replay's
        # overlap rule absorbs that) but can never be lost
        self._swapping = False
        self._pending_lines: list[str] = []
        self._lines = 0
        self._stats = {
            "appends": 0, "tok_lines": 0, "errors": 0,
            "compactions": 0, "replayed_sessions": 0,
            "replay_incomplete": 0,
        }
        fh = None
        err = False
        lines = 0
        try:
            os.makedirs(dir_path, exist_ok=True)
            jpath = os.path.join(dir_path, JOURNAL_NAME)
            try:
                # count what the previous incarnation left so the
                # compaction threshold fires across restarts — a
                # crash-looping router must not grow the journal
                # unboundedly, one sub-threshold run at a time
                with open(jpath, "r", encoding="utf-8") as f:
                    lines = sum(1 for _ in f)
            except OSError:
                lines = 0
            fh = open(jpath, "a", encoding="utf-8")
        except OSError:
            err = True
        with self._lock:
            self._fh = fh
            self._lines = lines
        if err:
            self._bump("errors")

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._stats[key] += n

    # ---- write side ----

    def _write(self, obj: dict) -> None:
        """Append one checksummed line; caller holds NO lock. Failure
        (injected mirror_journal_io or real I/O) drops the line —
        replay detects the hole via token offsets."""
        from . import faults

        line = _crc_line(json.dumps(obj, separators=(",", ":")))
        try:
            faults.maybe_fail("mirror_journal_io")
            with self._lock:
                if self._swapping:
                    self._pending_lines.append(line)
                else:
                    if self._fh is None:
                        raise OSError("journal unavailable")
                    self._fh.write(line)
                    self._fh.flush()
                    self._lines += 1
        except Exception:
            self._bump("errors")
            return
        self._bump("appends")

    def record_place(
        self, sid: str, rid: str, fence: int, generation: int = 0,
    ) -> None:
        self.flush(sid)
        self._write({
            "op": "place", "sid": sid, "rid": rid,
            "fence": int(fence), "gen": int(generation),
        })

    def append_tokens(
        self, sid: str, toks: list, offset: int
    ) -> None:
        """Buffer mirror tokens whose first element sits at mirror
        ``offset``; a full batch (or an adjacent-op flush) writes one
        ``tok`` line. Non-contiguous appends flush the old run
        first."""
        flush_line = None
        with self._lock:
            buf = self._buffers.get(sid)
            if buf is not None and buf[0] + len(buf[1]) == offset:
                buf[1].extend(int(t) for t in toks)
                start, pend = buf
            else:
                if buf is not None:
                    flush_line = (sid, buf)
                start, pend = offset, [int(t) for t in toks]
                self._buffers[sid] = (start, pend)
            if len(pend) >= self.batch:
                del self._buffers[sid]
                ready = (sid, (start, pend))
            else:
                ready = None
        if flush_line is not None:
            self._write_tok(*flush_line)
        if ready is not None:
            self._write_tok(*ready)

    def _write_tok(self, sid: str, buf: tuple[int, list]) -> None:
        self._bump("tok_lines")
        self._write({
            "op": "tok", "sid": sid, "off": buf[0], "t": buf[1],
        })

    def pending_snapshot(self) -> dict:
        """sid -> (start_offset, pending_len) for every un-flushed
        token buffer — the invariant witness's offset-contiguity
        probe (chaos/invariants.py) compares these against the live
        record mirrors."""
        with self._lock:
            return {
                sid: (buf[0], len(buf[1]))
                for sid, buf in self._buffers.items()
            }

    def record_release(self, sid: str) -> None:
        with self._lock:
            self._buffers.pop(sid, None)
        self._write({"op": "rel", "sid": sid})

    def record_drop(self, sid: str) -> None:
        """Tombstone a session's mirror for the REST of this journal
        (a cap eviction: the live mirror stops here but the session
        keeps streaming unjournaled). Unlike ``rel``, replay ignores
        every line for the sid afterwards — an in-flight token append
        racing the eviction cannot resurrect the truncated prefix as
        a complete history (the fork hazard). The next compaction
        rebuilds the snapshot from live records and clears the
        tombstone."""
        with self._lock:
            self._buffers.pop(sid, None)
        self._write({"op": "drop", "sid": sid})

    def flush(self, sid: Optional[str] = None) -> None:
        with self._lock:
            if sid is None:
                ready = list(self._buffers.items())
                self._buffers.clear()
            else:
                buf = self._buffers.pop(sid, None)
                ready = [(sid, buf)] if buf is not None else []
        for s, buf in ready:
            self._write_tok(s, buf)

    def flush_all(self) -> None:
        self.flush(None)

    # ---- compaction ----

    def should_compact(self) -> bool:
        with self._lock:
            return self._fh is not None and \
                self._lines >= self.compact_lines

    def compact(self, sessions) -> bool:
        """Rewrite the snapshot from the caller's authoritative record
        view and start a fresh journal. ``sessions`` is a list, or —
        the race-free form the fleet uses — a CALLABLE built AFTER
        this method parks concurrent appends in memory: any line
        racing the snapshot/swap lands in the new journal (a token
        the snapshot already covers replays as a harmless overlap,
        never a loss, never a hole). File opens/renames happen
        OUTSIDE the journal lock (lockmap blocking-under-lock)."""
        from . import faults

        with self._lock:
            self._swapping = True
        try:
            if callable(sessions):
                sessions = sessions()
            payload = json.dumps(sessions, separators=(",", ":"))
            snap = {
                "version": JOURNAL_VERSION,
                "written_at": time.time(),
                "sha256": hashlib.sha256(
                    payload.encode("utf-8")
                ).hexdigest(),
                "sessions": sessions,
            }
            path = os.path.join(self.dir, SNAPSHOT_NAME)
            jpath = os.path.join(self.dir, JOURNAL_NAME)
            tmp = path + ".tmp"
            jtmp = jpath + ".tmp"
            new_fh = None
            try:
                faults.maybe_fail("mirror_journal_io")
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(snap, f, separators=(",", ":"))
                new_fh = open(jtmp, "w", encoding="utf-8")
                os.replace(tmp, path)
                os.replace(jtmp, jpath)
            except Exception:
                self._bump("errors")
                if new_fh is not None:
                    try:
                        new_fh.close()
                    except OSError:
                        pass
                for p in (tmp, jtmp):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
                # parked lines still belong to the OLD journal
                self._unswap(None)
                return False
        except Exception:
            self._bump("errors")
            self._unswap(None)
            return False
        self._unswap(new_fh)
        self._bump("compactions")
        return True

    def _unswap(self, new_fh) -> None:
        """End a compaction window: swap in ``new_fh`` (None keeps
        the old journal — the failure path) and drain the lines that
        parked during the window into whichever journal survives."""
        with self._lock:
            old = None
            if new_fh is not None:
                old = self._fh
                self._fh = new_fh
                self._lines = 0
                # _buffers survives the swap: a batched token run the
                # snapshot already covers flushes later as an overlap
                # replay absorbs; clearing it would drop the tokens
                # appended during the window (offset hole, cold start)
            parked, self._pending_lines = self._pending_lines, []
            if parked and self._fh is not None:
                try:
                    for line in parked:
                        self._fh.write(line)
                    self._fh.flush()
                    self._lines += len(parked)
                except OSError:
                    parked_err = True
                else:
                    parked_err = False
            else:
                parked_err = bool(parked)
            self._swapping = False
        if parked_err:
            self._bump("errors")
        if old is not None:
            try:
                old.close()
            except OSError:
                pass

    def clear(self) -> None:
        """Consume the sidecar (a clean drain wrote a manifest; stale
        journal state must not resurrect released sessions)."""
        with self._lock:
            old = self._fh
            self._fh = None
            self._buffers.clear()
            self._lines = 0
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        for name in (JOURNAL_NAME, SNAPSHOT_NAME):
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass

    def close(self) -> None:
        self.flush_all()
        with self._lock:
            old = self._fh
            self._fh = None
        if old is not None:
            try:
                old.close()
            except OSError:
                pass

    # ---- crash seam (router-shard chaos) ----

    def crash(self) -> None:
        """Model the owning router shard dying hard: in-memory token
        buffers and any compaction-parked lines are LOST (a real
        process death loses exactly those), the file handle closes
        without a flush, and the on-disk journal/snapshot stay put for
        a surviving sibling to adopt via ``replay_journal_dir``."""
        with self._lock:
            old = self._fh
            self._fh = None
            self._buffers.clear()
            self._pending_lines = []
            self._swapping = False
        if old is not None:
            try:
                old.close()
            except OSError:
                pass

    def size_bytes(self) -> int:
        """On-disk sidecar footprint (journal + snapshot), for the
        per-shard health block."""
        total = 0
        for name in (JOURNAL_NAME, SNAPSHOT_NAME):
            try:
                total += os.path.getsize(os.path.join(self.dir, name))
            except OSError:
                pass
        return total

    # ---- replay ----

    def replay(self) -> dict[str, dict]:
        """Rebuild sid -> {tokens, rid, fence, generation, complete}
        from snapshot + journal. Never raises; a corrupt snapshot is
        ignored (journal offsets then expose the gap), corrupt lines
        are skipped, and any offset discontinuity marks that session
        ``complete=False`` — the caller must treat an incomplete
        mirror as cold (re-prefilling a holey history would fork).
        Tombstoned (cap-evicted) sessions do not appear here — the
        adoption path reads them via ``replay_journal_dir``."""
        state = replay_journal_dir(self.dir)
        good = sum(1 for e in state.values()
                   if e["complete"] and not e.get("dropped"))
        self._bump("replayed_sessions", good)
        self._bump(
            "replay_incomplete",
            sum(1 for e in state.values()
                if not e["complete"] and not e.get("dropped")),
        )
        return {
            sid: e for sid, e in state.items() if not e.get("dropped")
        }

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["pending_buffers"] = len(self._buffers)
            out["lines"] = self._lines
            out["batch"] = self.batch
        return out


def replay_journal_dir(dir_path: str) -> dict[str, dict]:
    """Rebuild sid -> {tokens, rid, fence, generation, complete,
    dropped} from one journal directory, WITHOUT a live MirrorJournal
    instance — the shard-adoption and boot-absorption paths read dead
    shards' sidecars this way. Same hole/overlap discipline as
    ``MirrorJournal.replay``; additionally, a tombstoned (``drop``)
    session survives as ``dropped=True`` carrying its last placement —
    the adopter must keep honoring the eviction (warm-only failover,
    never a resurrected prefix) while preserving the room's replica
    affinity."""
    from . import faults

    state: dict[str, dict] = {}

    def entry(sid: str) -> dict:
        e = state.get(sid)
        if e is None:
            e = state[sid] = {
                "tokens": [], "rid": "", "fence": 0,
                "generation": 0, "complete": True, "dropped": False,
            }
        return e

    try:
        faults.maybe_fail("mirror_journal_io")
        with open(os.path.join(dir_path, SNAPSHOT_NAME),
                  "r", encoding="utf-8") as f:
            snap = json.load(f)
    except Exception:
        snap = None
    if isinstance(snap, dict) and \
            snap.get("version") == JOURNAL_VERSION and \
            isinstance(snap.get("sessions"), list):
        payload = json.dumps(
            snap["sessions"], separators=(",", ":")
        )
        if hashlib.sha256(
            payload.encode("utf-8")
        ).hexdigest() == snap.get("sha256"):
            for s in snap["sessions"]:
                if not isinstance(s, dict) or not s.get("sid"):
                    continue
                e = entry(str(s["sid"]))
                e["tokens"] = [int(t) for t in s.get("tokens")
                               or []]
                e["rid"] = str(s.get("rid") or "")
                e["fence"] = int(s.get("fence") or 0)
                e["generation"] = int(s.get("gen") or 0)
    try:
        with open(os.path.join(dir_path, JOURNAL_NAME),
                  "r", encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        lines = []
    for line in lines:
        obj = _parse_line(line)
        if obj is None:
            continue
        op = obj.get("op")
        sid = str(obj.get("sid") or "")
        if not sid:
            continue
        if op == "drop":
            # tombstone: the mirror prefix is dead for the REST of
            # this journal, but the placement/fence survive so an
            # adopting shard keeps the room's affinity warm-only
            e = entry(sid)
            e["tokens"] = []
            e["complete"] = False
            e["dropped"] = True
            continue
        if state.get(sid, {}).get("dropped"):
            continue
        if op == "rel":
            state.pop(sid, None)
        elif op == "place":
            e = entry(sid)
            e["rid"] = str(obj.get("rid") or "")
            e["fence"] = max(
                e["fence"], int(obj.get("fence") or 0)
            )
            e["generation"] = int(obj.get("gen") or 0)
        elif op == "tok":
            e = entry(sid)
            off = int(obj.get("off") or 0)
            toks = obj.get("t") or []
            if off != len(e["tokens"]):
                if off < len(e["tokens"]):
                    # overlap from a line racing a compaction
                    # snapshot: positions are authoritative, so
                    # keep the covered prefix and extend with
                    # whatever suffix is new (possibly nothing)
                    skip = len(e["tokens"]) - off
                    if len(toks) > skip:
                        e["tokens"].extend(
                            int(t) for t in toks[skip:]
                        )
                    continue
                # off > len: a dropped line left a HOLE — only an
                # exact continuation is trustworthy
                e["complete"] = False
                continue
            e["tokens"].extend(int(t) for t in toks)
    return state


def consume_journal_dir(dir_path: str) -> None:
    """Unlink one journal directory's sidecar files (its sessions were
    absorbed elsewhere — a stale journal must not resurrect them at
    the next replay). Best-effort, like every journal file op."""
    for name in (JOURNAL_NAME, SNAPSHOT_NAME):
        try:
            os.unlink(os.path.join(dir_path, name))
        except OSError:
            pass
