"""On-mesh embedding service (reference: src/shared/embeddings.ts ran
all-MiniLM-L6-v2 on CPU ONNX; here the 384-d encoder is a JAX model on
the same platform as the LLM, with an on-device similarity index so
recall is one dot + top_k).

Hermetic default: tiny encoder + byte tokenizer, random weights (vector
quality is irrelevant to the machinery; tests pin determinism and
geometry). Production: ROOM_TPU_EMBED_CKPT + ROOM_TPU_TOKENIZER_PATH load
the real MiniLM-class weights."""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

import numpy as np

from ..utils import knobs, locks

_host_lock = locks.make_lock("embed_host")
_host: Optional["EmbedHost"] = None

MAX_TOKENS = 128


class EmbedHost:
    def __init__(self) -> None:
        import jax

        from ..models import embedder
        from ..models.config import minilm_384, tiny_encoder
        from .tokenizer import load_tokenizer

        use_real = bool(knobs.get_str("ROOM_TPU_EMBED_CKPT"))
        self.cfg = minilm_384() if use_real else tiny_encoder()
        self.tokenizer = load_tokenizer()
        params = embedder.init_params(self.cfg, jax.random.PRNGKey(7))
        ckpt = knobs.get_str("ROOM_TPU_EMBED_CKPT")
        if ckpt and os.path.isdir(ckpt):
            from ..utils.checkpoint import load_params

            params = load_params(ckpt, like=params)
        self.params = params
        self._encode = jax.jit(
            lambda p, t, m: embedder.encode(p, self.cfg, t, m)
        )
        self.dim = self.cfg.hidden

    def warmup(self) -> None:
        """Compile the encoder shapes up front so the first swarm cycles
        don't each pay a ~1s XLA compile mid-prompt. Rows are bucketed
        too, so each length bucket is warmed at 1 row AND the indexer's
        typical batch size (reference indexes in batches of 10 →
        rows bucket 16; embedding-indexer.ts:5)."""
        # probe by TOKEN count (tokenizers differ in tokens-per-char):
        # find a text unit, then size each probe to land in its bucket
        unit = "w "
        per_unit = max(1, len(self.tokenizer.encode(unit * 8)) // 8)
        for bucket in (16, 32, 64, 128):
            n_units = -(-(bucket // 2 + 1) // per_unit)  # ceil
            text = unit * n_units
            self.embed([text])
            self.embed([text] * 10)

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        import jax.numpy as jnp

        if not texts:
            return np.zeros((0, self.dim), np.float32)
        batch = []
        for text in texts:
            ids = self.tokenizer.encode(text)[:MAX_TOKENS]
            ids = [min(t, self.cfg.vocab_size - 1) for t in ids] or [0]
            batch.append(ids)
        max_len = max(len(x) for x in batch)
        # bucket BOTH dims so the jit cache converges to a handful of
        # shapes (an unpadded batch dim made every new batch size a
        # fresh ~1s XLA compile — a per-cycle stall under swarm load)
        bucket = 16
        while bucket < max_len:
            bucket *= 2
        rows = 1
        while rows < len(batch):
            rows *= 2
        toks = np.zeros((rows, bucket), np.int32)
        mask = np.zeros((rows, bucket), np.float32)
        for i, ids in enumerate(batch):
            toks[i, : len(ids)] = ids
            mask[i, : len(ids)] = 1.0
        out = self._encode(
            self.params, jnp.asarray(toks), jnp.asarray(mask)
        )
        return np.asarray(out, np.float32)[: len(batch)]


def get_embed_host() -> EmbedHost:
    global _host
    with _host_lock:
        if _host is None:
            _host = EmbedHost()
        return _host


def reset_embed_host() -> None:
    global _host
    with _host_lock:
        _host = None


def embed_texts(texts: Sequence[str]) -> np.ndarray:
    return get_embed_host().embed(texts)


class DeviceEmbedIndex:
    """Device-resident similarity index: the room's embedding matrix
    lives on the accelerator; recall = one matmul + top_k (the role
    sqlite-vec's vec_distance_cosine played in the reference)."""

    def __init__(self, dim: int) -> None:
        import jax.numpy as jnp

        self.dim = dim
        self._jnp = jnp
        self._matrix = jnp.zeros((0, dim), jnp.float32)
        self._ids: list[int] = []
        self._lock = locks.make_lock("embed_index")

    def rebuild(self, vectors: np.ndarray, ids: list[int]) -> None:
        import jax.numpy as jnp

        with self._lock:
            if len(ids) == 0:
                self._matrix = jnp.zeros((0, self.dim), jnp.float32)
                self._ids = []
                return
            norms = np.linalg.norm(vectors, axis=1, keepdims=True)
            self._matrix = jnp.asarray(
                vectors / np.maximum(norms, 1e-9), jnp.float32
            )
            self._ids = list(ids)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ids)

    def top_k(
        self, query: np.ndarray, k: int = 5
    ) -> list[tuple[int, float]]:
        import jax

        # snapshot under the lock, compute + materialize OUTSIDE it:
        # jax arrays are immutable, so concurrent rebuild() just swaps
        # the references — and the device matmul + host sync no longer
        # stall every reader on this lock (roomlint sync-under-lock)
        with self._lock:
            if not self._ids:
                return []
            matrix, ids = self._matrix, list(self._ids)
        q = np.asarray(query, np.float32)
        q = q / max(float(np.linalg.norm(q)), 1e-9)
        sims = matrix @ self._jnp.asarray(q)
        vals, idx = jax.lax.top_k(sims, min(k, len(ids)))
        return [
            (ids[int(i)], float(v))
            for v, i in zip(np.asarray(vals), np.asarray(idx))
        ]
