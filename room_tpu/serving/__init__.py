from . import disagg, faults, lifecycle, podnet, scheduler, trace
from .engine import ServingEngine, Turn
from .faults import FaultError
from .fleet import EngineFleet
from .kv_offload import TieredKVStore
from .prefix_store import SharedPrefixStore
from .kv_pages import PageTable, init_page_cache, make_paged_kv_hook
from .sampler import SamplingParams, sample, sample_batched
from .scheduler import TURN_CLASSES, ClassTargets, RequestScheduler
from .tokenizer import (
    ByteTokenizer,
    HFTokenizer,
    extract_tool_call,
    load_tokenizer,
    render_chat,
)

__all__ = [
    "ServingEngine",
    "EngineFleet",
    "SharedPrefixStore",
    "Turn",
    "disagg",
    "faults",
    "lifecycle",
    "scheduler",
    "trace",
    "TURN_CLASSES",
    "ClassTargets",
    "RequestScheduler",
    "FaultError",
    "PageTable",
    "TieredKVStore",
    "init_page_cache",
    "make_paged_kv_hook",
    "SamplingParams",
    "sample",
    "sample_batched",
    "ByteTokenizer",
    "HFTokenizer",
    "extract_tool_call",
    "load_tokenizer",
    "render_chat",
]
