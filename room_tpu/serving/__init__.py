from . import faults, lifecycle
from .engine import ServingEngine, Turn
from .faults import FaultError
from .kv_offload import TieredKVStore
from .kv_pages import PageTable, init_page_cache, make_paged_kv_hook
from .sampler import SamplingParams, sample, sample_batched
from .tokenizer import (
    ByteTokenizer,
    HFTokenizer,
    extract_tool_call,
    load_tokenizer,
    render_chat,
)

__all__ = [
    "ServingEngine",
    "Turn",
    "faults",
    "lifecycle",
    "FaultError",
    "PageTable",
    "TieredKVStore",
    "init_page_cache",
    "make_paged_kv_hook",
    "SamplingParams",
    "sample",
    "sample_batched",
    "ByteTokenizer",
    "HFTokenizer",
    "extract_tool_call",
    "load_tokenizer",
    "render_chat",
]
