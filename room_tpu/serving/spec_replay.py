"""Offline replay of speculative drafting against ground-truth text
(VERDICT r4 #5).

For greedy rows the engine accepts the longest draft prefix that
matches the model's own (tie-banded) argmax (`sampler.spec_verify`
inside the jitted window scan, `engine._spec_window_fn`). If a
transcript's continuation IS what the model would have emitted, then
acceptance is a pure function of (history, continuation, gamma) and
the drafting algorithm — so the per-class acceptance of prompt-lookup
drafting on realistic traffic can be measured exactly, offline, with
no model in the loop. tests/test_spec_acceptance.py pins
replay==engine on live engine output; scripts/spec_acceptance.py
reports the per-class table that backs the deployment gamma default.

Interaction with the multi-step dispatch window (docs/serving.md):
speculation rides INSIDE the window — drafting matches each lane's
device-resident recent-token tail (ops/spec.py; the same trailing
3-gram/2-gram rule as `propose_ngram` here), verification is the
window step's own batched forward, and accept/reject happens inside
the `lax.scan`, so a spec round is a normal window step emitting up
to 1+gamma tokens and NEVER flushes the pipeline. A "round" in this
replay therefore corresponds to one drafting window STEP, not one
dispatch; round structure is still unaffected by
ROOM_TPU_DECODE_STEPS_PER_DISPATCH. The live counterpart of this
module's accounting is `scheduler.SpecTuner`, which adapts each
traffic class's gamma (and its spec-off decision) from exactly these
proposed/accepted counts observed at window drains.

reference: none (the reference delegates decoding to Ollama and has no
speculative path); the drafting rule replayed here is
ops/spec.ngram_propose (== engine.propose_ngram) and the acceptance
rule is sampler.spec_verify's greedy reduction.
"""

from __future__ import annotations

from dataclasses import dataclass

from room_tpu.serving.engine import propose_ngram


@dataclass
class ReplayStats:
    """Counters matching the engine's spec telemetry semantics:
    `proposed`/`accepted` mirror stats()["spec_proposed"/"spec_accepted"],
    `rounds` counts forwards that carried a draft, `plain_steps` counts
    forwards where no context n-gram repeated (the engine's no-draft
    fallback — these cost exactly a normal decode step)."""

    proposed: int = 0
    accepted: int = 0
    rounds: int = 0
    plain_steps: int = 0
    emitted: int = 0
    throttles: int = 0

    @property
    def acceptance(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    @property
    def forwards(self) -> int:
        return self.rounds + self.plain_steps

    @property
    def tokens_per_forward(self) -> float:
        """The speedup lever: sequential decode is exactly 1.0."""
        return self.emitted / self.forwards if self.forwards else 0.0

    @property
    def draft_engage_rate(self) -> float:
        """Fraction of forwards where drafting engaged at all."""
        return self.rounds / self.forwards if self.forwards else 0.0


def replay_acceptance(history: list[int], continuation: list[int],
                      gamma: int, min_accept: float = 0.0,
                      cooldown: int = 16, ema_alpha: float = 0.1,
                      cost_ratio: float | None = None,
                      tail: int = 256) -> ReplayStats:
    """Replay the engine's greedy speculative loop: draft via
    propose_ngram over the trailing ``tail`` tokens of
    (history + emitted) — the engine's device-resident tail is
    bounded (ROOM_TPU_SPEC_TAIL, default 256), so an occurrence
    further back is invisible to live drafting and must be invisible
    here too — accept the longest prefix matching the true
    continuation, emit accepted+1 per round (the bonus/corrected
    token), fall back to a plain step when nothing drafts — the same
    per-step structure as the in-window scan (engine._spec_window_fn)
    with remaining-budget/coverage capping elided (replay has no
    max_new_tokens or page pool).

    The adaptive gate models scheduler.SpecTuner for a homogeneous
    single-row, single-class stream: `min_accept` gates on the
    acceptance EMA directly (the ROOM_TPU_SPEC_MIN_ACCEPT floor);
    `cost_ratio` keeps the legacy expected-emission rule
    (1 + sum ema^i over the draft must clear it;
    roofline.spec_cost_ratio supplies the ratio) for the published
    round-5 tables. An unprofitable round closes the gate for
    `cooldown` emitted tokens, then one probe round refreshes the
    EMA. Defaults disable both gates (an unthrottled engine)."""
    if gamma <= 0:
        raise ValueError(f"gamma must be positive, got {gamma}")
    tail = max(8, tail)   # engine.spec_tail_len's own lower bound
    st = ReplayStats()
    n = len(continuation)
    if n == 0:
        return st
    # the first continuation token comes out of the prefill forward —
    # the engine's first draft opportunity is after it (engine.py
    # prefill emits the first token; decode rounds start at token 2),
    # so the replay starts there too. emitted/forwards therefore count
    # decode work only, matching the engine's spec telemetry.
    seq = list(history) + [continuation[0]]
    pos = 1
    ema = 1.0
    resume_at = 0
    probe = False
    while pos < n:
        draft: list[int] = []
        if st.emitted >= resume_at and n - pos > 1:
            draft = propose_ngram(seq[-tail:], min(gamma, n - pos - 1))
        if draft:
            if probe:
                probe = False  # forced EMA-refresh round
            else:
                if min_accept > 0.0:
                    gated = ema < min_accept
                elif cost_ratio is not None:
                    exp_emit = 1.0 + sum(
                        ema ** k for k in range(1, len(draft) + 1)
                    )
                    gated = exp_emit < cost_ratio
                else:
                    gated = False
                if gated:
                    st.throttles += 1
                    resume_at = st.emitted + cooldown
                    probe = True
                    draft = []
        if not draft:
            seq.append(continuation[pos])
            pos += 1
            st.plain_steps += 1
            st.emitted += 1
            continue
        k = 0
        while k < len(draft) and pos + k < n \
                and draft[k] == continuation[pos + k]:
            k += 1
        step = min(k + 1, n - pos)  # accepted + bonus/corrected token
        seq.extend(continuation[pos:pos + step])
        pos += step
        st.rounds += 1
        st.proposed += len(draft)
        st.accepted += k
        st.emitted += step
        ema = (1 - ema_alpha) * ema + ema_alpha * (k / len(draft))
    return st
