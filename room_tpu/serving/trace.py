"""turnscope — end-to-end turn tracing + flight recorder
(docs/observability.md).

The serving stack is five layers deep (fleet router, EDF scheduler with
chunked prefill, fused decode windows, KV offload, failover) and the
production question is always the same: *why did this turn miss its
TTFT target?* This module answers it with an always-on, host-side span
recorder threading one correlation id — session id + turn sequence +
session generation — from submit through routing, admission, chunked
prefill, decode-window dispatch/drain, offload restore, and failover
re-home.

Span model (per turn, contiguous so components sum to wall):

    turn (submit -> done)                      wall_ms
      queue    submit -> first queue pop       queue_ms
      prefill  first pop -> slot admission     prefill_ms
               (chunk writes, budget defers, offload restore)
      decode   slot admission -> done          decode_ms
               = dispatch_ms + drain_ms + host_ms

TTFT/TPOT derive from host-side token-booking timestamps (the drain
for pipelined windows — the same moment the stream callback fires, so
the trace measures what the client experienced).

Discipline:

- **Monotonic clocks only** (`time.monotonic`), never wall clocks —
  spans must survive NTP steps.
- **No device sync**: every hook reads host state the engine already
  has; nothing here calls into jax. Token identity with tracing on vs
  off is pinned in tests/test_trace.py.
- **Bounded memory**: per-turn events are capped
  (ROOM_TPU_TRACE_EVENTS); the flight recorder keeps two rings —
  recently completed turns (ROOM_TPU_TRACE_RING) plus ALL
  SLO-violating / faulted / shed turns (ROOM_TPU_TRACE_VIOLATION_RING,
  a separate ring so a burst of healthy traffic never evicts
  evidence). Served at /api/tpu/trace, summarized in /metrics, and
  attached to telemetry crash reports.

Threading: a TurnTrace is created on the submit thread and mutated on
the engine thread; the fleet router annotates from the submit thread.
Every cross-thread mutation is a GIL-atomic attribute write or list
append; aggregate state (the recorder rings + per-class attribution)
mutates only under the recorder lock at turn finish.

The disarmed path (ROOM_TPU_TRACE=0) costs one boolean check at
submit: `begin()` returns None and every engine hook guards on
``turn.trace is None``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..utils import knobs, locks

__all__ = [
    "TurnTrace", "FlightRecorder", "recorder", "FAULT_EVENTS",
    "enabled", "set_enabled", "begin", "finish",
    "note_dequeue", "note_slotted", "note_route", "note_fault",
    "note_event",
]

# Every faults.FAULT_POINTS entry maps to the span-event / telemetry
# counter name a firing emits (faults.should_fire routes through
# _telemetry_count + _trace_event with these names). roomlint's
# fault-trace coverage cross-check (analysis/trace_checker.py) keeps
# this dict in lockstep with FAULT_POINTS: a new fault point cannot
# ship invisible to the trace layer. Keep it a literal dict — the
# checker parses it without importing this module.
FAULT_EVENTS = {
    "kv_alloc": "fault.kv_alloc",
    "prefill_oom": "fault.prefill_oom",
    "prefill_chunk": "fault.prefill_chunk",
    "decode_step": "fault.decode_step",
    "decode_window": "fault.decode_window",
    "decode_stall": "fault.decode_stall",
    "tokenizer": "fault.tokenizer",
    "engine_crash": "fault.engine_crash",
    "client_disconnect": "fault.client_disconnect",
    "provider_timeout": "fault.provider_timeout",
    "offload_io": "fault.offload_io",
    "shutdown_io": "fault.shutdown_io",
    "replica_crash": "fault.replica_crash",
    "router_io": "fault.router_io",
    "kv_wire": "fault.kv_wire",
    "prefix_io": "fault.prefix_io",
    "wire_partition": "fault.wire_partition",
    "heartbeat_loss": "fault.heartbeat_loss",
    "mirror_journal_io": "fault.mirror_journal_io",
    "placement_io": "fault.placement_io",
    "router_shard_crash": "fault.router_shard_crash",
    "db_io": "fault.db_io",
    "cycle_crash": "fault.cycle_crash",
    "loop_hang": "fault.loop_hang",
    "tool_exec": "fault.tool_exec",
    "shard_crash": "fault.shard_crash",
    "shard_proc_kill": "fault.shard_proc_kill",
    "shard_wire_io": "fault.shard_wire_io",
}

# attribution components (per class, ms): where a class's latency
# budget actually went, summed over finished turns
ATTRIBUTION_COMPONENTS = (
    "queue_ms", "prefill_ms", "dispatch_ms", "drain_ms",
    "decode_host_ms", "offload_restore_ms", "wall_ms",
)

_turn_seq = 0
_seq_lock = locks.make_lock("trace_seq")
# finish() can race between the engine thread and a fleet-router shed
# (the submit-side TOCTOU path): the idempotency flip must be atomic
# or a turn could book twice into the recorder
_finish_lock = locks.make_lock("trace_finish")
# tests / bench A/B override the knob without re-reading env per turn
_override: Optional[bool] = None


def enabled() -> bool:
    if _override is not None:
        return _override
    return knobs.get_bool("ROOM_TPU_TRACE")


def set_enabled(value: Optional[bool]) -> None:
    """Force tracing on/off (bench A/B, tests); None returns control
    to ROOM_TPU_TRACE."""
    global _override
    _override = value


def _next_seq() -> int:
    global _turn_seq
    with _seq_lock:
        _turn_seq += 1
        return _turn_seq


class TurnTrace:
    """Span accumulator for one turn. Engine-thread mutation except
    where noted; every field is host state (ints/floats/small lists)."""

    __slots__ = (
        "cid", "sid", "seq", "cls", "rid", "generation",
        "t_submit", "t_dequeue", "t_slotted", "t_done",
        "t_first_token", "t_last_token", "n_tokens",
        "windows", "dispatch_ms", "drain_ms",
        "chunks", "chunk_tokens", "chunk_defers",
        "spec_proposed", "spec_accepted",
        "offload_restore_ms", "offload_restores", "reprefills",
        "requeues", "rehomes",
        "events", "faults", "max_events",
        "shed", "finish_reason", "error", "finished",
        "ttft_target_s", "tpot_target_s",
    )

    def __init__(self, sid: str, cls: str, max_events: int,
                 t_submit: Optional[float] = None) -> None:
        self.sid = sid
        self.seq = _next_seq()
        self.cls = cls
        self.rid = ""
        self.generation = 0
        self.cid = f"{sid}#{self.seq}"
        self.t_submit = t_submit if t_submit is not None \
            else time.monotonic()
        self.t_dequeue: Optional[float] = None
        self.t_slotted: Optional[float] = None
        self.t_done: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        self.n_tokens = 0
        self.windows = 0
        self.dispatch_ms = 0.0
        self.drain_ms = 0.0
        self.chunks = 0
        self.chunk_tokens = 0
        self.chunk_defers = 0
        # on-mesh speculative drafting consumed by this turn
        # (docs/serving.md): drafts its verify forwards carried, and
        # how many it kept — the per-turn view of the class acceptance
        # the gamma tuner adapts on
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.offload_restore_ms = 0.0
        self.offload_restores = 0
        self.reprefills = 0
        self.requeues = 0
        self.rehomes = 0
        self.events: list[tuple] = []
        self.faults: list[str] = []
        self.max_events = max_events
        self.shed = False
        self.finish_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.finished = False
        self.ttft_target_s: Optional[float] = None
        self.tpot_target_s: Optional[float] = None

    # ---- hooks (hot path: attribute writes only) ----

    def ev(self, name: str, **detail) -> None:
        if len(self.events) >= self.max_events:
            return
        rel_ms = round((time.monotonic() - self.t_submit) * 1000.0, 3)
        self.events.append(
            (name, rel_ms, detail) if detail else (name, rel_ms)
        )

    def note_token(self, now: float) -> None:
        self.n_tokens += 1
        if self.t_first_token is None:
            self.t_first_token = now
            self.ev("first_token")
        self.t_last_token = now

    def note_window(self, dispatch_s: float) -> None:
        self.windows += 1
        self.dispatch_ms += dispatch_s * 1000.0

    def note_drain(self, wait_s: float) -> None:
        self.drain_ms += wait_s * 1000.0

    def note_fault(self, point: str) -> None:
        name = FAULT_EVENTS.get(point, f"fault.{point}")
        self.faults.append(point)
        self.ev(name)

    # ---- derived spans ----

    def ttft_ms(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return (self.t_first_token - self.t_submit) * 1000.0

    def tpot_ms(self) -> Optional[float]:
        if self.t_first_token is None or self.n_tokens < 2:
            return None
        return (
            (self.t_last_token - self.t_first_token) * 1000.0
            / (self.n_tokens - 1)
        )

    def spans(self) -> dict:
        """Contiguous top-level spans: queue + prefill + decode sum to
        wall exactly for a slotted turn (unattributed covers turns that
        died queued / mid-admission)."""
        done = self.t_done if self.t_done is not None else \
            time.monotonic()
        wall = (done - self.t_submit) * 1000.0
        dequeue = self.t_dequeue
        slotted = self.t_slotted
        queue = ((dequeue if dequeue is not None else done)
                 - self.t_submit) * 1000.0
        prefill = decode = 0.0
        if dequeue is not None:
            prefill = ((slotted if slotted is not None else done)
                       - dequeue) * 1000.0
        if slotted is not None:
            decode = (done - slotted) * 1000.0
        host = max(0.0, decode - self.dispatch_ms - self.drain_ms)
        return {
            "wall_ms": round(wall, 3),
            "queue_ms": round(queue, 3),
            "prefill_ms": round(prefill, 3),
            "decode_ms": round(decode, 3),
            "dispatch_ms": round(self.dispatch_ms, 3),
            "drain_ms": round(self.drain_ms, 3),
            "decode_host_ms": round(host, 3),
            "unattributed_ms": round(
                max(0.0, wall - queue - prefill - decode), 3
            ),
        }

    def violated(self) -> dict:
        """SLO verdicts against the class targets captured at finish."""
        ttft = self.ttft_ms()
        tpot = self.tpot_ms()
        return {
            "ttft": (
                self.ttft_target_s is not None and ttft is not None
                and ttft > self.ttft_target_s * 1000.0
            ),
            "tpot": (
                self.tpot_target_s is not None and tpot is not None
                and tpot > self.tpot_target_s * 1000.0
            ),
        }

    def to_dict(self) -> dict:
        ttft = self.ttft_ms()
        tpot = self.tpot_ms()
        return {
            "cid": self.cid,
            "session": self.sid,
            "class": self.cls,
            "replica": self.rid or None,
            "generation": self.generation,
            "finish_reason": self.finish_reason,
            "error": self.error,
            "shed": self.shed,
            "tokens": self.n_tokens,
            "requeues": self.requeues,
            "ttft_ms": round(ttft, 3) if ttft is not None else None,
            "tpot_ms": round(tpot, 3) if tpot is not None else None,
            "ttft_target_s": self.ttft_target_s,
            "tpot_target_s": self.tpot_target_s,
            "slo_violated": self.violated(),
            "spans": self.spans(),
            "prefill": {
                "chunks": self.chunks,
                "chunk_tokens": self.chunk_tokens,
                "chunk_defers": self.chunk_defers,
                "offload_restores": self.offload_restores,
                "offload_restore_ms": round(self.offload_restore_ms, 3),
                "reprefills": self.reprefills,
            },
            "decode": {
                "windows": self.windows,
                "dispatch_ms": round(self.dispatch_ms, 3),
                "drain_ms": round(self.drain_ms, 3),
                "spec_proposed": self.spec_proposed,
                "spec_accepted": self.spec_accepted,
            },
            "rehomes": self.rehomes,
            "faults": list(self.faults),
            "events": [list(e) for e in self.events],
        }


class _ClassAttribution:
    """Monotonic per-class budget-attribution sums (the /metrics
    counters and the TPU panel's attribution table). Mutated under
    the recorder lock."""

    __slots__ = (
        "turns", "errors", "shed", "ttft_violations",
        "tpot_violations", "faulted", "tokens", "ttft_ms_sum",
        "ttft_n",
    ) + ATTRIBUTION_COMPONENTS

    def __init__(self) -> None:
        self.turns = 0
        self.errors = 0
        self.shed = 0
        self.ttft_violations = 0
        self.tpot_violations = 0
        self.faulted = 0
        self.tokens = 0
        self.ttft_ms_sum = 0.0
        self.ttft_n = 0
        for c in ATTRIBUTION_COMPONENTS:
            setattr(self, c, 0.0)

    def snapshot(self) -> dict:
        out = {
            "turns": self.turns,
            "errors": self.errors,
            "shed": self.shed,
            "faulted": self.faulted,
            "ttft_violations": self.ttft_violations,
            "tpot_violations": self.tpot_violations,
            "tokens": self.tokens,
            "ttft_ms_mean": round(self.ttft_ms_sum / self.ttft_n, 3)
            if self.ttft_n else None,
        }
        for c in ATTRIBUTION_COMPONENTS:
            out[c] = round(getattr(self, c), 3)
        return out


class FlightRecorder:
    """Bounded retention of completed turn traces + global serving
    events (fault firings, re-homes, profile captures).

    Two turn rings: ``recent`` (every completed turn, FIFO-evicted)
    and ``violations`` (SLO-violating, faulted, errored, or shed turns
    — kept separately so a burst of healthy traffic can't evict the
    evidence an incident review needs)."""

    def __init__(
        self,
        recent_cap: Optional[int] = None,
        violation_cap: Optional[int] = None,
        event_cap: int = 512,
    ) -> None:
        if recent_cap is None:
            recent_cap = max(1, knobs.get_int("ROOM_TPU_TRACE_RING"))
        if violation_cap is None:
            violation_cap = max(
                1, knobs.get_int("ROOM_TPU_TRACE_VIOLATION_RING")
            )
        self._lock = locks.make_lock("trace_recorder")
        self._recent: deque = deque(maxlen=recent_cap)
        self._violations: deque = deque(maxlen=violation_cap)
        self._events: deque = deque(maxlen=event_cap)
        self._attr: dict[str, _ClassAttribution] = {}
        self._finished = 0

    def reset(self) -> None:
        """Re-read ring caps from the knobs and clear state (tests)."""
        with self._lock:
            self._recent = deque(
                maxlen=max(1, knobs.get_int("ROOM_TPU_TRACE_RING"))
            )
            self._violations = deque(maxlen=max(
                1, knobs.get_int("ROOM_TPU_TRACE_VIOLATION_RING")
            ))
            self._events.clear()
            self._attr.clear()
            self._finished = 0

    def note_event(self, kind: str, detail: Optional[dict] = None) -> None:
        """Global (non-turn) serving event: fault firings, failover
        re-homes, drains, profile captures."""
        rec = {"kind": kind, "t_mono": round(time.monotonic(), 3)}
        if detail:
            rec.update(detail)
        with self._lock:
            self._events.append(rec)

    def record(self, tr: TurnTrace) -> None:
        viol = tr.violated()
        keep_evidence = (
            viol["ttft"] or viol["tpot"] or tr.shed
            or bool(tr.faults) or tr.finish_reason == "error"
        )
        rec = tr.to_dict()
        with self._lock:
            self._finished += 1
            self._recent.append(rec)
            if keep_evidence:
                self._violations.append(rec)
            a = self._attr.get(tr.cls)
            if a is None:
                a = self._attr[tr.cls] = _ClassAttribution()
            a.turns += 1
            a.tokens += tr.n_tokens
            if tr.finish_reason == "error":
                a.errors += 1
            if tr.shed:
                a.shed += 1
            if tr.faults:
                a.faulted += 1
            if viol["ttft"]:
                a.ttft_violations += 1
            if viol["tpot"]:
                a.tpot_violations += 1
            ttft = tr.ttft_ms()
            if ttft is not None:
                a.ttft_ms_sum += ttft
                a.ttft_n += 1
            spans = rec["spans"]
            a.queue_ms += spans["queue_ms"]
            a.prefill_ms += spans["prefill_ms"]
            a.dispatch_ms += spans["dispatch_ms"]
            a.drain_ms += spans["drain_ms"]
            a.decode_host_ms += spans["decode_host_ms"]
            a.offload_restore_ms += tr.offload_restore_ms
            a.wall_ms += spans["wall_ms"]

    def _attribution_locked(self) -> dict:
        # callers hold self._lock
        return {
            "finished_turns": self._finished,
            "classes": {
                cls: a.snapshot()
                for cls, a in sorted(self._attr.items())
            },
        }

    def attribution(self) -> dict:
        """Per-class SLO attribution: where each class's latency
        budget went (health / /metrics / the TPU panel)."""
        with self._lock:
            return self._attribution_locked()

    def snapshot(self, limit: int = 64) -> dict:
        """The /api/tpu/trace payload: recent + violation turn traces
        (newest last), global events, attribution aggregates."""
        limit = max(1, limit)
        with self._lock:
            return {
                "enabled": enabled(),
                "recent": list(self._recent)[-limit:],
                "violations": list(self._violations)[-limit:],
                "events": list(self._events)[-limit:],
                "attribution": self._attribution_locked(),
            }


recorder = FlightRecorder()


# ---- module-level hooks (every caller guards on a None trace) ----

def begin(sid: str, cls: str,
          t_submit: Optional[float] = None) -> Optional[TurnTrace]:
    """Create a turn trace (submit thread). None when disabled — the
    engine's hooks all no-op on a None trace. ``t_submit`` aligns the
    trace origin with the Turn's own monotonic submit stamp."""
    if not enabled():
        return None
    return TurnTrace(
        sid, cls,
        max_events=max(8, knobs.get_int("ROOM_TPU_TRACE_EVENTS")),
        t_submit=t_submit,
    )


def note_dequeue(tr: Optional[TurnTrace]) -> None:
    """First pop from the admission queue ends the queue span
    (requeues keep the original boundary — the queue span measures
    time to FIRST service, the EDF wait)."""
    if tr is not None and tr.t_dequeue is None:
        tr.t_dequeue = time.monotonic()
        tr.ev("dequeue")


def note_slotted(tr: Optional[TurnTrace], generation: int) -> None:
    """Slot admission ends the prefill span and starts decode."""
    if tr is None:
        return
    if tr.t_slotted is None:
        tr.t_slotted = time.monotonic()
        tr.ev("slotted")
    tr.generation = generation


def note_route(tr: Optional[TurnTrace], rid: str) -> None:
    """Fleet router placement (submit thread)."""
    if tr is not None:
        tr.rid = rid
        tr.ev("routed", rid=rid)


def note_fault(tr: Optional[TurnTrace], point: Optional[str]) -> None:
    if tr is not None and point:
        tr.note_fault(point)


def note_event(kind: str, detail: Optional[dict] = None) -> None:
    """Global serving event into the flight recorder (fault firings
    via faults.should_fire, failover re-homes, profile captures).
    Cheap no-op path when tracing is disabled."""
    if not enabled():
        return
    recorder.note_event(kind, detail)


def finish(turn, targets=None) -> None:
    """Close a turn's trace and push it into the flight recorder.
    Idempotent (several death paths can reach the same turn). Reads
    the Turn's outcome fields directly; ``targets`` is the scheduler's
    class-targets map for the SLO verdict."""
    tr = getattr(turn, "trace", None)
    if tr is None:
        return
    with _finish_lock:
        if tr.finished:
            return
        tr.finished = True
    tr.t_done = time.monotonic()
    tr.finish_reason = getattr(turn, "finish_reason", None)
    tr.error = getattr(turn, "error", None)
    tr.shed = bool(getattr(turn, "shed", False))
    tr.requeues = int(getattr(turn, "requeues", 0))
    if targets is not None:
        t = targets.get(tr.cls)
        if t is not None:
            tr.ttft_target_s = t.ttft_s
            tr.tpot_target_s = t.tpot_s
    tr.ev("done", reason=tr.finish_reason)
    recorder.record(tr)
