"""Database schema for the room_tpu engine.

Logical data model mirrors the reference engine's SQLite schema
(reference: src/shared/schema.ts:1-481) — settings, workers, rooms, the
entity/observation/relation memory graph with an FTS5 mirror and an
embeddings side-table, tasks/runs, quorum decisions/votes, goals, skills,
self-modification audit+snapshots, escalations, credentials, wallets,
inter-room messages, worker cycles + cycle logs, agent sessions, and clerk
chat/usage. Differences from the reference are deliberate:

- timestamps are stored as UTC ISO-8601 (the reference used localtime);
- an explicit ``schema_migrations`` ledger replaces the single-row
  ``schema_version`` table;
- embeddings carry a ``dim`` column defaulting to the on-mesh embedder's
  output width (384).

All DDL is idempotent (CREATE ... IF NOT EXISTS) so it can run on any
database. Table order respects foreign keys (PRAGMA foreign_keys = ON).
"""

SCHEMA_VERSION = 3  # v3: cycle_journal kind 'xshard' (docs/swarmshard.md)

# UTC ISO-8601 with millisecond precision, e.g. 2026-07-28T19:04:11.123Z
NOW_SQL = "(strftime('%Y-%m-%dT%H:%M:%fZ','now'))"


def _t(sql: str) -> str:
    """Substitute the {NOW} placeholder in a DDL fragment."""
    return sql.replace("{NOW}", NOW_SQL)


SCHEMA = _t("""
PRAGMA foreign_keys = ON;

CREATE TABLE IF NOT EXISTS settings (
    key        TEXT PRIMARY KEY,
    value      TEXT,
    updated_at TEXT DEFAULT {NOW}
);

CREATE TABLE IF NOT EXISTS workers (
    id            INTEGER PRIMARY KEY AUTOINCREMENT,
    name          TEXT NOT NULL,
    role          TEXT,
    system_prompt TEXT NOT NULL,
    description   TEXT,
    model         TEXT,
    is_default    INTEGER NOT NULL DEFAULT 0,
    task_count    INTEGER NOT NULL DEFAULT 0,
    cycle_gap_ms  INTEGER,
    max_turns     INTEGER,
    room_id       INTEGER,
    agent_state   TEXT NOT NULL DEFAULT 'idle',
    votes_cast    INTEGER NOT NULL DEFAULT 0,
    votes_missed  INTEGER NOT NULL DEFAULT 0,
    wip           TEXT,
    created_at    TEXT DEFAULT {NOW},
    updated_at    TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_workers_name ON workers(name);
CREATE INDEX IF NOT EXISTS ix_workers_room ON workers(room_id);

CREATE TABLE IF NOT EXISTS rooms (
    id                  INTEGER PRIMARY KEY AUTOINCREMENT,
    name                TEXT NOT NULL,
    queen_worker_id     INTEGER REFERENCES workers(id) ON DELETE SET NULL,
    goal                TEXT,
    status              TEXT NOT NULL DEFAULT 'active',
    visibility          TEXT NOT NULL DEFAULT 'private',
    autonomy_mode       TEXT NOT NULL DEFAULT 'semi',
    max_concurrent_tasks INTEGER NOT NULL DEFAULT 3,
    worker_model        TEXT NOT NULL DEFAULT 'tpu',
    queen_cycle_gap_ms  INTEGER NOT NULL DEFAULT 1800000,
    queen_max_turns     INTEGER NOT NULL DEFAULT 50,
    queen_quiet_from    TEXT,
    queen_quiet_until   TEXT,
    config              TEXT,
    webhook_token       TEXT,
    queen_nickname      TEXT,
    chat_session_id     TEXT,
    referred_by_code    TEXT,
    allowed_tools       TEXT,
    created_at          TEXT DEFAULT {NOW},
    updated_at          TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_rooms_status ON rooms(status);

-- ---- semantic memory: entity graph + FTS mirror + embeddings ----

CREATE TABLE IF NOT EXISTS entities (
    id          INTEGER PRIMARY KEY AUTOINCREMENT,
    name        TEXT NOT NULL,
    type        TEXT NOT NULL DEFAULT 'fact',
    category    TEXT,
    embedded_at TEXT,
    room_id     INTEGER REFERENCES rooms(id) ON DELETE SET NULL,
    created_at  TEXT DEFAULT {NOW},
    updated_at  TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_entities_category ON entities(category);
CREATE INDEX IF NOT EXISTS ix_entities_type ON entities(type);
CREATE INDEX IF NOT EXISTS ix_entities_room ON entities(room_id);

CREATE TABLE IF NOT EXISTS observations (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    entity_id  INTEGER NOT NULL REFERENCES entities(id) ON DELETE CASCADE,
    content    TEXT NOT NULL,
    source     TEXT NOT NULL DEFAULT 'agent',
    created_at TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_observations_entity ON observations(entity_id);

CREATE TABLE IF NOT EXISTS relations (
    id            INTEGER PRIMARY KEY AUTOINCREMENT,
    from_entity   INTEGER NOT NULL REFERENCES entities(id) ON DELETE CASCADE,
    to_entity     INTEGER NOT NULL REFERENCES entities(id) ON DELETE CASCADE,
    relation_type TEXT NOT NULL,
    created_at    TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_relations_from ON relations(from_entity);
CREATE INDEX IF NOT EXISTS ix_relations_to ON relations(to_entity);

-- Standalone FTS5 index kept in sync by triggers. Unlike the reference's
-- external-content design (which indexed entity names only), observation
-- text is folded into the searchable ``content`` column.
CREATE VIRTUAL TABLE IF NOT EXISTS memory_fts USING fts5(
    entity_id UNINDEXED, name, content, category
);

-- FTS rowid is pinned to the entity id so trigger maintenance is an O(1)
-- rowid lookup rather than a table scan.
CREATE TRIGGER IF NOT EXISTS trg_entities_fts_ins AFTER INSERT ON entities BEGIN
    INSERT INTO memory_fts(rowid, entity_id, name, content, category)
    VALUES (new.id, new.id, new.name, '', new.category);
END;
CREATE TRIGGER IF NOT EXISTS trg_entities_fts_del AFTER DELETE ON entities BEGIN
    DELETE FROM memory_fts WHERE rowid = old.id;
END;
CREATE TRIGGER IF NOT EXISTS trg_entities_fts_upd
AFTER UPDATE OF name, category ON entities BEGIN
    UPDATE memory_fts SET name = new.name, category = new.category
    WHERE rowid = new.id;
END;
CREATE TRIGGER IF NOT EXISTS trg_observations_fts_ins
AFTER INSERT ON observations BEGIN
    UPDATE memory_fts SET content = (
        SELECT group_concat(content, ' ') FROM observations
        WHERE entity_id = new.entity_id
    ) WHERE rowid = new.entity_id;
END;
CREATE TRIGGER IF NOT EXISTS trg_observations_fts_del
AFTER DELETE ON observations BEGIN
    UPDATE memory_fts SET content = COALESCE((
        SELECT group_concat(content, ' ') FROM observations
        WHERE entity_id = old.entity_id
    ), '') WHERE rowid = old.entity_id;
END;

CREATE TABLE IF NOT EXISTS embeddings (
    id          INTEGER PRIMARY KEY AUTOINCREMENT,
    entity_id   INTEGER NOT NULL REFERENCES entities(id) ON DELETE CASCADE,
    source_type TEXT NOT NULL DEFAULT 'entity',
    source_id   INTEGER NOT NULL,
    text_hash   TEXT NOT NULL,
    vector      BLOB NOT NULL,
    model       TEXT NOT NULL DEFAULT 'tpu-embed-384',
    dim         INTEGER NOT NULL DEFAULT 384,
    created_at  TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_embeddings_entity ON embeddings(entity_id);
CREATE UNIQUE INDEX IF NOT EXISTS ux_embeddings_source
    ON embeddings(source_type, source_id, model);

-- ---- scheduled tasks ----

CREATE TABLE IF NOT EXISTS tasks (
    id                 INTEGER PRIMARY KEY AUTOINCREMENT,
    name               TEXT NOT NULL,
    description        TEXT,
    prompt             TEXT NOT NULL,
    cron_expression    TEXT,
    trigger_type       TEXT NOT NULL DEFAULT 'cron',
    trigger_config     TEXT,
    webhook_token      TEXT,
    executor           TEXT NOT NULL DEFAULT 'agent',
    status             TEXT NOT NULL DEFAULT 'active',
    last_run           TEXT,
    last_result        TEXT,
    error_count        INTEGER NOT NULL DEFAULT 0,
    scheduled_at       TEXT,
    max_runs           INTEGER,
    run_count          INTEGER NOT NULL DEFAULT 0,
    memory_entity_id   INTEGER REFERENCES entities(id) ON DELETE SET NULL,
    worker_id          INTEGER REFERENCES workers(id) ON DELETE SET NULL,
    session_continuity INTEGER NOT NULL DEFAULT 0,
    session_id         TEXT,
    timeout_minutes    INTEGER,
    max_turns          INTEGER,
    allowed_tools      TEXT,
    disallowed_tools   TEXT,
    learned_context    TEXT,
    room_id            INTEGER REFERENCES rooms(id) ON DELETE SET NULL,
    created_at         TEXT DEFAULT {NOW},
    updated_at         TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_tasks_status ON tasks(status);
CREATE INDEX IF NOT EXISTS ix_tasks_sched ON tasks(scheduled_at);
CREATE INDEX IF NOT EXISTS ix_tasks_trigger ON tasks(trigger_type);
CREATE INDEX IF NOT EXISTS ix_tasks_room ON tasks(room_id);

CREATE TABLE IF NOT EXISTS task_runs (
    id               INTEGER PRIMARY KEY AUTOINCREMENT,
    task_id          INTEGER NOT NULL REFERENCES tasks(id) ON DELETE CASCADE,
    started_at       TEXT DEFAULT {NOW},
    finished_at      TEXT,
    status           TEXT NOT NULL DEFAULT 'running',
    result           TEXT,
    result_file      TEXT,
    error_message    TEXT,
    duration_ms      INTEGER,
    progress         REAL,
    progress_message TEXT,
    session_id       TEXT
);
CREATE INDEX IF NOT EXISTS ix_task_runs_task ON task_runs(task_id);
CREATE INDEX IF NOT EXISTS ix_task_runs_started ON task_runs(started_at);
CREATE INDEX IF NOT EXISTS ix_task_runs_status ON task_runs(status);

CREATE TABLE IF NOT EXISTS console_logs (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id     INTEGER NOT NULL REFERENCES task_runs(id) ON DELETE CASCADE,
    seq        INTEGER NOT NULL,
    entry_type TEXT NOT NULL,
    content    TEXT NOT NULL,
    created_at TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_console_logs_run_seq ON console_logs(run_id, seq);

CREATE TABLE IF NOT EXISTS watches (
    id             INTEGER PRIMARY KEY AUTOINCREMENT,
    path           TEXT NOT NULL,
    description    TEXT,
    action_prompt  TEXT,
    status         TEXT NOT NULL DEFAULT 'active',
    last_triggered TEXT,
    trigger_count  INTEGER NOT NULL DEFAULT 0,
    room_id        INTEGER REFERENCES rooms(id) ON DELETE SET NULL,
    created_at     TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_watches_room ON watches(room_id);

-- ---- conversation + activity ----

CREATE TABLE IF NOT EXISTS chat_messages (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id    INTEGER NOT NULL REFERENCES rooms(id) ON DELETE CASCADE,
    role       TEXT NOT NULL CHECK(role IN ('user','assistant')),
    content    TEXT NOT NULL,
    created_at TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_chat_messages_room ON chat_messages(room_id);

CREATE TABLE IF NOT EXISTS room_activity (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id    INTEGER NOT NULL REFERENCES rooms(id) ON DELETE CASCADE,
    event_type TEXT NOT NULL,
    actor_id   INTEGER,
    summary    TEXT NOT NULL,
    details    TEXT,
    is_public  INTEGER NOT NULL DEFAULT 1,
    created_at TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_room_activity_room ON room_activity(room_id);
CREATE INDEX IF NOT EXISTS ix_room_activity_type ON room_activity(event_type);

-- ---- quorum governance ----

CREATE TABLE IF NOT EXISTS quorum_decisions (
    id            INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id       INTEGER NOT NULL REFERENCES rooms(id) ON DELETE CASCADE,
    proposer_id   INTEGER REFERENCES workers(id) ON DELETE SET NULL,
    proposal      TEXT NOT NULL,
    decision_type TEXT NOT NULL DEFAULT 'low_impact',
    status        TEXT NOT NULL DEFAULT 'voting',
    result        TEXT,
    threshold     TEXT NOT NULL DEFAULT 'majority',
    timeout_at    TEXT,
    keeper_vote   TEXT,
    min_voters    INTEGER NOT NULL DEFAULT 0,
    sealed        INTEGER NOT NULL DEFAULT 0,
    effective_at  TEXT,
    created_at    TEXT DEFAULT {NOW},
    resolved_at   TEXT
);
CREATE INDEX IF NOT EXISTS ix_qd_room ON quorum_decisions(room_id);
CREATE INDEX IF NOT EXISTS ix_qd_status ON quorum_decisions(status);

CREATE TABLE IF NOT EXISTS quorum_votes (
    id          INTEGER PRIMARY KEY AUTOINCREMENT,
    decision_id INTEGER NOT NULL REFERENCES quorum_decisions(id) ON DELETE CASCADE,
    worker_id   INTEGER NOT NULL REFERENCES workers(id) ON DELETE CASCADE,
    vote        TEXT NOT NULL,
    reasoning   TEXT,
    created_at  TEXT DEFAULT {NOW},
    UNIQUE(decision_id, worker_id)
);
CREATE INDEX IF NOT EXISTS ix_qv_decision ON quorum_votes(decision_id);

-- ---- goals ----

CREATE TABLE IF NOT EXISTS goals (
    id                 INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id            INTEGER NOT NULL REFERENCES rooms(id) ON DELETE CASCADE,
    description        TEXT NOT NULL,
    status             TEXT NOT NULL DEFAULT 'active',
    parent_goal_id     INTEGER REFERENCES goals(id) ON DELETE CASCADE,
    assigned_worker_id INTEGER REFERENCES workers(id) ON DELETE SET NULL,
    progress           REAL NOT NULL DEFAULT 0.0,
    created_at         TEXT DEFAULT {NOW},
    updated_at         TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_goals_room ON goals(room_id);
CREATE INDEX IF NOT EXISTS ix_goals_parent ON goals(parent_goal_id);
CREATE INDEX IF NOT EXISTS ix_goals_status ON goals(status);

CREATE TABLE IF NOT EXISTS goal_updates (
    id           INTEGER PRIMARY KEY AUTOINCREMENT,
    goal_id      INTEGER NOT NULL REFERENCES goals(id) ON DELETE CASCADE,
    worker_id    INTEGER REFERENCES workers(id) ON DELETE SET NULL,
    observation  TEXT NOT NULL,
    metric_value REAL,
    created_at   TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_goal_updates_goal ON goal_updates(goal_id);

-- ---- skills + self-modification ----

CREATE TABLE IF NOT EXISTS skills (
    id                   INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id              INTEGER REFERENCES rooms(id) ON DELETE CASCADE,
    name                 TEXT NOT NULL,
    content              TEXT NOT NULL,
    activation_context   TEXT,
    auto_activate        INTEGER NOT NULL DEFAULT 0,
    agent_created        INTEGER NOT NULL DEFAULT 0,
    created_by_worker_id INTEGER REFERENCES workers(id) ON DELETE SET NULL,
    version              INTEGER NOT NULL DEFAULT 1,
    created_at           TEXT DEFAULT {NOW},
    updated_at           TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_skills_room ON skills(room_id);
CREATE INDEX IF NOT EXISTS ix_skills_name ON skills(name);

CREATE TABLE IF NOT EXISTS self_mod_audit (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id    INTEGER REFERENCES rooms(id) ON DELETE CASCADE,
    worker_id  INTEGER REFERENCES workers(id) ON DELETE SET NULL,
    file_path  TEXT NOT NULL,
    old_hash   TEXT,
    new_hash   TEXT,
    reason     TEXT,
    reversible INTEGER NOT NULL DEFAULT 1,
    reverted   INTEGER NOT NULL DEFAULT 0,
    created_at TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_self_mod_audit_room ON self_mod_audit(room_id);

CREATE TABLE IF NOT EXISTS self_mod_snapshots (
    audit_id    INTEGER PRIMARY KEY REFERENCES self_mod_audit(id) ON DELETE CASCADE,
    target_type TEXT NOT NULL,
    target_id   INTEGER,
    old_content TEXT,
    new_content TEXT
);
CREATE INDEX IF NOT EXISTS ix_self_mod_snap_target
    ON self_mod_snapshots(target_type, target_id);

-- ---- escalations / credentials / wallet ----

CREATE TABLE IF NOT EXISTS escalations (
    id            INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id       INTEGER NOT NULL REFERENCES rooms(id) ON DELETE CASCADE,
    from_agent_id INTEGER REFERENCES workers(id) ON DELETE SET NULL,
    to_agent_id   INTEGER REFERENCES workers(id) ON DELETE SET NULL,
    question      TEXT NOT NULL,
    answer        TEXT,
    status        TEXT NOT NULL DEFAULT 'pending',
    created_at    TEXT DEFAULT {NOW},
    resolved_at   TEXT
);
CREATE INDEX IF NOT EXISTS ix_escalations_room ON escalations(room_id);
CREATE INDEX IF NOT EXISTS ix_escalations_status ON escalations(status);

CREATE TABLE IF NOT EXISTS credentials (
    id              INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id         INTEGER NOT NULL REFERENCES rooms(id) ON DELETE CASCADE,
    name            TEXT NOT NULL,
    type            TEXT NOT NULL DEFAULT 'other',
    value_encrypted TEXT NOT NULL,
    provided_by     TEXT NOT NULL DEFAULT 'keeper',
    created_at      TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_credentials_room ON credentials(room_id);
CREATE UNIQUE INDEX IF NOT EXISTS ux_credentials_room_name
    ON credentials(room_id, name);

CREATE TABLE IF NOT EXISTS wallets (
    id                    INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id               INTEGER NOT NULL REFERENCES rooms(id) ON DELETE CASCADE,
    address               TEXT NOT NULL,
    private_key_encrypted TEXT NOT NULL,
    chain                 TEXT NOT NULL DEFAULT 'base',
    erc8004_agent_id      TEXT,
    created_at            TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_wallets_room ON wallets(room_id);

CREATE TABLE IF NOT EXISTS wallet_transactions (
    id           INTEGER PRIMARY KEY AUTOINCREMENT,
    wallet_id    INTEGER NOT NULL REFERENCES wallets(id) ON DELETE CASCADE,
    type         TEXT NOT NULL,
    amount       TEXT NOT NULL,
    counterparty TEXT,
    tx_hash      TEXT,
    description  TEXT,
    status       TEXT NOT NULL DEFAULT 'confirmed',
    category     TEXT,
    created_at   TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_wallet_tx_wallet ON wallet_transactions(wallet_id);

-- ---- inter-room messaging ----

CREATE TABLE IF NOT EXISTS room_messages (
    id           INTEGER PRIMARY KEY AUTOINCREMENT,
    room_id      INTEGER NOT NULL REFERENCES rooms(id) ON DELETE CASCADE,
    direction    TEXT NOT NULL CHECK(direction IN ('inbound','outbound')),
    from_room_id TEXT,
    to_room_id   TEXT,
    subject      TEXT NOT NULL,
    body         TEXT NOT NULL,
    status       TEXT NOT NULL DEFAULT 'unread',
    created_at   TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_room_messages_room ON room_messages(room_id);
CREATE INDEX IF NOT EXISTS ix_room_messages_status ON room_messages(status);

-- ---- agent loop execution tracking ----

CREATE TABLE IF NOT EXISTS worker_cycles (
    id            INTEGER PRIMARY KEY AUTOINCREMENT,
    worker_id     INTEGER NOT NULL REFERENCES workers(id) ON DELETE CASCADE,
    room_id       INTEGER NOT NULL REFERENCES rooms(id) ON DELETE CASCADE,
    model         TEXT,
    started_at    TEXT DEFAULT {NOW},
    finished_at   TEXT,
    status        TEXT NOT NULL DEFAULT 'running',
    error_message TEXT,
    duration_ms   INTEGER,
    input_tokens  INTEGER,
    output_tokens INTEGER
);
CREATE INDEX IF NOT EXISTS ix_worker_cycles_room
    ON worker_cycles(room_id, started_at DESC);
CREATE INDEX IF NOT EXISTS ix_worker_cycles_status ON worker_cycles(status);

-- Durable crash journal (docs/swarm_recovery.md): intent records for
-- agent cycles and task runs. 'started'/'provider_call' entries stay
-- 'open' while work is in flight and flip to 'closed' on a clean
-- finish; an entry still open at startup marks work a crash
-- interrupted, and recovery fails/requeues its ref row. 'effect'
-- entries track journaled tool side effects: 'intent' before the
-- effect runs, 'committed' after — recovery flags committed effects of
-- interrupted work as 'replay_skip' so a retried cycle never fires the
-- same wallet tx / message send / self-mod twice ('consumed' once the
-- retry skips it, 'abandoned' for intents that never committed).
CREATE TABLE IF NOT EXISTS cycle_journal (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    kind       TEXT NOT NULL CHECK(kind IN ('cycle','task_run','xshard')),
    ref_id     INTEGER NOT NULL,
    room_id    INTEGER,
    worker_id  INTEGER,
    entry      TEXT NOT NULL CHECK(entry IN
                   ('started','provider_call','effect')),
    status     TEXT NOT NULL DEFAULT 'open',
    idem_key   TEXT,
    payload    TEXT,
    created_at TEXT DEFAULT {NOW},
    updated_at TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_journal_ref ON cycle_journal(kind, ref_id);
CREATE INDEX IF NOT EXISTS ix_journal_status ON cycle_journal(status);
CREATE INDEX IF NOT EXISTS ix_journal_idem ON cycle_journal(idem_key);

CREATE TABLE IF NOT EXISTS cycle_logs (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    cycle_id   INTEGER NOT NULL REFERENCES worker_cycles(id) ON DELETE CASCADE,
    seq        INTEGER NOT NULL,
    entry_type TEXT NOT NULL,
    content    TEXT NOT NULL,
    created_at TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_cycle_logs_seq ON cycle_logs(cycle_id, seq);

-- Conversation continuity across cycles. session_id names a serving-engine
-- session (paged-KV session for the tpu: provider, upstream id for external
-- CLIs); messages_json holds the full turn array for stateless API models.
CREATE TABLE IF NOT EXISTS agent_sessions (
    worker_id     INTEGER PRIMARY KEY REFERENCES workers(id) ON DELETE CASCADE,
    session_id    TEXT,
    messages_json TEXT,
    model         TEXT NOT NULL DEFAULT '',
    turn_count    INTEGER NOT NULL DEFAULT 0,
    updated_at    TEXT DEFAULT {NOW}
);

-- ---- clerk (global keeper assistant) ----

CREATE TABLE IF NOT EXISTS clerk_messages (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    role       TEXT NOT NULL CHECK(role IN ('user','assistant','commentary')),
    content    TEXT NOT NULL,
    source     TEXT,
    created_at TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_clerk_messages_created ON clerk_messages(created_at);

CREATE TABLE IF NOT EXISTS clerk_usage (
    id            INTEGER PRIMARY KEY AUTOINCREMENT,
    source        TEXT NOT NULL CHECK(source IN ('chat','commentary')),
    model         TEXT NOT NULL,
    input_tokens  INTEGER NOT NULL DEFAULT 0,
    output_tokens INTEGER NOT NULL DEFAULT 0,
    total_tokens  INTEGER NOT NULL DEFAULT 0,
    success       INTEGER NOT NULL DEFAULT 1,
    used_fallback INTEGER NOT NULL DEFAULT 0,
    attempts      INTEGER NOT NULL DEFAULT 1,
    created_at    TEXT DEFAULT {NOW}
);
CREATE INDEX IF NOT EXISTS ix_clerk_usage_created ON clerk_usage(created_at);
CREATE INDEX IF NOT EXISTS ix_clerk_usage_source
    ON clerk_usage(source, created_at);

-- ---- migration ledger ----

CREATE TABLE IF NOT EXISTS schema_migrations (
    version    INTEGER PRIMARY KEY,
    applied_at TEXT DEFAULT {NOW}
);
""")


# v3 rebuild of cycle_journal for pre-v3 databases: SQLite cannot widen
# a CHECK in place, so the table is renamed, recreated with the 'xshard'
# kind admitted (cross-shard dispatch entries, docs/swarmshard.md), and
# copied back. Indexes follow the rename and die with the old table, so
# they are recreated. Fresh databases get this shape straight from
# SCHEMA and only stamp the version (database.MIGRATIONS).
MIGRATION_V3 = _t("""
ALTER TABLE cycle_journal RENAME TO cycle_journal_v2;
CREATE TABLE cycle_journal (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    kind       TEXT NOT NULL CHECK(kind IN ('cycle','task_run','xshard')),
    ref_id     INTEGER NOT NULL,
    room_id    INTEGER,
    worker_id  INTEGER,
    entry      TEXT NOT NULL CHECK(entry IN
                   ('started','provider_call','effect')),
    status     TEXT NOT NULL DEFAULT 'open',
    idem_key   TEXT,
    payload    TEXT,
    created_at TEXT DEFAULT {NOW},
    updated_at TEXT DEFAULT {NOW}
);
INSERT INTO cycle_journal SELECT * FROM cycle_journal_v2;
DROP TABLE cycle_journal_v2;
CREATE INDEX IF NOT EXISTS ix_journal_ref ON cycle_journal(kind, ref_id);
CREATE INDEX IF NOT EXISTS ix_journal_status ON cycle_journal(status);
CREATE INDEX IF NOT EXISTS ix_journal_idem ON cycle_journal(idem_key);
""")
