from .database import (
    Database,
    get_database,
    reset_database_singleton,
    utc_now,
)
from .schema import SCHEMA, SCHEMA_VERSION

__all__ = [
    "Database",
    "get_database",
    "reset_database_singleton",
    "utc_now",
    "SCHEMA",
    "SCHEMA_VERSION",
]
