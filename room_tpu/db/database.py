"""Connection management and migrations.

The engine keeps the reference's storage posture (reference:
src/server/db.ts:32-55): one SQLite file in WAL mode with foreign keys on
and a generous busy timeout, opened by each surface (server, MCP, tests).
Unlike the reference's synchronous single-threaded Node access, the Python
engine serves HTTP and runtime loops from multiple threads, so the
connection is wrapped in a re-entrant lock.
"""

from __future__ import annotations

import os
import sqlite3
import sys
import threading
import time
from contextlib import contextmanager
from datetime import datetime, timezone
from typing import Any, Iterator, Optional

from .schema import MIGRATION_V3, SCHEMA, SCHEMA_VERSION
from ..utils import knobs, locks

# Ordered (version, ddl) pairs applied after the base schema. Version 1 is
# the base schema itself. Future migrations append here.
MIGRATIONS: list[tuple[int, str]] = [
    # v2: cycle_journal (docs/swarm_recovery.md). The idempotent base
    # SCHEMA — executescript'd on every open, before _migrate — already
    # creates the table on pre-v2 databases, so the body is empty: the
    # stamp records the shape change without duplicating DDL here.
    (2, ""),
    # v3: admit kind='xshard' (cross-shard dispatch journal entries,
    # docs/swarmshard.md). A CHECK can't be widened in place, so pre-v3
    # files get the rename/recreate/copy rebuild.
    (3, MIGRATION_V3),
]


def _maybe_db_fault() -> None:
    """`db_io` chaos fault point (docs/chaos.md) on every statement
    helper. Resolved through sys.modules so the data layer never
    imports the serving package (and its jax dependency): if the fault
    registry was never imported, nothing can be armed and this is a
    dict lookup. Raises sqlite3.OperationalError — the same shape as a
    real locked/corrupt-database hiccup — so recovery paths see exactly
    what production would throw."""
    faults = sys.modules.get("room_tpu.serving.faults")
    if faults is not None and faults.is_armed():
        faults.maybe_fail(
            "db_io", exc_factory=sqlite3.OperationalError
        )


def utc_now() -> str:
    """UTC ISO-8601 timestamp with millisecond precision, Z-suffixed."""
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


class Database:
    """Thread-safe wrapper around a sqlite3 connection.

    All engine code takes a ``Database`` and uses :meth:`query`,
    :meth:`query_one`, :meth:`execute`, and :meth:`transaction`. Rows come
    back as plain dicts.
    """

    def __init__(self, path: str = ":memory:") -> None:
        self.path = path
        self._lock = locks.make_rlock("db")
        self._txn_depth = 0
        # opt-in contention probe (ROOM_TPU_DB_LOCK_STATS): the
        # swarm_storm bench reads these to compare journal-write
        # contention 1-shard vs N-shard; counters are mutated under
        # the db lock itself, so no extra lock is needed
        self._track_contention = knobs.get_bool("ROOM_TPU_DB_LOCK_STATS")
        self.lock_waits = 0
        self.lock_wait_s = 0.0
        self._conn = sqlite3.connect(
            path, check_same_thread=False, isolation_level=None
        )
        self._conn.row_factory = sqlite3.Row
        with self._lock:
            if path != ":memory:":
                self._conn.execute("PRAGMA journal_mode = WAL")
            self._conn.execute("PRAGMA foreign_keys = ON")
            self._conn.execute("PRAGMA busy_timeout = 5000")
            self._conn.executescript(SCHEMA)
            self._migrate()

    # -- migrations ------------------------------------------------------

    def _migrate(self) -> None:
        applied = {
            r[0]
            for r in self._conn.execute(
                "SELECT version FROM schema_migrations"
            ).fetchall()
        }
        fresh = not applied
        if SCHEMA_VERSION not in applied:
            self._conn.execute(
                "INSERT OR IGNORE INTO schema_migrations(version) VALUES (?)",
                (SCHEMA_VERSION,),
            )
        for version, ddl in MIGRATIONS:
            if version in applied:
                continue
            # A fresh database already has the latest shape from SCHEMA, so
            # migrations are stamped as applied without being executed.
            if not fresh:
                self._conn.executescript(ddl)
            self._conn.execute(
                "INSERT OR IGNORE INTO schema_migrations(version) VALUES (?)",
                (version,),
            )

    @property
    def schema_version(self) -> int:
        row = self._conn.execute(
            "SELECT MAX(version) FROM schema_migrations"
        ).fetchone()
        return int(row[0] or 0)

    # -- statement helpers ----------------------------------------------

    @contextmanager
    def _guard(self) -> Iterator[None]:
        """The connection lock, with the opt-in contention probe: a
        contended acquire is counted and timed (a per-shard writer's
        queueing delay IS the single-writer bottleneck the swarm shard
        tier exists to split)."""
        if self._track_contention and not self._lock.acquire(
            blocking=False
        ):
            t0 = time.perf_counter()
            self._lock.acquire()
            self.lock_waits += 1
            self.lock_wait_s += time.perf_counter() - t0
        elif not self._track_contention:
            self._lock.acquire()
        try:
            yield
        finally:
            self._lock.release()

    def execute(self, sql: str, params: tuple | dict = ()) -> sqlite3.Cursor:
        _maybe_db_fault()
        with self._guard():
            return self._conn.execute(sql, params)

    def insert(self, sql: str, params: tuple | dict = ()) -> int:
        """Execute an INSERT and return the new rowid.

        Only meaningful for plain INSERTs: when an upsert resolves to its
        UPDATE branch, sqlite leaves lastrowid at the previous successful
        insert. Upsert callers must re-select the id instead.
        """
        _maybe_db_fault()
        with self._guard():
            return int(self._conn.execute(sql, params).lastrowid or 0)

    def query(self, sql: str, params: tuple | dict = ()) -> list[dict[str, Any]]:
        _maybe_db_fault()
        with self._guard():
            return [dict(r) for r in self._conn.execute(sql, params).fetchall()]

    def query_one(
        self, sql: str, params: tuple | dict = ()
    ) -> Optional[dict[str, Any]]:
        _maybe_db_fault()
        with self._guard():
            row = self._conn.execute(sql, params).fetchone()
            return dict(row) if row is not None else None

    @contextmanager
    def transaction(self) -> Iterator["Database"]:
        """Group statements atomically; rolls back on exception.

        Re-entrant: nested calls become savepoints, so an inner rollback
        only unwinds the inner scope.
        """
        with self._guard():
            if self._txn_depth == 0:
                begin, commit, rollback = (
                    "BEGIN IMMEDIATE", "COMMIT", "ROLLBACK"
                )
            else:
                sp = f"sp_{self._txn_depth}"
                begin = f"SAVEPOINT {sp}"
                commit = f"RELEASE {sp}"
                rollback = f"ROLLBACK TO {sp}; RELEASE {sp}"
            self._conn.execute(begin)
            self._txn_depth += 1
            try:
                yield self
            except BaseException:
                for stmt in rollback.split(";"):
                    self._conn.execute(stmt)
                raise
            else:
                self._conn.execute(commit)
            finally:
                self._txn_depth -= 1

    def close(self) -> None:
        with self._lock:
            self._conn.close()


_default_db: Optional[Database] = None
_default_lock = locks.make_lock("db_default")


def default_db_path() -> str:
    """Resolve the on-disk database path (env-overridable like the
    reference's QUOROOM_DB_PATH / QUOROOM_DATA_DIR, src/server/db.ts:28-39)."""
    explicit = knobs.get_str("ROOM_TPU_DB_PATH")
    if explicit:
        return explicit
    data_dir = os.path.expanduser(knobs.get_str("ROOM_TPU_DATA_DIR"))
    os.makedirs(data_dir, exist_ok=True)
    return os.path.join(data_dir, "data.db")


def get_database(room_id: Optional[int] = None) -> Database:
    """Process-wide singleton — or, with ``ROOM_TPU_SWARM_SHARDS`` > 1,
    the room-id-keyed shard resolver (docs/swarmshard.md): ``room_id``
    selects the owning shard's database file, ``None`` resolves to
    shard 0 (which carries the swarm-global tables). The classic path
    costs one knob read; the swarm package is only imported once
    sharding is actually configured."""
    if knobs.get_int("ROOM_TPU_SWARM_SHARDS") > 1:
        from ..swarm import shard as swarm_shard

        return swarm_shard.default_router().db_for(room_id)
    global _default_db
    with _default_lock:
        if _default_db is None:
            _default_db = Database(default_db_path())
        return _default_db


def reset_database_singleton() -> None:
    """Testing hook: drop the singleton so the next get_database()
    reopens. Also drops the swarm shard router, when one was built —
    the two are the same process-wide storage root."""
    global _default_db
    with _default_lock:
        if _default_db is not None:
            _default_db.close()
        _default_db = None
    swarm_shard = sys.modules.get("room_tpu.swarm.shard")
    if swarm_shard is not None:
        swarm_shard.reset_default_router()
