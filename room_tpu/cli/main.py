"""CLI entry point (reference: src/cli/index.ts — serve / mcp / status /
help). Run as `python -m room_tpu.cli.main <command>` or via the
`room-tpu` console script."""

from __future__ import annotations

import argparse
import sys
import time


def cmd_serve(args: argparse.Namespace) -> int:
    from ..server.app import start_server

    app = start_server(port=args.port, install_signal_handlers=True)
    print(f"room-tpu server listening on http://127.0.0.1:{app.port}")
    print(f"data dir: {app.db.path}")
    try:
        while not getattr(app, "_done").wait(timeout=3600):
            pass
    except KeyboardInterrupt:
        app.stop()
    return 0


def cmd_mcp(args: argparse.Namespace) -> int:
    from ..mcp.server import run_stdio_server

    return run_stdio_server()


def cmd_status(args: argparse.Namespace) -> int:
    import json
    import os
    import urllib.request

    from ..server.auth import data_dir

    try:
        with open(os.path.join(data_dir(), "api.port")) as f:
            port = int(f.read().strip())
        with open(os.path.join(data_dir(), "api.token")) as f:
            token = f.read().strip()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/status",
            headers={"Authorization": f"Bearer {token}"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            print(json.dumps(json.loads(resp.read())["data"], indent=2))
        return 0
    except Exception as e:
        print(f"server not reachable: {e}", file=sys.stderr)
        return 1


def cmd_bench(args: argparse.Namespace) -> int:
    import runpy

    runpy.run_module("bench", run_name="__main__")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="room-tpu",
        description="TPU-native autonomous agent-swarm engine",
    )
    sub = parser.add_subparsers(dest="command")

    serve = sub.add_parser("serve", help="run the API server + runtime")
    serve.add_argument("--port", type=int, default=3700)
    serve.set_defaults(fn=cmd_serve)

    mcp = sub.add_parser("mcp", help="run the MCP stdio server")
    mcp.set_defaults(fn=cmd_mcp)

    status = sub.add_parser("status", help="query a running server")
    status.set_defaults(fn=cmd_status)

    bench = sub.add_parser("bench", help="run the decode benchmark")
    bench.set_defaults(fn=cmd_bench)

    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 2
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
