"""CLI entry point (reference: src/cli/index.ts — serve / mcp / status /
help). Run as `python -m room_tpu.cli.main <command>` or via the
`room-tpu` console script."""

from __future__ import annotations

import argparse
import sys
import time


def cmd_serve(args: argparse.Namespace) -> int:
    # multi-host pods: jax.distributed must initialize before anything
    # touches an XLA backend, so this runs before the server imports
    # (env contract: ROOM_TPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID)
    from ..parallel.multihost import initialize_multihost

    initialize_multihost()

    from ..server.app import start_server

    app = start_server(port=args.port, install_signal_handlers=True)
    print(f"room-tpu server listening on http://127.0.0.1:{app.port}")
    print(f"data dir: {app.db.path}")
    try:
        while not getattr(app, "_done").wait(timeout=3600):
            pass
    except KeyboardInterrupt:
        app.stop()
    return 0


def cmd_mcp(args: argparse.Namespace) -> int:
    from ..mcp.server import run_stdio_server

    return run_stdio_server()


def cmd_status(args: argparse.Namespace) -> int:
    import json
    import os
    import urllib.request

    from ..server.auth import data_dir

    try:
        with open(os.path.join(data_dir(), "api.port")) as f:
            port = int(f.read().strip())
        with open(os.path.join(data_dir(), "api.token")) as f:
            token = f.read().strip()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/status",
            headers={"Authorization": f"Bearer {token}"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            print(json.dumps(json.loads(resp.read())["data"], indent=2))
        return 0
    except Exception as e:
        print(f"server not reachable: {e}", file=sys.stderr)
        return 1


def cmd_bench(args: argparse.Namespace) -> int:
    import runpy

    runpy.run_module("bench", run_name="__main__")
    return 0


def cmd_update(args: argparse.Namespace) -> int:
    """Check for (and optionally apply) a staged update (reference:
    updateChecker/autoUpdate driven from the CLI)."""
    import json

    from ..server.updater import (
        UpdateChecker, get_ready_update_version, promote_staged_update,
    )

    checker = UpdateChecker()
    checker.force_check(ignore_backoff=True)
    view = checker.status_view()
    print(json.dumps(view, indent=1, default=str))
    ready = get_ready_update_version()
    if ready and args.apply:
        version = promote_staged_update()
        print(f"update v{version} promoted; restart the server to "
              "pick it up")
    elif ready:
        print(f"update v{ready} staged; run `room-tpu update --apply` "
              "or POST /api/server/update-restart")
    return 0


def cmd_uninstall(args: argparse.Namespace) -> int:
    """Remove the data directory (DB, tokens, staged updates). Keeps
    user files outside the data dir untouched; refuses without
    --yes."""
    import shutil

    from ..server.auth import data_dir

    target = data_dir()
    if not args.yes:
        print(f"would remove {target} (db, tokens, staged updates); "
              "re-run with --yes to confirm")
        return 2
    shutil.rmtree(target, ignore_errors=True)
    print(f"removed {target}")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="room-tpu",
        description="TPU-native autonomous agent-swarm engine",
    )
    sub = parser.add_subparsers(dest="command")

    serve = sub.add_parser("serve", help="run the API server + runtime")
    serve.add_argument("--port", type=int, default=3700)
    serve.set_defaults(fn=cmd_serve)

    mcp = sub.add_parser("mcp", help="run the MCP stdio server")
    mcp.set_defaults(fn=cmd_mcp)

    status = sub.add_parser("status", help="query a running server")
    status.set_defaults(fn=cmd_status)

    bench = sub.add_parser("bench", help="run the decode benchmark")
    bench.set_defaults(fn=cmd_bench)

    update = sub.add_parser("update", help="check for updates")
    update.add_argument("--apply", action="store_true",
                        help="promote a staged update")
    update.set_defaults(fn=cmd_update)

    uninstall = sub.add_parser(
        "uninstall", help="remove the data directory"
    )
    uninstall.add_argument("--yes", action="store_true")
    uninstall.set_defaults(fn=cmd_uninstall)

    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 2
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
