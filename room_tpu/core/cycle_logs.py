"""Cycle log buffer: seq-numbered entries buffered and flushed to the DB
periodically, with a live event per entry for WS streaming (reference:
src/shared/console-log-buffer.ts — 1 s flush cadence)."""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..db import Database
from .events import event_bus
from ..utils import locks

FLUSH_INTERVAL_S = 1.0


class CycleLogBuffer:
    def __init__(
        self,
        db: Database,
        cycle_id: int,
        flush_interval_s: float = FLUSH_INTERVAL_S,
    ) -> None:
        self.db = db
        self.cycle_id = cycle_id
        self.flush_interval_s = flush_interval_s
        self._seq = 0
        self._pending: list[tuple[int, str, str]] = []
        self._lock = locks.make_lock("cycle_logs")
        self._last_flush = time.monotonic()

    def append(self, entry_type: str, content: str) -> int:
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._pending.append((seq, entry_type, content))
        event_bus.emit(
            "cycle:log",
            f"cycle:{self.cycle_id}",
            {"seq": seq, "entry_type": entry_type, "content": content},
        )
        if time.monotonic() - self._last_flush >= self.flush_interval_s:
            self.flush()
        return seq

    def flush(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
            self._last_flush = time.monotonic()
        if not pending:
            return
        with self.db.transaction():
            for seq, entry_type, content in pending:
                self.db.insert(
                    "INSERT INTO cycle_logs(cycle_id, seq, entry_type, "
                    "content) VALUES (?,?,?,?)",
                    (self.cycle_id, seq, entry_type, content),
                )

    def close(self) -> None:
        self.flush()


def get_cycle_logs(db: Database, cycle_id: int) -> list[dict]:
    return db.query(
        "SELECT * FROM cycle_logs WHERE cycle_id=? ORDER BY seq",
        (cycle_id,),
    )
