"""Clerk: the system-wide keeper assistant (reference:
src/shared/clerk-tools.ts, src/server/clerk-profile.ts,
clerk-profile-config.ts).

One chat turn = one provider execution with the clerk tool surface
(room/task/runtime management executed directly against the engine),
tried across a fallback chain of models; token burn lands in
clerk_usage."""

from __future__ import annotations

import json
from typing import Any, Optional

from ..db import Database
from ..providers import ExecutionRequest, get_model_provider
from . import rooms as rooms_mod, task_runner, workers as workers_mod
from . import escalations as escalations_mod, quorum as quorum_mod
from .messages import add_chat_message, get_setting
from .queen_tools import _tool

CLERK_SYSTEM_PROMPT = (
    "You are the Clerk: the keeper's assistant for managing their agent "
    "rooms. You can create and configure rooms, start/stop them, manage "
    "scheduled tasks, relay messages, resolve escalations, and cast "
    "keeper votes. Be concise and act through tools; confirm what you "
    "did. Never invent room or task ids — list first if unsure."
)

CLERK_FALLBACK_CHAIN = (
    "tpu:qwen3-coder-30b", "openai:gpt-4o-mini",
    "anthropic:claude-3-5-haiku-latest",
)

CLERK_TOOLS: list[dict] = [
    _tool("list_rooms", "List all rooms with status.", {}, []),
    _tool(
        "create_room",
        "Create a new room with a queen.",
        {"name": {"type": "string"}, "goal": {"type": "string"}},
        ["name"],
    ),
    _tool(
        "start_room", "Start a room's agent loops.",
        {"room_id": {"type": "integer"}}, ["room_id"],
    ),
    _tool(
        "stop_room", "Stop a room's agent loops.",
        {"room_id": {"type": "integer"}}, ["room_id"],
    ),
    _tool(
        "room_status", "Aggregate status of one room.",
        {"room_id": {"type": "integer"}}, ["room_id"],
    ),
    _tool("list_tasks", "List scheduled tasks.",
          {"room_id": {"type": "integer"}}, []),
    _tool(
        "create_task",
        "Create a scheduled task (cron or one-time).",
        {
            "name": {"type": "string"},
            "prompt": {"type": "string"},
            "cron_expression": {"type": "string"},
            "scheduled_at": {"type": "string"},
            "room_id": {"type": "integer"},
        },
        ["name", "prompt"],
    ),
    _tool(
        "run_task_now", "Trigger a task immediately.",
        {"task_id": {"type": "integer"}}, ["task_id"],
    ),
    _tool(
        "create_reminder",
        "Schedule a one-time keeper reminder at an ISO datetime.",
        {
            "text": {"type": "string"},
            "at": {"type": "string", "description": "UTC ISO timestamp"},
        },
        ["text", "at"],
    ),
    _tool(
        "message_room",
        "Leave a keeper chat message for a room's queen.",
        {
            "room_id": {"type": "integer"},
            "content": {"type": "string"},
        },
        ["room_id", "content"],
    ),
    _tool(
        "answer_escalation", "Answer a pending escalation.",
        {
            "escalation_id": {"type": "integer"},
            "answer": {"type": "string"},
        },
        ["escalation_id", "answer"],
    ),
    _tool(
        "keeper_vote", "Cast the keeper's vote on a decision.",
        {
            "decision_id": {"type": "integer"},
            "vote": {"type": "string", "enum": ["yes", "no"]},
        },
        ["decision_id", "vote"],
    ),
]


def execute_clerk_tool(
    db: Database, name: str, args: dict, runtime=None
) -> str:
    try:
        return _dispatch(db, name, args or {}, runtime)
    except Exception as e:
        return f"tool error: {type(e).__name__}: {e}"


def _dispatch(db: Database, name: str, args: dict, runtime) -> str:
    if name == "list_rooms":
        return json.dumps([
            {"id": r["id"], "name": r["name"], "status": r["status"],
             "goal": r["goal"]}
            for r in rooms_mod.list_rooms(db)
        ])
    if name == "create_room":
        room = rooms_mod.create_room(
            db, args["name"], goal=args.get("goal"),
            worker_model=get_setting(db, "worker_model", "tpu") or "tpu",
        )
        return f"room #{room['id']} '{room['name']}' created"
    if name == "start_room":
        if runtime is None:
            return "runtime not running"
        okay = runtime.start_room(int(args["room_id"]))
        return f"room #{args['room_id']} " + ("started" if okay else
                                              "could not start")
    if name == "stop_room":
        if runtime is None:
            return "runtime not running"
        runtime.stop_room(int(args["room_id"]))
        return f"room #{args['room_id']} stopped"
    if name == "room_status":
        st = rooms_mod.get_room_status(db, int(args["room_id"]))
        if st is None:
            return "room not found"
        st = dict(st)
        st["room"] = {"id": st["room"]["id"], "name": st["room"]["name"],
                      "status": st["room"]["status"]}
        return json.dumps(st)
    if name == "list_tasks":
        return json.dumps([
            {"id": t["id"], "name": t["name"], "status": t["status"],
             "trigger": t["trigger_type"], "cron": t["cron_expression"]}
            for t in task_runner.list_tasks(db, args.get("room_id"))
        ])
    if name == "create_task":
        trigger = "cron" if args.get("cron_expression") else "once"
        tid = task_runner.create_task(
            db, args["name"], args["prompt"], trigger_type=trigger,
            cron_expression=args.get("cron_expression"),
            scheduled_at=args.get("scheduled_at"),
            room_id=args.get("room_id"),
        )
        return f"task #{tid} created ({trigger})"
    if name == "run_task_now":
        if runtime is None:
            return "runtime not running"
        queued = runtime.run_task_now(int(args["task_id"]))
        return f"task #{args['task_id']} " + ("queued" if queued else
                                              "already pending")
    if name == "create_reminder":
        tid = task_runner.create_task(
            db, f"reminder: {args['text'][:40]}", args["text"],
            trigger_type="once", scheduled_at=args["at"],
            executor="keeper_reminder",
        )
        return f"reminder #{tid} scheduled for {args['at']}"
    if name == "message_room":
        add_chat_message(db, int(args["room_id"]), "user",
                         args["content"])
        return f"message left for room #{args['room_id']}"
    if name == "answer_escalation":
        escalations_mod.answer_escalation(
            db, int(args["escalation_id"]), args["answer"]
        )
        return f"escalation #{args['escalation_id']} answered"
    if name == "keeper_vote":
        d = quorum_mod.keeper_vote(
            db, int(args["decision_id"]), args["vote"]
        )
        return f"keeper vote recorded; decision now {d['status']}"
    return f"unknown tool {name!r}"


def run_clerk_turn(
    db: Database, content: str, runtime=None
) -> dict[str, Any]:
    """One keeper↔clerk chat turn with model fallback (reference:
    executeClerkWithFallback)."""
    db.insert(
        "INSERT INTO clerk_messages(role, content, source) "
        "VALUES ('user', ?, 'chat')",
        (content,),
    )
    history = list(reversed(db.query(
        "SELECT role, content FROM clerk_messages "
        "WHERE role IN ('user','assistant') ORDER BY id DESC LIMIT 20"
    )))[:-1]

    preferred = get_setting(db, "clerk_model")
    chain = ([preferred] if preferred else []) + [
        m for m in CLERK_FALLBACK_CHAIN if m != preferred
    ]

    last_error = "no provider available"
    for attempt, model in enumerate(chain):
        provider = get_model_provider(model, db)
        ready, why = provider.is_ready()
        if not ready:
            last_error = why
            continue
        result = provider.execute(ExecutionRequest(
            prompt=content,
            system_prompt=CLERK_SYSTEM_PROMPT,
            model=model,
            tools=CLERK_TOOLS,
            on_tool_call=lambda n, a: execute_clerk_tool(
                db, n, a, runtime
            ),
            messages=[
                {"role": m["role"], "content": m["content"]}
                for m in history
            ],
            max_turns=8,
            timeout_s=300,
        ))
        db.insert(
            "INSERT INTO clerk_usage(source, model, input_tokens, "
            "output_tokens, total_tokens, success, used_fallback, "
            "attempts) VALUES ('chat', ?,?,?,?,?,?,?)",
            (
                model, result.input_tokens, result.output_tokens,
                result.input_tokens + result.output_tokens,
                int(result.success), int(attempt > 0), attempt + 1,
            ),
        )
        if result.success:
            reply = result.text or "(no reply)"
            db.insert(
                "INSERT INTO clerk_messages(role, content, source) "
                "VALUES ('assistant', ?, 'chat')",
                (reply,),
            )
            return {"reply": reply, "model": model,
                    "toolCalls": result.tool_calls}
        last_error = result.error or "execution failed"

    reply = f"(clerk unavailable: {last_error})"
    db.insert(
        "INSERT INTO clerk_messages(role, content, source) "
        "VALUES ('assistant', ?, 'chat')",
        (reply,),
    )
    return {"reply": reply, "model": None, "toolCalls": []}
