"""LLM rate-limit detection + wait policy (reference:
src/shared/rate-limit.ts — regex detection, reset-time parsing, wait
clamped 30 s–60 min, abortable sleep)."""

from __future__ import annotations

import re
import threading
from datetime import datetime, timezone
from typing import Optional

WAIT_MIN_S = 30.0
WAIT_MAX_S = 60 * 60.0
WAIT_DEFAULT_S = 5 * 60.0
MAX_RETRIES = 3

_PATTERNS = [
    re.compile(p, re.IGNORECASE)
    for p in (
        r"rate[ _-]?limit",
        r"usage[ _-]?limit",
        r"too many requests",
        r"\b429\b",
        r"quota exceeded",
        r"overloaded",
        r"capacity .*exceeded",
    )
]

_RESET_AT = re.compile(
    r"reset(?:s)?\s+at\s+(\d{1,2}):(\d{2})\s*(am|pm)?", re.IGNORECASE
)
_RESET_IN = re.compile(
    r"(?:in|after)\s+(\d+)\s*(seconds?|secs?|minutes?|mins?|hours?|hrs?)",
    re.IGNORECASE,
)
_RESET_TS = re.compile(r"reset[^0-9]*(1[6-9]\d{8})")


def detect_rate_limit(text: str) -> Optional[float]:
    """Returns the wait in seconds if the text looks like a rate-limit
    failure, else None."""
    if not text or not any(p.search(text) for p in _PATTERNS):
        return None
    return clamp_wait(parse_reset_wait(text))


def parse_reset_wait(text: str) -> float:
    m = _RESET_IN.search(text)
    if m:
        n, unit = int(m.group(1)), m.group(2).lower()
        if unit.startswith(("sec",)):
            return float(n)
        if unit.startswith(("min",)):
            return n * 60.0
        return n * 3600.0

    m = _RESET_TS.search(text)
    if m:
        ts = int(m.group(1))
        return ts - datetime.now(timezone.utc).timestamp()

    m = _RESET_AT.search(text)
    if m:
        hour, minute = int(m.group(1)), int(m.group(2))
        ampm = (m.group(3) or "").lower()
        if ampm == "pm" and hour < 12:
            hour += 12
        if ampm == "am" and hour == 12:
            hour = 0
        now = datetime.now()
        target = now.replace(
            hour=hour % 24, minute=minute, second=0, microsecond=0
        )
        wait = (target - now).total_seconds()
        if wait < 0:
            wait += 24 * 3600
        return wait

    return WAIT_DEFAULT_S


def clamp_wait(wait_s: float) -> float:
    return max(WAIT_MIN_S, min(WAIT_MAX_S, wait_s))


def abortable_sleep(
    seconds: float, abort: Optional[threading.Event] = None
) -> bool:
    """Sleep up to `seconds`; returns True if aborted early."""
    if abort is None:
        abort = threading.Event()
    return abort.wait(timeout=seconds)
