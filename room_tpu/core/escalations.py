"""Keeper escalations: questions an agent can't resolve inside the room."""

from __future__ import annotations

from typing import Optional

from ..db import Database, utc_now


def create_escalation(
    db: Database,
    room_id: int,
    question: str,
    from_agent_id: Optional[int] = None,
    to_agent_id: Optional[int] = None,
) -> int:
    eid = db.insert(
        "INSERT INTO escalations(room_id, from_agent_id, to_agent_id, "
        "question) VALUES (?,?,?,?)",
        (room_id, from_agent_id, to_agent_id, question),
    )
    # emitted here so EVERY creation path (queen tool, webhook, MCP)
    # reaches the dashboard's desktop-notification handler
    from .events import event_bus

    event_bus.emit("escalation:created", f"room:{room_id}",
                   {"id": eid, "question": question})
    return eid


def get_escalation(db: Database, escalation_id: int) -> Optional[dict]:
    return db.query_one(
        "SELECT * FROM escalations WHERE id=?", (escalation_id,)
    )


def answer_escalation(db: Database, escalation_id: int, answer: str) -> None:
    db.execute(
        "UPDATE escalations SET answer=?, status='answered', resolved_at=? "
        "WHERE id=?",
        (answer, utc_now(), escalation_id),
    )


def dismiss_escalation(db: Database, escalation_id: int) -> None:
    db.execute(
        "UPDATE escalations SET status='dismissed', resolved_at=? WHERE id=?",
        (utc_now(), escalation_id),
    )


def pending_escalations(db: Database, room_id: Optional[int] = None) -> list[dict]:
    if room_id is None:
        return db.query(
            "SELECT * FROM escalations WHERE status='pending' ORDER BY id"
        )
    return db.query(
        "SELECT * FROM escalations WHERE room_id=? AND status='pending' "
        "ORDER BY id",
        (room_id,),
    )


def recently_answered(db: Database, room_id: int, limit: int = 5) -> list[dict]:
    """Answered-but-unseen keeper replies surfaced into the next cycle
    prompt."""
    return db.query(
        "SELECT * FROM escalations WHERE room_id=? AND status='answered' "
        "ORDER BY resolved_at DESC LIMIT ?",
        (room_id, limit),
    )
