"""Queen/worker tool surface: OpenAI-format tool defs + the dispatcher
that executes them against the engine (reference:
src/shared/queen-tools.ts — QUEEN_TOOLS:348, WORKER_TOOLS:361,
executeQueenTool:394)."""

from __future__ import annotations

import json
from typing import Optional

from ..db import Database
from . import (
    escalations as escalations_mod,
    goals as goals_mod,
    memory as memory_mod,
    messages as messages_mod,
    quorum as quorum_mod,
    rooms as rooms_mod,
    skills as skills_mod,
    wallet as wallet_mod,
    workers as workers_mod,
)
from .activity import log_room_activity
from .constants import WIP_MAX_CHARS
from .events import event_bus


def _tool(name: str, description: str, properties: dict,
          required: list[str]) -> dict:
    return {
        "name": name,
        "description": description,
        "parameters": {
            "type": "object",
            "properties": properties,
            "required": required,
        },
    }


_SHARED_TOOLS = [
    _tool(
        "remember",
        "Store a durable fact in the room's semantic memory.",
        {
            "name": {"type": "string", "description": "short entity name"},
            "content": {"type": "string"},
            "category": {"type": "string"},
        },
        ["name", "content"],
    ),
    _tool(
        "recall",
        "Search the room's memory (hybrid full-text + semantic).",
        {"query": {"type": "string"}},
        ["query"],
    ),
    _tool(
        "send_message",
        "Send a message to another room (to_room_id) or to the keeper "
        "(to='keeper').",
        {
            "to": {"type": "string",
                   "description": "'keeper' or a room id"},
            "subject": {"type": "string"},
            "body": {"type": "string"},
        },
        ["to", "body"],
    ),
    _tool(
        "save_wip",
        "Save a work-in-progress note; the next cycle starts from it.",
        {"note": {"type": "string"}},
        ["note"],
    ),
    _tool(
        "web_fetch",
        "Fetch a URL and return readable text.",
        {"url": {"type": "string"}},
        ["url"],
    ),
    _tool(
        "web_search",
        "Search the web; returns result titles+urls+snippets.",
        {"query": {"type": "string"}},
        ["query"],
    ),
    _tool(
        "web_browse",
        "Persistent browser session (cookies survive between calls). "
        "action=open navigates (url required; omit session_id to start "
        "a session); click follows link #index from the last snapshot; "
        "submit fills+submits form #index with fields; text returns "
        "page text (optionally only lines matching find); back goes to "
        "the previous page; close ends the session.",
        {
            "action": {"type": "string",
                       "enum": ["open", "click", "submit", "text",
                                "back", "close"]},
            "session_id": {"type": "string"},
            "url": {"type": "string"},
            "index": {"type": "integer"},
            "fields": {"type": "object"},
            "find": {"type": "string"},
        },
        ["action"],
    ),
]

QUEEN_TOOLS: list[dict] = [
    _tool(
        "set_goal",
        "Create a goal (optionally under a parent goal).",
        {
            "description": {"type": "string"},
            "parent_goal_id": {"type": "integer"},
        },
        ["description"],
    ),
    _tool(
        "delegate",
        "Create a goal and assign it to a worker; wakes the worker.",
        {
            "description": {"type": "string"},
            "worker_id": {"type": "integer"},
            "parent_goal_id": {"type": "integer"},
        },
        ["description", "worker_id"],
    ),
    _tool(
        "announce_decision",
        "Announce a decision for quorum review; it becomes effective "
        "after the objection window unless a worker objects.",
        {
            "proposal": {"type": "string"},
            "decision_type": {
                "type": "string",
                "enum": ["low_impact", "high_impact", "critical"],
            },
        },
        ["proposal"],
    ),
    _tool(
        "open_ballot",
        "Open an explicit vote on a proposal; workers cast votes and "
        "it resolves by the room's threshold when the electorate "
        "(at least the room's min-voters setting) has spoken or the "
        "timeout passes.",
        {
            "proposal": {"type": "string"},
            "timeout_minutes": {"type": "number"},
        },
        ["proposal"],
    ),
    _tool(
        "create_worker",
        "Add a worker to the room with a role preset.",
        {
            "name": {"type": "string"},
            "role": {
                "type": "string",
                "enum": ["executor", "guardian", "analyst", "writer",
                         "researcher"],
            },
            "system_prompt": {"type": "string"},
        },
        ["name", "role"],
    ),
    _tool(
        "update_worker",
        "Update a worker's prompt/cadence.",
        {
            "worker_id": {"type": "integer"},
            "system_prompt": {"type": "string"},
            "cycle_gap_ms": {"type": "integer"},
            "max_turns": {"type": "integer"},
        },
        ["worker_id"],
    ),
    _tool(
        "configure_room",
        "Update room settings (cycle gap, autonomy, quiet hours).",
        {
            "queen_cycle_gap_ms": {"type": "integer"},
            "autonomy_mode": {"type": "string",
                              "enum": ["manual", "semi", "full"]},
            "queen_quiet_from": {"type": "string"},
            "queen_quiet_until": {"type": "string"},
        },
        [],
    ),
    _tool(
        "escalate_to_keeper",
        "Ask the keeper a question the room cannot resolve itself.",
        {"question": {"type": "string"}},
        ["question"],
    ),
    _tool(
        "wallet_status",
        "Room wallet address and recorded transactions.",
        {},
        [],
    ),
] + _SHARED_TOOLS

WORKER_TOOLS: list[dict] = [
    _tool(
        "complete_goal",
        "Mark an assigned goal complete (include evidence).",
        {
            "goal_id": {"type": "integer"},
            "evidence": {"type": "string"},
        },
        ["goal_id"],
    ),
    _tool(
        "update_goal_progress",
        "Report progress (0..1) on an assigned goal.",
        {
            "goal_id": {"type": "integer"},
            "progress": {"type": "number"},
            "observation": {"type": "string"},
        },
        ["goal_id", "progress"],
    ),
    _tool(
        "object_to_decision",
        "Object to an announced decision before it becomes effective.",
        {
            "decision_id": {"type": "integer"},
            "reason": {"type": "string"},
        },
        ["decision_id", "reason"],
    ),
    _tool(
        "create_skill",
        "Save a reusable skill (recipe) for the room.",
        {
            "name": {"type": "string"},
            "content": {"type": "string"},
            "activation_context": {"type": "string"},
        },
        ["name", "content"],
    ),
] + _SHARED_TOOLS


def execute_queen_tool(
    db: Database,
    room_id: int,
    worker_id: int,
    name: str,
    args: dict,
) -> str:
    """Dispatch one tool call; returns the string shown to the model."""
    try:
        return _dispatch(db, room_id, worker_id, name, args or {})
    except Exception as e:
        return f"tool error: {type(e).__name__}: {e}"


def _dispatch(
    db: Database, room_id: int, worker_id: int, name: str, args: dict
) -> str:
    if name == "set_goal":
        gid = goals_mod.create_goal(
            db, room_id, args["description"],
            parent_goal_id=args.get("parent_goal_id"),
        )
        return f"goal #{gid} created"

    if name == "delegate":
        target = workers_mod.get_worker(db, int(args["worker_id"]))
        if target is None or target["room_id"] != room_id:
            return f"no worker #{args['worker_id']} in this room"
        gid = goals_mod.create_goal(
            db, room_id, args["description"],
            parent_goal_id=args.get("parent_goal_id"),
            assigned_worker_id=target["id"],
        )
        log_room_activity(
            db, room_id, "delegate",
            f"Delegated to {target['name']}: {args['description']}",
            actor_id=worker_id,
        )
        from .agent_loop import trigger_agent

        trigger_agent(db, room_id, target["id"])
        return f"goal #{gid} delegated to {target['name']}"

    if name == "announce_decision":
        # dedupe: identical open proposal -> return existing
        for d in quorum_mod.pending_decisions(db, room_id):
            if d["proposal"] == args["proposal"]:
                return f"decision #{d['id']} already announced"
        d = quorum_mod.announce(
            db, room_id, worker_id, args["proposal"],
            args.get("decision_type", "low_impact"),
        )
        return f"decision #{d['id']} {d['status']}"

    if name == "open_ballot":
        for d in quorum_mod.pending_decisions(db, room_id):
            if d["proposal"] == args["proposal"]:
                return f"decision #{d['id']} already open"
        d = quorum_mod.open_ballot(
            db, room_id, worker_id, args["proposal"],
            timeout_minutes=float(args.get("timeout_minutes", 10)),
        )
        return (f"ballot #{d['id']} open (threshold "
                f"{d['threshold']}, min voters {d['min_voters']})")

    if name == "create_worker":
        wid = workers_mod.create_worker(
            db,
            name=args["name"],
            system_prompt=args.get("system_prompt", ""),
            room_id=room_id,
            role=args["role"],
        )
        log_room_activity(
            db, room_id, "worker",
            f"Created worker {args['name']} ({args['role']})",
            actor_id=worker_id,
        )
        return f"worker #{wid} created"

    if name == "update_worker":
        wid = int(args.pop("worker_id"))
        target = workers_mod.get_worker(db, wid)
        if target is None or target["room_id"] != room_id:
            return f"no worker #{wid} in this room"
        workers_mod.update_worker(db, wid, **args)
        return f"worker #{wid} updated"

    if name == "configure_room":
        rooms_mod.update_room(db, room_id, **args)
        return "room configured"

    if name == "escalate_to_keeper":
        # create_escalation emits escalation:created itself (all
        # creation paths must reach the notification handler)
        eid = escalations_mod.create_escalation(
            db, room_id, args["question"], from_agent_id=worker_id
        )
        return f"escalation #{eid} sent to keeper"

    if name == "wallet_status":
        w = wallet_mod.get_room_wallet(db, room_id)
        if w is None:
            return "no wallet for this room"
        txs = wallet_mod.list_transactions(db, w["id"])[:5]
        return json.dumps(
            {"address": w["address"], "chain": w["chain"],
             "recent_transactions": txs}
        )

    if name == "complete_goal":
        goal = goals_mod.get_goal(db, int(args["goal_id"]))
        if goal is None or goal["room_id"] != room_id:
            return f"no goal #{args['goal_id']} in this room"
        if args.get("evidence"):
            goals_mod.add_goal_update(
                db, goal["id"], args["evidence"], worker_id=worker_id
            )
        goals_mod.complete_goal(db, goal["id"])
        log_room_activity(
            db, room_id, "goal",
            f"Goal completed: {goal['description']}", actor_id=worker_id,
        )
        return f"goal #{goal['id']} completed"

    if name == "update_goal_progress":
        goal = goals_mod.get_goal(db, int(args["goal_id"]))
        if goal is None or goal["room_id"] != room_id:
            return f"no goal #{args['goal_id']} in this room"
        goals_mod.add_goal_update(
            db, goal["id"], args.get("observation", ""),
            worker_id=worker_id,
            metric_value=float(args["progress"]),
        )
        return f"goal #{goal['id']} progress={args['progress']}"

    if name == "object_to_decision":
        d = quorum_mod.object_to(
            db, int(args["decision_id"]), worker_id, args["reason"]
        )
        return f"objected to decision #{d['id']}"

    if name == "create_skill":
        sid = skills_mod.create_skill(
            db, args["name"], args["content"], room_id=room_id,
            activation_context=args.get("activation_context"),
            agent_created=True, created_by_worker_id=worker_id,
        )
        return f"skill #{sid} saved"

    if name == "remember":
        eid = memory_mod.remember(
            db, args["name"], args["content"],
            category=args.get("category"), room_id=room_id,
        )
        return f"remembered as entity #{eid}"

    if name == "recall":
        hits = memory_mod.hybrid_search(
            db, args["query"], query_vector=_embed_query(args["query"]),
            room_id=room_id,
        )
        if not hits:
            return "no memories found"
        return "\n".join(
            f"- {h['name']}: {'; '.join(h['observations'][-2:])}"
            for h in hits
        )

    if name == "send_message":
        to = str(args["to"])
        if to == "keeper":
            messages_mod.add_chat_message(
                db, room_id, "assistant", args["body"]
            )
            event_bus.emit(
                "chat:message", f"room:{room_id}", {"body": args["body"]}
            )
            return "message delivered to keeper"
        try:
            to_id = int(to)
        except ValueError:
            return f"unknown recipient {to!r}"
        if rooms_mod.get_room(db, to_id) is None:
            return f"no room #{to_id}"
        messages_mod.send_room_message(
            db, room_id, to_id, args.get("subject", ""), args["body"]
        )
        return f"message sent to room #{to_id}"

    if name == "save_wip":
        workers_mod.save_wip(db, worker_id, args["note"][:WIP_MAX_CHARS])
        return "WIP saved"

    if name == "web_fetch":
        from .web_tools import web_fetch

        return web_fetch(args["url"])

    if name == "web_search":
        from .web_tools import web_search

        return web_search(args["query"])

    if name == "web_browse":
        return _web_browse(args)

    return f"unknown tool {name!r}"


def _web_browse(args: dict) -> str:
    import json as _json

    from .web_tools import (
        close_web_session, get_web_session, open_web_session,
    )

    action = args.get("action")
    sid = args.get("session_id")
    if action == "open" and not sid:
        sess = open_web_session()
    else:
        sess = get_web_session(sid or "")
        if sess is None:
            return (
                f"unknown web session {sid!r}; start one with "
                "action=open"
            )

    if action == "open":
        if not args.get("url"):
            return "url is required for action=open"
        out = sess.goto(args["url"])
    elif action == "click":
        out = sess.click(int(args.get("index", -1)))
    elif action == "submit":
        out = sess.submit_form(
            int(args.get("index", 0)), args.get("fields") or {}
        )
    elif action == "text":
        return sess.text(args.get("find"))
    elif action == "back":
        out = sess.back()
    elif action == "close":
        close_web_session(sess.id)
        return "session closed"
    else:
        return f"unknown action {action!r}"
    return _json.dumps({"session_id": sess.id, **out}, indent=1)


def _embed_query(query: str):
    """Query embedding via the on-mesh embedder when it is live; None
    degrades recall to FTS-only."""
    try:
        from ..serving.embed_service import embed_texts

        return embed_texts([query])[0]
    except Exception:
        return None
