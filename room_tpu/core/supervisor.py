"""Process supervisor (reference: src/shared/process-supervisor.ts):
registry of managed child processes with tree-kill (descendant walk) and
a graceful-then-forced shutdown sweep. Agents and tasks that spawn
external programs register them here so server shutdown never strands
orphans."""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from typing import Optional
from ..utils import locks

_managed: dict[int, str] = {}
_lock = locks.make_lock("supervisor")


def register_managed_process(pid: int, label: str = "") -> None:
    with _lock:
        _managed[pid] = label


def unregister_managed_process(pid: int) -> None:
    with _lock:
        _managed.pop(pid, None)


def managed_processes() -> dict[int, str]:
    with _lock:
        return dict(_managed)


def _descendants(root_pid: int) -> list[int]:
    """Walk /proc (or ps fallback) for the full descendant set."""
    children: dict[int, list[int]] = {}
    try:
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    fields = f.read().split()
                ppid = int(fields[3])
            except (OSError, IndexError, ValueError):
                continue
            children.setdefault(ppid, []).append(int(entry))
    except OSError:
        try:
            out = subprocess.run(
                ["ps", "-axo", "pid,ppid"], capture_output=True,
                text=True, timeout=10,
            ).stdout
            for line in out.splitlines()[1:]:
                parts = line.split()
                if len(parts) >= 2:
                    children.setdefault(
                        int(parts[1]), []
                    ).append(int(parts[0]))
        except (OSError, subprocess.SubprocessError, ValueError):
            return []

    result: list[int] = []
    stack = [root_pid]
    while stack:
        pid = stack.pop()
        for child in children.get(pid, []):
            result.append(child)
            stack.append(child)
    return result


def kill_pid_tree(
    pid: int, sig: int = signal.SIGTERM, include_root: bool = True
) -> int:
    """Signal a process and all its descendants (deepest first)."""
    targets = _descendants(pid)
    if include_root:
        targets = targets + [pid]
    killed = 0
    for target in reversed(targets):
        try:
            os.kill(target, sig)
            killed += 1
        except (ProcessLookupError, PermissionError):
            pass
    return killed


def terminate_managed_processes(grace_s: float = 3.0) -> int:
    """SIGTERM every managed tree, wait, then SIGKILL survivors."""
    pids = list(managed_processes())
    for pid in pids:
        kill_pid_tree(pid, signal.SIGTERM)
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        alive = [p for p in pids if _alive(p)]
        if not alive:
            break
        time.sleep(0.1)
    for pid in pids:
        if _alive(pid):
            kill_pid_tree(pid, signal.SIGKILL)
    with _lock:
        for pid in pids:
            _managed.pop(pid, None)
    return len(pids)


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    # an unreaped zombie answers signal 0 but is effectively dead
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split()[2] != "Z"
    except (OSError, IndexError):
        return True


def spawn_managed(
    args: list[str], label: str = "", **popen_kwargs
) -> subprocess.Popen:
    """Popen + registration in one step."""
    proc = subprocess.Popen(args, **popen_kwargs)
    register_managed_process(proc.pid, label or args[0])
    return proc
