"""Scheduled-task execution (reference: src/shared/task-runner.ts):
per-room concurrency slots (1-10, default 3), built-in non-LLM
executors, session continuity with rotation after 20 runs, learned
context + memory injection, rate-limit retry ×3, result persistence,
auto-pause on repeated terminal errors — with the LLM leg running
through the provider registry (tpu: by default) instead of a spawned
CLI."""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from ..db import Database, utc_now
from ..utils import knobs, locks
from ..providers import (
    ExecutionRequest, RateLimitExceeded, get_model_provider,
)
from . import journal as journal_mod
from . import memory as memory_mod
from .constants import (
    MAX_CONCURRENT_TASKS_DEFAULT,
    MAX_CONCURRENT_TASKS_MAX,
    MAX_CONCURRENT_TASKS_MIN,
    TASK_SESSION_ROTATE_RUNS,
)
from .events import event_bus
from .learned_context import distill_learned_context, should_distill
from .rate_limit import MAX_RETRIES, abortable_sleep, clamp_wait

AUTO_PAUSE_ERROR_COUNT = 5


# ---- concurrency slots ----

class _SlotPool:
    def __init__(self) -> None:
        self._used: dict[int, int] = {}
        self._lock = locks.make_lock("task_slots")

    def acquire(self, room_id: Optional[int], limit: int) -> bool:
        key = room_id or 0
        with self._lock:
            if self._used.get(key, 0) >= limit:
                return False
            self._used[key] = self._used.get(key, 0) + 1
            return True

    def release(self, room_id: Optional[int]) -> None:
        key = room_id or 0
        with self._lock:
            self._used[key] = max(0, self._used.get(key, 0) - 1)

    def in_use(self, room_id: Optional[int]) -> int:
        with self._lock:
            return self._used.get(room_id or 0, 0)


slots = _SlotPool()


def max_concurrent_tasks(db: Database, room_id: Optional[int]) -> int:
    if room_id is not None:
        room = db.query_one(
            "SELECT max_concurrent_tasks FROM rooms WHERE id=?", (room_id,)
        )
        if room:
            return max(
                MAX_CONCURRENT_TASKS_MIN,
                min(MAX_CONCURRENT_TASKS_MAX,
                    room["max_concurrent_tasks"]),
            )
    from .messages import get_setting

    raw = get_setting(db, "max_concurrent_tasks")
    try:
        return max(MAX_CONCURRENT_TASKS_MIN,
                   min(MAX_CONCURRENT_TASKS_MAX, int(raw or "")))
    except ValueError:
        return MAX_CONCURRENT_TASKS_DEFAULT


# ---- task CRUD ----

def create_task(
    db: Database,
    name: str,
    prompt: str,
    trigger_type: str = "cron",
    cron_expression: Optional[str] = None,
    scheduled_at: Optional[str] = None,
    room_id: Optional[int] = None,
    worker_id: Optional[int] = None,
    session_continuity: bool = False,
    max_runs: Optional[int] = None,
    description: Optional[str] = None,
    timeout_minutes: Optional[int] = None,
    max_turns: Optional[int] = None,
    executor: str = "agent",
) -> int:
    if trigger_type == "cron":
        from .cron import validate_cron

        err = validate_cron(cron_expression or "")
        if err:
            raise ValueError(f"invalid cron expression: {err}")
    import secrets as _secrets

    return db.insert(
        "INSERT INTO tasks(name, description, prompt, cron_expression, "
        "trigger_type, webhook_token, room_id, worker_id, "
        "session_continuity, scheduled_at, max_runs, timeout_minutes, "
        "max_turns, executor) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
        (
            name, description, prompt, cron_expression, trigger_type,
            _secrets.token_urlsafe(16), room_id, worker_id,
            int(session_continuity), scheduled_at, max_runs,
            timeout_minutes, max_turns, executor,
        ),
    )


def get_task(db: Database, task_id: int) -> Optional[dict]:
    return db.query_one("SELECT * FROM tasks WHERE id=?", (task_id,))


def list_tasks(db: Database, room_id: Optional[int] = None) -> list[dict]:
    if room_id is None:
        return db.query("SELECT * FROM tasks ORDER BY id")
    return db.query(
        "SELECT * FROM tasks WHERE room_id=? ORDER BY id", (room_id,)
    )


def pause_task(db: Database, task_id: int) -> None:
    db.execute(
        "UPDATE tasks SET status='paused', updated_at=? WHERE id=?",
        (utc_now(), task_id),
    )


def resume_task(db: Database, task_id: int) -> None:
    db.execute(
        "UPDATE tasks SET status='active', error_count=0, updated_at=? "
        "WHERE id=?",
        (utc_now(), task_id),
    )


def delete_task(db: Database, task_id: int) -> bool:
    return db.execute(
        "DELETE FROM tasks WHERE id=?", (task_id,)
    ).rowcount > 0


def cancel_running_tasks_for_room(db: Database, room_id: int) -> int:
    rows = db.query(
        "SELECT r.id FROM task_runs r JOIN tasks t ON t.id = r.task_id "
        "WHERE t.room_id=? AND r.status='running'",
        (room_id,),
    )
    for r in rows:
        db.execute(
            "UPDATE task_runs SET status='cancelled', finished_at=? "
            "WHERE id=?",
            (utc_now(), r["id"]),
        )
        journal_mod.record_finished(db, "task_run", r["id"])
    return len(rows)


# ---- execution ----

def execute_task(
    db: Database,
    task_id: int,
    abort: Optional[threading.Event] = None,
) -> Optional[dict]:
    """Run one task now. Returns the finished task_runs row (None if it
    could not start)."""
    task = get_task(db, task_id)
    if task is None or task["status"] != "active":
        return None

    # cross-process duplicate guard: a run already marked running
    if db.query_one(
        "SELECT 1 AS x FROM task_runs WHERE task_id=? AND "
        "status='running'",
        (task_id,),
    ):
        return None

    limit = max_concurrent_tasks(db, task["room_id"])
    if not slots.acquire(task["room_id"], limit):
        return None

    # everything after the slot acquire sits inside try/finally: no
    # exception path — injected or real — may leak a slot
    run_id: Optional[int] = None
    try:
        # run row + journal entry commit atomically (see run_cycle)
        with db.transaction():
            run_id = db.insert(
                "INSERT INTO task_runs(task_id) VALUES (?)", (task_id,)
            )
            journal_mod.record_started(
                db, "task_run", run_id, task["room_id"],
                task["worker_id"],
            )
        event_bus.emit("run:created", "tasks",
                       {"run_id": run_id, "task_id": task_id})
        started = time.monotonic()
        # crash model as in run_cycle: fires before the error handler,
        # so the run stays 'running' and only recovery can requeue it
        journal_mod.chaos("cycle_crash")
        try:
            if task["executor"] in _BUILTIN_EXECUTORS:
                result_text = _BUILTIN_EXECUTORS[task["executor"]](db,
                                                                   task)
                success, error = True, None
                session_id = None
            else:
                success, result_text, error, session_id = _run_llm_task(
                    db, task, run_id, abort
                )
            _finish_run(
                db, task, run_id, success, result_text, error,
                session_id, int((time.monotonic() - started) * 1000),
            )
        except Exception as e:
            if getattr(e, "transient", True) is False:
                # hard-crash model: skip _finish_run so the run keeps
                # status 'running' with an open journal entry — exactly
                # the state a killed process leaves behind
                raise
            _finish_run(
                db, task, run_id, False, "", str(e), None,
                int((time.monotonic() - started) * 1000),
            )
    finally:
        slots.release(task["room_id"])
    if run_id is None:
        return None
    return db.query_one("SELECT * FROM task_runs WHERE id=?", (run_id,))


def _run_llm_task(
    db: Database, task: dict, run_id: int,
    abort: Optional[threading.Event],
) -> tuple[bool, str, Optional[str], Optional[str]]:
    model = _resolve_task_model(db, task)
    provider = get_model_provider(model, db)
    ready, why = provider.is_ready()
    if not ready:
        return False, "", f"model {model!r} not ready: {why}", None

    prompt = _assemble_task_prompt(db, task)
    session_id = (
        task["session_id"] if task["session_continuity"] else None
    )
    if session_id and task["run_count"] >= TASK_SESSION_ROTATE_RUNS and \
            task["run_count"] % TASK_SESSION_ROTATE_RUNS == 0:
        session_id = None  # rotate

    call_key = f"task:{task['id']}:run:{run_id}"
    journal_mod.record_provider_call(
        db, "task_run", run_id, call_key, task["room_id"],
        task["worker_id"],
    )
    request = ExecutionRequest(
        prompt=prompt,
        model=model,
        session_id=session_id,
        max_turns=task["max_turns"] or 10,
        timeout_s=(task["timeout_minutes"] or 15) * 60,
        idempotency_key=call_key,
        # scheduled task runs are the shed-first, chunk-budget-last
        # SLO class (docs/scheduler.md): their multi-thousand-token
        # prompts must never stall a queen turn
        turn_class="background",
    )

    last_error: Optional[str] = None
    for attempt in range(MAX_RETRIES):
        try:
            result = provider.execute(request)
        except RateLimitExceeded as e:
            last_error = str(e)
            if abortable_sleep(clamp_wait(e.wait_s), abort):
                return False, "", "aborted during rate-limit wait", None
            continue
        if result.success:
            return True, result.text, None, result.session_id
        # resume failure: retry once without the session
        if session_id and attempt == 0:
            request.session_id = None
            session_id = None
            last_error = result.error
            continue
        return False, result.text, result.error, result.session_id
    return False, "", last_error or "retries exhausted", None


def _resolve_task_model(db: Database, task: dict) -> str:
    """worker model > room worker_model > global default (reference
    :343-377, including the 'queen' indirection)."""
    if task["worker_id"]:
        w = db.query_one(
            "SELECT model FROM workers WHERE id=?", (task["worker_id"],)
        )
        if w and w["model"]:
            return w["model"]
    if task["room_id"]:
        room = db.query_one(
            "SELECT worker_model FROM rooms WHERE id=?",
            (task["room_id"],),
        )
        if room and room["worker_model"]:
            return room["worker_model"]
    from .messages import get_setting

    return get_setting(db, "default_task_model", "tpu") or "tpu"


def _assemble_task_prompt(db: Database, task: dict) -> str:
    parts = [task["prompt"]]
    if task["learned_context"]:
        parts.insert(
            0,
            f"Methodology memo from previous runs:\n"
            f"{task['learned_context']}\n",
        )
    if task["room_id"]:
        hits = memory_mod.hybrid_search(
            db, task["name"] + " " + task["prompt"][:200],
            room_id=task["room_id"], limit=3,
        )
        if hits:
            parts.insert(
                0,
                "Relevant memory:\n" + "\n".join(
                    f"- {h['name']}: {'; '.join(h['observations'][-1:])}"
                    for h in hits
                ) + "\n",
            )
    return "\n".join(parts)


def _finish_run(
    db: Database,
    task: dict,
    run_id: int,
    success: bool,
    result_text: str,
    error: Optional[str],
    session_id: Optional[str],
    duration_ms: int,
) -> None:
    status = "success" if success else "error"
    result_file = _save_result_file(task, run_id, result_text) if (
        success and result_text
    ) else None
    db.execute(
        "UPDATE task_runs SET finished_at=?, status=?, result=?, "
        "result_file=?, error_message=?, duration_ms=?, session_id=? "
        "WHERE id=?",
        (
            utc_now(), status, result_text[:10_000], result_file, error,
            duration_ms, session_id, run_id,
        ),
    )
    # journal close strictly AFTER the ref row flips terminal (same
    # order as run_cycle): a crash in between leaves an open entry
    # recovery can find, never a stuck 'running' row with a closed one
    journal_mod.record_finished(db, "task_run", run_id)
    db.execute(
        "UPDATE tasks SET last_run=?, last_result=?, run_count=run_count+1,"
        " error_count=?, session_id=?, updated_at=? WHERE id=?",
        (
            utc_now(),
            (result_text or error or "")[:1000],
            0 if success else task["error_count"] + 1,
            session_id if task["session_continuity"] else None,
            utc_now(),
            task["id"],
        ),
    )

    if success and result_text and task["room_id"]:
        memory_mod.remember(
            db, f"task result: {task['name']}", result_text[:1000],
            category="task", room_id=task["room_id"], source="task",
        )

    task_after = get_task(db, task["id"])
    if task_after:
        if success and should_distill(task_after):
            threading.Thread(
                target=distill_learned_context,
                args=(db, task_after, _resolve_task_model(db, task_after)),
                daemon=True,
            ).start()
        if not success and task_after["error_count"] >= \
                AUTO_PAUSE_ERROR_COUNT:
            pause_task(db, task["id"])
            event_bus.emit("task:auto_paused", "tasks",
                           {"task_id": task["id"], "error": error})
        if task_after["trigger_type"] == "once" or (
                task_after["max_runs"] and
                task_after["run_count"] >= task_after["max_runs"]):
            db.execute(
                "UPDATE tasks SET status='archived', updated_at=? "
                "WHERE id=?",
                (utc_now(), task["id"]),
            )

    event_bus.emit(
        "run:finished", "tasks",
        {"run_id": run_id, "task_id": task["id"], "status": status},
    )


def _save_result_file(task: dict, run_id: int, text: str) -> Optional[str]:
    base = os.path.expanduser(knobs.get_str("ROOM_TPU_DATA_DIR"))
    try:
        results_dir = os.path.join(base, "results")
        os.makedirs(results_dir, exist_ok=True)
        path = os.path.join(
            results_dir, f"task{task['id']}-run{run_id}.md"
        )
        with open(path, "w") as f:
            f.write(text)
        return path
    except OSError:
        return None


# ---- built-in non-LLM executors (reference :256-329) ----

def _keeper_reminder(db: Database, task: dict) -> str:
    from .messages import add_chat_message

    if task["room_id"]:
        add_chat_message(
            db, task["room_id"], "assistant",
            f"Reminder: {task['prompt']}",
        )
    db.insert(
        "INSERT INTO clerk_messages(role, content, source) "
        "VALUES ('assistant', ?, 'reminder')",
        (f"Reminder: {task['prompt']}",),
    )
    event_bus.emit("reminder", "clerk", {"text": task["prompt"]})
    return f"reminder delivered: {task['prompt'][:100]}"


def _keeper_contact_check(db: Database, task: dict) -> str:
    from .messages import get_setting

    channels = [
        k for k in ("keeper_email", "keeper_telegram")
        if get_setting(db, k)
    ]
    msg = (
        "keeper contact configured: " + ", ".join(channels)
        if channels
        else "no keeper contact configured — ask the keeper to add email "
        "or telegram in settings"
    )
    db.insert(
        "INSERT INTO clerk_messages(role, content, source) "
        "VALUES ('assistant', ?, 'contact_check')",
        (msg,),
    )
    return msg


_BUILTIN_EXECUTORS = {
    "keeper_reminder": _keeper_reminder,
    "keeper_contact_check": _keeper_contact_check,
}
