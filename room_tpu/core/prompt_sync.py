"""Worker prompt sync (reference: src/shared/worker-prompt-sync.ts):
explicit export/import of worker system prompts as YAML-frontmatter
markdown under <data>/prompts/workers/room-<id>/worker-<id>.md, with a
newest-mtime-wins conflict policy unless forced."""

from __future__ import annotations

import os
import re
from datetime import datetime, timezone
from typing import Optional

from ..db import Database
from ..utils import knobs
from . import workers as workers_mod


def prompts_dir(room_id: int) -> str:
    base = os.path.expanduser(knobs.get_str("ROOM_TPU_DATA_DIR"))
    d = os.path.join(base, "prompts", "workers", f"room-{room_id}")
    os.makedirs(d, exist_ok=True)
    return d


def _worker_path(room_id: int, worker_id: int) -> str:
    return os.path.join(prompts_dir(room_id), f"worker-{worker_id}.md")


def _render(worker: dict) -> str:
    return (
        "---\n"
        f"worker_id: {worker['id']}\n"
        f"name: {worker['name']}\n"
        f"role: {worker['role'] or ''}\n"
        f"model: {worker['model'] or ''}\n"
        f"updated_at: {worker['updated_at']}\n"
        "---\n\n"
        f"{worker['system_prompt']}\n"
    )


_FRONTMATTER = re.compile(
    r"^---\n(.*?)\n---\n\n?(.*)$", re.DOTALL
)


def _parse(text: str) -> Optional[tuple[dict, str]]:
    m = _FRONTMATTER.match(text)
    if m is None:
        return None
    meta: dict = {}
    for line in m.group(1).splitlines():
        if ":" in line:
            k, v = line.split(":", 1)
            meta[k.strip()] = v.strip()
    return meta, m.group(2).rstrip("\n")


def export_worker_prompts(db: Database, room_id: int) -> list[str]:
    """Write every worker's prompt file. Returns paths written."""
    paths = []
    for w in workers_mod.list_room_workers(db, room_id):
        path = _worker_path(room_id, w["id"])
        with open(path, "w") as f:
            f.write(_render(w))
        paths.append(path)
    return paths


def _db_updated_at(worker: dict) -> float:
    try:
        return datetime.strptime(
            worker["updated_at"], "%Y-%m-%dT%H:%M:%S.%fZ"
        ).replace(tzinfo=timezone.utc).timestamp()
    except (ValueError, TypeError):
        return 0.0


def import_worker_prompts(
    db: Database, room_id: int, force: bool = False
) -> dict:
    """Apply edited prompt files back to the DB. Without force, a file
    only wins when its mtime is newer than the DB row's updated_at."""
    applied, skipped = [], []
    d = prompts_dir(room_id)
    for fname in sorted(os.listdir(d)):
        m = re.match(r"worker-(\d+)\.md$", fname)
        if not m:
            continue
        wid = int(m.group(1))
        worker = workers_mod.get_worker(db, wid)
        if worker is None or worker["room_id"] != room_id:
            skipped.append((fname, "no such worker in room"))
            continue
        path = os.path.join(d, fname)
        with open(path) as f:
            parsed = _parse(f.read())
        if parsed is None:
            skipped.append((fname, "missing frontmatter"))
            continue
        _, prompt = parsed
        if prompt == worker["system_prompt"]:
            skipped.append((fname, "unchanged"))
            continue
        if not force and os.path.getmtime(path) <= _db_updated_at(worker):
            skipped.append((fname, "db is newer (use force)"))
            continue
        workers_mod.update_worker(db, wid, system_prompt=prompt)
        applied.append(fname)
    return {"applied": applied, "skipped": skipped}
