"""Per-room EVM wallet (reference: src/shared/wallet.ts).

Key generation, address derivation, and transaction signing run fully
offline (secp256k1 + RFC 6979 + EIP-1559 in-tree via core.ethtx,
Keccak-256 in-tree). Balance reads and broadcast need chain RPC; with no
network they fail closed with a clear error, mirroring the reference's
fail-closed posture for its local model."""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Optional

from ..db import Database
from ..utils import knobs
from .chains import CHAINS, DEFAULT_CHAIN
from .ethtx import pubkey_point
from .keccak import keccak256
from .secrets import decrypt_secret, encrypt_secret


class WalletError(RuntimeError):
    pass


def private_key_to_address(private_key: bytes) -> str:
    """0x-address = last 20 bytes of keccak256(uncompressed pubkey x||y).

    Derivation runs on the in-tree secp256k1 (core.ethtx, cross-checked
    against an independent verifier in tests/test_ethtx.py) — no
    external crypto dependency on this path."""
    x, y = pubkey_point(private_key)
    pub = x.to_bytes(32, "big") + y.to_bytes(32, "big")
    return to_checksum_address("0x" + keccak256(pub)[-20:].hex())


def to_checksum_address(address: str) -> str:
    """EIP-55 mixed-case checksum."""
    addr = address.lower().replace("0x", "")
    digest = keccak256(addr.encode()).hex()
    out = "".join(
        c.upper() if int(digest[i], 16) >= 8 else c
        for i, c in enumerate(addr)
    )
    return "0x" + out


def create_room_wallet(
    db: Database, room_id: int, chain: str = DEFAULT_CHAIN
) -> dict:
    existing = get_room_wallet(db, room_id)
    if existing:
        return existing
    private_key = os.urandom(32)
    address = private_key_to_address(private_key)
    encrypted = encrypt_secret(private_key.hex(), context=f"wallet:{room_id}")
    wid = db.insert(
        "INSERT INTO wallets(room_id, address, private_key_encrypted, chain) "
        "VALUES (?,?,?,?)",
        (room_id, address, encrypted, chain),
    )
    return db.query_one("SELECT * FROM wallets WHERE id=?", (wid,))  # type: ignore[return-value]


def get_room_wallet(db: Database, room_id: int) -> Optional[dict]:
    return db.query_one(
        "SELECT * FROM wallets WHERE room_id=? ORDER BY id LIMIT 1",
        (room_id,),
    )


def decrypt_wallet_key(wallet: dict) -> bytes:
    hexkey = decrypt_secret(
        wallet["private_key_encrypted"], context=f"wallet:{wallet['room_id']}"
    )
    return bytes.fromhex(hexkey)


def record_transaction(
    db: Database,
    wallet_id: int,
    type_: str,
    amount: str,
    counterparty: Optional[str] = None,
    tx_hash: Optional[str] = None,
    description: Optional[str] = None,
    status: str = "confirmed",
    category: Optional[str] = None,
) -> int:
    return db.insert(
        "INSERT INTO wallet_transactions(wallet_id, type, amount, "
        "counterparty, tx_hash, description, status, category) "
        "VALUES (?,?,?,?,?,?,?,?)",
        (
            wallet_id, type_, amount, counterparty, tx_hash, description,
            status, category,
        ),
    )


def list_transactions(db: Database, wallet_id: int) -> list[dict]:
    return db.query(
        "SELECT * FROM wallet_transactions WHERE wallet_id=? ORDER BY id DESC",
        (wallet_id,),
    )


# ---- chain RPC (fail-closed without network) ----

_ERC20_BALANCE_OF = "70a08231"  # balanceOf(address)
_ERC20_TRANSFER = "a9059cbb"    # transfer(address,uint256)


def _rpc(chain: str, method: str, params: list) -> dict:
    cfg = CHAINS.get(chain)
    if cfg is None:
        raise WalletError(f"unknown chain {chain!r}")
    url = knobs.get_dynamic(
        "ROOM_TPU_RPC_{CHAIN}", chain.upper(), default=cfg.rpc_url
    )
    body = json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
    ).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            out = json.loads(resp.read())
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise WalletError(
            f"chain RPC unreachable for {chain} ({e}); wallet operations "
            "requiring the network are unavailable"
        ) from e
    if "error" in out:
        raise WalletError(f"RPC error: {out['error']}")
    return out["result"]


def get_native_balance(db: Database, room_id: int) -> int:
    wallet = get_room_wallet(db, room_id)
    if wallet is None:
        raise WalletError(f"room {room_id} has no wallet")
    result = _rpc(
        wallet["chain"], "eth_getBalance", [wallet["address"], "latest"]
    )
    return int(result, 16)


def get_token_balance(
    db: Database, room_id: int, token: str = "usdc"
) -> int:
    wallet = get_room_wallet(db, room_id)
    if wallet is None:
        raise WalletError(f"room {room_id} has no wallet")
    cfg = CHAINS[wallet["chain"]]
    token_addr = getattr(cfg, token, None)
    if not token_addr:
        raise WalletError(f"no {token} on chain {wallet['chain']}")
    calldata = (
        "0x" + _ERC20_BALANCE_OF
        + wallet["address"][2:].lower().rjust(64, "0")
    )
    result = _rpc(
        wallet["chain"], "eth_call",
        [{"to": token_addr, "data": calldata}, "latest"],
    )
    return int(result, 16) if result not in (None, "0x") else 0


# ---- transfers (reference: wallet.ts:19-37 signs + sends via viem) ----

DEFAULT_GAS_LIMIT = 120_000


def build_signed_transfer(
    db: Database,
    room_id: int,
    to: str,
    amount: int,
    token: str = "usdc",
    *,
    nonce: int,
    max_fee_per_gas: int,
    max_priority_fee_per_gas: int,
    gas_limit: int = DEFAULT_GAS_LIMIT,
) -> dict:
    """Sign an ERC-20 transfer fully offline (explicit nonce/fees).
    Returns {"raw", "hash", ...} for eth_sendRawTransaction."""
    from .ethtx import erc20_transfer_data, sign_eip1559

    wallet = get_room_wallet(db, room_id)
    if wallet is None:
        raise WalletError(f"room {room_id} has no wallet")
    cfg = CHAINS[wallet["chain"]]
    token_addr = getattr(cfg, token, None)
    if not token_addr:
        raise WalletError(f"no {token} on chain {wallet['chain']}")
    if not (isinstance(to, str) and to.startswith("0x")
            and len(to) == 42):
        raise WalletError(f"invalid recipient address {to!r}")
    if amount <= 0:
        raise WalletError("amount must be positive")
    key = decrypt_wallet_key(wallet)
    return sign_eip1559(
        key,
        chain_id=cfg.chain_id,
        nonce=nonce,
        max_priority_fee_per_gas=max_priority_fee_per_gas,
        max_fee_per_gas=max_fee_per_gas,
        gas_limit=gas_limit,
        to=token_addr,
        value=0,
        data=erc20_transfer_data(to, amount),
    )


def transfer_token(
    db: Database,
    room_id: int,
    to: str,
    amount: int,
    token: str = "usdc",
    description: Optional[str] = None,
) -> dict:
    """Online transfer: fetch nonce + fees over RPC, sign, broadcast,
    record. Fail-closed without network (the RPC fetch raises first)."""
    wallet = get_room_wallet(db, room_id)
    if wallet is None:
        raise WalletError(f"room {room_id} has no wallet")
    chain = wallet["chain"]
    nonce = int(_rpc(
        chain, "eth_getTransactionCount",
        [wallet["address"], "pending"],
    ), 16)
    base_fee = int(_rpc(chain, "eth_gasPrice", []), 16)
    priority = max(base_fee // 10, 1_000_000)  # modest tip
    signed = build_signed_transfer(
        db, room_id, to, amount, token,
        nonce=nonce,
        max_fee_per_gas=base_fee * 2 + priority,
        max_priority_fee_per_gas=priority,
    )
    tx_hash = _rpc(chain, "eth_sendRawTransaction", [signed["raw"]])
    record_transaction(
        db, wallet["id"], "debit", str(amount), counterparty=to,
        tx_hash=tx_hash, description=description, status="pending",
        category="transfer",
    )
    return {"txHash": tx_hash, "raw": signed["raw"]}
