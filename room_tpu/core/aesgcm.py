"""Pure-Python AES-GCM — dependency-gated fallback for the secret store.

The container image this system deploys into does not always carry the
``cryptography`` wheel; the secret store (core.secrets) must keep its
``enc:v1`` envelope format working either way, so this module provides a
wire-compatible AES-GCM (NIST SP 800-38D) on top of a from-scratch AES
(FIPS 197), in the same in-tree spirit as core.keccak / core.ethtx.
Validated against the NIST AES-256-GCM known-answer vector in
tests/test_chaos_serving.py, and byte-identical to ``cryptography``'s
AESGCM when both are present.

Caveat (documented, accepted for the fallback role): table-based
pure-Python AES is not constant-time. Deployments handling adversarial
local timing should install ``cryptography``; this fallback keeps a
gated container functional, not hardened.
"""

from __future__ import annotations

# ---- AES block cipher (encrypt-only: GCM never needs the inverse) ----

_SBOX = [
    0x63, 0x7C, 0x77, 0x7B, 0xF2, 0x6B, 0x6F, 0xC5,
    0x30, 0x01, 0x67, 0x2B, 0xFE, 0xD7, 0xAB, 0x76,
    0xCA, 0x82, 0xC9, 0x7D, 0xFA, 0x59, 0x47, 0xF0,
    0xAD, 0xD4, 0xA2, 0xAF, 0x9C, 0xA4, 0x72, 0xC0,
    0xB7, 0xFD, 0x93, 0x26, 0x36, 0x3F, 0xF7, 0xCC,
    0x34, 0xA5, 0xE5, 0xF1, 0x71, 0xD8, 0x31, 0x15,
    0x04, 0xC7, 0x23, 0xC3, 0x18, 0x96, 0x05, 0x9A,
    0x07, 0x12, 0x80, 0xE2, 0xEB, 0x27, 0xB2, 0x75,
    0x09, 0x83, 0x2C, 0x1A, 0x1B, 0x6E, 0x5A, 0xA0,
    0x52, 0x3B, 0xD6, 0xB3, 0x29, 0xE3, 0x2F, 0x84,
    0x53, 0xD1, 0x00, 0xED, 0x20, 0xFC, 0xB1, 0x5B,
    0x6A, 0xCB, 0xBE, 0x39, 0x4A, 0x4C, 0x58, 0xCF,
    0xD0, 0xEF, 0xAA, 0xFB, 0x43, 0x4D, 0x33, 0x85,
    0x45, 0xF9, 0x02, 0x7F, 0x50, 0x3C, 0x9F, 0xA8,
    0x51, 0xA3, 0x40, 0x8F, 0x92, 0x9D, 0x38, 0xF5,
    0xBC, 0xB6, 0xDA, 0x21, 0x10, 0xFF, 0xF3, 0xD2,
    0xCD, 0x0C, 0x13, 0xEC, 0x5F, 0x97, 0x44, 0x17,
    0xC4, 0xA7, 0x7E, 0x3D, 0x64, 0x5D, 0x19, 0x73,
    0x60, 0x81, 0x4F, 0xDC, 0x22, 0x2A, 0x90, 0x88,
    0x46, 0xEE, 0xB8, 0x14, 0xDE, 0x5E, 0x0B, 0xDB,
    0xE0, 0x32, 0x3A, 0x0A, 0x49, 0x06, 0x24, 0x5C,
    0xC2, 0xD3, 0xAC, 0x62, 0x91, 0x95, 0xE4, 0x79,
    0xE7, 0xC8, 0x37, 0x6D, 0x8D, 0xD5, 0x4E, 0xA9,
    0x6C, 0x56, 0xF4, 0xEA, 0x65, 0x7A, 0xAE, 0x08,
    0xBA, 0x78, 0x25, 0x2E, 0x1C, 0xA6, 0xB4, 0xC6,
    0xE8, 0xDD, 0x74, 0x1F, 0x4B, 0xBD, 0x8B, 0x8A,
    0x70, 0x3E, 0xB5, 0x66, 0x48, 0x03, 0xF6, 0x0E,
    0x61, 0x35, 0x57, 0xB9, 0x86, 0xC1, 0x1D, 0x9E,
    0xE1, 0xF8, 0x98, 0x11, 0x69, 0xD9, 0x8E, 0x94,
    0x9B, 0x1E, 0x87, 0xE9, 0xCE, 0x55, 0x28, 0xDF,
    0x8C, 0xA1, 0x89, 0x0D, 0xBF, 0xE6, 0x42, 0x68,
    0x41, 0x99, 0x2D, 0x0F, 0xB0, 0x54, 0xBB, 0x16,
]

_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36,
         0x6C, 0xD8, 0xAB, 0x4D]


def _xtime(a: int) -> int:
    a <<= 1
    if a & 0x100:
        a ^= 0x11B
    return a & 0xFF


def _expand_key(key: bytes) -> list[list[int]]:
    nk = len(key) // 4
    if nk not in (4, 6, 8):
        raise ValueError("AES key must be 16, 24, or 32 bytes")
    nr = nk + 6
    words = [list(key[4 * i: 4 * i + 4]) for i in range(nk)]
    for i in range(nk, 4 * (nr + 1)):
        t = list(words[i - 1])
        if i % nk == 0:
            t = t[1:] + t[:1]
            t = [_SBOX[b] for b in t]
            t[0] ^= _RCON[i // nk - 1]
        elif nk > 6 and i % nk == 4:
            t = [_SBOX[b] for b in t]
        words.append([a ^ b for a, b in zip(words[i - nk], t)])
    # group into round keys of 16 bytes
    return [
        sum(words[4 * r: 4 * r + 4], [])
        for r in range(nr + 1)
    ]


def _encrypt_block(round_keys: list[list[int]], block: bytes) -> bytes:
    nr = len(round_keys) - 1
    s = [b ^ k for b, k in zip(block, round_keys[0])]
    for rnd in range(1, nr + 1):
        # SubBytes
        s = [_SBOX[b] for b in s]
        # ShiftRows (state is column-major: byte index = 4*col + row)
        s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
        if rnd < nr:
            # MixColumns
            t = []
            for c in range(4):
                col = s[4 * c: 4 * c + 4]
                t += [
                    _xtime(col[0]) ^ _xtime(col[1]) ^ col[1]
                    ^ col[2] ^ col[3],
                    col[0] ^ _xtime(col[1]) ^ _xtime(col[2])
                    ^ col[2] ^ col[3],
                    col[0] ^ col[1] ^ _xtime(col[2])
                    ^ _xtime(col[3]) ^ col[3],
                    _xtime(col[0]) ^ col[0] ^ col[1] ^ col[2]
                    ^ _xtime(col[3]),
                ]
            s = t
        s = [b ^ k for b, k in zip(s, round_keys[rnd])]
    return bytes(s)


# ---- GCM (SP 800-38D) ----


def _ghash_mult(x: int, y: int) -> int:
    """Carry-less multiply in GF(2^128) with the GCM polynomial."""
    r = 0xE1 << 120
    z = 0
    v = x
    for i in range(127, -1, -1):
        if (y >> i) & 1:
            z ^= v
        if v & 1:
            v = (v >> 1) ^ r
        else:
            v >>= 1
    return z


def _ghash(h: int, aad: bytes, ct: bytes) -> bytes:
    def blocks(data: bytes):
        for i in range(0, len(data), 16):
            yield data[i: i + 16].ljust(16, b"\x00")

    y = 0
    for chunk in (aad, ct):
        for block in blocks(chunk):
            y = _ghash_mult(y ^ int.from_bytes(block, "big"), h)
    lens = (len(aad) * 8).to_bytes(8, "big") + \
        (len(ct) * 8).to_bytes(8, "big")
    y = _ghash_mult(y ^ int.from_bytes(lens, "big"), h)
    return y.to_bytes(16, "big")


def _inc32(block: bytes) -> bytes:
    ctr = (int.from_bytes(block[12:], "big") + 1) & 0xFFFFFFFF
    return block[:12] + ctr.to_bytes(4, "big")


class InvalidTag(ValueError):
    """Authentication failure (mirrors cryptography's InvalidTag)."""


class SoftAESGCM:
    """Drop-in for ``cryptography``'s AESGCM on the encrypt/decrypt
    surface the secret store uses. Same wire format: ciphertext || tag,
    12-byte nonce, optional AAD."""

    def __init__(self, key: bytes) -> None:
        self._rk = _expand_key(bytes(key))
        self._h = int.from_bytes(
            _encrypt_block(self._rk, b"\x00" * 16), "big"
        )

    def _ctr_stream(self, j0: bytes, n: int) -> bytes:
        out = bytearray()
        block = j0
        for _ in range((n + 15) // 16):
            block = _inc32(block)
            out += _encrypt_block(self._rk, block)
        return bytes(out[:n])

    def _j0(self, nonce: bytes) -> bytes:
        if len(nonce) == 12:
            return nonce + b"\x00\x00\x00\x01"
        # general case: J0 = GHASH(H; {}, nonce) per SP 800-38D §7.1
        pad = b"\x00" * ((16 - len(nonce) % 16) % 16)
        data = nonce + pad + b"\x00" * 8 + \
            (len(nonce) * 8).to_bytes(8, "big")
        y = 0
        for i in range(0, len(data), 16):
            y = _ghash_mult(
                y ^ int.from_bytes(data[i: i + 16], "big"), self._h
            )
        return y.to_bytes(16, "big")

    def encrypt(self, nonce: bytes, data: bytes,
                aad: bytes | None) -> bytes:
        aad = aad or b""
        j0 = self._j0(nonce)
        ct = bytes(
            a ^ b for a, b in zip(data, self._ctr_stream(j0, len(data)))
        )
        tag_mask = _encrypt_block(self._rk, j0)
        tag = bytes(
            a ^ b for a, b in zip(_ghash(self._h, aad, ct), tag_mask)
        )
        return ct + tag

    def decrypt(self, nonce: bytes, data: bytes,
                aad: bytes | None) -> bytes:
        import hmac

        aad = aad or b""
        if len(data) < 16:
            raise InvalidTag("ciphertext shorter than the GCM tag")
        ct, tag = data[:-16], data[-16:]
        j0 = self._j0(nonce)
        tag_mask = _encrypt_block(self._rk, j0)
        want = bytes(
            a ^ b for a, b in zip(_ghash(self._h, aad, ct), tag_mask)
        )
        if not hmac.compare_digest(want, tag):
            raise InvalidTag("GCM tag mismatch")
        return bytes(
            a ^ b for a, b in zip(ct, self._ctr_stream(j0, len(ct)))
        )
