"""Secret store: AES-256-GCM envelope for credentials and wallet keys
(reference: src/shared/secret-store.ts — enc:v1: envelope, key derived
from env override or host identity)."""

from __future__ import annotations

import base64
import getpass
import hashlib
import os
import socket
from ..utils import knobs

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:
    # gated dependency: containers without the cryptography wheel fall
    # back to the in-tree pure-Python AES-GCM (core.aesgcm, NIST-vector
    # validated, byte-identical wire format) so the enc:v1 envelope —
    # and everything built on it — keeps working
    from .aesgcm import SoftAESGCM as AESGCM

ENVELOPE_PREFIX = "enc:v1:"


def _derive_key(extra: str = "") -> bytes:
    seed = knobs.get_str("ROOM_TPU_SECRET_KEY")
    if not seed:
        seed = socket.gethostname() + ":" + getpass.getuser()
    return hashlib.sha256((seed + extra).encode()).digest()


def encrypt_secret(plaintext: str, context: str = "") -> str:
    key = _derive_key(context)
    nonce = os.urandom(12)
    ct = AESGCM(key).encrypt(nonce, plaintext.encode(), None)
    return ENVELOPE_PREFIX + base64.b64encode(nonce + ct).decode()


def decrypt_secret(envelope: str, context: str = "") -> str:
    if not envelope.startswith(ENVELOPE_PREFIX):
        raise ValueError("not an encrypted envelope")
    raw = base64.b64decode(envelope[len(ENVELOPE_PREFIX):])
    nonce, ct = raw[:12], raw[12:]
    key = _derive_key(context)
    return AESGCM(key).decrypt(nonce, ct, None).decode()


def is_encrypted(value: str) -> bool:
    return value.startswith(ENVELOPE_PREFIX)
