"""Room activity audit log (reference: room_activity writes scattered
through src/shared; public/private flag feeds the public feed)."""

from __future__ import annotations

import json
from typing import Any, Optional

from ..db import Database


def log_room_activity(
    db: Database,
    room_id: int,
    event_type: str,
    summary: str,
    details: Optional[Any] = None,
    actor_id: Optional[int] = None,
    is_public: bool = True,
) -> int:
    return db.insert(
        "INSERT INTO room_activity(room_id, event_type, actor_id, summary, "
        "details, is_public) VALUES (?,?,?,?,?,?)",
        (
            room_id,
            event_type,
            actor_id,
            summary,
            json.dumps(details) if details is not None else None,
            int(is_public),
        ),
    )


def recent_activity(
    db: Database, room_id: int, limit: int = 50, public_only: bool = False
) -> list[dict]:
    sql = "SELECT * FROM room_activity WHERE room_id=?"
    if public_only:
        sql += " AND is_public=1"
    sql += " ORDER BY id DESC LIMIT ?"
    return db.query(sql, (room_id, limit))


def get_public_feed(db: Database, limit: int = 100) -> list[dict]:
    """Cross-room public feed (reference: src/shared/public-feed.ts)."""
    return db.query(
        "SELECT a.*, r.name AS room_name FROM room_activity a "
        "JOIN rooms r ON r.id = a.room_id "
        "WHERE a.is_public=1 AND r.visibility='public' "
        "ORDER BY a.id DESC LIMIT ?",
        (limit,),
    )
