"""Audited self-modification with rate limiting, forbidden paths, and true
revert from snapshots (reference: src/shared/self-mod.ts)."""

from __future__ import annotations

import hashlib
import re
import time
from typing import Optional

from ..db import Database, utc_now
from .constants import SELF_MOD_MIN_INTERVAL_S

# Paths agents may never modify: credentials, wallets, env files, and the
# self-modification machinery itself.
FORBIDDEN_PATTERNS = [
    re.compile(p, re.IGNORECASE)
    for p in (
        r"secret", r"credential", r"wallet", r"private[_-]?key",
        r"\.env", r"selfmod", r"self[_-]mod", r"auth\.tokens",
    )
]


class SelfModError(RuntimeError):
    pass


def _content_hash(content: Optional[str]) -> Optional[str]:
    if content is None:
        return None
    return hashlib.sha256(content.encode()).hexdigest()[:16]


def can_modify(db: Database, worker_id: Optional[int], path: str) -> tuple[bool, str]:
    for pat in FORBIDDEN_PATTERNS:
        if pat.search(path):
            return False, f"path {path!r} is protected from self-modification"
    if worker_id is not None:
        last = db.query_one(
            "SELECT created_at FROM self_mod_audit WHERE worker_id=? "
            "ORDER BY id DESC LIMIT 1",
            (worker_id,),
        )
        if last:
            # created_at is UTC ISO; compare against now-60s
            from datetime import datetime, timezone

            then = datetime.strptime(
                last["created_at"], "%Y-%m-%dT%H:%M:%S.%fZ"
            ).replace(tzinfo=timezone.utc)
            age = (datetime.now(timezone.utc) - then).total_seconds()
            if age < SELF_MOD_MIN_INTERVAL_S:
                return False, (
                    f"rate limited: one modification per "
                    f"{SELF_MOD_MIN_INTERVAL_S}s per worker"
                )
    return True, ""


def perform_modification(
    db: Database,
    room_id: Optional[int],
    worker_id: Optional[int],
    target_type: str,
    target_id: Optional[int],
    path: str,
    old_content: Optional[str],
    new_content: str,
    reason: str,
) -> int:
    """Record the audit row + snapshot, then apply the edit for known
    target types (currently 'skill')."""
    ok, why = can_modify(db, worker_id, path)
    if not ok:
        raise SelfModError(why)
    with db.transaction():
        audit_id = db.insert(
            "INSERT INTO self_mod_audit(room_id, worker_id, file_path, "
            "old_hash, new_hash, reason) VALUES (?,?,?,?,?,?)",
            (
                room_id, worker_id, path,
                _content_hash(old_content), _content_hash(new_content),
                reason,
            ),
        )
        db.insert(
            "INSERT INTO self_mod_snapshots(audit_id, target_type, "
            "target_id, old_content, new_content) VALUES (?,?,?,?,?)",
            (audit_id, target_type, target_id, old_content, new_content),
        )
        if target_type == "skill" and target_id is not None:
            from .skills import update_skill

            update_skill(db, target_id, new_content)
    return audit_id


def revert_modification(db: Database, audit_id: int) -> bool:
    """Restore the snapshot's old content (reference: true revert of skill
    content, self-mod.ts:57-84)."""
    audit = db.query_one(
        "SELECT * FROM self_mod_audit WHERE id=?", (audit_id,)
    )
    snap = db.query_one(
        "SELECT * FROM self_mod_snapshots WHERE audit_id=?", (audit_id,)
    )
    if audit is None or snap is None:
        return False
    if audit["reverted"]:
        return False
    if not audit["reversible"] or snap["old_content"] is None:
        raise SelfModError(f"audit {audit_id} is not reversible")
    with db.transaction():
        if snap["target_type"] == "skill" and snap["target_id"] is not None:
            from .skills import update_skill

            update_skill(db, snap["target_id"], snap["old_content"])
        db.execute(
            "UPDATE self_mod_audit SET reverted=1 WHERE id=?", (audit_id,)
        )
    return True


def audit_log(db: Database, room_id: Optional[int] = None) -> list[dict]:
    if room_id is None:
        return db.query("SELECT * FROM self_mod_audit ORDER BY id DESC")
    return db.query(
        "SELECT * FROM self_mod_audit WHERE room_id=? ORDER BY id DESC",
        (room_id,),
    )
