"""Learned-context distillation: after every 3 runs, distill a compact
methodology memo from recent results and feed it into future prompts
(reference: src/shared/learned-context.ts — ≤1,500 chars, refreshed every
3 runs, via a single 1-turn LLM call)."""

from __future__ import annotations

from typing import Optional

from ..db import Database, utc_now
from ..providers import ExecutionRequest, get_model_provider

DISTILL_EVERY_RUNS = 3
MEMO_MAX_CHARS = 1500


def should_distill(task: dict) -> bool:
    runs = task["run_count"]
    return runs >= DISTILL_EVERY_RUNS and runs % DISTILL_EVERY_RUNS == 0


def distill_learned_context(
    db: Database, task: dict, model: str
) -> Optional[str]:
    runs = db.query(
        "SELECT status, result, error_message FROM task_runs "
        "WHERE task_id=? ORDER BY id DESC LIMIT 5",
        (task["id"],),
    )
    if not runs:
        return None
    digest = "\n".join(
        f"- [{r['status']}] {(r['result'] or r['error_message'] or '')[:300]}"
        for r in runs
    )
    try:
        provider = get_model_provider(model, db)
        r = provider.execute(ExecutionRequest(
            prompt=(
                "You maintain a methodology memo for a recurring task.\n"
                f"Task: {task['name']} — {task['prompt'][:500]}\n"
                f"Recent runs:\n{digest}\n\n"
                "Write a concise memo (max 1200 chars): what approach "
                "works, what to avoid, and any state worth carrying "
                "forward."
            ),
            max_turns=1,
            max_new_tokens=400,
            timeout_s=120,
            turn_class="background",
        ))
        if not (r.success and r.text):
            return None
    except Exception:
        return None
    memo = r.text[:MEMO_MAX_CHARS]
    db.execute(
        "UPDATE tasks SET learned_context=?, updated_at=? WHERE id=?",
        (memo, utc_now(), task["id"]),
    )
    return memo
