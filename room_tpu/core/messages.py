"""Inter-room messages + room chat + settings KV."""

from __future__ import annotations

from typing import Optional

from ..db import Database, utc_now


# ---- inter-room messages ----

def send_room_message(
    db: Database,
    from_room_id: int,
    to_room_id: int,
    subject: str,
    body: str,
) -> tuple[int, int]:
    """Record outbound on sender + inbound on recipient. Returns both ids."""
    out_id = db.insert(
        "INSERT INTO room_messages(room_id, direction, from_room_id, "
        "to_room_id, subject, body, status) "
        "VALUES (?,?,?,?,?,?,'read')",
        (from_room_id, "outbound", str(from_room_id), str(to_room_id),
         subject, body),
    )
    in_id = db.insert(
        "INSERT INTO room_messages(room_id, direction, from_room_id, "
        "to_room_id, subject, body) VALUES (?,?,?,?,?,?)",
        (to_room_id, "inbound", str(from_room_id), str(to_room_id),
         subject, body),
    )
    return out_id, in_id


def receive_external_message(
    db: Database,
    room_id: int,
    from_room_id: str,
    subject: str,
    body: str,
) -> int:
    """Inbound message from another machine (cloud relay)."""
    return db.insert(
        "INSERT INTO room_messages(room_id, direction, from_room_id, "
        "to_room_id, subject, body) VALUES (?,?,?,?,?,?)",
        (room_id, "inbound", from_room_id, str(room_id), subject, body),
    )


def unread_messages(db: Database, room_id: int) -> list[dict]:
    return db.query(
        "SELECT * FROM room_messages WHERE room_id=? AND direction='inbound' "
        "AND status='unread' ORDER BY id",
        (room_id,),
    )


def mark_message_read(db: Database, message_id: int) -> None:
    db.execute(
        "UPDATE room_messages SET status='read' WHERE id=?", (message_id,)
    )


def mark_message_replied(db: Database, message_id: int) -> None:
    db.execute(
        "UPDATE room_messages SET status='replied' WHERE id=?", (message_id,)
    )


# ---- room chat (keeper <-> queen) ----

def add_chat_message(
    db: Database, room_id: int, role: str, content: str
) -> int:
    return db.insert(
        "INSERT INTO chat_messages(room_id, role, content) VALUES (?,?,?)",
        (room_id, role, content),
    )


def chat_history(db: Database, room_id: int, limit: int = 50) -> list[dict]:
    rows = db.query(
        "SELECT * FROM chat_messages WHERE room_id=? ORDER BY id DESC LIMIT ?",
        (room_id, limit),
    )
    return list(reversed(rows))


def unanswered_keeper_messages(db: Database, room_id: int) -> list[dict]:
    """User chat messages newer than the last assistant reply — the queen
    inbox poll looks for these."""
    last_reply = db.query_one(
        "SELECT id FROM chat_messages WHERE room_id=? AND role='assistant' "
        "ORDER BY id DESC LIMIT 1",
        (room_id,),
    )
    floor = last_reply["id"] if last_reply else 0
    return db.query(
        "SELECT * FROM chat_messages WHERE room_id=? AND role='user' "
        "AND id > ? ORDER BY id",
        (room_id, floor),
    )


# ---- settings KV ----

def get_setting(db: Database, key: str, default: Optional[str] = None) -> Optional[str]:
    row = db.query_one("SELECT value FROM settings WHERE key=?", (key,))
    return row["value"] if row else default


def set_setting(db: Database, key: str, value: Optional[str]) -> None:
    db.execute(
        "INSERT INTO settings(key, value, updated_at) VALUES (?,?,?) "
        "ON CONFLICT(key) DO UPDATE SET value=excluded.value, "
        "updated_at=excluded.updated_at",
        (key, value, utc_now()),
    )


def all_settings(db: Database) -> dict[str, Optional[str]]:
    return {
        r["key"]: r["value"] for r in db.query("SELECT * FROM settings")
    }
