"""Announce-and-object quorum governance (reference: src/shared/quorum.ts).

The queen announces a decision; it auto-becomes effective after a delay
(default 10 minutes) unless a worker objects first. Decision types on the
room's auto-approve list skip the delay entirely. A legacy ballot model
(explicit yes/no/abstain votes with thresholds) is kept for MCP tools and
the keeper."""

from __future__ import annotations

from datetime import datetime, timedelta, timezone
from typing import Optional

from ..db import Database, utc_now
from .activity import log_room_activity
from .constants import RoomConfig
from .rooms import get_room, room_config

ANNOUNCE_DELAY_MINUTES_DEFAULT = 10


class QuorumError(ValueError):
    pass


def _future(minutes: float) -> str:
    t = datetime.now(timezone.utc) + timedelta(minutes=minutes)
    return t.strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


def get_decision(db: Database, decision_id: int) -> Optional[dict]:
    return db.query_one(
        "SELECT * FROM quorum_decisions WHERE id=?", (decision_id,)
    )


def _resolve(db: Database, decision_id: int, status: str, result: str) -> None:
    db.execute(
        "UPDATE quorum_decisions SET status=?, result=?, resolved_at=? "
        "WHERE id=?",
        (status, result, utc_now(), decision_id),
    )


def announce(
    db: Database,
    room_id: int,
    proposer_id: Optional[int],
    proposal: str,
    decision_type: str = "low_impact",
    delay_minutes: Optional[float] = None,
) -> dict:
    room = get_room(db, room_id)
    if room is None:
        raise QuorumError(f"room {room_id} not found")
    cfg = room_config(room)

    if decision_type in cfg.auto_approve:
        did = db.insert(
            "INSERT INTO quorum_decisions"
            "(room_id, proposer_id, proposal, decision_type, status, result, "
            "resolved_at) VALUES (?,?,?,?,'approved','Auto-approved',?)",
            (room_id, proposer_id, proposal, decision_type, utc_now()),
        )
        log_room_activity(
            db, room_id, "decision", f"Auto-approved: {proposal}",
            actor_id=proposer_id,
        )
        return get_decision(db, did)  # type: ignore[return-value]

    delay = (
        delay_minutes
        if delay_minutes is not None
        else ANNOUNCE_DELAY_MINUTES_DEFAULT
    )
    did = db.insert(
        "INSERT INTO quorum_decisions"
        "(room_id, proposer_id, proposal, decision_type, status, "
        "effective_at) VALUES (?,?,?,?,'announced',?)",
        (room_id, proposer_id, proposal, decision_type, _future(delay)),
    )
    log_room_activity(
        db, room_id, "decision",
        f"Announced: {proposal} (effective in {delay:g} min)",
        actor_id=proposer_id,
    )
    _emit_decision(room_id, did, proposal, "announced")
    return get_decision(db, did)  # type: ignore[return-value]


def _emit_decision(room_id: int, did: int, proposal: str,
                   status: str) -> None:
    """Open decisions reach the dashboard's desktop-notification
    handler (decision:announced on the room channel)."""
    from .events import event_bus

    event_bus.emit("decision:announced", f"room:{room_id}",
                   {"id": did, "proposal": proposal, "status": status})


def object_to(
    db: Database, decision_id: int, worker_id: int, reason: str
) -> dict:
    decision = get_decision(db, decision_id)
    if decision is None:
        raise QuorumError(f"decision {decision_id} not found")
    if decision["status"] != "announced":
        raise QuorumError(
            f"decision {decision_id} is not open for objection "
            f"(status: {decision['status']})"
        )
    _resolve(
        db, decision_id, "objected",
        f"Objected by worker #{worker_id}: {reason}",
    )
    log_room_activity(
        db, decision["room_id"], "decision",
        f"Objected: {decision['proposal']} — {reason}", actor_id=worker_id,
    )
    return get_decision(db, decision_id)  # type: ignore[return-value]


def check_expired_decisions(db: Database) -> int:
    """Flip past-deadline announcements to effective and expire stale
    ballots. Called at the top of every agent cycle."""
    count = 0
    now = utc_now()
    for d in db.query(
        "SELECT * FROM quorum_decisions WHERE status='announced' "
        "AND effective_at IS NOT NULL AND effective_at <= ?",
        (now,),
    ):
        _resolve(db, d["id"], "effective", "No objections — auto-effective")
        log_room_activity(
            db, d["room_id"], "decision",
            f"Effective: {d['proposal']} (no objections)",
        )
        count += 1
    for d in db.query(
        "SELECT * FROM quorum_decisions WHERE status='voting' "
        "AND timeout_at IS NOT NULL AND timeout_at <= ?",
        (now,),
    ):
        resolved = _tally_and_resolve(db, d)
        if not resolved:
            _resolve(db, d["id"], "expired", "Voting period expired")
            log_room_activity(
                db, d["room_id"], "decision", f"Expired: {d['proposal']}"
            )
        count += 1
    return count


# ---- legacy ballot model ----

def open_ballot(
    db: Database,
    room_id: int,
    proposer_id: Optional[int],
    proposal: str,
    decision_type: str = "high_impact",
    timeout_minutes: float = 10,
    threshold: Optional[str] = None,
    min_voters: Optional[int] = None,
    sealed: bool = False,
) -> dict:
    room = get_room(db, room_id)
    if room is None:
        raise QuorumError(f"room {room_id} not found")
    cfg = room_config(room)
    if min_voters is None:
        # the room-settings knob (config.minVoters) is the default;
        # an explicit argument still wins
        min_voters = cfg.min_voters
    did = db.insert(
        "INSERT INTO quorum_decisions"
        "(room_id, proposer_id, proposal, decision_type, status, threshold, "
        "timeout_at, min_voters, sealed) VALUES (?,?,?,?,'voting',?,?,?,?)",
        (
            room_id, proposer_id, proposal, decision_type,
            threshold or cfg.vote_threshold,
            _future(timeout_minutes), min_voters, int(sealed),
        ),
    )
    _emit_decision(room_id, did, proposal, "voting")
    return get_decision(db, did)  # type: ignore[return-value]


def vote(
    db: Database,
    decision_id: int,
    worker_id: int,
    vote_value: str,
    reasoning: Optional[str] = None,
) -> dict:
    if vote_value not in ("yes", "no", "abstain"):
        raise QuorumError(f"invalid vote {vote_value!r}")
    decision = get_decision(db, decision_id)
    if decision is None:
        raise QuorumError(f"decision {decision_id} not found")
    if decision["status"] != "voting":
        raise QuorumError(
            f"decision {decision_id} is not open for voting "
            f"(status: {decision['status']})"
        )
    first_vote = db.query_one(
        "SELECT 1 AS x FROM quorum_votes WHERE decision_id=? AND worker_id=?",
        (decision_id, worker_id),
    ) is None
    db.insert(
        "INSERT INTO quorum_votes(decision_id, worker_id, vote, reasoning) "
        "VALUES (?,?,?,?) ON CONFLICT(decision_id, worker_id) DO UPDATE SET "
        "vote=excluded.vote, reasoning=excluded.reasoning",
        (decision_id, worker_id, vote_value, reasoning),
    )
    if first_vote:  # vote changes don't inflate the participation metric
        db.execute(
            "UPDATE workers SET votes_cast = votes_cast + 1 WHERE id=?",
            (worker_id,),
        )
    decision = get_decision(db, decision_id)
    _tally_and_resolve(db, decision)  # resolve early if outcome is decided
    return get_decision(db, decision_id)  # type: ignore[return-value]


def keeper_vote(db: Database, decision_id: int, vote_value: str) -> dict:
    if vote_value not in ("yes", "no"):
        # fail loudly: the non-"no" branch below approves, so a typo'd
        # veto must never silently become an approval
        raise QuorumError(f"invalid keeper vote {vote_value!r}")
    decision = get_decision(db, decision_id)
    if decision is None:
        raise QuorumError(f"decision {decision_id} not found")
    if decision["status"] == "announced":
        if vote_value == "no":
            _resolve(db, decision_id, "objected", "Keeper objected")
        else:
            _resolve(db, decision_id, "effective", "Keeper approved")
        return get_decision(db, decision_id)  # type: ignore[return-value]
    if decision["status"] != "voting":
        raise QuorumError(
            f"decision {decision_id} is not open for voting "
            f"(status: {decision['status']})"
        )
    db.execute(
        "UPDATE quorum_decisions SET keeper_vote=? WHERE id=?",
        (vote_value, decision_id),
    )
    _tally_and_resolve(db, get_decision(db, decision_id))
    return get_decision(db, decision_id)  # type: ignore[return-value]


def _threshold_fraction(threshold: str) -> float:
    return {
        "majority": 0.5,
        "two_thirds": 2.0 / 3.0,
        "unanimous": 1.0,
    }.get(threshold, 0.5)


def tally(db: Database, decision_id: int) -> dict:
    votes = db.query(
        "SELECT vote FROM quorum_votes WHERE decision_id=?", (decision_id,)
    )
    yes = sum(1 for v in votes if v["vote"] == "yes")
    no = sum(1 for v in votes if v["vote"] == "no")
    abstain = sum(1 for v in votes if v["vote"] == "abstain")
    return {"yes": yes, "no": no, "abstain": abstain, "total": len(votes)}


def _tally_and_resolve(db: Database, decision: dict) -> bool:
    """Resolve a ballot whose outcome is already decided by the eligible
    electorate. Returns True if resolved."""
    if decision["status"] != "voting":
        return False
    voters = db.query(
        "SELECT id FROM workers WHERE room_id=?", (decision["room_id"],)
    )
    electorate = max(len(voters), decision["min_voters"], 1)
    t = tally(db, decision["id"])
    frac = _threshold_fraction(decision["threshold"])
    need = int(electorate * frac) + (1 if frac < 1.0 else 0)
    need = max(need, 1)
    if decision["threshold"] == "unanimous":
        need = electorate

    keeper = decision["keeper_vote"]
    yes = t["yes"] + (1 if keeper == "yes" else 0)
    no = t["no"] + (1 if keeper == "no" else 0)

    if yes >= need:
        _resolve(db, decision["id"], "passed", f"{yes}/{electorate} yes")
        log_room_activity(
            db, decision["room_id"], "decision",
            f"Passed: {decision['proposal']}",
        )
        return True
    # rejection once yes can no longer reach the threshold
    remaining = electorate - t["total"]
    if yes + remaining < need:
        _resolve(db, decision["id"], "rejected", f"{no}/{electorate} no")
        log_room_activity(
            db, decision["room_id"], "decision",
            f"Rejected: {decision['proposal']}",
        )
        return True
    return False


def pending_decisions(db: Database, room_id: int) -> list[dict]:
    return db.query(
        "SELECT * FROM quorum_decisions WHERE room_id=? AND status IN "
        "('announced','voting') ORDER BY id",
        (room_id,),
    )
