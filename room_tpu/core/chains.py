"""EVM chain + token constants (reference: src/shared/constants.ts:72-159).

Multi-chain USDC/USDT addresses and the ERC-8004 identity-registry
addresses used for on-chain room identity."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ChainConfig:
    key: str
    chain_id: int
    name: str
    rpc_url: str
    explorer: str
    usdc: str
    usdt: str | None = None


CHAINS: dict[str, ChainConfig] = {
    "base": ChainConfig(
        "base", 8453, "Base", "https://mainnet.base.org",
        "https://basescan.org",
        usdc="0x833589fCD6eDb6E08f4c7C32D4f71b54bdA02913",
    ),
    "ethereum": ChainConfig(
        "ethereum", 1, "Ethereum", "https://eth.llamarpc.com",
        "https://etherscan.io",
        usdc="0xA0b86991c6218b36c1d19D4a2e9Eb0cE3606eB48",
        usdt="0xdAC17F958D2ee523a2206206994597C13D831ec7",
    ),
    "arbitrum": ChainConfig(
        "arbitrum", 42161, "Arbitrum One", "https://arb1.arbitrum.io/rpc",
        "https://arbiscan.io",
        usdc="0xaf88d065e77c8cC2239327C5EDb3A432268e5831",
        usdt="0xFd086bC7CD5C481DCC9C85ebE478A1C0b69FCbb9",
    ),
    "optimism": ChainConfig(
        "optimism", 10, "OP Mainnet", "https://mainnet.optimism.io",
        "https://optimistic.etherscan.io",
        usdc="0x0b2C639c533813f4Aa9D7837CAf62653d097Ff85",
        usdt="0x94b008aA00579c1307B0EF2c499aD98a8ce58e58",
    ),
    "polygon": ChainConfig(
        "polygon", 137, "Polygon PoS", "https://polygon-rpc.com",
        "https://polygonscan.com",
        usdc="0x3c499c542cEF5E3811e1192ce70d8cC03d5c3359",
        usdt="0xc2132D05D31c914a87C6611C10748AEb04B58e8F",
    ),
}

DEFAULT_CHAIN = "base"

# ERC-8004 identity registry (agent registration), per chain.
ERC8004_REGISTRY: dict[str, str] = {
    "base": "0x8004A169FB4a3325136EB29fA0d6Dc21C87d1cb3",
}
