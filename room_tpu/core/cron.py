"""Minimal 5-field cron matcher (the reference leans on node-cron;
src/server/runtime.ts:244-275 refreshes a cron job registry every 15 s —
here the runtime tick asks "is this expression due now?")."""

from __future__ import annotations

from datetime import datetime
from typing import Optional


class CronError(ValueError):
    pass


_FIELDS = (
    ("minute", 0, 59),
    ("hour", 0, 23),
    ("dom", 1, 31),
    ("month", 1, 12),
    ("dow", 0, 6),  # 0 = Sunday; 7 normalized to 0
)


def _parse_field(expr: str, lo: int, hi: int, name: str) -> set[int]:
    values: set[int] = set()
    for part in expr.split(","):
        part = part.strip()
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise CronError(f"bad step in {name}: {step_s!r}")
            if step <= 0:
                raise CronError(f"step must be positive in {name}")
        if part in ("*", ""):
            rng = range(lo, hi + 1)
        elif "-" in part:
            a_s, b_s = part.split("-", 1)
            try:
                a, b = int(a_s), int(b_s)
            except ValueError:
                raise CronError(f"bad range in {name}: {part!r}")
            if not (lo <= a <= hi and lo <= b <= hi and a <= b):
                raise CronError(f"range out of bounds in {name}: {part!r}")
            rng = range(a, b + 1)
        else:
            try:
                v = int(part)
            except ValueError:
                raise CronError(f"bad value in {name}: {part!r}")
            if name == "dow" and v == 7:
                v = 0
            if not lo <= v <= hi:
                raise CronError(f"{name} value out of bounds: {v}")
            rng = range(v, v + 1)
        values.update(x for x in rng if (x - rng.start) % step == 0)
    return values


def parse_cron(expr: str) -> list[set[int]]:
    parts = expr.split()
    if len(parts) != 5:
        raise CronError(
            f"cron needs 5 fields (minute hour dom month dow), got "
            f"{len(parts)}: {expr!r}"
        )
    return [
        _parse_field(p, lo, hi, name)
        for p, (name, lo, hi) in zip(parts, _FIELDS)
    ]


def cron_matches(expr: str, at: Optional[datetime] = None) -> bool:
    minute, hour, dom, month, dow = parse_cron(expr)
    t = at or datetime.now()
    return (
        t.minute in minute
        and t.hour in hour
        and t.day in dom
        and t.month in month
        and t.weekday() in {(d - 1) % 7 for d in dow}
        # python weekday(): Monday=0; cron: Sunday=0 → shift
    )


def validate_cron(expr: str) -> Optional[str]:
    """Returns an error message or None."""
    try:
        parse_cron(expr)
        return None
    except CronError as e:
        return str(e)
