"""In-process event bus (reference: src/server/event-bus.ts — channel +
wildcard pub/sub, fanned out over WebSocket by the server layer)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..db import utc_now
from ..utils import locks

Handler = Callable[["Event"], None]


@dataclass
class Event:
    type: str
    channel: str
    data: Any = None
    timestamp: str = field(default_factory=utc_now)


class EventBus:
    def __init__(self) -> None:
        self._handlers: dict[str, list[Handler]] = {}
        self._wildcard: list[Handler] = []
        self._lock = locks.make_lock("event_bus")

    def subscribe(
        self, channel: Optional[str], handler: Handler
    ) -> Callable[[], None]:
        """channel=None subscribes to everything. Returns unsubscribe."""
        with self._lock:
            if channel is None:
                self._wildcard.append(handler)
            else:
                self._handlers.setdefault(channel, []).append(handler)

        def unsubscribe() -> None:
            with self._lock:
                try:
                    if channel is None:
                        self._wildcard.remove(handler)
                    else:
                        self._handlers.get(channel, []).remove(handler)
                except ValueError:
                    pass

        return unsubscribe

    def emit(self, type_: str, channel: str, data: Any = None) -> Event:
        event = Event(type_, channel, data)
        with self._lock:
            handlers = list(self._handlers.get(channel, []))
            handlers += list(self._wildcard)
        for h in handlers:
            try:
                h(event)
            except Exception:
                pass  # a broken subscriber must not break the emitter
        return event


event_bus = EventBus()
