"""Hierarchical goals (reference: src/shared/goals.ts, progress recalc in
src/shared/db-queries.ts:1488-1520)."""

from __future__ import annotations

from typing import Optional

from ..db import Database, utc_now


def set_room_objective(db: Database, room_id: int, description: str) -> int:
    """The root goal. A room has exactly one active root; setting a new one
    abandons the old root."""
    existing = db.query_one(
        "SELECT id FROM goals WHERE room_id=? AND parent_goal_id IS NULL "
        "AND status='active'",
        (room_id,),
    )
    if existing:
        db.execute(
            "UPDATE goals SET status='abandoned', updated_at=? WHERE id=?",
            (utc_now(), existing["id"]),
        )
    db.execute(
        "UPDATE rooms SET goal=?, updated_at=? WHERE id=?",
        (description, utc_now(), room_id),
    )
    return db.insert(
        "INSERT INTO goals(room_id, description) VALUES (?,?)",
        (room_id, description),
    )


def get_root_goal(db: Database, room_id: int) -> Optional[dict]:
    return db.query_one(
        "SELECT * FROM goals WHERE room_id=? AND parent_goal_id IS NULL "
        "AND status='active' ORDER BY id DESC LIMIT 1",
        (room_id,),
    )


def create_goal(
    db: Database,
    room_id: int,
    description: str,
    parent_goal_id: Optional[int] = None,
    assigned_worker_id: Optional[int] = None,
) -> int:
    return db.insert(
        "INSERT INTO goals(room_id, description, parent_goal_id, "
        "assigned_worker_id) VALUES (?,?,?,?)",
        (room_id, description, parent_goal_id, assigned_worker_id),
    )


def get_goal(db: Database, goal_id: int) -> Optional[dict]:
    return db.query_one("SELECT * FROM goals WHERE id=?", (goal_id,))


def assign_goal(db: Database, goal_id: int, worker_id: Optional[int]) -> None:
    db.execute(
        "UPDATE goals SET assigned_worker_id=?, updated_at=? WHERE id=?",
        (worker_id, utc_now(), goal_id),
    )


def add_goal_update(
    db: Database,
    goal_id: int,
    observation: str,
    worker_id: Optional[int] = None,
    metric_value: Optional[float] = None,
) -> int:
    uid = db.insert(
        "INSERT INTO goal_updates(goal_id, worker_id, observation, "
        "metric_value) VALUES (?,?,?,?)",
        (goal_id, worker_id, observation, metric_value),
    )
    if metric_value is not None:
        set_goal_progress(db, goal_id, max(0.0, min(1.0, metric_value)))
    return uid


def set_goal_progress(db: Database, goal_id: int, progress: float) -> None:
    db.execute(
        "UPDATE goals SET progress=?, updated_at=? WHERE id=?",
        (progress, utc_now(), goal_id),
    )
    _recalc_ancestors(db, goal_id)


def complete_goal(db: Database, goal_id: int) -> None:
    db.execute(
        "UPDATE goals SET status='completed', progress=1.0, updated_at=? "
        "WHERE id=?",
        (utc_now(), goal_id),
    )
    _recalc_ancestors(db, goal_id)


def abandon_goal(db: Database, goal_id: int) -> None:
    db.execute(
        "UPDATE goals SET status='abandoned', updated_at=? WHERE id=?",
        (utc_now(), goal_id),
    )
    _recalc_ancestors(db, goal_id)


def _recalc_ancestors(db: Database, goal_id: int) -> None:
    """Parent progress = mean of non-abandoned children, recursively
    upward (reference: db-queries.ts:1488-1520)."""
    goal = get_goal(db, goal_id)
    while goal and goal["parent_goal_id"] is not None:
        pid = goal["parent_goal_id"]
        row = db.query_one(
            "SELECT AVG(CASE WHEN status='completed' THEN 1.0 ELSE progress "
            "END) AS p FROM goals WHERE parent_goal_id=? AND "
            "status != 'abandoned'",
            (pid,),
        )
        if row and row["p"] is not None:
            db.execute(
                "UPDATE goals SET progress=?, updated_at=? WHERE id=?",
                (float(row["p"]), utc_now(), pid),
            )
        goal = get_goal(db, pid)


def get_goal_tree(db: Database, room_id: int) -> list[dict]:
    """Nested goal forest for the room, children under 'children'."""
    rows = db.query(
        "SELECT * FROM goals WHERE room_id=? ORDER BY id", (room_id,)
    )
    by_id: dict[int, dict] = {}
    for r in rows:
        r["children"] = []
        by_id[r["id"]] = r
    roots = []
    for r in rows:
        pid = r["parent_goal_id"]
        if pid is not None and pid in by_id:
            by_id[pid]["children"].append(r)
        else:
            roots.append(r)
    return roots


def active_goals_for_worker(db: Database, worker_id: int) -> list[dict]:
    return db.query(
        "SELECT * FROM goals WHERE assigned_worker_id=? AND status='active' "
        "ORDER BY id",
        (worker_id,),
    )
