"""Domain enums, presets, and defaults.

Behavioral parity with the reference's constants module (reference:
src/shared/constants.ts:16-231): state enums, worker role presets with
cadences, plan-aware queen cycle defaults, and the default room governance
config. Chain/wallet constants live in ``room_tpu.core.chains``.
"""

from __future__ import annotations

from dataclasses import dataclass, field


# ---- state enums (stored as TEXT in SQLite) ----

TRIGGER_TYPES = ("cron", "once", "webhook", "watch")
TASK_STATUSES = ("active", "paused", "archived")
RUN_STATUSES = ("running", "success", "error", "cancelled")
ROOM_STATUSES = ("active", "paused", "archived")
AGENT_STATES = ("idle", "running", "waiting", "rate_limited", "stopped")
DECISION_STATUSES = (
    "voting", "announced", "approved", "objected", "effective",
    "passed", "rejected", "expired",
)
DECISION_TYPES = ("low_impact", "high_impact", "critical")
GOAL_STATUSES = ("active", "completed", "abandoned")
ESCALATION_STATUSES = ("pending", "answered", "dismissed")
TX_STATUSES = ("pending", "confirmed", "failed")
MESSAGE_STATUSES = ("unread", "read", "replied")
VISIBILITIES = ("private", "public")
AUTONOMY_MODES = ("manual", "semi", "full")


# ---- queen cycle cadence, plan-aware defaults ----
# (reference: src/shared/constants.ts:161-175 — cadence scales with the
# keeper's provider plan; the tpu: provider is in-tree so it gets the
# fastest cadence.)

QUEEN_CYCLE_GAP_MS_DEFAULT = 30 * 60 * 1000
QUEEN_MAX_TURNS_DEFAULT = 50
QUEEN_MAX_TURNS_FLOOR = 50

PLAN_QUEEN_DEFAULTS: dict[str, int] = {
    # plan -> queen cycle gap ms
    "none": 10 * 60 * 1000,
    "pro": 5 * 60 * 1000,
    "max": 30 * 1000,
    "api": 2 * 60 * 1000,
    "tpu": 30 * 1000,
}


# ---- worker role presets ----
# (reference: src/shared/constants.ts:183-219)

@dataclass(frozen=True)
class RolePreset:
    role: str
    cycle_gap_ms: int
    max_turns: int
    prompt_prefix: str


WORKER_ROLE_PRESETS: dict[str, RolePreset] = {
    "executor": RolePreset(
        "executor", 15_000, 200,
        "You are an executor: pick up assigned goals and drive them to "
        "completion with tools. Prefer action over discussion.",
    ),
    "guardian": RolePreset(
        "guardian", 30_000, 30,
        "You are a guardian: review announced decisions and recent activity "
        "for risk; object when a decision would harm the room.",
    ),
    "analyst": RolePreset(
        "analyst", 60_000, 100,
        "You are an analyst: study the room's goals, memory, and metrics; "
        "produce concise findings that help the queen decide.",
    ),
    "writer": RolePreset(
        "writer", 60_000, 100,
        "You are a writer: turn the room's work into clear prose — reports, "
        "summaries, documentation.",
    ),
    "researcher": RolePreset(
        "researcher", 30_000, 100,
        "You are a researcher: gather information with web tools, verify it, "
        "and store durable findings in memory.",
    ),
}


# ---- room governance config ----
# (reference: src/shared/constants.ts:221-231, types.ts:262-272)

@dataclass
class RoomConfig:
    vote_threshold: str = "majority"        # majority | two_thirds | unanimous
    vote_timeout_minutes: int = 10          # announce->effective delay
    queen_tie_breaker: bool = True
    auto_approve: tuple[str, ...] = ("low_impact",)
    sealed_ballot: bool = False
    min_voter_health: float = 0.0
    # ballots resolve against max(actual voters, min_voters): a keeper
    # can require e.g. 3 votes even in a 2-worker room
    min_voters: int = 0

    @classmethod
    def from_json(cls, raw: dict | None) -> "RoomConfig":
        cfg = cls()
        if not raw:
            return cfg
        cfg.vote_threshold = raw.get("voteThreshold", cfg.vote_threshold)
        cfg.vote_timeout_minutes = int(
            raw.get("voteTimeoutMinutes", cfg.vote_timeout_minutes)
        )
        cfg.queen_tie_breaker = bool(
            raw.get("queenTieBreaker", cfg.queen_tie_breaker)
        )
        aa = raw.get("autoApprove")
        if aa is not None:
            cfg.auto_approve = tuple(aa)
        cfg.sealed_ballot = bool(raw.get("sealedBallot", cfg.sealed_ballot))
        cfg.min_voter_health = float(
            raw.get("minVoterHealth", cfg.min_voter_health)
        )
        cfg.min_voters = int(raw.get("minVoters", cfg.min_voters))
        return cfg

    def to_json(self) -> dict:
        return {
            "voteThreshold": self.vote_threshold,
            "voteTimeoutMinutes": self.vote_timeout_minutes,
            "queenTieBreaker": self.queen_tie_breaker,
            "autoApprove": list(self.auto_approve),
            "sealedBallot": self.sealed_ballot,
            "minVoterHealth": self.min_voter_health,
            "minVoters": self.min_voters,
        }


# ---- context/session policy knobs ----
# (reference: agent-loop.ts:462-532, queen-tools.ts:647, skills.ts:5-6,
#  task-runner.ts:33)

CLI_SESSION_ROTATE_CYCLES = 20
CLI_SESSION_ROTATE_DAYS = 7
API_HISTORY_COMPRESS_AT = 30
API_HISTORY_TRIM_AT = 40
TASK_SESSION_ROTATE_RUNS = 20
WIP_MAX_CHARS = 2000
SKILLS_CONTEXT_MAX = 8
SKILLS_CONTEXT_MAX_CHARS = 6000
MEMORY_RECALL_TOP_K = 5

# default queen system prompt: the control-plane contract. The queen plans,
# delegates, and governs; she does not execute work herself.
# (reference: src/shared/room.ts:9-24)
DEFAULT_QUEEN_PROMPT = (
    "You are the Queen of this room: its coordinator and planner, not its "
    "executor. Each cycle: (1) review the objective, goal tree, announced "
    "decisions, escalations, and unread messages; (2) decompose the "
    "objective into goals and delegate them to workers with delegate(); "
    "(3) announce significant decisions for quorum review before acting on "
    "them; (4) record durable facts with remember(); (5) save a WIP note "
    "describing where to continue. Create workers when the room lacks the "
    "needed role. Escalate to the keeper only when blocked on something "
    "outside the room's authority."
)

MAX_CONCURRENT_TASKS_DEFAULT = 3
MAX_CONCURRENT_TASKS_MIN = 1
MAX_CONCURRENT_TASKS_MAX = 10

SELF_MOD_MIN_INTERVAL_S = 60
