"""EVM transaction signing: secp256k1 ECDSA (RFC 6979 deterministic
nonce, EIP-2 low-s), RLP, and EIP-1559 (type-2) encoding — fully
offline, stdlib-only (reference: src/shared/wallet.ts:19-37 signs and
sends via viem; identity.ts:19-61 registers on-chain).

Pure Python is the right tool here: signing happens a handful of times
per agent action on the host, nowhere near the TPU hot path. The ECDSA
implementation is cross-checked in tests against the independent
`cryptography` package verifier and the widely published RFC 6979
secp256k1 vectors.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import Optional, Sequence, Union

from .keccak import keccak256

# secp256k1 domain parameters
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
Gx = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
Gy = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


# ---- EC arithmetic (Jacobian coordinates) ----

def _jac_double(p):
    x, y, z = p
    if y == 0:
        return (0, 0, 0)
    ysq = (y * y) % P
    s = (4 * x * ysq) % P
    m = (3 * x * x) % P
    nx = (m * m - 2 * s) % P
    ny = (m * (s - nx) - 8 * ysq * ysq) % P
    nz = (2 * y * z) % P
    return (nx, ny, nz)


def _jac_add(p, q):
    if p[2] == 0:
        return q
    if q[2] == 0:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = (z1 * z1) % P
    z2z2 = (z2 * z2) % P
    u1 = (x1 * z2z2) % P
    u2 = (x2 * z1z1) % P
    s1 = (y1 * z2 * z2z2) % P
    s2 = (y2 * z1 * z1z1) % P
    if u1 == u2:
        if s1 != s2:
            return (0, 0, 0)
        return _jac_double(p)
    h = (u2 - u1) % P
    r = (s2 - s1) % P
    h2 = (h * h) % P
    h3 = (h * h2) % P
    u1h2 = (u1 * h2) % P
    nx = (r * r - h3 - 2 * u1h2) % P
    ny = (r * (u1h2 - nx) - s1 * h3) % P
    nz = (h * z1 * z2) % P
    return (nx, ny, nz)


def _jac_mul(p, k: int):
    result = (0, 0, 0)
    addend = p
    while k:
        if k & 1:
            result = _jac_add(result, addend)
        addend = _jac_double(addend)
        k >>= 1
    return result


def _to_affine(p) -> Optional[tuple[int, int]]:
    x, y, z = p
    if z == 0:
        return None
    zinv = pow(z, P - 2, P)
    zinv2 = (zinv * zinv) % P
    return (x * zinv2) % P, (y * zinv2 * zinv) % P


def pubkey_point(private_key: bytes) -> tuple[int, int]:
    d = int.from_bytes(private_key, "big")
    if not 0 < d < N:
        raise ValueError("private key out of range")
    pt = _to_affine(_jac_mul((Gx, Gy, 1), d))
    assert pt is not None
    return pt


# ---- RFC 6979 deterministic nonce ----

def _rfc6979_k(msg_hash: bytes, private_key: bytes) -> int:
    qlen = 32
    v = b"\x01" * 32
    k = b"\x00" * 32
    x = private_key.rjust(qlen, b"\x00")
    h1 = int.from_bytes(msg_hash, "big") % N
    bh = h1.to_bytes(qlen, "big")
    k = hmac.new(k, v + b"\x00" + x + bh, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + bh, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def ecdsa_sign(msg_hash: bytes, private_key: bytes) -> tuple[int, int, int]:
    """Sign a 32-byte digest. Returns (r, s, y_parity) with low-s
    (EIP-2) so the signature is Ethereum-canonical."""
    if len(msg_hash) != 32:
        raise ValueError("msg_hash must be 32 bytes")
    d = int.from_bytes(private_key, "big")
    if not 0 < d < N:
        raise ValueError("private key out of range")
    z = int.from_bytes(msg_hash, "big") % N
    while True:
        k = _rfc6979_k(msg_hash, private_key)
        pt = _to_affine(_jac_mul((Gx, Gy, 1), k))
        if pt is None:
            continue
        x1, y1 = pt
        r = x1 % N
        if r == 0:
            continue
        s = (pow(k, N - 2, N) * (z + r * d)) % N
        if s == 0:
            continue
        recid = (y1 & 1) | (2 if x1 >= N else 0)
        if s > N // 2:
            s = N - s
            recid ^= 1
        return r, s, recid


def ecdsa_recover(msg_hash: bytes, r: int, s: int,
                  y_parity: int) -> tuple[int, int]:
    """Recover the public key point (the ecrecover primitive)."""
    x = r + (N if y_parity >= 2 else 0)
    if x >= P:
        raise ValueError("invalid r")
    alpha = (pow(x, 3, P) + 7) % P
    y = pow(alpha, (P + 1) // 4, P)
    if (y * y) % P != alpha:
        raise ValueError("point not on curve")
    if (y & 1) != (y_parity & 1):
        y = P - y
    z = int.from_bytes(msg_hash, "big") % N
    rinv = pow(r, N - 2, N)
    # Q = r^-1 (sR - zG)
    srp = _jac_mul((x, y, 1), s)
    zg = _jac_mul((Gx, Gy, 1), (N - z) % N)
    q = _to_affine(_jac_mul(_jac_add(srp, zg), rinv))
    if q is None:
        raise ValueError("recovery failed")
    return q


def point_to_address(pt: tuple[int, int]) -> str:
    pub = pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")
    return "0x" + keccak256(pub)[-20:].hex()


# ---- RLP ----

RlpItem = Union[bytes, int, str, Sequence]


def _to_bytes(item: RlpItem) -> bytes:
    if isinstance(item, bytes):
        return item
    if isinstance(item, bytearray):
        return bytes(item)
    if isinstance(item, int):
        if item < 0:
            raise ValueError("RLP cannot encode negative ints")
        if item == 0:
            return b""
        return item.to_bytes((item.bit_length() + 7) // 8, "big")
    if isinstance(item, str):
        if item.startswith("0x"):
            h = item[2:]
            if len(h) % 2:
                h = "0" + h
            return bytes.fromhex(h)
        return item.encode()
    raise TypeError(f"cannot RLP-encode {type(item)}")


def rlp_encode(item: RlpItem) -> bytes:
    if isinstance(item, (list, tuple)):
        payload = b"".join(rlp_encode(x) for x in item)
        if len(payload) <= 55:
            return bytes([0xC0 + len(payload)]) + payload
        ln = _to_bytes(len(payload))
        return bytes([0xF7 + len(ln)]) + ln + payload
    b = _to_bytes(item)
    if len(b) == 1 and b[0] <= 0x7F:
        return b
    if len(b) <= 55:
        return bytes([0x80 + len(b)]) + b
    ln = _to_bytes(len(b))
    return bytes([0xB7 + len(ln)]) + ln + b


# ---- EIP-1559 transactions ----

def encode_eip1559_unsigned(
    *,
    chain_id: int,
    nonce: int,
    max_priority_fee_per_gas: int,
    max_fee_per_gas: int,
    gas_limit: int,
    to: Optional[str],
    value: int,
    data: bytes = b"",
    access_list: Sequence = (),
) -> bytes:
    fields = [
        chain_id, nonce, max_priority_fee_per_gas, max_fee_per_gas,
        gas_limit, to if to is not None else b"", value, data,
        list(access_list),
    ]
    return b"\x02" + rlp_encode(fields)


def sign_eip1559(
    private_key: bytes,
    *,
    chain_id: int,
    nonce: int,
    max_priority_fee_per_gas: int,
    max_fee_per_gas: int,
    gas_limit: int,
    to: Optional[str],
    value: int,
    data: bytes = b"",
    access_list: Sequence = (),
) -> dict:
    """Returns {"raw": 0x-hex raw tx, "hash": 0x-hex tx hash, r, s,
    yParity} ready for eth_sendRawTransaction."""
    unsigned = encode_eip1559_unsigned(
        chain_id=chain_id, nonce=nonce,
        max_priority_fee_per_gas=max_priority_fee_per_gas,
        max_fee_per_gas=max_fee_per_gas, gas_limit=gas_limit, to=to,
        value=value, data=data, access_list=access_list,
    )
    digest = keccak256(unsigned)
    r, s, y_parity = ecdsa_sign(digest, private_key)
    if y_parity >= 2:  # astronomically rare r >= N wrap; not canonical
        raise ValueError("non-canonical signature (r overflow), retry")
    fields = [
        chain_id, nonce, max_priority_fee_per_gas, max_fee_per_gas,
        gas_limit, to if to is not None else b"", value, data,
        list(access_list), y_parity, r, s,
    ]
    raw = b"\x02" + rlp_encode(fields)
    return {
        "raw": "0x" + raw.hex(),
        "hash": "0x" + keccak256(raw).hex(),
        "r": hex(r),
        "s": hex(s),
        "yParity": y_parity,
    }


def erc20_transfer_data(to: str, amount: int) -> bytes:
    """transfer(address,uint256) calldata."""
    selector = bytes.fromhex("a9059cbb")
    addr = bytes.fromhex(to[2:].lower()).rjust(32, b"\x00")
    return selector + addr + amount.to_bytes(32, "big")
