"""Durable cycle journal: crash-safe intent records for the swarm
runtime (docs/swarm_recovery.md).

The serving layer survives failure (docs/chaos.md); this module gives
the swarm runtime above it the same property. Every agent cycle and
task run appends intent records to the ``cycle_journal`` table —
*started*, *provider_call* (with an idempotency key), *effect*
intent/commit around journaled tool side effects, and a close on
finish. Work interrupted by a crash leaves its entries open; startup
recovery (:func:`recover`) scans them and immediately fails/requeues
the ref rows — replacing the 120-minute stale sweep for crash cases —
while flagging committed side effects so a retried cycle never fires
the same wallet tx, message send, or self-mod twice.

Entry lifecycle::

    started / provider_call:  open -> closed            (clean finish)
                              open -> recovered         (crash recovery)
    effect:                   intent -> committed        (ran cleanly)
                              intent -> abandoned        (never committed:
                                                          replay RE-RUNS it)
                              committed -> replay_skip   (recovery: parent
                                                          was interrupted)
                              replay_skip -> consumed    (a retry skipped it
                                                          and reused the
                                                          recorded result)

Idempotency keys are content-derived (kind + actor + tool + canonical
args), so the retried incarnation of interrupted work — a brand-new
cycle/run row — still matches the committed effects of its dead
predecessor. ``replay_skip`` matches are bounded by
``ROOM_TPU_REPLAY_WINDOW_S`` so a legitimate repeat of the same action
next week executes normally.
"""

from __future__ import annotations

import hashlib
import json
from ..utils import knobs
import sys
from typing import Callable, Optional

from ..db import Database, utc_now

# tables a journal kind refers into (ref_id -> <table>.id)
KIND_TABLE = {"cycle": "worker_cycles", "task_run": "task_runs"}

# Tool side effects that are externally visible or irreversible enough
# to warrant exactly-once-on-replay protection. Everything else
# (save_wip, recall, web_fetch, ...) is idempotent or harmless to
# repeat and stays un-journaled.
JOURNALED_TOOLS = frozenset({
    "send_message", "escalate_to_keeper", "announce_decision",
    "create_worker", "create_skill",
})

# how long a recovery-flagged effect stays skippable (seconds)
REPLAY_WINDOW_S = knobs.get_float("ROOM_TPU_REPLAY_WINDOW_S")
# queen_tools.execute_queen_tool's error convention: tool failures come
# back as strings with this prefix, never as exceptions
TOOL_ERROR_PREFIX = "tool error:"
# terminal journal rows older than this are pruned (hours)
PRUNE_AFTER_H = knobs.get_float("ROOM_TPU_JOURNAL_PRUNE_H")

_TERMINAL = ("closed", "recovered", "committed", "consumed",
             "abandoned")


def _incr(name: str, n: int = 1) -> None:
    from .telemetry import incr_counter

    incr_counter(name, n)


def chaos(point: str) -> None:
    """Swarm-layer chaos fault point, resolved through sys.modules like
    the db layer's: no serving import unless the fault registry is
    already loaded (in which case arming was possible at all). The
    agent loop and task runner call this for ``cycle_crash`` /
    ``loop_hang``; this module calls it for ``tool_exec``."""
    faults = sys.modules.get("room_tpu.serving.faults")
    if faults is not None and faults.is_armed():
        faults.maybe_fail(point)


def chaos_delay(point: str) -> float:
    """Latency-style fault point (``loop_hang``): sleeps the armed
    spec's latency, returns seconds slept."""
    faults = sys.modules.get("room_tpu.serving.faults")
    if faults is not None and faults.is_armed():
        return faults.maybe_delay(point)
    return 0.0


def effect_key(kind: str, actor_id: Optional[int], name: str,
               args: dict) -> str:
    """Content-derived idempotency key: stable across the crash/retry
    boundary (the retry is a different cycle row, same logical act)."""
    canon = json.dumps(args, sort_keys=True, separators=(",", ":"),
                       default=str)
    digest = hashlib.sha256(
        f"{kind}:{actor_id}:{name}:{canon}".encode()
    ).hexdigest()[:24]
    return f"{name}:{digest}"


# ---- append paths (hot: one insert each) ----

def record_started(
    db: Database, kind: str, ref_id: int,
    room_id: Optional[int] = None, worker_id: Optional[int] = None,
) -> int:
    return db.insert(
        "INSERT INTO cycle_journal(kind, ref_id, room_id, worker_id, "
        "entry, status) VALUES (?,?,?,?,'started','open')",
        (kind, ref_id, room_id, worker_id),
    )


def record_provider_call(
    db: Database, kind: str, ref_id: int, idem_key: str,
    room_id: Optional[int] = None, worker_id: Optional[int] = None,
) -> int:
    return db.insert(
        "INSERT INTO cycle_journal(kind, ref_id, room_id, worker_id, "
        "entry, status, idem_key) VALUES "
        "(?,?,?,?,'provider_call','open',?)",
        (kind, ref_id, room_id, worker_id, idem_key),
    )


def record_finished(db: Database, kind: str, ref_id: int) -> None:
    """Close the ref's open bookkeeping on any clean finish (success,
    error, cancel). Dangling effect intents — the tool never committed
    because the cycle failed mid-call — are marked abandoned so a
    retry re-runs them (at-least-once for uncommitted effects)."""
    now = utc_now()
    # intents first, the 'started' entry last: a crash between the two
    # statements leaves the ref discoverable by recovery either way
    db.execute(
        "UPDATE cycle_journal SET status='abandoned', updated_at=? "
        "WHERE kind=? AND ref_id=? AND entry='effect' AND "
        "status='intent'",
        (now, kind, ref_id),
    )
    db.execute(
        "UPDATE cycle_journal SET status='closed', updated_at=? "
        "WHERE kind=? AND ref_id=? AND "
        "entry IN ('started','provider_call') AND status='open'",
        (now, kind, ref_id),
    )


# ---- journaled side effects ----

def run_journaled_effect(
    db: Database,
    kind: str,
    ref_id: int,
    room_id: Optional[int],
    actor_id: Optional[int],
    name: str,
    args: dict,
    fn: Callable[[], str],
) -> str:
    """Execute a side-effecting tool under journal protection: intent
    before, commit after. If crash recovery flagged a committed entry
    with the same idempotency key (the effect already fired in an
    interrupted predecessor), skip execution and return the recorded
    result instead — the replay never double-fires."""
    key = effect_key(kind, actor_id, name, args)
    cutoff = f"-{int(REPLAY_WINDOW_S)} seconds"
    # windowed on updated_at — recovery stamps it when flagging
    # replay_skip — so the skip survives an outage of ANY length and
    # the window runs from the restart, not the original execution
    prior = db.query_one(
        "SELECT * FROM cycle_journal WHERE entry='effect' AND "
        "idem_key=? AND status='replay_skip' AND updated_at > "
        "strftime('%Y-%m-%dT%H:%M:%fZ','now', ?) "
        "ORDER BY id DESC LIMIT 1",
        (key, cutoff),
    )
    if prior is not None:
        payload = json.loads(prior["payload"] or "{}")
        result = payload.get(
            "result", f"[recovered] {name} already executed before the "
            "crash; not re-fired"
        )
        # consume the old marker AND record a committed marker on the
        # consuming ref, atomically: if THIS retry also crashes after
        # the skip point, recovery flags the new marker replay_skip and
        # the next retry skips again — the protection chains through
        # any number of crash/retry rounds
        with db.transaction():
            db.execute(
                "UPDATE cycle_journal SET status='consumed', "
                "updated_at=? WHERE id=?",
                (utc_now(), prior["id"]),
            )
            db.insert(
                "INSERT INTO cycle_journal(kind, ref_id, room_id, "
                "worker_id, entry, status, idem_key, payload) VALUES "
                "(?,?,?,?,'effect','committed',?,?)",
                (kind, ref_id, room_id, actor_id, key,
                 json.dumps({"tool": name, "args": args,
                             "result": result,
                             "replayed_from": prior["id"]},
                            default=str)),
            )
        _incr("journal.effects_skipped")
        return result

    if kind == "cycle":
        # a committed entry with this key from ANOTHER still-running
        # cycle of the same worker means the act already fired in a
        # predecessor that never reached terminal state — an
        # un-recovered in-process crash orphan, or the hung twin a
        # supervision hang-replacement left behind. Skip without
        # consuming (the owner's recovery settles its entry); record a
        # committed marker on this ref so the protection chains.
        live = db.query_one(
            "SELECT j.payload FROM cycle_journal j "
            "JOIN worker_cycles c ON c.id = j.ref_id "
            "WHERE j.entry='effect' AND j.status='committed' AND "
            "j.kind='cycle' AND j.idem_key=? AND j.worker_id=? AND "
            "j.ref_id != ? AND c.status='running' AND j.updated_at > "
            "strftime('%Y-%m-%dT%H:%M:%fZ','now', ?) "
            "ORDER BY j.id DESC LIMIT 1",
            (key, actor_id, ref_id, cutoff),
        )
        if live is not None:
            payload = json.loads(live["payload"] or "{}")
            result = payload.get(
                "result", f"[recovered] {name} already executed by an "
                "interrupted predecessor; not re-fired"
            )
            db.insert(
                "INSERT INTO cycle_journal(kind, ref_id, room_id, "
                "worker_id, entry, status, idem_key, payload) VALUES "
                "(?,?,?,?,'effect','committed',?,?)",
                (kind, ref_id, room_id, actor_id, key,
                 json.dumps({"tool": name, "args": args,
                             "result": result, "live_skip": True},
                            default=str)),
            )
            _incr("journal.effects_skipped")
            return result

    entry_id = db.insert(
        "INSERT INTO cycle_journal(kind, ref_id, room_id, worker_id, "
        "entry, status, idem_key, payload) VALUES "
        "(?,?,?,?,'effect','intent',?,?)",
        (kind, ref_id, room_id, actor_id, key,
         json.dumps({"tool": name, "args": args}, default=str)),
    )
    chaos("tool_exec")
    # journaled tools are db-only: effect AND its committed marker land
    # in ONE transaction, so every crash leaves exactly two possible
    # states — intent (nothing applied; replay re-runs) or committed
    # (fully applied; replay skips). No partial apply, no applied-but-
    # unmarked window.
    with db.transaction():
        out = fn()
        # execute_queen_tool converts tool exceptions into a
        # "tool error: ..." string instead of raising — that is a
        # FAILED effect, and committing it would make replay suppress
        # a retry of something that never happened
        failed = (out or "").startswith(TOOL_ERROR_PREFIX)
        db.execute(
            "UPDATE cycle_journal SET status=?, payload=?, "
            "updated_at=? WHERE id=?",
            ("abandoned" if failed else "committed",
             json.dumps({"tool": name, "args": args,
                         "result": (out or "")[:2000]}, default=str),
             utc_now(), entry_id),
        )
    return out


# ---- startup recovery ----

def recover(db: Database, worker_id: Optional[int] = None) -> dict:
    """Scan open journal entries and resolve every crash-interrupted
    ref to a terminal state *now* (not 120 minutes from now):

    - cycles / task runs still ``running`` are failed with an explicit
      recovery message; interrupted ``once`` tasks stay active, so the
      scheduler immediately requeues them (archiving only happens in a
      clean ``_finish_run``);
    - their committed effects become ``replay_skip`` (never re-fired),
      their un-committed intents ``abandoned`` (re-run on retry);
    - entries whose ref already reached a terminal state (the crash hit
      after the status update but before the journal close) are closed
      quietly.

    With ``worker_id`` the scan is scoped to that worker's refs — the
    supervised in-process restart path (agent_loop.supervise_loops)
    uses this so a crashed loop's interrupted cycle is resolved and its
    committed effects are replay-protected *before* the replacement
    loop runs, not at the next full process restart. Scoped runs skip
    the orphan-intent catch-all: other workers' intents are live.
    """
    summary = {"cycles": 0, "task_runs": 0, "effects_flagged": 0,
               "closed": 0}
    if worker_id is None:
        open_rows = db.query(
            "SELECT DISTINCT kind, ref_id FROM cycle_journal WHERE "
            "entry IN ('started','provider_call') AND status='open' "
            "ORDER BY ref_id",
        )
    else:
        # cycles only: task runs execute on their own threads and are
        # not interrupted by a loop-thread death
        open_rows = db.query(
            "SELECT DISTINCT kind, ref_id FROM cycle_journal WHERE "
            "entry IN ('started','provider_call') AND status='open' "
            "AND worker_id=? AND kind='cycle' ORDER BY ref_id",
            (worker_id,),
        )
    now = utc_now()
    for row in open_rows:
        kind, ref_id = row["kind"], row["ref_id"]
        table = KIND_TABLE[kind]
        with db.transaction():
            ref = db.query_one(
                f"SELECT id, status FROM {table} WHERE id=?", (ref_id,)
            )
            if ref is not None and ref["status"] == "running":
                db.execute(
                    f"UPDATE {table} SET status='error', "
                    "error_message='recovered: interrupted by crash', "
                    "finished_at=? WHERE id=?",
                    (now, ref_id),
                )
                flagged = db.execute(
                    "UPDATE cycle_journal SET status='replay_skip', "
                    "updated_at=? WHERE kind=? AND ref_id=? AND "
                    "entry='effect' AND status='committed'",
                    (now, kind, ref_id),
                ).rowcount
                db.execute(
                    "UPDATE cycle_journal SET status='abandoned', "
                    "updated_at=? WHERE kind=? AND ref_id=? AND "
                    "entry='effect' AND status='intent'",
                    (now, kind, ref_id),
                )
                db.execute(
                    "UPDATE cycle_journal SET status='recovered', "
                    "updated_at=? WHERE kind=? AND ref_id=? AND "
                    "entry IN ('started','provider_call') AND "
                    "status='open'",
                    (now, kind, ref_id),
                )
                summary["effects_flagged"] += flagged
                if kind == "cycle":
                    summary["cycles"] += 1
                    _incr("journal.recovered_cycles")
                else:
                    summary["task_runs"] += 1
                    _incr("journal.recovered_runs")
            else:
                # ref finished (or was deleted) but the journal close
                # was lost: pure bookkeeping
                db.execute(
                    "UPDATE cycle_journal SET status='closed', "
                    "updated_at=? WHERE kind=? AND ref_id=? AND "
                    "status IN ('open','intent')",
                    (now, kind, ref_id),
                )
                summary["closed"] += 1
    # catch-all (startup only): recovery runs when nothing is in
    # flight, so any intent still standing is an orphan (e.g. a crash
    # inside the journal close itself) — abandon it so backlog reads
    # true
    if worker_id is None:
        db.execute(
            "UPDATE cycle_journal SET status='abandoned', updated_at=? "
            "WHERE entry='effect' AND status='intent'",
            (now,),
        )
    if summary["cycles"] or summary["task_runs"]:
        from .events import event_bus

        event_bus.emit("journal:recovered", "runtime", summary)
    return summary


# ---- observability + hygiene ----

def backlog(db: Database) -> int:
    """Open in-flight entries — the health surface's 'journal backlog'.
    Grows while work is in flight; a persistently large value means
    cycles are piling up faster than they finish (or leak)."""
    row = db.query_one(
        "SELECT COUNT(*) AS n FROM cycle_journal WHERE "
        "status IN ('open','intent')",
    )
    return row["n"] if row else 0


def stats(db: Database) -> dict:
    counts = {
        r["status"]: r["n"]
        for r in db.query(
            "SELECT status, COUNT(*) AS n FROM cycle_journal "
            "GROUP BY status"
        )
    }
    return {
        "backlog": counts.get("open", 0) + counts.get("intent", 0),
        "recovered": counts.get("recovered", 0),
        "replay_pending": counts.get("replay_skip", 0),
        "replay_consumed": counts.get("consumed", 0),
    }


def prune(db: Database, keep_hours: Optional[float] = None) -> int:
    """Delete terminal journal rows past the retention window. Open
    rows are never pruned — they carry recovery state. A replay_skip
    row older than REPLAY_WINDOW_S can never match the consumption
    query again (the retry evidently never repeated the act), so those
    expire too instead of accumulating forever."""
    hours = PRUNE_AFTER_H if keep_hours is None else keep_hours
    cutoff = f"-{int(hours * 3600)} seconds"
    placeholders = ",".join("?" for _ in _TERMINAL)
    n = db.execute(
        f"DELETE FROM cycle_journal WHERE status IN ({placeholders}) "
        "AND updated_at < strftime('%Y-%m-%dT%H:%M:%fZ','now', ?)",
        (*_TERMINAL, cutoff),
    ).rowcount
    n += db.execute(
        "DELETE FROM cycle_journal WHERE status='replay_skip' AND "
        "updated_at < strftime('%Y-%m-%dT%H:%M:%fZ','now', ?)",
        (f"-{int(REPLAY_WINDOW_S)} seconds",),
    ).rowcount
    return n
