"""Worker CRUD and agent-state bookkeeping."""

from __future__ import annotations

from typing import Optional

from ..db import Database, utc_now
from .constants import WORKER_ROLE_PRESETS


def create_worker(
    db: Database,
    name: str,
    system_prompt: str,
    room_id: Optional[int] = None,
    role: Optional[str] = None,
    model: Optional[str] = None,
    cycle_gap_ms: Optional[int] = None,
    max_turns: Optional[int] = None,
    description: Optional[str] = None,
) -> int:
    preset = WORKER_ROLE_PRESETS.get(role or "")
    if preset is not None:
        if cycle_gap_ms is None:
            cycle_gap_ms = preset.cycle_gap_ms
        if max_turns is None:
            max_turns = preset.max_turns
        if preset.prompt_prefix not in system_prompt:
            system_prompt = preset.prompt_prefix + "\n\n" + system_prompt
    return db.insert(
        "INSERT INTO workers(name, role, system_prompt, description, model, "
        "room_id, cycle_gap_ms, max_turns) VALUES (?,?,?,?,?,?,?,?)",
        (
            name, role, system_prompt, description, model, room_id,
            cycle_gap_ms, max_turns,
        ),
    )


def get_worker(db: Database, worker_id: int) -> Optional[dict]:
    return db.query_one("SELECT * FROM workers WHERE id=?", (worker_id,))


def list_room_workers(db: Database, room_id: int) -> list[dict]:
    return db.query(
        "SELECT * FROM workers WHERE room_id=? ORDER BY id", (room_id,)
    )


def update_worker(db: Database, worker_id: int, **fields) -> None:
    allowed = {
        "name", "role", "system_prompt", "description", "model",
        "cycle_gap_ms", "max_turns", "agent_state", "wip",
    }
    cols = {k: v for k, v in fields.items() if k in allowed}
    if not cols:
        return
    assignments = ", ".join(f"{k}=?" for k in cols)
    db.execute(
        f"UPDATE workers SET {assignments}, updated_at=? WHERE id=?",
        (*cols.values(), utc_now(), worker_id),
    )


def delete_worker(db: Database, worker_id: int) -> bool:
    return db.execute(
        "DELETE FROM workers WHERE id=?", (worker_id,)
    ).rowcount > 0


def set_agent_state(db: Database, worker_id: int, state: str) -> None:
    db.execute(
        "UPDATE workers SET agent_state=?, updated_at=? WHERE id=?",
        (state, utc_now(), worker_id),
    )


def save_wip(db: Database, worker_id: int, wip: Optional[str]) -> None:
    from .constants import WIP_MAX_CHARS

    if wip is not None:
        wip = wip[:WIP_MAX_CHARS]
    db.execute(
        "UPDATE workers SET wip=?, updated_at=? WHERE id=?",
        (wip, utc_now(), worker_id),
    )
