"""Anonymous telemetry (reference: src/shared/telemetry.ts): machine id
= sha256(hostname+user) prefix; crash reports + daily heartbeats are
dispatched only when an endpoint token is configured at build/deploy
time — disabled entirely otherwise."""

from __future__ import annotations

import getpass
import hashlib
import json
import socket
import threading
import traceback
import urllib.request
from collections import Counter
from typing import Optional

from ..db import Database, utc_now
from ..utils import knobs
from .messages import get_setting, set_setting

# ---- in-process resilience counters (fault injection, degradation,
# provider fallback). Independent of the endpoint-token gate: local
# observability (/api/tpu/health, the TPU panel) reads these whether or
# not remote telemetry is configured; heartbeats attach them when it is.

_counters: Counter = Counter()
_counters_lock = threading.Lock()


def incr_counter(name: str, n: int = 1) -> None:
    with _counters_lock:
        _counters[name] += n


def observe_ms(name: str, ms: float,
               buckets: tuple = (1, 5, 20, 100, 500)) -> None:
    """Cheap latency histogram over the shared counter map: one
    ``<name>.le_<edge>ms`` bucket counter per observation (or
    ``.gt_<last>ms`` past the final edge). Heartbeats and
    /api/tpu/health pick the buckets up with every other counter."""
    for edge in buckets:
        if ms <= edge:
            incr_counter(f"{name}.le_{edge:g}ms")
            return
    incr_counter(f"{name}.gt_{buckets[-1]:g}ms")


def counters_snapshot() -> dict[str, int]:
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    with _counters_lock:
        _counters.clear()


def get_machine_id() -> str:
    seed = socket.gethostname() + ":" + getpass.getuser()
    return hashlib.sha256(seed.encode()).hexdigest()[:12]


def telemetry_enabled() -> bool:
    return bool(knobs.get_str("ROOM_TPU_TELEMETRY_TOKEN"))


def _endpoint() -> Optional[str]:
    return knobs.get_str("ROOM_TPU_TELEMETRY_URL")


def _post(payload: dict) -> bool:
    url = _endpoint()
    if not url or not telemetry_enabled():
        return False
    try:
        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={
                "Content-Type": "application/json",
                "Authorization":
                    f"Bearer {knobs.get_str('ROOM_TPU_TELEMETRY_TOKEN')}",
            },
        )
        with urllib.request.urlopen(req, timeout=10):
            return True
    except OSError:
        return False


def submit_crash_report(
    db: Database, error: BaseException, context: str = ""
) -> bool:
    """Deduped by error signature (one report per signature per day)."""
    if not telemetry_enabled():
        return False
    sig = hashlib.sha256(
        f"{type(error).__name__}:{error}".encode()
    ).hexdigest()[:16]
    key = f"telemetry_crash_{sig}"
    today = utc_now()[:10]
    if (get_setting(db, key) or "")[:10] == today:
        return False
    set_setting(db, key, utc_now())
    return _post({
        "kind": "crash",
        "machine": get_machine_id(),
        "signature": sig,
        "error": f"{type(error).__name__}: {error}",
        "trace": "".join(traceback.format_exception(error))[-4000:],
        "context": context,
    })


def submit_heartbeat(db: Database) -> bool:
    if not telemetry_enabled():
        return False
    today = utc_now()[:10]
    if (get_setting(db, "telemetry_heartbeat") or "")[:10] == today:
        return False
    set_setting(db, "telemetry_heartbeat", utc_now())
    rooms = db.query_one("SELECT COUNT(*) AS n FROM rooms")
    return _post({
        "kind": "heartbeat",
        "machine": get_machine_id(),
        "rooms": rooms["n"] if rooms else 0,
        "counters": counters_snapshot(),
    })
