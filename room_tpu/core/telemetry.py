"""Anonymous telemetry (reference: src/shared/telemetry.ts): machine id
= sha256(hostname+user) prefix; crash reports + daily heartbeats are
dispatched only when an endpoint token is configured at build/deploy
time — disabled entirely otherwise."""

from __future__ import annotations

import getpass
import hashlib
import json
import socket
import threading
import traceback
import urllib.request
from collections import Counter
from typing import Optional

from ..db import Database, utc_now
from ..utils import knobs, locks
from .messages import get_setting, set_setting

# ---- in-process resilience counters (fault injection, degradation,
# provider fallback). Independent of the endpoint-token gate: local
# observability (/api/tpu/health, the TPU panel, /metrics) reads these
# whether or not remote telemetry is configured; heartbeats attach them
# when it is.

_counters: Counter = Counter()
_counters_lock = locks.make_lock("telemetry")

# fixed latency histograms (Prometheus semantics): per-bin counts
# internally, CUMULATIVE `le` counts + _count/_sum at exposition.
# Buckets are fixed at a histogram's first observation — mixed-bucket
# observations against one name would corrupt the percentile math, so
# they raise.
DEFAULT_MS_BUCKETS = (1.0, 5.0, 20.0, 100.0, 500.0)


class _Hist:
    __slots__ = ("buckets", "bins", "count", "sum")

    def __init__(self, buckets: tuple) -> None:
        self.buckets = tuple(float(b) for b in buckets)
        self.bins = [0] * (len(self.buckets) + 1)   # last = +Inf
        self.count = 0
        self.sum = 0.0


_hists: dict[str, _Hist] = {}


def incr_counter(name: str, n: int = 1) -> None:
    with _counters_lock:
        _counters[name] += n


def observe_ms(name: str, ms: float,
               buckets: tuple = DEFAULT_MS_BUCKETS) -> None:
    """Record one latency observation into the named fixed-bucket
    histogram. Exposition (``histograms_snapshot`` / the /metrics
    endpoint) is Prometheus-cumulative: each ``le`` bucket counts
    every observation <= its edge, closed by ``_count``/``_sum`` —
    NOT the old one-bucket-per-observation counters, whose
    non-cumulative counts made downstream percentile math wrong."""
    with _counters_lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = _Hist(buckets)
        elif h.buckets != tuple(float(b) for b in buckets):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}, got {buckets}"
            )
        for i, edge in enumerate(h.buckets):
            if ms <= edge:
                h.bins[i] += 1
                break
        else:
            h.bins[-1] += 1
        h.count += 1
        h.sum += ms


def counters_snapshot() -> dict[str, int]:
    with _counters_lock:
        return dict(_counters)


def histograms_snapshot() -> dict[str, dict]:
    """Cumulative (``le``-semantics) view of every histogram:
    ``buckets`` are the finite edges, ``cumulative`` the running
    counts per edge (the +Inf bucket equals ``count``)."""
    with _counters_lock:
        out = {}
        for name, h in _hists.items():
            cum = []
            running = 0
            for n in h.bins[:-1]:
                running += n
                cum.append(running)
            out[name] = {
                "buckets": list(h.buckets),
                "cumulative": cum,
                "count": h.count,
                "sum": round(h.sum, 6),
            }
        return out


def reset_counters() -> None:
    with _counters_lock:
        _counters.clear()
        _hists.clear()


def get_machine_id() -> str:
    seed = socket.gethostname() + ":" + getpass.getuser()
    return hashlib.sha256(seed.encode()).hexdigest()[:12]


def telemetry_enabled() -> bool:
    return bool(knobs.get_str("ROOM_TPU_TELEMETRY_TOKEN"))


def _endpoint() -> Optional[str]:
    return knobs.get_str("ROOM_TPU_TELEMETRY_URL")


def _post(payload: dict) -> bool:
    url = _endpoint()
    if not url or not telemetry_enabled():
        return False
    try:
        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={
                "Content-Type": "application/json",
                "Authorization":
                    f"Bearer {knobs.get_str('ROOM_TPU_TELEMETRY_TOKEN')}",
            },
        )
        with urllib.request.urlopen(req, timeout=10):
            return True
    except OSError:
        return False


def _flight_recorder_evidence(limit: int = 8) -> list:
    """Recent SLO-violating / faulted turn traces for crash reports —
    resolved through sys.modules (the db-layer faults pattern) so
    telemetry never drags the serving stack in; a process that never
    imported it simply attaches nothing."""
    import sys

    mod = sys.modules.get("room_tpu.serving.trace")
    if mod is None:
        return []
    try:
        return mod.recorder.snapshot(limit=limit)["violations"]
    except Exception:
        return []


def _active_chaos_schedule() -> Optional[dict]:
    """{id, seed, workload} of the fuzz schedule running when we
    crashed, via sys.modules (same pattern as the flight recorder) —
    None outside a fuzz run or when chaos was never imported."""
    import sys

    mod = sys.modules.get("room_tpu.chaos.fuzz")
    if mod is None:
        return None
    try:
        return mod.active_schedule_info()
    except Exception:
        return None


def submit_crash_report(
    db: Database, error: BaseException, context: str = ""
) -> bool:
    """Deduped by error signature (one report per signature per day)."""
    if not telemetry_enabled():
        return False
    sig = hashlib.sha256(
        f"{type(error).__name__}:{error}".encode()
    ).hexdigest()[:16]
    key = f"telemetry_crash_{sig}"
    today = utc_now()[:10]
    if (get_setting(db, key) or "")[:10] == today:
        return False
    set_setting(db, key, utc_now())
    return _post({
        "kind": "crash",
        "machine": get_machine_id(),
        "signature": sig,
        "error": f"{type(error).__name__}: {error}",
        "trace": "".join(traceback.format_exception(error))[-4000:],
        "context": context,
        # flight-recorder evidence (docs/observability.md): the turn
        # traces that were violating SLOs or faulting when we died
        "turn_traces": _flight_recorder_evidence(),
        # chaosfuzz reproducer (docs/chaosfuzz.md): when the crash
        # happened under a fuzz schedule, its id + seed make the
        # report replayable (--replay)
        "chaos_schedule": _active_chaos_schedule(),
    })


def submit_heartbeat(db: Database) -> bool:
    if not telemetry_enabled():
        return False
    today = utc_now()[:10]
    if (get_setting(db, "telemetry_heartbeat") or "")[:10] == today:
        return False
    set_setting(db, "telemetry_heartbeat", utc_now())
    rooms = db.query_one("SELECT COUNT(*) AS n FROM rooms")
    return _post({
        "kind": "heartbeat",
        "machine": get_machine_id(),
        "rooms": rooms["n"] if rooms else 0,
        "counters": counters_snapshot(),
        "histograms": histograms_snapshot(),
    })
