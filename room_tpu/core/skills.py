"""Reusable skills with versioning + context loader caps (reference:
src/shared/skills.ts — max 8 skills / 6,000 chars injected)."""

from __future__ import annotations

from typing import Optional

from ..db import Database, utc_now
from .constants import SKILLS_CONTEXT_MAX, SKILLS_CONTEXT_MAX_CHARS


def create_skill(
    db: Database,
    name: str,
    content: str,
    room_id: Optional[int] = None,
    activation_context: Optional[str] = None,
    auto_activate: bool = False,
    agent_created: bool = False,
    created_by_worker_id: Optional[int] = None,
) -> int:
    return db.insert(
        "INSERT INTO skills(room_id, name, content, activation_context, "
        "auto_activate, agent_created, created_by_worker_id) "
        "VALUES (?,?,?,?,?,?,?)",
        (
            room_id, name, content, activation_context,
            int(auto_activate), int(agent_created), created_by_worker_id,
        ),
    )


def get_skill(db: Database, skill_id: int) -> Optional[dict]:
    return db.query_one("SELECT * FROM skills WHERE id=?", (skill_id,))


def list_skills(db: Database, room_id: Optional[int] = None) -> list[dict]:
    if room_id is None:
        return db.query("SELECT * FROM skills ORDER BY id")
    return db.query(
        "SELECT * FROM skills WHERE room_id=? OR room_id IS NULL ORDER BY id",
        (room_id,),
    )


def update_skill(db: Database, skill_id: int, content: str) -> None:
    db.execute(
        "UPDATE skills SET content=?, version=version+1, updated_at=? "
        "WHERE id=?",
        (content, utc_now(), skill_id),
    )


def delete_skill(db: Database, skill_id: int) -> bool:
    return db.execute(
        "DELETE FROM skills WHERE id=?", (skill_id,)
    ).rowcount > 0


def load_skills_for_agent(
    db: Database, room_id: Optional[int], context_hint: str = ""
) -> str:
    """Auto-activating skills rendered for the cycle prompt, capped at 8
    skills / 6,000 chars. Skills with an activation_context are included
    only when the hint mentions it."""
    skills = [
        s for s in list_skills(db, room_id) if s["auto_activate"]
    ]
    hint = context_hint.lower()
    chosen = []
    for s in skills:
        ctx = (s["activation_context"] or "").lower()
        if ctx and ctx not in hint:
            continue
        chosen.append(s)
        if len(chosen) >= SKILLS_CONTEXT_MAX:
            break
    out: list[str] = []
    used = 0
    for s in chosen:
        block = f"## Skill: {s['name']} (v{s['version']})\n{s['content']}\n"
        if used + len(block) > SKILLS_CONTEXT_MAX_CHARS:
            break
        out.append(block)
        used += len(block)
    return "\n".join(out)
