"""Room lifecycle (reference: src/shared/room.ts).

create_room builds the full collective in one transaction: the room row,
its queen worker, the root goal, and the room wallet."""

from __future__ import annotations

import json
import secrets
from typing import Optional

from ..db import Database, utc_now
from .constants import (
    DEFAULT_QUEEN_PROMPT,
    QUEEN_CYCLE_GAP_MS_DEFAULT,
    QUEEN_MAX_TURNS_DEFAULT,
    RoomConfig,
)


def room_config(room: dict) -> RoomConfig:
    raw = room.get("config")
    return RoomConfig.from_json(json.loads(raw) if raw else None)


def create_room(
    db: Database,
    name: str,
    goal: Optional[str] = None,
    worker_model: str = "tpu",
    queen_model: Optional[str] = None,
    queen_cycle_gap_ms: int = QUEEN_CYCLE_GAP_MS_DEFAULT,
    config: Optional[RoomConfig] = None,
    create_wallet: bool = True,
    room_id: Optional[int] = None,
) -> dict:
    """Create room + queen + root goal (+ wallet). Returns the room row.

    ``room_id`` pins an explicit id instead of the file's AUTOINCREMENT
    sequence — the swarm shard router allocates ids from a swarm-global
    counter so a room's id (and hence its placement hash) is unique
    across every shard file (docs/swarmshard.md)."""
    from . import goals as goals_mod
    from . import wallet as wallet_mod
    from .workers import create_worker

    with db.transaction():
        if room_id is None:
            room_id = db.insert(
                "INSERT INTO rooms(name, goal, worker_model, "
                "queen_cycle_gap_ms, queen_max_turns, config, "
                "webhook_token) VALUES (?,?,?,?,?,?,?)",
                (
                    name, goal, worker_model, queen_cycle_gap_ms,
                    QUEEN_MAX_TURNS_DEFAULT,
                    json.dumps((config or RoomConfig()).to_json()),
                    secrets.token_urlsafe(24),
                ),
            )
        else:
            db.insert(
                "INSERT INTO rooms(id, name, goal, worker_model, "
                "queen_cycle_gap_ms, queen_max_turns, config, "
                "webhook_token) VALUES (?,?,?,?,?,?,?,?)",
                (
                    room_id, name, goal, worker_model,
                    queen_cycle_gap_ms, QUEEN_MAX_TURNS_DEFAULT,
                    json.dumps((config or RoomConfig()).to_json()),
                    secrets.token_urlsafe(24),
                ),
            )
        queen_id = create_worker(
            db,
            name=f"{name} Queen",
            system_prompt=DEFAULT_QUEEN_PROMPT,
            room_id=room_id,
            role="queen",
            model=queen_model or worker_model,
            cycle_gap_ms=queen_cycle_gap_ms,
            max_turns=QUEEN_MAX_TURNS_DEFAULT,
        )
        db.execute(
            "UPDATE rooms SET queen_worker_id=? WHERE id=?",
            (queen_id, room_id),
        )
        # workers.is_default mirrors queen_worker_id so list consumers
        # (dashboard swarm cards/graph, MCP worker_list) can spot the
        # queen without a rooms join
        db.execute(
            "UPDATE workers SET is_default=1 WHERE id=?", (queen_id,)
        )
        if goal:
            goals_mod.set_room_objective(db, room_id, goal)
        if create_wallet:
            wallet_mod.create_room_wallet(db, room_id)
    return get_room(db, room_id)  # type: ignore[return-value]


def get_room(db: Database, room_id: int) -> Optional[dict]:
    return db.query_one("SELECT * FROM rooms WHERE id=?", (room_id,))


def list_rooms(db: Database, status: Optional[str] = None) -> list[dict]:
    if status is None:
        return db.query("SELECT * FROM rooms ORDER BY id")
    return db.query(
        "SELECT * FROM rooms WHERE status=? ORDER BY id", (status,)
    )


def update_room(db: Database, room_id: int, **fields) -> None:
    allowed = {
        "name", "goal", "status", "visibility", "autonomy_mode",
        "max_concurrent_tasks", "worker_model", "queen_cycle_gap_ms",
        "queen_max_turns", "queen_quiet_from", "queen_quiet_until",
        "config", "queen_nickname", "allowed_tools",
    }
    cols = {k: v for k, v in fields.items() if k in allowed}
    if not cols:
        return
    assignments = ", ".join(f"{k}=?" for k in cols)
    db.execute(
        f"UPDATE rooms SET {assignments}, updated_at=? WHERE id=?",
        (*cols.values(), utc_now(), room_id),
    )


def pause_room(db: Database, room_id: int) -> None:
    update_room(db, room_id, status="paused")


def restart_room(db: Database, room_id: int) -> None:
    update_room(db, room_id, status="active")


def delete_room(db: Database, room_id: int) -> bool:
    """Deletes the room and everything cascading from it; the queen worker
    row is removed explicitly (workers have no FK to rooms)."""
    with db.transaction():
        db.execute("DELETE FROM workers WHERE room_id=?", (room_id,))
        return db.execute(
            "DELETE FROM rooms WHERE id=?", (room_id,)
        ).rowcount > 0


def get_room_status(db: Database, room_id: int) -> Optional[dict]:
    """Aggregate dashboard view (reference: room.ts getRoomStatus)."""
    room = get_room(db, room_id)
    if room is None:
        return None
    workers = db.query(
        "SELECT COUNT(*) AS n FROM workers WHERE room_id=?", (room_id,)
    )[0]["n"]
    goals_active = db.query(
        "SELECT COUNT(*) AS n FROM goals WHERE room_id=? AND status='active'",
        (room_id,),
    )[0]["n"]
    decisions_open = db.query(
        "SELECT COUNT(*) AS n FROM quorum_decisions WHERE room_id=? "
        "AND status IN ('announced','voting')",
        (room_id,),
    )[0]["n"]
    escalations_pending = db.query(
        "SELECT COUNT(*) AS n FROM escalations WHERE room_id=? "
        "AND status='pending'",
        (room_id,),
    )[0]["n"]
    unread_messages = db.query(
        "SELECT COUNT(*) AS n FROM room_messages WHERE room_id=? "
        "AND direction='inbound' AND status='unread'",
        (room_id,),
    )[0]["n"]
    tasks_active = db.query(
        "SELECT COUNT(*) AS n FROM tasks WHERE room_id=? AND status='active'",
        (room_id,),
    )[0]["n"]
    return {
        "room": room,
        "worker_count": workers,
        "active_goals": goals_active,
        "open_decisions": decisions_open,
        "pending_escalations": escalations_pending,
        "unread_messages": unread_messages,
        "active_tasks": tasks_active,
    }
