"""Semantic memory: entity/observation/relation graph + hybrid recall.

Behavioral parity with the reference memory model (reference:
src/shared/schema.ts:69-130, src/shared/db-queries.ts:927-1059): entities
carry observations and typed relations; full-text search runs over an FTS5
mirror; semantic search runs over stored embedding vectors; hybrid recall
merges both rankings with reciprocal-rank fusion (k=60, weights 0.4 FTS /
0.6 semantic).

TPU-first difference: vectors are stored as float32 blobs for durability,
but ranking happens over an in-process matrix (numpy on host; the serving
engine mirrors the same matrix on-device and ranks with one dot + top_k on
the mesh — see room_tpu.serving.embed_index).
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, Optional, Sequence

import numpy as np

from ..db import Database, utc_now

RRF_K = 60
FTS_WEIGHT = 0.4
SEMANTIC_WEIGHT = 0.6
EMBED_DIM = 384
EMBED_MODEL = "tpu-embed-384"


# ---- entity graph ----

def create_entity(
    db: Database,
    name: str,
    type_: str = "fact",
    category: Optional[str] = None,
    room_id: Optional[int] = None,
) -> int:
    return db.insert(
        "INSERT INTO entities(name, type, category, room_id) VALUES (?,?,?,?)",
        (name, type_, category, room_id),
    )


def get_entity(db: Database, entity_id: int) -> Optional[dict]:
    return db.query_one("SELECT * FROM entities WHERE id=?", (entity_id,))


def find_entity(
    db: Database, name: str, room_id: Optional[int] = None
) -> Optional[dict]:
    if room_id is None:
        return db.query_one("SELECT * FROM entities WHERE name=?", (name,))
    return db.query_one(
        "SELECT * FROM entities WHERE name=? AND room_id=?", (name, room_id)
    )


def delete_entity(db: Database, entity_id: int) -> bool:
    return db.execute(
        "DELETE FROM entities WHERE id=?", (entity_id,)
    ).rowcount > 0


def add_observation(
    db: Database, entity_id: int, content: str, source: str = "agent"
) -> int:
    oid = db.insert(
        "INSERT INTO observations(entity_id, content, source) VALUES (?,?,?)",
        (entity_id, content, source),
    )
    db.execute(
        "UPDATE entities SET updated_at=?, embedded_at=NULL WHERE id=?",
        (utc_now(), entity_id),
    )
    return oid


def get_observations(
    db: Database, entity_id: int,
    newest_first: bool = False, limit: Optional[int] = None,
) -> list[dict]:
    order = "DESC" if newest_first else "ASC"
    sql = f"SELECT * FROM observations WHERE entity_id=? ORDER BY id {order}"
    if limit is not None:
        return db.query(sql + " LIMIT ?", (entity_id, limit))
    return db.query(sql, (entity_id,))


def create_relation(
    db: Database, from_entity: int, to_entity: int, relation_type: str
) -> int:
    return db.insert(
        "INSERT INTO relations(from_entity, to_entity, relation_type) "
        "VALUES (?,?,?)",
        (from_entity, to_entity, relation_type),
    )


def get_relations(db: Database, entity_id: int) -> list[dict]:
    return db.query(
        "SELECT * FROM relations WHERE from_entity=? OR to_entity=?",
        (entity_id, entity_id),
    )


def remember(
    db: Database,
    name: str,
    content: str,
    category: Optional[str] = None,
    room_id: Optional[int] = None,
    source: str = "agent",
) -> int:
    """Upsert-style memory write: find-or-create the entity, then append
    the observation."""
    existing = find_entity(db, name, room_id)
    eid = existing["id"] if existing else create_entity(
        db, name, "fact", category, room_id
    )
    add_observation(db, eid, content, source)
    return eid


# ---- embeddings store ----

def vector_to_blob(vec: Sequence[float]) -> bytes:
    return np.asarray(vec, dtype=np.float32).tobytes()


def blob_to_vector(blob: bytes) -> np.ndarray:
    return np.frombuffer(blob, dtype=np.float32)


def text_hash(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()[:32]


def store_embedding(
    db: Database,
    entity_id: int,
    text: str,
    vector: Sequence[float],
    source_type: str = "entity",
    source_id: Optional[int] = None,
    model: str = EMBED_MODEL,
) -> int:
    vec = np.asarray(vector, dtype=np.float32)
    sid = source_id if source_id is not None else entity_id
    db.execute(
        "INSERT INTO embeddings"
        "(entity_id, source_type, source_id, text_hash, vector, model, dim) "
        "VALUES (?,?,?,?,?,?,?) "
        "ON CONFLICT(source_type, source_id, model) DO UPDATE SET "
        "vector=excluded.vector, text_hash=excluded.text_hash, "
        "entity_id=excluded.entity_id",
        (
            entity_id,
            source_type,
            sid,
            text_hash(text),
            vec.tobytes(),
            model,
            int(vec.shape[0]),
        ),
    )
    db.execute(
        "UPDATE entities SET embedded_at=? WHERE id=?", (utc_now(), entity_id)
    )
    row = db.query_one(
        "SELECT id FROM embeddings WHERE source_type=? AND source_id=? "
        "AND model=?",
        (source_type, sid, model),
    )
    return int(row["id"])  # upserts can't trust lastrowid


def embedding_matrix(
    db: Database, room_id: Optional[int] = None, model: str = EMBED_MODEL
) -> tuple[np.ndarray, list[int]]:
    """All stored vectors as an (N, D) float32 matrix + parallel entity ids.

    Room-scoped recall includes global (room-less) memories, matching the
    reference's scoping.
    """
    if room_id is None:
        rows = db.query(
            "SELECT e.entity_id AS eid, e.vector FROM embeddings e "
            "WHERE e.model=? ORDER BY e.id",
            (model,),
        )
    else:
        rows = db.query(
            "SELECT e.entity_id AS eid, e.vector FROM embeddings e "
            "JOIN entities t ON t.id = e.entity_id "
            "WHERE e.model=? AND (t.room_id=? OR t.room_id IS NULL) "
            "ORDER BY e.id",
            (model, room_id),
        )
    if not rows:
        return np.zeros((0, EMBED_DIM), dtype=np.float32), []
    mat = np.stack([blob_to_vector(r["vector"]) for r in rows])
    return mat, [r["eid"] for r in rows]


# ---- search ----

def sanitize_fts_query(query: str) -> str:
    """Turn arbitrary user text into a safe FTS5 MATCH expression: bare
    terms OR'd together, quoted to disarm operators."""
    terms = re.findall(r"[\w]+", query, flags=re.UNICODE)
    if not terms:
        return '""'
    return " OR ".join(f'"{t}"' for t in terms[:16])


def fts_search(
    db: Database,
    query: str,
    limit: int = 20,
    room_id: Optional[int] = None,
) -> list[dict]:
    """BM25-ranked full-text hits: [{entity_id, score, name}] best-first."""
    match = sanitize_fts_query(query)
    if room_id is None:
        rows = db.query(
            "SELECT f.entity_id, f.name, bm25(memory_fts) AS rank "
            "FROM memory_fts f WHERE memory_fts MATCH ? "
            "ORDER BY rank LIMIT ?",
            (match, limit),
        )
    else:
        rows = db.query(
            "SELECT f.entity_id, f.name, bm25(memory_fts) AS rank "
            "FROM memory_fts f JOIN entities t ON t.id = f.entity_id "
            "WHERE memory_fts MATCH ? AND (t.room_id=? OR t.room_id IS NULL) "
            "ORDER BY rank LIMIT ?",
            (match, room_id, limit),
        )
    return [
        {"entity_id": r["entity_id"], "name": r["name"], "score": -r["rank"]}
        for r in rows
    ]


def semantic_search(
    db: Database,
    query_vector: Sequence[float],
    limit: int = 20,
    room_id: Optional[int] = None,
) -> list[dict]:
    """Cosine-ranked semantic hits over the stored embedding matrix."""
    mat, eids = embedding_matrix(db, room_id)
    if not eids:
        return []
    from ..utils.native import topk_cosine

    idx, scores = topk_cosine(
        mat, np.asarray(query_vector, dtype=np.float32), limit
    )
    return [
        {"entity_id": eids[int(i)], "score": float(s)}
        for i, s in zip(idx, scores)
    ]


def hybrid_search(
    db: Database,
    query: str,
    query_vector: Optional[Sequence[float]] = None,
    limit: int = 5,
    room_id: Optional[int] = None,
) -> list[dict]:
    """Reciprocal-rank fusion of FTS and semantic rankings (reference:
    src/shared/db-queries.ts:1021-1059 — RRF k=60, 0.4 FTS / 0.6 semantic).

    Falls back to pure FTS when no query vector is supplied (embedder
    offline)."""
    fts_hits = fts_search(db, query, limit=20, room_id=room_id)
    sem_hits = (
        semantic_search(db, query_vector, limit=20, room_id=room_id)
        if query_vector is not None
        else []
    )
    scores: dict[int, float] = {}
    for rank, hit in enumerate(fts_hits):
        scores[hit["entity_id"]] = scores.get(hit["entity_id"], 0.0) + (
            FTS_WEIGHT / (RRF_K + rank + 1)
        )
    for rank, hit in enumerate(sem_hits):
        scores[hit["entity_id"]] = scores.get(hit["entity_id"], 0.0) + (
            SEMANTIC_WEIGHT / (RRF_K + rank + 1)
        )
    ranked = sorted(scores.items(), key=lambda kv: -kv[1])[:limit]
    out = []
    for eid, score in ranked:
        ent = get_entity(db, eid)
        if ent is None:
            continue
        obs = get_observations(db, eid)
        out.append(
            {
                "entity_id": eid,
                "name": ent["name"],
                "category": ent["category"],
                "score": score,
                "observations": [o["content"] for o in obs[-5:]],
            }
        )
    return out


def entities_needing_embedding(db: Database, limit: int = 10) -> list[dict]:
    """Background-indexer work queue: entities whose content changed since
    they were last embedded (reference: src/shared/embedding-indexer.ts)."""
    return db.query(
        "SELECT * FROM entities WHERE embedded_at IS NULL "
        "ORDER BY updated_at LIMIT ?",
        (limit,),
    )


def embedding_text_for_entity(db: Database, entity: dict) -> str:
    """Entity name + its most recent 5 observations, the same digest the
    reference embeds (src/shared/embedding-indexer.ts:7-61)."""
    obs = get_observations(db, entity["id"])[-5:]
    parts = [entity["name"]] + [o["content"] for o in obs]
    return "\n".join(parts)
