"""File watches with a real polling runtime.

The reference stored watches and validated paths but never actually
watched anything (SURVEY.md: markWatchTriggered never called — vestigial
trigger path). Here the runtime polls registered paths and fires the
watch's action prompt as a one-time task when content changes.

Path safety mirrors the reference (src/shared/watch-path.ts): home/tmp
only, sensitive directories denied, symlinks resolved before checking."""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Optional

from ..db import Database, utc_now
from ..utils import knobs

DENIED_PARTS = {
    ".ssh", ".aws", ".gnupg", ".gpg", ".keychain", ".password-store",
    ".config/gcloud", ".kube", ".docker", ".netrc",
}


def validate_watch_path(path: str) -> Optional[str]:
    """Returns an error message, or None when the path is watchable."""
    real = os.path.realpath(os.path.expanduser(path))
    home = os.path.realpath(os.path.expanduser("~"))
    tmp = os.path.realpath("/tmp")
    data_dir = os.path.realpath(
        os.path.expanduser(knobs.get_str("ROOM_TPU_DATA_DIR"))
    )
    if not (
        real == home or real.startswith(home + os.sep)
        or real.startswith(tmp + os.sep)
        or real.startswith(data_dir + os.sep)
    ):
        return f"path {path!r} is outside the home/tmp sandbox"
    rel = real[len(home):] if real.startswith(home) else real
    # normalize to /-separated with sentinels so both single components
    # (".ssh") and nested entries (".config/gcloud") match anywhere on
    # the path, including files inside them
    hay = "/" + "/".join(p for p in rel.split(os.sep) if p) + "/"
    for denied in DENIED_PARTS:
        if f"/{denied}/" in hay:
            return f"path {path!r} touches a protected directory"
    return None


def create_watch(
    db: Database,
    path: str,
    action_prompt: str,
    description: Optional[str] = None,
    room_id: Optional[int] = None,
) -> int:
    err = validate_watch_path(path)
    if err:
        raise ValueError(err)
    return db.insert(
        "INSERT INTO watches(path, description, action_prompt, room_id) "
        "VALUES (?,?,?,?)",
        (os.path.realpath(os.path.expanduser(path)), description,
         action_prompt, room_id),
    )


def list_watches(db: Database, room_id: Optional[int] = None) -> list[dict]:
    if room_id is None:
        return db.query("SELECT * FROM watches ORDER BY id")
    return db.query(
        "SELECT * FROM watches WHERE room_id=? ORDER BY id", (room_id,)
    )


def delete_watch(db: Database, watch_id: int) -> bool:
    return db.execute(
        "DELETE FROM watches WHERE id=?", (watch_id,)
    ).rowcount > 0


def _fingerprint(path: str) -> Optional[str]:
    """Cheap change detector: mtime+size for files, listing hash for
    directories."""
    try:
        if os.path.isdir(path):
            entries = sorted(os.listdir(path))[:500]
            seed = "|".join(entries)
        else:
            st = os.stat(path)
            seed = f"{st.st_mtime_ns}:{st.st_size}"
    except OSError:
        return None
    return hashlib.sha256(seed.encode()).hexdigest()[:16]


class WatchRuntime:
    """Polls active watches; on change, fires the action prompt as a
    one-time task for the watch's room."""

    def __init__(self, db: Database, interval_s: float = 10.0) -> None:
        self.db = db
        self.interval_s = interval_s
        self.stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fingerprints: dict[int, Optional[str]] = {}

    def poll_once(self) -> int:
        """Returns how many watches fired."""
        fired = 0
        for w in self.db.query(
            "SELECT * FROM watches WHERE status='active'"
        ):
            fp = _fingerprint(w["path"])
            if fp is None:
                # transient stat failure: keep the old fingerprint so
                # the next successful poll doesn't false-fire
                continue
            prev = self._fingerprints.get(w["id"], "__first__")
            self._fingerprints[w["id"]] = fp
            if prev == "__first__" or fp == prev:
                continue
            self._trigger(w)
            fired += 1
        return fired

    def _trigger(self, watch: dict) -> None:
        from .task_runner import create_task

        self.db.execute(
            "UPDATE watches SET last_triggered=?, "
            "trigger_count=trigger_count+1 WHERE id=?",
            (utc_now(), watch["id"]),
        )
        if watch["action_prompt"]:
            create_task(
                self.db,
                name=f"watch: {os.path.basename(watch['path'])}",
                prompt=(
                    f"The watched path {watch['path']} changed.\n"
                    f"{watch['action_prompt']}"
                ),
                trigger_type="once",
                scheduled_at=utc_now(),
                room_id=watch["room_id"],
            )

    def start(self) -> None:
        def loop():
            while not self.stop_event.wait(timeout=self.interval_s):
                try:
                    self.poll_once()
                except Exception:
                    pass

        self._thread = threading.Thread(
            target=loop, daemon=True, name="watch-runtime"
        )
        self._thread.start()

    def stop(self) -> None:
        self.stop_event.set()
        if self._thread:
            self._thread.join(timeout=5)
