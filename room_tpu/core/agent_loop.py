"""Per-worker agent loop: observe → prompt → execute → persist.

Behavioral parity with the reference loop (reference:
src/shared/agent-loop.ts): quiet hours (:30-51), WIP momentum gap
(:204-217), rate-limit wait state (:166-190), stuck detector (:605-617),
session rotation after 20 cycles (:462-493), history compression at 30
messages (:495-532), auto-created executor for a worker-less queen
(:414-449), auto-WIP fallback (:855-863), and the §3.2 prompt assembly
order — re-built on Python threads with the tpu: provider as the default
execution path.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from datetime import datetime
from typing import Optional

from ..db import Database, utc_now
from ..utils import knobs, locks
from ..providers import (
    ExecutionRequest, RateLimitExceeded, get_model_provider,
)
from . import (
    escalations as escalations_mod,
    goals as goals_mod,
    journal as journal_mod,
    memory as memory_mod,
    messages as messages_mod,
    quorum as quorum_mod,
    rooms as rooms_mod,
    skills as skills_mod,
    workers as workers_mod,
)
from .constants import (
    API_HISTORY_COMPRESS_AT,
    API_HISTORY_TRIM_AT,
    CLI_SESSION_ROTATE_CYCLES,
    MEMORY_RECALL_TOP_K,
)
from .cycle_logs import CycleLogBuffer
from .events import event_bus
from .queen_tools import (
    QUEEN_TOOLS, WORKER_TOOLS, execute_queen_tool,
)
from .rate_limit import clamp_wait

WIP_MOMENTUM_GAP_S = 10.0
STUCK_CYCLE_WINDOW = 5
CYCLE_ERROR_GAP_S = 30.0  # backoff after an unexpected cycle error

# Loop-thread supervision (docs/swarm_recovery.md), mirroring the
# engine's crash budget: a dead/hung loop is restarted until more than
# LOOP_RESTART_BUDGET strikes land inside LOOP_RESTART_WINDOW_S, then
# the worker is marked unhealthy and keeper-escalated. A loop counts as
# hung when it has been inside one cycle (state == "running") longer
# than LOOP_HANG_S without a heartbeat.
LOOP_RESTART_BUDGET = knobs.get_int("ROOM_TPU_LOOP_MAX_RESTARTS")
LOOP_RESTART_WINDOW_S = knobs.get_float(
    "ROOM_TPU_LOOP_RESTART_WINDOW_S"
)
LOOP_HANG_S = knobs.get_float("ROOM_TPU_LOOP_HANG_S")

# execution-plane tools: fine for workers, a logged deviation when the
# queen runs them herself instead of delegating
QUEEN_DEVIATION_TOOLS = {"web_fetch", "web_search"}


@dataclass
class LoopHandle:
    worker_id: int
    room_id: int
    thread: Optional[threading.Thread] = None
    stop: threading.Event = field(default_factory=threading.Event)
    wake: threading.Event = field(default_factory=threading.Event)
    state: str = "idle"
    # supervision telemetry: last iteration heartbeat (monotonic), the
    # deadline by which the loop promises its next heartbeat (stalls
    # ANYWHERE in the iteration — db fetch, cycle, state write — blow
    # past it; sleeps extend it by their own duration first), and the
    # error that killed the thread, if it crashed
    beat: float = field(default_factory=time.monotonic)
    expect_by: float = field(
        default_factory=lambda: time.monotonic() + LOOP_HANG_S
    )
    crash_error: Optional[str] = None
    # the supervision domain this loop is registered in (None = the
    # process default; swarm shards pass their own)
    domain: Optional["LoopDomain"] = None


class LoopDomain:
    """One agent-loop supervision domain: loop registry, room launch
    roster, crash-strike history, unhealthy roster, and the restart
    counters — everything supervise_loops arbitrates over. The classic
    single-runtime process uses the module default; each swarm shard
    (docs/swarmshard.md) owns a private domain, so one shard's crash
    storm, hang replacements, or budget lockouts never bleed into a
    sibling shard's supervision."""

    def __init__(self) -> None:
        self._registry_lock = locks.make_lock("agent_registry")
        self._supervision_lock = locks.make_lock("agent_supervision")
        self.loops: dict[int, LoopHandle] = {}
        self.launched_rooms: set[int] = set()
        self.strikes: dict[int, deque] = {}
        self.unhealthy: dict[int, dict] = {}
        self.counts = {"restarts": 0, "hang_replacements": 0,
                       "crashes": 0, "budget_exhausted": 0}


_DEFAULT_DOMAIN = LoopDomain()

# Back-compat aliases: the default domain's state under the classic
# module-level names. Same objects — mutations through either name are
# seen by both — so pre-domain call sites and tests keep working.
_running_loops = _DEFAULT_DOMAIN.loops
_launched_rooms = _DEFAULT_DOMAIN.launched_rooms
_registry_lock = _DEFAULT_DOMAIN._registry_lock
_supervision_lock = _DEFAULT_DOMAIN._supervision_lock
_strikes = _DEFAULT_DOMAIN.strikes
_unhealthy = _DEFAULT_DOMAIN.unhealthy
_supervision_counts = _DEFAULT_DOMAIN.counts


def _incr(name: str, n: int = 1) -> None:
    from .telemetry import incr_counter

    incr_counter(name, n)


def _owns_registry_entry(handle: LoopHandle) -> bool:
    dom = handle.domain or _DEFAULT_DOMAIN
    with dom._registry_lock:
        return dom.loops.get(handle.worker_id) is handle


# ---- lifecycle ----

def set_room_launch_enabled(
    room_id: int, enabled: bool,
    domain: Optional[LoopDomain] = None,
) -> None:
    dom = domain or _DEFAULT_DOMAIN
    with dom._registry_lock:
        if enabled:
            dom.launched_rooms.add(room_id)
        else:
            dom.launched_rooms.discard(room_id)


def is_room_launched(
    room_id: int, domain: Optional[LoopDomain] = None
) -> bool:
    dom = domain or _DEFAULT_DOMAIN
    with dom._registry_lock:
        return room_id in dom.launched_rooms


def running_workers(domain: Optional[LoopDomain] = None) -> list[int]:
    dom = domain or _DEFAULT_DOMAIN
    with dom._registry_lock:
        return [
            wid for wid, h in dom.loops.items()
            if h.thread is not None and h.thread.is_alive()
        ]


def _locked_out_handle(worker_id: int, room_id: int) -> LoopHandle:
    """Inert handle for a worker past its restart budget: no thread is
    started and nothing is registered — only a keeper room restart
    (reset_supervision) revives the worker."""
    handle = LoopHandle(worker_id=worker_id, room_id=room_id)
    handle.stop.set()
    handle.state = "unhealthy"
    return handle


def start_agent_loop(
    db: Database, room_id: int, worker_id: int,
    domain: Optional[LoopDomain] = None,
) -> LoopHandle:
    dom = domain or _DEFAULT_DOMAIN
    with dom._supervision_lock:
        locked_out = worker_id in dom.unhealthy
    if locked_out:
        return _locked_out_handle(worker_id, room_id)
    with dom._registry_lock:
        existing = dom.loops.get(worker_id)
        if (
            existing
            and existing.thread
            and existing.thread.is_alive()
            and not existing.stop.is_set()
        ):
            existing.wake.set()
            return existing
        crashed_corpse = (
            existing is not None
            and existing.thread is not None
            and not existing.thread.is_alive()
            and not existing.stop.is_set()
        )
    if crashed_corpse:
        # a crashed loop must pass through supervision — journal
        # recovery, strike accounting, the unhealthy lockout — before
        # any replacement runs. Wake paths (inbox poll, webhooks,
        # delegation) used to replace the corpse silently, bypassing
        # all three.
        supervise_loops(db, domain=dom)
        with dom._registry_lock:
            replacement = dom.loops.get(worker_id)
        if replacement is not None:
            return replacement
        with dom._supervision_lock:
            if worker_id in dom.unhealthy:
                return _locked_out_handle(worker_id, room_id)
        # supervision declined to restart (room stopped/gone): fall
        # through and let the normal path re-check the room state
    with dom._registry_lock:
        # re-check under the lock: between the first check and here a
        # concurrent wake path may have registered a live loop (two
        # threads for one worker would cycle unsupervised forever), or
        # supervision may have locked the worker out
        with dom._supervision_lock:
            if worker_id in dom.unhealthy:
                return _locked_out_handle(worker_id, room_id)
        existing = dom.loops.get(worker_id)
        if (
            existing
            and existing.thread
            and existing.thread.is_alive()
            and not existing.stop.is_set()
        ):
            existing.wake.set()
            return existing
        # a stopping handle is as good as dead: replace it (the old
        # thread only deletes the registry entry if it is still its own)
        handle = LoopHandle(worker_id=worker_id, room_id=room_id,
                            domain=dom)
        dom.loops[worker_id] = handle
    handle.thread = threading.Thread(
        target=_loop_main, args=(db, handle), daemon=True,
        name=f"agent-loop-{worker_id}",
    )
    handle.thread.start()
    return handle


def trigger_agent(
    db: Database,
    room_id: int,
    worker_id: int,
    allow_cold_start: bool = False,
    domain: Optional[LoopDomain] = None,
) -> Optional[LoopHandle]:
    """Wake a sleeping loop, or start one (reference: triggerAgent:266)."""
    if allow_cold_start:
        set_room_launch_enabled(room_id, True, domain=domain)
    if not is_room_launched(room_id, domain=domain):
        return None
    return start_agent_loop(db, room_id, worker_id, domain=domain)


def pause_agent(
    worker_id: int, domain: Optional[LoopDomain] = None
) -> bool:
    dom = domain or _DEFAULT_DOMAIN
    with dom._registry_lock:
        handle = dom.loops.get(worker_id)
    if handle is None:
        return False
    handle.stop.set()
    handle.wake.set()
    return True


def stop_worker_loop(
    worker_id: int, domain: Optional[LoopDomain] = None
) -> bool:
    """Stop one worker's loop thread (reference: per-worker stop route
    routes/workers.ts)."""
    dom = domain or _DEFAULT_DOMAIN
    with dom._registry_lock:
        handle = dom.loops.get(worker_id)
    if handle is None:
        return False
    handle.stop.set()
    handle.wake.set()
    return True


def stop_room_loops(
    db: Database, room_id: int, reason: str = "",
    domain: Optional[LoopDomain] = None,
) -> int:
    dom = domain or _DEFAULT_DOMAIN
    set_room_launch_enabled(room_id, False, domain=dom)
    n = 0
    with dom._registry_lock:
        handles = [
            h for h in dom.loops.values() if h.room_id == room_id
        ]
    for h in handles:
        h.stop.set()
        h.wake.set()
        n += 1
    return n


def stop_domain_loops(domain: LoopDomain) -> int:
    """Stop every loop in a domain without touching its launch roster —
    the swarm shard crash path (SwarmRouter.kill_shard): the dead
    shard's threads must die, but the rooms stay launch-enabled so the
    adopter can restart them in its own domain."""
    with domain._registry_lock:
        handles = list(domain.loops.values())
    for h in handles:
        h.stop.set()
        h.wake.set()
    return len(handles)


# ---- loop-thread supervision (docs/swarm_recovery.md) ----

def supervise_loops(
    db: Database, domain: Optional[LoopDomain] = None
) -> dict:
    """Detect dead or hung loop threads and restart them under the
    restart budget; past budget, mark the worker unhealthy and escalate
    to the keeper. Called from the server runtime's supervision tick
    (and directly by chaos tests). Returns a summary of actions taken.

    Mirrors the engine's crash supervision: strikes inside
    LOOP_RESTART_WINDOW_S count against LOOP_RESTART_BUDGET; a budget
    breach is terminal until the keeper restarts the room (which resets
    the budget via reset_supervision)."""
    dom = domain or _DEFAULT_DOMAIN
    actions = {"restarted": [], "replaced_hung": [], "unhealthy": []}
    now = time.monotonic()
    with dom._registry_lock:
        snapshot = list(dom.loops.values())
    for h in snapshot:
        if h.thread is None:
            continue
        dead = not h.thread.is_alive()
        # a loop is hung when it blew past its own promised-heartbeat
        # deadline — covers stalls anywhere in the iteration (db fetch,
        # cycle, state write), not just inside run_cycle; sleeping
        # loops extend expect_by before waiting, so they never trip it
        hung = (
            not dead
            and not h.stop.is_set()
            and now > h.expect_by
        )
        if h.stop.is_set():
            if dead:
                # crashed mid-shutdown: just drop the stale entry
                with dom._registry_lock:
                    if dom.loops.get(h.worker_id) is h:
                        del dom.loops[h.worker_id]
            continue
        if not dead and not hung:
            continue

        # a dead-or-hung loop whose room is gone/stopped needs no
        # restart — clear the corpse and move on
        try:
            worker = workers_mod.get_worker(db, h.worker_id)
            room = rooms_mod.get_room(db, h.room_id)
        except Exception:
            continue  # db unavailable; retry next tick
        with dom._registry_lock:
            # claim the corpse exactly once: the supervision tick and a
            # wake-path start_agent_loop may both be supervising
            already_claimed = h.stop.is_set()
            h.stop.set()
            if dom.loops.get(h.worker_id) is h:
                del dom.loops[h.worker_id]
        h.wake.set()
        if already_claimed:
            continue
        if dead:
            # resolve the dead loop's interrupted cycle and arm replay
            # protection BEFORE any replacement runs — the exactly-once
            # guarantee must hold across a supervised in-process
            # restart, not just a full process restart. (Hung threads
            # are excluded: they may still complete their cycle.)
            try:
                journal_mod.recover(db, worker_id=h.worker_id)
            except Exception:
                pass  # db unavailable; startup recovery will catch it
        if (
            worker is None or room is None
            or room["status"] != "active"
            or not is_room_launched(h.room_id, domain=dom)
        ):
            continue

        with dom._supervision_lock:
            strikes = dom.strikes.setdefault(
                h.worker_id, deque(maxlen=32)
            )
            strikes.append(now)
            recent = sum(
                1 for t in strikes if now - t < LOOP_RESTART_WINDOW_S
            )
        if recent > LOOP_RESTART_BUDGET:
            detail = h.crash_error or (
                f"hung for >{LOOP_HANG_S:g}s" if hung else "thread died"
            )
            with dom._supervision_lock:
                dom.counts["budget_exhausted"] += 1
                dom.unhealthy[h.worker_id] = {
                    "room_id": h.room_id,
                    "error": detail,
                    "strikes": recent,
                    "at": utc_now(),
                }
            _incr("loop.budget_exhausted")
            # close the race with a wake path that slipped a fresh loop
            # in between the corpse claim and the lockout insertion
            # above: anything registered for this worker now dies
            with dom._registry_lock:
                raced = dom.loops.pop(h.worker_id, None)
            if raced is not None:
                raced.stop.set()
                raced.wake.set()
            try:
                workers_mod.set_agent_state(db, h.worker_id, "unhealthy")
                escalations_mod.create_escalation(
                    db, h.room_id,
                    f"Worker #{h.worker_id} ({worker['name']}) agent "
                    f"loop failed {recent} times inside "
                    f"{LOOP_RESTART_WINDOW_S:g}s (last: {detail}). "
                    "Loop stopped past its restart budget — investigate "
                    "and restart the room to re-arm it.",
                    from_agent_id=h.worker_id,
                )
            except Exception:
                pass  # escalation is best-effort under db chaos
            event_bus.emit(
                "loop:unhealthy", f"room:{h.room_id}",
                {"worker_id": h.worker_id, "error": detail},
            )
            actions["unhealthy"].append(h.worker_id)
            continue

        start_agent_loop(db, h.room_id, h.worker_id, domain=dom)
        with dom._supervision_lock:
            dom.counts["restarts"] += 1
            if hung:
                dom.counts["hang_replacements"] += 1
        _incr("loop.restarts")
        if hung:
            _incr("loop.hang_replacements")
        event_bus.emit(
            "loop:restarted", f"room:{h.room_id}",
            {"worker_id": h.worker_id, "hung": hung,
             "error": h.crash_error},
        )
        (actions["replaced_hung"] if hung
         else actions["restarted"]).append(h.worker_id)
    return actions


def reset_supervision(
    worker_ids, domain: Optional[LoopDomain] = None
) -> None:
    """Forget crash strikes and unhealthy status for these workers —
    called when the keeper restarts a room, so a deliberate restart
    re-arms the full budget."""
    dom = domain or _DEFAULT_DOMAIN
    with dom._supervision_lock:
        for wid in worker_ids:
            dom.strikes.pop(wid, None)
            dom.unhealthy.pop(wid, None)


def supervision_snapshot(domain: Optional[LoopDomain] = None) -> dict:
    """Swarm-loop health for /api/tpu/health and the TPU panel."""
    dom = domain or _DEFAULT_DOMAIN
    with dom._registry_lock:
        alive = sum(
            1 for h in dom.loops.values()
            if h.thread is not None and h.thread.is_alive()
        )
    with dom._supervision_lock:
        return {
            "loops_alive": alive,
            "unhealthy_workers": {
                str(k): dict(v) for k, v in dom.unhealthy.items()
            },
            **dict(dom.counts),
        }


# ---- the loop ----

def _loop_main(db: Database, handle: LoopHandle) -> None:
    """Thread target: run the loop, and on an escaped exception leave
    the registry entry in place with the crash recorded, so
    supervise_loops can find the corpse and restart under budget (a
    dead thread silently unregistering itself is exactly the failure
    mode this PR removes)."""
    dom = handle.domain or _DEFAULT_DOMAIN
    try:
        _loop(db, handle)
    except Exception as e:
        handle.crash_error = f"{type(e).__name__}: {e}"
        handle.state = "crashed"
        with dom._supervision_lock:
            dom.counts["crashes"] += 1
        _incr("loop.crashes")
        event_bus.emit(
            "loop:crashed", f"room:{handle.room_id}",
            {"worker_id": handle.worker_id, "error": handle.crash_error},
        )


def _loop(db: Database, handle: LoopHandle) -> None:
    import sqlite3

    dom = handle.domain or _DEFAULT_DOMAIN
    while not handle.stop.is_set():
        handle.beat = time.monotonic()
        handle.expect_by = handle.beat + LOOP_HANG_S
        try:
            worker = workers_mod.get_worker(db, handle.worker_id)
            room = rooms_mod.get_room(db, handle.room_id)
        except sqlite3.ProgrammingError:
            break  # database closed underneath us: shutdown in progress
        if worker is None or room is None:
            break
        if room["status"] != "active" or not is_room_launched(
            room["id"], domain=dom
        ):
            break

        if _in_quiet_hours(room):
            handle.state = "waiting"
            if _owns_registry_entry(handle):
                workers_mod.set_agent_state(db, worker["id"], "waiting")
            handle.expect_by = time.monotonic() + 60 + LOOP_HANG_S
            if handle.wake.wait(timeout=60):
                handle.wake.clear()
            continue

        handle.state = "running"
        journal_mod.chaos_delay("loop_hang")
        rate_limited = False
        try:
            run_cycle(db, room, worker)
            gap_s = _cycle_gap_s(db, room, worker)
        except RateLimitExceeded as e:
            rate_limited = True
            gap_s = clamp_wait(e.wait_s)
        except Exception as e:
            if getattr(e, "transient", True) is False:
                # a non-transient fault models a real crash escaping
                # the cycle handler: propagate so the thread dies and
                # supervision (not this handler) owns recovery
                raise
            event_bus.emit(
                "cycle:error", f"room:{room['id']}",
                {"worker_id": worker["id"], "error": str(e)},
            )
            gap_s = CYCLE_ERROR_GAP_S

        # the wait state stays observable for the whole backoff window;
        # a loop supervision already replaced (hang) must not clobber
        # its successor's — or an unhealthy worker's — db state
        state = "rate_limited" if rate_limited else "idle"
        handle.state = state
        try:
            if _owns_registry_entry(handle):
                workers_mod.set_agent_state(db, handle.worker_id, state)
        except sqlite3.ProgrammingError:
            break
        handle.expect_by = time.monotonic() + gap_s + LOOP_HANG_S
        if handle.wake.wait(timeout=gap_s):
            handle.wake.clear()

    handle.state = "stopped"
    # a hung loop that supervision already replaced must not clobber
    # its successor's registry entry or the worker's agent_state
    with dom._registry_lock:
        own = dom.loops.get(handle.worker_id) is handle
        if own:
            del dom.loops[handle.worker_id]
    if own:
        try:
            workers_mod.set_agent_state(db, handle.worker_id, "stopped")
        except Exception:
            pass  # database already closed during shutdown


def _cycle_gap_s(db: Database, room: dict, worker: dict) -> float:
    gap_ms = worker["cycle_gap_ms"] or room["queen_cycle_gap_ms"]
    gap_s = gap_ms / 1000.0
    fresh = workers_mod.get_worker(db, worker["id"])
    if fresh and fresh.get("wip"):
        # momentum: keep pushing while work is in flight
        return min(gap_s, WIP_MOMENTUM_GAP_S)
    return gap_s


def _in_quiet_hours(room: dict) -> bool:
    start, end = room.get("queen_quiet_from"), room.get("queen_quiet_until")
    if not start or not end:
        return False
    now = datetime.now().strftime("%H:%M")
    if start <= end:
        return start <= now < end
    return now >= start or now < end  # window crosses midnight


# ---- one cycle ----

def run_cycle(db: Database, room: dict, worker: dict) -> dict:
    """Execute one observe→prompt→execute→persist cycle. Returns the
    worker_cycles row."""
    # refetch both rows: callers may hold stale dicts
    room = rooms_mod.get_room(db, room["id"]) or room
    worker = workers_mod.get_worker(db, worker["id"]) or worker
    is_queen = worker["id"] == room["queen_worker_id"]
    model = worker["model"] or room["worker_model"]

    # the cycle row and its journal entry commit atomically: a crash
    # between them would leave a 'running' row recovery can never find
    with db.transaction():
        cycle_id = db.insert(
            "INSERT INTO worker_cycles(worker_id, room_id, model) "
            "VALUES (?,?,?)",
            (worker["id"], room["id"], model),
        )
        journal_mod.record_started(
            db, "cycle", cycle_id, room["id"], worker["id"]
        )
    logs = CycleLogBuffer(db, cycle_id)
    event_bus.emit(
        "cycle:started", f"room:{room['id']}",
        {"cycle_id": cycle_id, "worker_id": worker["id"]},
    )
    started = time.monotonic()

    # cycle_crash fires BEFORE the error handler exists: the cycle row
    # stays 'running' and the journal entry open, exactly like a real
    # crash — only journal recovery can resolve it to a terminal state
    journal_mod.chaos("cycle_crash")

    try:
        provider = get_model_provider(model, db)
        ready, why = provider.is_ready()
        if not ready:
            raise RuntimeError(f"model {model!r} not ready: {why}")

        quorum_mod.check_expired_decisions(db)
        if is_queen:
            _ensure_executor_exists(db, room)

        prompt = _build_cycle_prompt(db, room, worker, is_queen)
        logs.append("prompt", prompt[-2000:])

        session_id, messages = _load_session(db, worker, model)
        tools = QUEEN_TOOLS if is_queen else WORKER_TOOLS

        def on_tool_call(name: str, args: dict) -> str:
            logs.append("tool_call", json.dumps({"name": name,
                                                 "args": args}))
            if is_queen and name in QUEEN_DEVIATION_TOOLS:
                # control-plane contract: the queen plans and delegates;
                # doing execution work herself is logged as a deviation
                # (reference "Model B" policy, agent-loop.ts:22-28,699-728)
                from .activity import log_room_activity

                log_room_activity(
                    db, room["id"], "deviation",
                    f"Queen executed {name} directly instead of "
                    "delegating",
                    actor_id=worker["id"], is_public=False,
                )
            if name in journal_mod.JOURNALED_TOOLS:
                # externally-visible side effects run under journal
                # protection: a retry after crash recovery skips the
                # ones that already committed
                out = journal_mod.run_journaled_effect(
                    db, "cycle", cycle_id, room["id"], worker["id"],
                    name, args,
                    lambda: execute_queen_tool(
                        db, room["id"], worker["id"], name, args
                    ),
                )
            else:
                out = execute_queen_tool(db, room["id"], worker["id"],
                                         name, args)
            logs.append("tool_result", out[:2000])
            return out

        call_key = f"cycle:{cycle_id}:w{worker['id']}"
        journal_mod.record_provider_call(
            db, "cycle", cycle_id, call_key, room["id"], worker["id"]
        )
        result = provider.execute(ExecutionRequest(
            prompt=prompt,
            system_prompt=worker["system_prompt"],
            model=model,
            tools=tools,
            on_tool_call=on_tool_call,
            max_turns=worker["max_turns"] or room["queen_max_turns"],
            session_id=session_id,
            messages=messages,
            on_text=lambda t: logs.append("assistant", t[:4000]),
            idempotency_key=call_key,
            # SLO class for the serving scheduler (docs/scheduler.md):
            # queen turns are the room's p50-critical path
            turn_class="queen" if is_queen else "worker",
        ))

        if not result.success and result.error:
            from .rate_limit import detect_rate_limit

            wait = detect_rate_limit(result.error)
            if wait is not None:
                raise RateLimitExceeded(result.error, wait)

        _save_session(db, worker, model, result, provider)
        _auto_wip(db, worker, result)

        status = "success" if result.success else "error"
        # flush buffered logs BEFORE the row flips to finished: a reader
        # that sees status=success must also see the cycle's logs
        logs.flush()
        duration_ms = int((time.monotonic() - started) * 1000)
        db.execute(
            "UPDATE worker_cycles SET finished_at=?, status=?, "
            "error_message=?, duration_ms=?, input_tokens=?, "
            "output_tokens=? WHERE id=?",
            (
                utc_now(), status, result.error, duration_ms,
                result.input_tokens, result.output_tokens, cycle_id,
            ),
        )
        journal_mod.record_finished(db, "cycle", cycle_id)
        _prune_old_cycles(db, room["id"])
        event_bus.emit(
            "cycle:finished", f"room:{room['id']}",
            {
                "cycle_id": cycle_id, "status": status,
                "worker_id": worker["id"],
                "duration_ms": duration_ms,
                "output_tokens": result.output_tokens,
            },
        )
        return db.query_one(
            "SELECT * FROM worker_cycles WHERE id=?", (cycle_id,)
        )  # type: ignore[return-value]
    except Exception as e:
        db.execute(
            "UPDATE worker_cycles SET finished_at=?, status='error', "
            "error_message=?, duration_ms=? WHERE id=?",
            (utc_now(), str(e),
             int((time.monotonic() - started) * 1000), cycle_id),
        )
        # a clean failure closes its own journal; if the db is already
        # gone the entry stays open and startup recovery resolves it
        journal_mod.record_finished(db, "cycle", cycle_id)
        raise
    finally:
        logs.close()


# ---- prompt assembly (reference order, agent-loop.ts:451-685) ----

def _build_cycle_prompt(
    db: Database, room: dict, worker: dict, is_queen: bool
) -> str:
    parts: list[str] = []
    role = "Queen (coordinator)" if is_queen else \
        f"Worker ({worker['role'] or 'generalist'})"
    parts.append(
        f"You are {worker['name']}, {role} of room "
        f"'{room['name']}' (room #{room['id']}, your worker id "
        f"#{worker['id']})."
    )

    if worker.get("wip"):
        parts.append(
            "CONTINUE FORWARD — your work-in-progress note from last "
            f"cycle:\n{worker['wip']}"
        )

    if room.get("goal"):
        parts.append(f"Room objective: {room['goal']}")

    # goals / assignments
    if is_queen:
        tree = goals_mod.get_goal_tree(db, room["id"])
        if tree:
            parts.append("Goal tree:\n" + _render_goal_tree(tree))
        team = workers_mod.list_room_workers(db, room["id"])
        others = [w for w in team if w["id"] != worker["id"]]
        if others:
            parts.append(
                "Workers:\n" + "\n".join(
                    f"- #{w['id']} {w['name']} ({w['role']}) "
                    f"state={w['agent_state']}"
                    for w in others
                )
            )
    else:
        assigned = goals_mod.active_goals_for_worker(db, worker["id"])
        if assigned:
            parts.append(
                "Your assigned goals:\n" + "\n".join(
                    f"- #{g['id']} {g['description']} "
                    f"(progress {g['progress']:.0%})"
                    for g in assigned
                )
            )

    # memory: top-5 hybrid hits against objective+WIP
    query = " ".join(
        x for x in (room.get("goal"), worker.get("wip")) if x
    )
    if query:
        from .queen_tools import _embed_query

        hits = memory_mod.hybrid_search(
            db, query, query_vector=_embed_query(query),
            limit=MEMORY_RECALL_TOP_K, room_id=room["id"],
        )
        if hits:
            parts.append(
                "Relevant memory:\n" + "\n".join(
                    f"- {h['name']}: "
                    f"{'; '.join(h['observations'][-2:])}"
                    for h in hits
                )
            )

    skills_ctx = skills_mod.load_skills_for_agent(
        db, room["id"], context_hint=query or ""
    )
    if skills_ctx:
        parts.append(skills_ctx)

    stuck = _stuck_note(db, worker)
    if stuck:
        parts.append(stuck)

    # housekeeping: decisions / escalations / messages
    pending = quorum_mod.pending_decisions(db, room["id"])
    if pending:
        parts.append(
            "Open decisions:\n" + "\n".join(
                f"- #{d['id']} [{d['status']}] {d['proposal']}"
                for d in pending
            )
        )
    answered = escalations_mod.recently_answered(db, room["id"], limit=3)
    if answered:
        parts.append(
            "Keeper answers:\n" + "\n".join(
                f"- Q: {e['question']} → A: {e['answer']}"
                for e in answered
            )
        )
    keeper_msgs = messages_mod.unanswered_keeper_messages(db, room["id"])
    if is_queen and keeper_msgs:
        parts.append(
            "Unanswered keeper messages (reply with send_message "
            "to='keeper'):\n" + "\n".join(
                f"- {m['content']}" for m in keeper_msgs[-5:]
            )
        )
    unread = messages_mod.unread_messages(db, room["id"])
    if unread:
        parts.append(
            "Unread inter-room messages:\n" + "\n".join(
                f"- #{m['id']} from room {m['from_room_id']}: "
                f"[{m['subject']}] {m['body'][:200]}"
                for m in unread[:5]
            )
        )

    parts.append(
        "Act now using your tools. Finish by saving a WIP note "
        "(save_wip) describing exactly where to continue next cycle."
    )
    return "\n\n".join(parts)


def _render_goal_tree(tree: list[dict], depth: int = 0) -> str:
    lines = []
    for g in tree:
        assignee = (
            f" → worker #{g['assigned_worker_id']}"
            if g.get("assigned_worker_id") else ""
        )
        lines.append(
            "  " * depth
            + f"- #{g['id']} [{g['status']} {g['progress']:.0%}] "
            f"{g['description']}{assignee}"
        )
        if g.get("children"):
            lines.append(_render_goal_tree(g["children"], depth + 1))
    return "\n".join(lines)


def _stuck_note(db: Database, worker: dict) -> Optional[str]:
    """Flag repeated failing cycles (reference stuck detector :605-617)."""
    recent = db.query(
        "SELECT status FROM worker_cycles WHERE worker_id=? "
        "ORDER BY id DESC LIMIT ?",
        (worker["id"], STUCK_CYCLE_WINDOW),
    )
    failures = sum(1 for r in recent if r["status"] == "error")
    if len(recent) >= STUCK_CYCLE_WINDOW and failures >= STUCK_CYCLE_WINDOW - 1:
        return (
            "NOTE: your recent cycles keep failing. Change approach: "
            "simplify the next action, or escalate to the keeper."
        )
    return None


def _ensure_executor_exists(db: Database, room: dict) -> None:
    """A queen alone gets a default executor (reference :414-449)."""
    team = workers_mod.list_room_workers(db, room["id"])
    if len(team) > 1:
        return
    workers_mod.create_worker(
        db,
        name=f"{room['name']} Executor",
        system_prompt="Execute goals delegated by the Queen.",
        room_id=room["id"],
        role="executor",
        model=room["worker_model"],
    )


# ---- session continuity ----

def _load_session(
    db: Database, worker: dict, model: str
) -> tuple[Optional[str], Optional[list[dict]]]:
    row = db.query_one(
        "SELECT * FROM agent_sessions WHERE worker_id=?", (worker["id"],)
    )
    if row is None:
        return None, None
    rotate = (
        row["model"] != model
        or row["turn_count"] >= CLI_SESSION_ROTATE_CYCLES
    )
    if rotate:
        _release_engine_session(row["session_id"], model)
        db.execute(
            "DELETE FROM agent_sessions WHERE worker_id=?",
            (worker["id"],),
        )
        return None, None
    messages = (
        json.loads(row["messages_json"]) if row["messages_json"] else None
    )
    if messages is not None and len(messages) >= API_HISTORY_COMPRESS_AT:
        messages = _compress_messages(db, worker, model, messages)
    return row["session_id"], messages


def _save_session(
    db: Database, worker: dict, model: str, result, provider
) -> None:
    messages_json = (
        json.dumps(result.messages[-API_HISTORY_TRIM_AT:])
        if result.messages else None
    )
    db.execute(
        "INSERT INTO agent_sessions(worker_id, session_id, messages_json, "
        "model, turn_count, updated_at) VALUES (?,?,?,?,1,?) "
        "ON CONFLICT(worker_id) DO UPDATE SET session_id=excluded."
        "session_id, messages_json=excluded.messages_json, "
        "model=excluded.model, turn_count=turn_count+1, "
        "updated_at=excluded.updated_at",
        (
            worker["id"], result.session_id, messages_json, model,
            utc_now(),
        ),
    )


def _compress_messages(
    db: Database, worker: dict, model: str, messages: list[dict]
) -> list[dict]:
    """Summarize old history into one message via a single LLM call,
    persisting the summary to room memory (reference compressSession,
    agent-executor.ts:878-948). Falls back to a hard trim."""
    head, tail = messages[:-10], messages[-10:]
    try:
        provider = get_model_provider(model, db)
        digest = "\n".join(
            f"{m.get('role')}: {str(m.get('content'))[:300]}"
            for m in head
        )
        r = provider.execute(ExecutionRequest(
            prompt=(
                "Summarize this conversation history into a compact "
                "briefing (decisions, open threads, facts):\n" + digest
            ),
            max_turns=1,
            max_new_tokens=512,
            timeout_s=120,
            turn_class="background",
        ))
        summary = r.text if r.success and r.text else None
    except Exception:
        summary = None
    if summary:
        if worker.get("room_id"):
            memory_mod.remember(
                db, f"session summary: {worker['name']}", summary,
                category="session", room_id=worker["room_id"],
            )
        return (
            [{"role": "user",
              "content": f"[Conversation summary]\n{summary}"}] + tail
        )
    return messages[-API_HISTORY_TRIM_AT:]


def _release_engine_session(
    session_id: Optional[str], model: str
) -> None:
    """Rotation frees the paged-KV session on the engine side."""
    if not session_id:
        return
    try:
        from ..providers.registry import model_name, provider_kind
        from ..providers.tpu import get_model_host

        if provider_kind(model) == "tpu":
            host = get_model_host(model_name(model) or "qwen3-coder-30b")
            if host._engine is not None:
                host._engine.release_session(session_id)
    except Exception:
        pass


def _auto_wip(db: Database, worker: dict, result) -> None:
    """If the agent didn't save a WIP, derive one from its final text
    (reference auto-WIP fallback :855-863)."""
    fresh = workers_mod.get_worker(db, worker["id"])
    if fresh is None:
        return
    before = worker.get("wip") or ""
    if (fresh.get("wip") or "") != before:
        return  # agent saved one itself this cycle
    if result.text:
        workers_mod.save_wip(
            db, worker["id"], f"[auto] last output: {result.text[:500]}"
        )


def _prune_old_cycles(
    db: Database, room_id: int, keep: int = 200
) -> None:
    db.execute(
        "DELETE FROM worker_cycles WHERE room_id=? AND id NOT IN ("
        "SELECT id FROM worker_cycles WHERE room_id=? "
        "ORDER BY id DESC LIMIT ?)",
        (room_id, room_id, keep),
    )
