"""Per-worker agent loop: observe → prompt → execute → persist.

Behavioral parity with the reference loop (reference:
src/shared/agent-loop.ts): quiet hours (:30-51), WIP momentum gap
(:204-217), rate-limit wait state (:166-190), stuck detector (:605-617),
session rotation after 20 cycles (:462-493), history compression at 30
messages (:495-532), auto-created executor for a worker-less queen
(:414-449), auto-WIP fallback (:855-863), and the §3.2 prompt assembly
order — re-built on Python threads with the tpu: provider as the default
execution path.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from datetime import datetime
from typing import Optional

from ..db import Database, utc_now
from ..providers import (
    ExecutionRequest, RateLimitExceeded, get_model_provider,
)
from . import (
    escalations as escalations_mod,
    goals as goals_mod,
    memory as memory_mod,
    messages as messages_mod,
    quorum as quorum_mod,
    rooms as rooms_mod,
    skills as skills_mod,
    workers as workers_mod,
)
from .constants import (
    API_HISTORY_COMPRESS_AT,
    API_HISTORY_TRIM_AT,
    CLI_SESSION_ROTATE_CYCLES,
    MEMORY_RECALL_TOP_K,
)
from .cycle_logs import CycleLogBuffer
from .events import event_bus
from .queen_tools import (
    QUEEN_TOOLS, WORKER_TOOLS, execute_queen_tool,
)
from .rate_limit import clamp_wait

WIP_MOMENTUM_GAP_S = 10.0
STUCK_CYCLE_WINDOW = 5

# execution-plane tools: fine for workers, a logged deviation when the
# queen runs them herself instead of delegating
QUEEN_DEVIATION_TOOLS = {"web_fetch", "web_search"}


@dataclass
class LoopHandle:
    worker_id: int
    room_id: int
    thread: Optional[threading.Thread] = None
    stop: threading.Event = field(default_factory=threading.Event)
    wake: threading.Event = field(default_factory=threading.Event)
    state: str = "idle"


_running_loops: dict[int, LoopHandle] = {}
_launched_rooms: set[int] = set()
_registry_lock = threading.Lock()


# ---- lifecycle ----

def set_room_launch_enabled(room_id: int, enabled: bool) -> None:
    with _registry_lock:
        if enabled:
            _launched_rooms.add(room_id)
        else:
            _launched_rooms.discard(room_id)


def is_room_launched(room_id: int) -> bool:
    with _registry_lock:
        return room_id in _launched_rooms


def running_workers() -> list[int]:
    with _registry_lock:
        return [
            wid for wid, h in _running_loops.items()
            if h.thread is not None and h.thread.is_alive()
        ]


def start_agent_loop(
    db: Database, room_id: int, worker_id: int
) -> LoopHandle:
    with _registry_lock:
        existing = _running_loops.get(worker_id)
        if (
            existing
            and existing.thread
            and existing.thread.is_alive()
            and not existing.stop.is_set()
        ):
            existing.wake.set()
            return existing
        # a stopping handle is as good as dead: replace it (the old
        # thread only deletes the registry entry if it is still its own)
        handle = LoopHandle(worker_id=worker_id, room_id=room_id)
        _running_loops[worker_id] = handle
    handle.thread = threading.Thread(
        target=_loop, args=(db, handle), daemon=True,
        name=f"agent-loop-{worker_id}",
    )
    handle.thread.start()
    return handle


def trigger_agent(
    db: Database,
    room_id: int,
    worker_id: int,
    allow_cold_start: bool = False,
) -> Optional[LoopHandle]:
    """Wake a sleeping loop, or start one (reference: triggerAgent:266)."""
    if allow_cold_start:
        set_room_launch_enabled(room_id, True)
    if not is_room_launched(room_id):
        return None
    return start_agent_loop(db, room_id, worker_id)


def pause_agent(worker_id: int) -> bool:
    with _registry_lock:
        handle = _running_loops.get(worker_id)
    if handle is None:
        return False
    handle.stop.set()
    handle.wake.set()
    return True


def stop_worker_loop(worker_id: int) -> bool:
    """Stop one worker's loop thread (reference: per-worker stop route
    routes/workers.ts)."""
    with _registry_lock:
        handle = _running_loops.get(worker_id)
    if handle is None:
        return False
    handle.stop.set()
    handle.wake.set()
    return True


def stop_room_loops(db: Database, room_id: int, reason: str = "") -> int:
    set_room_launch_enabled(room_id, False)
    n = 0
    with _registry_lock:
        handles = [
            h for h in _running_loops.values() if h.room_id == room_id
        ]
    for h in handles:
        h.stop.set()
        h.wake.set()
        n += 1
    return n


# ---- the loop ----

def _loop(db: Database, handle: LoopHandle) -> None:
    import sqlite3

    while not handle.stop.is_set():
        try:
            worker = workers_mod.get_worker(db, handle.worker_id)
            room = rooms_mod.get_room(db, handle.room_id)
        except sqlite3.ProgrammingError:
            return  # database closed underneath us: shutdown in progress
        if worker is None or room is None:
            break
        if room["status"] != "active" or not is_room_launched(room["id"]):
            break

        if _in_quiet_hours(room):
            handle.state = "waiting"
            workers_mod.set_agent_state(db, worker["id"], "waiting")
            if handle.wake.wait(timeout=60):
                handle.wake.clear()
            continue

        handle.state = "running"
        rate_limited = False
        try:
            run_cycle(db, room, worker)
            gap_s = _cycle_gap_s(db, room, worker)
        except RateLimitExceeded as e:
            rate_limited = True
            gap_s = clamp_wait(e.wait_s)
        except Exception as e:
            event_bus.emit(
                "cycle:error", f"room:{room['id']}",
                {"worker_id": worker["id"], "error": str(e)},
            )
            gap_s = 30.0

        # the wait state stays observable for the whole backoff window
        state = "rate_limited" if rate_limited else "idle"
        handle.state = state
        try:
            workers_mod.set_agent_state(db, handle.worker_id, state)
        except sqlite3.ProgrammingError:
            return
        if handle.wake.wait(timeout=gap_s):
            handle.wake.clear()

    handle.state = "stopped"
    try:
        workers_mod.set_agent_state(db, handle.worker_id, "stopped")
    except sqlite3.ProgrammingError:
        pass  # database already closed during shutdown
    with _registry_lock:
        if _running_loops.get(handle.worker_id) is handle:
            del _running_loops[handle.worker_id]


def _cycle_gap_s(db: Database, room: dict, worker: dict) -> float:
    gap_ms = worker["cycle_gap_ms"] or room["queen_cycle_gap_ms"]
    gap_s = gap_ms / 1000.0
    fresh = workers_mod.get_worker(db, worker["id"])
    if fresh and fresh.get("wip"):
        # momentum: keep pushing while work is in flight
        return min(gap_s, WIP_MOMENTUM_GAP_S)
    return gap_s


def _in_quiet_hours(room: dict) -> bool:
    start, end = room.get("queen_quiet_from"), room.get("queen_quiet_until")
    if not start or not end:
        return False
    now = datetime.now().strftime("%H:%M")
    if start <= end:
        return start <= now < end
    return now >= start or now < end  # window crosses midnight


# ---- one cycle ----

def run_cycle(db: Database, room: dict, worker: dict) -> dict:
    """Execute one observe→prompt→execute→persist cycle. Returns the
    worker_cycles row."""
    # refetch both rows: callers may hold stale dicts
    room = rooms_mod.get_room(db, room["id"]) or room
    worker = workers_mod.get_worker(db, worker["id"]) or worker
    is_queen = worker["id"] == room["queen_worker_id"]
    model = worker["model"] or room["worker_model"]

    cycle_id = db.insert(
        "INSERT INTO worker_cycles(worker_id, room_id, model) "
        "VALUES (?,?,?)",
        (worker["id"], room["id"], model),
    )
    logs = CycleLogBuffer(db, cycle_id)
    event_bus.emit(
        "cycle:started", f"room:{room['id']}",
        {"cycle_id": cycle_id, "worker_id": worker["id"]},
    )
    started = time.monotonic()

    try:
        provider = get_model_provider(model, db)
        ready, why = provider.is_ready()
        if not ready:
            raise RuntimeError(f"model {model!r} not ready: {why}")

        quorum_mod.check_expired_decisions(db)
        if is_queen:
            _ensure_executor_exists(db, room)

        prompt = _build_cycle_prompt(db, room, worker, is_queen)
        logs.append("prompt", prompt[-2000:])

        session_id, messages = _load_session(db, worker, model)
        tools = QUEEN_TOOLS if is_queen else WORKER_TOOLS

        def on_tool_call(name: str, args: dict) -> str:
            logs.append("tool_call", json.dumps({"name": name,
                                                 "args": args}))
            if is_queen and name in QUEEN_DEVIATION_TOOLS:
                # control-plane contract: the queen plans and delegates;
                # doing execution work herself is logged as a deviation
                # (reference "Model B" policy, agent-loop.ts:22-28,699-728)
                from .activity import log_room_activity

                log_room_activity(
                    db, room["id"], "deviation",
                    f"Queen executed {name} directly instead of "
                    "delegating",
                    actor_id=worker["id"], is_public=False,
                )
            out = execute_queen_tool(db, room["id"], worker["id"], name,
                                     args)
            logs.append("tool_result", out[:2000])
            return out

        result = provider.execute(ExecutionRequest(
            prompt=prompt,
            system_prompt=worker["system_prompt"],
            model=model,
            tools=tools,
            on_tool_call=on_tool_call,
            max_turns=worker["max_turns"] or room["queen_max_turns"],
            session_id=session_id,
            messages=messages,
            on_text=lambda t: logs.append("assistant", t[:4000]),
        ))

        if not result.success and result.error:
            from .rate_limit import detect_rate_limit

            wait = detect_rate_limit(result.error)
            if wait is not None:
                raise RateLimitExceeded(result.error, wait)

        _save_session(db, worker, model, result, provider)
        _auto_wip(db, worker, result)

        status = "success" if result.success else "error"
        # flush buffered logs BEFORE the row flips to finished: a reader
        # that sees status=success must also see the cycle's logs
        logs.flush()
        duration_ms = int((time.monotonic() - started) * 1000)
        db.execute(
            "UPDATE worker_cycles SET finished_at=?, status=?, "
            "error_message=?, duration_ms=?, input_tokens=?, "
            "output_tokens=? WHERE id=?",
            (
                utc_now(), status, result.error, duration_ms,
                result.input_tokens, result.output_tokens, cycle_id,
            ),
        )
        _prune_old_cycles(db, room["id"])
        event_bus.emit(
            "cycle:finished", f"room:{room['id']}",
            {
                "cycle_id": cycle_id, "status": status,
                "worker_id": worker["id"],
                "duration_ms": duration_ms,
                "output_tokens": result.output_tokens,
            },
        )
        return db.query_one(
            "SELECT * FROM worker_cycles WHERE id=?", (cycle_id,)
        )  # type: ignore[return-value]
    except Exception as e:
        db.execute(
            "UPDATE worker_cycles SET finished_at=?, status='error', "
            "error_message=?, duration_ms=? WHERE id=?",
            (utc_now(), str(e),
             int((time.monotonic() - started) * 1000), cycle_id),
        )
        raise
    finally:
        logs.close()


# ---- prompt assembly (reference order, agent-loop.ts:451-685) ----

def _build_cycle_prompt(
    db: Database, room: dict, worker: dict, is_queen: bool
) -> str:
    parts: list[str] = []
    role = "Queen (coordinator)" if is_queen else \
        f"Worker ({worker['role'] or 'generalist'})"
    parts.append(
        f"You are {worker['name']}, {role} of room "
        f"'{room['name']}' (room #{room['id']}, your worker id "
        f"#{worker['id']})."
    )

    if worker.get("wip"):
        parts.append(
            "CONTINUE FORWARD — your work-in-progress note from last "
            f"cycle:\n{worker['wip']}"
        )

    if room.get("goal"):
        parts.append(f"Room objective: {room['goal']}")

    # goals / assignments
    if is_queen:
        tree = goals_mod.get_goal_tree(db, room["id"])
        if tree:
            parts.append("Goal tree:\n" + _render_goal_tree(tree))
        team = workers_mod.list_room_workers(db, room["id"])
        others = [w for w in team if w["id"] != worker["id"]]
        if others:
            parts.append(
                "Workers:\n" + "\n".join(
                    f"- #{w['id']} {w['name']} ({w['role']}) "
                    f"state={w['agent_state']}"
                    for w in others
                )
            )
    else:
        assigned = goals_mod.active_goals_for_worker(db, worker["id"])
        if assigned:
            parts.append(
                "Your assigned goals:\n" + "\n".join(
                    f"- #{g['id']} {g['description']} "
                    f"(progress {g['progress']:.0%})"
                    for g in assigned
                )
            )

    # memory: top-5 hybrid hits against objective+WIP
    query = " ".join(
        x for x in (room.get("goal"), worker.get("wip")) if x
    )
    if query:
        from .queen_tools import _embed_query

        hits = memory_mod.hybrid_search(
            db, query, query_vector=_embed_query(query),
            limit=MEMORY_RECALL_TOP_K, room_id=room["id"],
        )
        if hits:
            parts.append(
                "Relevant memory:\n" + "\n".join(
                    f"- {h['name']}: "
                    f"{'; '.join(h['observations'][-2:])}"
                    for h in hits
                )
            )

    skills_ctx = skills_mod.load_skills_for_agent(
        db, room["id"], context_hint=query or ""
    )
    if skills_ctx:
        parts.append(skills_ctx)

    stuck = _stuck_note(db, worker)
    if stuck:
        parts.append(stuck)

    # housekeeping: decisions / escalations / messages
    pending = quorum_mod.pending_decisions(db, room["id"])
    if pending:
        parts.append(
            "Open decisions:\n" + "\n".join(
                f"- #{d['id']} [{d['status']}] {d['proposal']}"
                for d in pending
            )
        )
    answered = escalations_mod.recently_answered(db, room["id"], limit=3)
    if answered:
        parts.append(
            "Keeper answers:\n" + "\n".join(
                f"- Q: {e['question']} → A: {e['answer']}"
                for e in answered
            )
        )
    keeper_msgs = messages_mod.unanswered_keeper_messages(db, room["id"])
    if is_queen and keeper_msgs:
        parts.append(
            "Unanswered keeper messages (reply with send_message "
            "to='keeper'):\n" + "\n".join(
                f"- {m['content']}" for m in keeper_msgs[-5:]
            )
        )
    unread = messages_mod.unread_messages(db, room["id"])
    if unread:
        parts.append(
            "Unread inter-room messages:\n" + "\n".join(
                f"- #{m['id']} from room {m['from_room_id']}: "
                f"[{m['subject']}] {m['body'][:200]}"
                for m in unread[:5]
            )
        )

    parts.append(
        "Act now using your tools. Finish by saving a WIP note "
        "(save_wip) describing exactly where to continue next cycle."
    )
    return "\n\n".join(parts)


def _render_goal_tree(tree: list[dict], depth: int = 0) -> str:
    lines = []
    for g in tree:
        assignee = (
            f" → worker #{g['assigned_worker_id']}"
            if g.get("assigned_worker_id") else ""
        )
        lines.append(
            "  " * depth
            + f"- #{g['id']} [{g['status']} {g['progress']:.0%}] "
            f"{g['description']}{assignee}"
        )
        if g.get("children"):
            lines.append(_render_goal_tree(g["children"], depth + 1))
    return "\n".join(lines)


def _stuck_note(db: Database, worker: dict) -> Optional[str]:
    """Flag repeated failing cycles (reference stuck detector :605-617)."""
    recent = db.query(
        "SELECT status FROM worker_cycles WHERE worker_id=? "
        "ORDER BY id DESC LIMIT ?",
        (worker["id"], STUCK_CYCLE_WINDOW),
    )
    failures = sum(1 for r in recent if r["status"] == "error")
    if len(recent) >= STUCK_CYCLE_WINDOW and failures >= STUCK_CYCLE_WINDOW - 1:
        return (
            "NOTE: your recent cycles keep failing. Change approach: "
            "simplify the next action, or escalate to the keeper."
        )
    return None


def _ensure_executor_exists(db: Database, room: dict) -> None:
    """A queen alone gets a default executor (reference :414-449)."""
    team = workers_mod.list_room_workers(db, room["id"])
    if len(team) > 1:
        return
    workers_mod.create_worker(
        db,
        name=f"{room['name']} Executor",
        system_prompt="Execute goals delegated by the Queen.",
        room_id=room["id"],
        role="executor",
        model=room["worker_model"],
    )


# ---- session continuity ----

def _load_session(
    db: Database, worker: dict, model: str
) -> tuple[Optional[str], Optional[list[dict]]]:
    row = db.query_one(
        "SELECT * FROM agent_sessions WHERE worker_id=?", (worker["id"],)
    )
    if row is None:
        return None, None
    rotate = (
        row["model"] != model
        or row["turn_count"] >= CLI_SESSION_ROTATE_CYCLES
    )
    if rotate:
        _release_engine_session(row["session_id"], model)
        db.execute(
            "DELETE FROM agent_sessions WHERE worker_id=?",
            (worker["id"],),
        )
        return None, None
    messages = (
        json.loads(row["messages_json"]) if row["messages_json"] else None
    )
    if messages is not None and len(messages) >= API_HISTORY_COMPRESS_AT:
        messages = _compress_messages(db, worker, model, messages)
    return row["session_id"], messages


def _save_session(
    db: Database, worker: dict, model: str, result, provider
) -> None:
    messages_json = (
        json.dumps(result.messages[-API_HISTORY_TRIM_AT:])
        if result.messages else None
    )
    db.execute(
        "INSERT INTO agent_sessions(worker_id, session_id, messages_json, "
        "model, turn_count, updated_at) VALUES (?,?,?,?,1,?) "
        "ON CONFLICT(worker_id) DO UPDATE SET session_id=excluded."
        "session_id, messages_json=excluded.messages_json, "
        "model=excluded.model, turn_count=turn_count+1, "
        "updated_at=excluded.updated_at",
        (
            worker["id"], result.session_id, messages_json, model,
            utc_now(),
        ),
    )


def _compress_messages(
    db: Database, worker: dict, model: str, messages: list[dict]
) -> list[dict]:
    """Summarize old history into one message via a single LLM call,
    persisting the summary to room memory (reference compressSession,
    agent-executor.ts:878-948). Falls back to a hard trim."""
    head, tail = messages[:-10], messages[-10:]
    try:
        provider = get_model_provider(model, db)
        digest = "\n".join(
            f"{m.get('role')}: {str(m.get('content'))[:300]}"
            for m in head
        )
        r = provider.execute(ExecutionRequest(
            prompt=(
                "Summarize this conversation history into a compact "
                "briefing (decisions, open threads, facts):\n" + digest
            ),
            max_turns=1,
            max_new_tokens=512,
            timeout_s=120,
        ))
        summary = r.text if r.success and r.text else None
    except Exception:
        summary = None
    if summary:
        if worker.get("room_id"):
            memory_mod.remember(
                db, f"session summary: {worker['name']}", summary,
                category="session", room_id=worker["room_id"],
            )
        return (
            [{"role": "user",
              "content": f"[Conversation summary]\n{summary}"}] + tail
        )
    return messages[-API_HISTORY_TRIM_AT:]


def _release_engine_session(
    session_id: Optional[str], model: str
) -> None:
    """Rotation frees the paged-KV session on the engine side."""
    if not session_id:
        return
    try:
        from ..providers.registry import model_name, provider_kind
        from ..providers.tpu import get_model_host

        if provider_kind(model) == "tpu":
            host = get_model_host(model_name(model) or "qwen3-coder-30b")
            if host._engine is not None:
                host._engine.release_session(session_id)
    except Exception:
        pass


def _auto_wip(db: Database, worker: dict, result) -> None:
    """If the agent didn't save a WIP, derive one from its final text
    (reference auto-WIP fallback :855-863)."""
    fresh = workers_mod.get_worker(db, worker["id"])
    if fresh is None:
        return
    before = worker.get("wip") or ""
    if (fresh.get("wip") or "") != before:
        return  # agent saved one itself this cycle
    if result.text:
        workers_mod.save_wip(
            db, worker["id"], f"[auto] last output: {result.text[:500]}"
        )


def _prune_old_cycles(
    db: Database, room_id: int, keep: int = 200
) -> None:
    db.execute(
        "DELETE FROM worker_cycles WHERE room_id=? AND id NOT IN ("
        "SELECT id FROM worker_cycles WHERE room_id=? "
        "ORDER BY id DESC LIMIT ?)",
        (room_id, room_id, keep),
    )
