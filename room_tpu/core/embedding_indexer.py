"""Background embedding indexer (reference:
src/shared/embedding-indexer.ts): batches of dirty entities (name + last
observations, hash-deduped) get embedded and stored; the device index is
refreshed so semantic recall sees new memories within one pass."""

from __future__ import annotations

import threading
from typing import Optional

from ..db import Database
from . import memory as memory_mod

BATCH_SIZE = 10
PASS_INTERVAL_S = 5.0


class EmbeddingIndexer:
    def __init__(
        self, db: Database, interval_s: float = PASS_INTERVAL_S
    ) -> None:
        self.db = db
        self.interval_s = interval_s
        self.stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._index = None

    def index_pass(self) -> int:
        """Embed one batch of stale entities; returns how many."""
        from ..serving.embed_service import embed_texts

        entities = memory_mod.entities_needing_embedding(
            self.db, limit=BATCH_SIZE
        )
        if not entities:
            return 0
        texts, keep = [], []
        for ent in entities:
            text = memory_mod.embedding_text_for_entity(self.db, ent)
            h = memory_mod.text_hash(text)
            existing = self.db.query_one(
                "SELECT text_hash FROM embeddings WHERE source_type="
                "'entity' AND source_id=?",
                (ent["id"],),
            )
            if existing and existing["text_hash"] == h:
                # unchanged content: just clear the dirty flag
                from ..db import utc_now

                self.db.execute(
                    "UPDATE entities SET embedded_at=? WHERE id=?",
                    (utc_now(), ent["id"]),
                )
                continue
            texts.append(text)
            keep.append(ent)
        if not texts:
            return 0
        vectors = embed_texts(texts)
        for ent, text, vec in zip(keep, texts, vectors):
            memory_mod.store_embedding(self.db, ent["id"], text, vec)
        self.refresh_device_index()
        return len(keep)

    def refresh_device_index(self) -> None:
        from ..serving.embed_service import DeviceEmbedIndex

        mat, ids = memory_mod.embedding_matrix(self.db)
        if self._index is None:
            dim = mat.shape[1] if len(ids) else 384
            self._index = DeviceEmbedIndex(dim)
        self._index.rebuild(mat, ids)

    @property
    def device_index(self):
        return self._index

    def start(self) -> None:
        def loop():
            while not self.stop_event.wait(timeout=self.interval_s):
                try:
                    self.index_pass()
                except Exception:
                    pass

        self._thread = threading.Thread(
            target=loop, daemon=True, name="embedding-indexer"
        )
        self._thread.start()

    def stop(self) -> None:
        self.stop_event.set()
        if self._thread:
            self._thread.join(timeout=5)
