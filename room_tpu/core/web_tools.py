"""Keyless web access for agents (reference: src/shared/web-tools.ts —
Jina Reader + DDG via a persistent browser; here: stdlib HTTP with
readable-text extraction, fail-closed offline).

A browser-automation backend can be layered in later; the tool contract
(web_fetch/web_search returning text) stays the same."""

from __future__ import annotations

import html.parser
import json
import re
import urllib.error
import urllib.parse
import urllib.request

FETCH_TIMEOUT_S = 20
MAX_TEXT_CHARS = 8000
_UA = "Mozilla/5.0 (compatible; room-tpu/0.1)"


class _TextExtractor(html.parser.HTMLParser):
    SKIP = {"script", "style", "noscript", "svg", "head"}

    def __init__(self) -> None:
        super().__init__()
        self._skip_depth = 0
        self.chunks: list[str] = []

    def handle_starttag(self, tag, attrs):
        if tag in self.SKIP:
            self._skip_depth += 1

    def handle_endtag(self, tag):
        if tag in self.SKIP and self._skip_depth > 0:
            self._skip_depth -= 1

    def handle_data(self, data):
        if self._skip_depth == 0 and data.strip():
            self.chunks.append(data.strip())


def _extract_text(html_text: str) -> str:
    p = _TextExtractor()
    try:
        p.feed(html_text)
    except Exception:
        pass
    text = "\n".join(p.chunks)
    return re.sub(r"\n{3,}", "\n\n", text)


def web_fetch(url: str) -> str:
    if not url.startswith(("http://", "https://")):
        return f"invalid url: {url!r}"
    req = urllib.request.Request(url, headers={"User-Agent": _UA})
    try:
        with urllib.request.urlopen(req, timeout=FETCH_TIMEOUT_S) as resp:
            raw = resp.read(2_000_000)
            ctype = resp.headers.get("Content-Type", "")
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        return f"fetch failed: {e} (network may be unavailable)"
    body = raw.decode("utf-8", errors="replace")
    if "html" in ctype:
        body = _extract_text(body)
    return body[:MAX_TEXT_CHARS]


def web_search(query: str, max_results: int = 5) -> str:
    """DuckDuckGo HTML endpoint, parsed for title/url/snippet."""
    url = (
        "https://html.duckduckgo.com/html/?q="
        + urllib.parse.quote(query)
    )
    req = urllib.request.Request(url, headers={"User-Agent": _UA})
    try:
        with urllib.request.urlopen(req, timeout=FETCH_TIMEOUT_S) as resp:
            body = resp.read().decode("utf-8", errors="replace")
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        return f"search failed: {e} (network may be unavailable)"

    results = []
    for m in re.finditer(
        r'<a[^>]+class="result__a"[^>]+href="([^"]+)"[^>]*>(.*?)</a>',
        body,
        re.DOTALL,
    ):
        href, title = m.group(1), re.sub(r"<[^>]+>", "", m.group(2))
        results.append(
            {"title": title.strip(), "url": _resolve_ddg_url(href)}
        )
        if len(results) >= max_results:
            break
    if not results:
        return "no results"
    # snippets, matched positionally with the result links
    snippets = re.findall(
        r'class="result__snippet"[^>]*>(.*?)</a>', body, re.DOTALL
    )
    for i, s in enumerate(snippets[: len(results)]):
        results[i]["snippet"] = re.sub(r"<[^>]+>", "", s).strip()[:300]
    return json.dumps(results, indent=1)


def _resolve_ddg_url(href: str) -> str:
    """DDG wraps targets in //duckduckgo.com/l/?uddg=<encoded> redirect
    links; unwrap to the real URL so web_fetch accepts it."""
    if href.startswith("//"):
        href = "https:" + href
    if "duckduckgo.com/l/" in href:
        qs = urllib.parse.parse_qs(urllib.parse.urlparse(href).query)
        target = (qs.get("uddg") or [None])[0]
        if target:
            return target
    return href
