"""Keyless web access for agents (reference: src/shared/web-tools.ts —
persistent Playwright sessions with accessibility-tree snapshots + Jina
fallback; here: a stdlib browser-lite).

Two layers:
- one-shot `web_fetch` / `web_search` (readable-text extraction,
  fail-closed offline)
- persistent `WebSession`s (the reference's browser-session
  equivalent): cookie jar shared across navigations, page snapshots as
  an accessibility-style outline (headings, indexed links, forms,
  buttons), link clicking by index, form fill+submit, history/back.
  No JS execution — the snapshot contract matches what agents actually
  consume from the reference's ARIA dumps (roles + names + refs).
"""

from __future__ import annotations

import html.parser
import http.cookiejar
import json
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from ..utils import locks

FETCH_TIMEOUT_S = 20
MAX_TEXT_CHARS = 8000
_UA = "Mozilla/5.0 (compatible; room-tpu/0.1)"


class _TextExtractor(html.parser.HTMLParser):
    SKIP = {"script", "style", "noscript", "svg", "head"}

    def __init__(self) -> None:
        super().__init__()
        self._skip_depth = 0
        self.chunks: list[str] = []

    def handle_starttag(self, tag, attrs):
        if tag in self.SKIP:
            self._skip_depth += 1

    def handle_endtag(self, tag):
        if tag in self.SKIP and self._skip_depth > 0:
            self._skip_depth -= 1

    def handle_data(self, data):
        if self._skip_depth == 0 and data.strip():
            self.chunks.append(data.strip())


def _extract_text(html_text: str) -> str:
    p = _TextExtractor()
    try:
        p.feed(html_text)
    except Exception:
        pass
    text = "\n".join(p.chunks)
    return re.sub(r"\n{3,}", "\n\n", text)


_SCRIPT_TAG_RE = re.compile(r"<script\b", re.IGNORECASE)
_NOSCRIPT_PLEA_RE = re.compile(
    r"(enable|requires?|turn\s+on|need)\s+(javascript|js\b)|"
    r"javascript\s+(is\s+)?(required|disabled)",
    re.IGNORECASE,
)
JS_RENDERED_NOTICE = (
    "page appears to be JS-rendered (script-heavy document with almost "
    "no static text); its content is unavailable here — this session "
    "does not execute JavaScript. Try the site's API, an alternate "
    "static page, or a search engine cache instead."
)


_SCRIPT_SPAN_RE = re.compile(
    r"<script\b[^>]*>.*?</script>", re.IGNORECASE | re.DOTALL
)
_SPA_MOUNT_RE = re.compile(
    r"<(?:div|main|section)\b[^>]*\bid\s*=\s*[\"']?"
    r"(?:root|app|__next|__nuxt|main)[\"'\s>]",
    re.IGNORECASE,
)


def _script_fraction(body: str) -> float:
    """Fraction of the document's bytes inside <script> spans (inline
    code + tag overhead; external bundles count their tag only)."""
    if not body:
        return 0.0
    total = sum(
        len(m.group(0)) for m in _SCRIPT_SPAN_RE.finditer(body)
    )
    return total / len(body)


def detect_js_rendered(body: str, extracted_text: str) -> bool:
    """Heuristic for SPA shells the stdlib browser cannot read
    (VERDICT r4 #7): a script-heavy document whose static text is
    near-empty, or an explicit noscript plea on a page with little
    other text. The reference solves this with real Chromium
    (src/shared/web-tools.ts:19-116); here the agent at least gets an
    explicit signal instead of silent emptiness.

    Sparse-but-complete pages (a minimal landing/redirect page that
    happens to load three analytics scripts) must NOT be flagged
    (ADVICE r5): beyond being script-heavy with thin text, the page
    must also look like an app shell — script bytes dominating the
    body, or a root SPA mount point (#root/#app/#__next/...)."""
    text_len = len(extracted_text.strip())
    if _NOSCRIPT_PLEA_RE.search(body) and text_len < 400:
        return True
    script_heavy = (len(_SCRIPT_TAG_RE.findall(body)) >= 3
                    and text_len < 200
                    and len(body) > 2000)
    if not script_heavy:
        return False
    return (_script_fraction(body) >= 0.25
            or _SPA_MOUNT_RE.search(body) is not None)


def web_fetch(url: str) -> str:
    if not url.startswith(("http://", "https://")):
        return f"invalid url: {url!r}"
    req = urllib.request.Request(url, headers={"User-Agent": _UA})
    try:
        with urllib.request.urlopen(req, timeout=FETCH_TIMEOUT_S) as resp:
            raw = resp.read(2_000_000)
            ctype = resp.headers.get("Content-Type", "")
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        return f"fetch failed: {e} (network may be unavailable)"
    body = raw.decode("utf-8", errors="replace")
    if "html" in ctype:
        text = _extract_text(body)
        if detect_js_rendered(body, text):
            return f"[{JS_RENDERED_NOTICE}]\n{text}"[:MAX_TEXT_CHARS]
        body = text
    return body[:MAX_TEXT_CHARS]


def web_search(query: str, max_results: int = 5) -> str:
    """DuckDuckGo HTML endpoint, parsed for title/url/snippet."""
    url = (
        "https://html.duckduckgo.com/html/?q="
        + urllib.parse.quote(query)
    )
    req = urllib.request.Request(url, headers={"User-Agent": _UA})
    try:
        with urllib.request.urlopen(req, timeout=FETCH_TIMEOUT_S) as resp:
            body = resp.read().decode("utf-8", errors="replace")
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        return f"search failed: {e} (network may be unavailable)"

    results = []
    for m in re.finditer(
        r'<a[^>]+class="result__a"[^>]+href="([^"]+)"[^>]*>(.*?)</a>',
        body,
        re.DOTALL,
    ):
        href, title = m.group(1), re.sub(r"<[^>]+>", "", m.group(2))
        results.append(
            {"title": title.strip(), "url": _resolve_ddg_url(href)}
        )
        if len(results) >= max_results:
            break
    if not results:
        return "no results"
    # snippets, matched positionally with the result links
    snippets = re.findall(
        r'class="result__snippet"[^>]*>(.*?)</a>', body, re.DOTALL
    )
    for i, s in enumerate(snippets[: len(results)]):
        results[i]["snippet"] = re.sub(r"<[^>]+>", "", s).strip()[:300]
    return json.dumps(results, indent=1)


def _resolve_ddg_url(href: str) -> str:
    """DDG wraps targets in //duckduckgo.com/l/?uddg=<encoded> redirect
    links; unwrap to the real URL so web_fetch accepts it."""
    if href.startswith("//"):
        href = "https:" + href
    if "duckduckgo.com/l/" in href:
        qs = urllib.parse.parse_qs(urllib.parse.urlparse(href).query)
        target = (qs.get("uddg") or [None])[0]
        if target:
            return target
    return href


# ---- persistent sessions (reference: web-tools.ts:19-116) ----

class _OutlineParser(html.parser.HTMLParser):
    """Accessibility-style page outline: headings, indexed links,
    forms with their fields, buttons, and title."""

    # unlike the text extractor, <head> stays parsed: <title> lives there
    SKIP = {"script", "style", "noscript", "svg"}
    HEADINGS = {"h1", "h2", "h3", "h4", "h5", "h6"}

    def __init__(self) -> None:
        super().__init__()
        self.title = ""
        self.links: list[dict] = []
        self.forms: list[dict] = []
        self.buttons: list[str] = []
        self.outline: list[str] = []
        self._skip = 0
        self._capture: list[str] | None = None
        self._capture_tag = ""
        self._form: dict | None = None
        self._in_title = False

    def handle_starttag(self, tag, attrs):
        a = dict(attrs)
        if tag in self.SKIP:
            self._skip += 1
            return
        if self._skip:
            return
        if tag == "title":
            self._in_title = True
        elif tag in self.HEADINGS or tag == "a" or tag == "button":
            self._capture = []
            self._capture_tag = tag
            if tag == "a":
                self._capture_href = a.get("href") or ""
        elif tag == "form":
            self._form = {
                "action": a.get("action") or "",
                "method": (a.get("method") or "get").lower(),
                "fields": [],
            }
            self.forms.append(self._form)
        elif tag in ("input", "textarea", "select") and \
                self._form is not None:
            if a.get("type") in ("submit", "hidden"):
                if a.get("type") == "hidden" and a.get("name"):
                    self._form["fields"].append({
                        "name": a["name"], "type": "hidden",
                        "value": a.get("value", ""),
                    })
                return
            if a.get("name"):
                self._form["fields"].append({
                    "name": a["name"],
                    "type": a.get("type") or tag,
                    "placeholder": a.get("placeholder", ""),
                })

    def handle_endtag(self, tag):
        if tag in self.SKIP and self._skip:
            self._skip -= 1
            return
        if tag == "title":
            self._in_title = False
        elif tag == "form":
            self._form = None
        elif self._capture is not None and tag == self._capture_tag:
            text = re.sub(r"\s+", " ", " ".join(self._capture)).strip()
            if self._capture_tag in self.HEADINGS:
                depth = int(self._capture_tag[1])
                self.outline.append(f"{'#' * depth} {text}")
            elif self._capture_tag == "a":
                if text or self._capture_href:
                    self.links.append(
                        {"text": text, "href": self._capture_href}
                    )
            elif self._capture_tag == "button" and text:
                self.buttons.append(text)
            self._capture = None

    def handle_data(self, data):
        if self._skip:
            return
        if self._in_title:
            self.title += data
        if self._capture is not None and data.strip():
            self._capture.append(data.strip())


class WebSession:
    """One persistent browsing session: cookies + history + the parsed
    current page."""

    def __init__(self, session_id: str) -> None:
        self.id = session_id
        self.created_at = time.time()
        self.last_used = time.time()
        self._jar = http.cookiejar.CookieJar()
        self._opener = urllib.request.build_opener(
            urllib.request.HTTPCookieProcessor(self._jar)
        )
        self.url: str | None = None
        self.history: list[str] = []
        self._page: _OutlineParser | None = None
        self._text = ""
        self._js_rendered = False

    # -- navigation --

    def goto(self, url: str, data: bytes | None = None) -> dict:
        if not url.startswith(("http://", "https://")):
            return {"error": f"invalid url: {url!r}"}
        self.last_used = time.time()
        req = urllib.request.Request(
            url, data=data, headers={"User-Agent": _UA}
        )
        try:
            with self._opener.open(req, timeout=FETCH_TIMEOUT_S) as resp:
                raw = resp.read(2_000_000)
                final_url = resp.geturl()
                ctype = resp.headers.get("Content-Type", "")
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            return {"error":
                    f"fetch failed: {e} (network may be unavailable)"}
        body = raw.decode("utf-8", errors="replace")
        if self.url:
            self.history.append(self.url)
        self.url = final_url
        if "html" in ctype or body.lstrip()[:1] == "<":
            page = _OutlineParser()
            try:
                page.feed(body)
            except Exception:
                pass
            self._page = page
            self._text = _extract_text(body)
            self._js_rendered = detect_js_rendered(body, self._text)
        else:
            self._page = None
            self._text = body
            self._js_rendered = False
        return self.snapshot()

    def back(self) -> dict:
        if not self.history:
            return {"error": "no history"}
        url = self.history.pop()
        prev_history = list(self.history)
        out = self.goto(url)
        # goto() pushed the page we came FROM; restore the real stack
        self.history = prev_history
        return out

    # -- interaction --

    def click(self, link_index: int) -> dict:
        """Follow link #i from the current snapshot."""
        if self._page is None:
            return {"error": "no page loaded"}
        links = self._page.links
        if not 0 <= link_index < len(links):
            return {"error":
                    f"link index {link_index} out of range "
                    f"(0..{len(links) - 1})"}
        href = links[link_index]["href"]
        if not href:
            return {"error": "link has no href"}
        return self.goto(urllib.parse.urljoin(self.url or "", href))

    def submit_form(self, form_index: int, fields: dict) -> dict:
        """Fill + submit form #i (GET query or POST urlencoded)."""
        if self._page is None:
            return {"error": "no page loaded"}
        forms = self._page.forms
        if not 0 <= form_index < len(forms):
            return {"error": f"form index {form_index} out of range"}
        form = forms[form_index]
        values = {
            f["name"]: f.get("value", "")
            for f in form["fields"] if f.get("type") == "hidden"
        }
        values.update(fields or {})
        action = urllib.parse.urljoin(
            self.url or "", form["action"] or (self.url or "")
        )
        encoded = urllib.parse.urlencode(values)
        if form["method"] == "post":
            return self.goto(action, data=encoded.encode())
        sep = "&" if "?" in action else "?"
        return self.goto(f"{action}{sep}{encoded}")

    # -- views --

    def snapshot(self) -> dict:
        """Accessibility-style outline the agent navigates by."""
        self.last_used = time.time()
        if self._page is None:
            return {
                "url": self.url,
                "text": self._text[:MAX_TEXT_CHARS],
            }
        p = self._page
        out: dict = {
            "url": self.url,
            "title": re.sub(r"\s+", " ", p.title).strip(),
            "outline": p.outline[:40],
            "links": [
                {"i": i, "text": l["text"][:80], "href": l["href"][:200]}
                for i, l in enumerate(p.links[:60])
            ],
            "forms": [
                {"i": i, "action": f["action"], "method": f["method"],
                 "fields": [x for x in f["fields"]
                            if x.get("type") != "hidden"]}
                for i, f in enumerate(p.forms[:10])
            ],
            "buttons": p.buttons[:20],
        }
        if self._js_rendered:
            # explicit signal beats silent emptiness: the agent can
            # route around (API, cache, different page) instead of
            # concluding the page is blank
            out["js_rendered"] = True
            out["warning"] = JS_RENDERED_NOTICE
        return out

    def text(self, find: str | None = None) -> str:
        self.last_used = time.time()
        if find:
            hits = []
            for line in self._text.splitlines():
                if find.lower() in line.lower():
                    hits.append(line.strip())
                if len(hits) >= 20:
                    break
            return "\n".join(hits) or f"{find!r} not found"
        return self._text[:MAX_TEXT_CHARS]


SESSION_TTL_S = 1800.0
MAX_SESSIONS = 8

_sessions: dict[str, WebSession] = {}
_sessions_lock = locks.make_lock("web_sessions")
_session_seq = 0


def open_web_session() -> WebSession:
    global _session_seq
    with _sessions_lock:
        now = time.time()
        for sid in [s for s, v in _sessions.items()
                    if now - v.last_used > SESSION_TTL_S]:
            del _sessions[sid]
        if len(_sessions) >= MAX_SESSIONS:
            oldest = min(_sessions.values(), key=lambda s: s.last_used)
            del _sessions[oldest.id]
        # sequence suffix: millisecond ids alone collide when two
        # sessions open inside the same ms, silently aliasing them
        _session_seq += 1
        sess = WebSession(
            f"web-{int(now * 1000) % 10**10}-{_session_seq}"
        )
        _sessions[sess.id] = sess
        return sess


def get_web_session(session_id: str) -> WebSession | None:
    with _sessions_lock:
        return _sessions.get(session_id)


def close_web_session(session_id: str) -> bool:
    with _sessions_lock:
        return _sessions.pop(session_id, None) is not None


def reset_web_sessions() -> None:
    with _sessions_lock:
        _sessions.clear()
