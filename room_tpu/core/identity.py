"""On-chain room identity: ERC-8004 agent registration on Base
(reference: src/shared/identity.ts — minimal registry ABI, data-URI
metadata describing the room).

Offline parts (metadata, calldata construction, registration records)
work everywhere; the actual chain write needs RPC and fails closed like
the wallet."""

from __future__ import annotations

import base64
import json
from typing import Optional

from ..db import Database
from .chains import ERC8004_REGISTRY
from .keccak import keccak256
from .wallet import WalletError, get_room_wallet
from . import rooms as rooms_mod


def _selector(signature: str) -> str:
    return keccak256(signature.encode())[:4].hex()


# registerAgent(string metadataURI)
REGISTER_SELECTOR = _selector("registerAgent(string)")
# updateAgent(uint256 agentId, string metadataURI)
UPDATE_SELECTOR = _selector("updateAgent(uint256,string)")


def build_agent_metadata(db: Database, room_id: int) -> dict:
    room = rooms_mod.get_room(db, room_id)
    if room is None:
        raise ValueError(f"room {room_id} not found")
    wallet = get_room_wallet(db, room_id)
    workers = db.query(
        "SELECT name, role FROM workers WHERE room_id=?", (room_id,)
    )
    return {
        "name": room["name"],
        "description": room["goal"] or "",
        "type": "autonomous-agent-collective",
        "framework": "room-tpu",
        "address": wallet["address"] if wallet else None,
        "agents": [
            {"name": w["name"], "role": w["role"]} for w in workers
        ],
    }


def metadata_data_uri(metadata: dict) -> str:
    payload = base64.b64encode(
        json.dumps(metadata, separators=(",", ":")).encode()
    ).decode()
    return f"data:application/json;base64,{payload}"


def _abi_encode_string(s: str) -> str:
    raw = s.encode()
    padded = raw + b"\x00" * (-len(raw) % 32)
    return (
        hex(32)[2:].rjust(64, "0")          # offset
        + hex(len(raw))[2:].rjust(64, "0")  # length
        + padded.hex()
    )


def build_register_calldata(metadata_uri: str) -> str:
    return "0x" + REGISTER_SELECTOR + _abi_encode_string(metadata_uri)


def register_room_identity(
    db: Database, room_id: int, chain: str = "base",
    dry_run: bool = True,
) -> dict:
    """Prepare (and, with RPC access, submit) the registration. dry_run
    returns the transaction without network access."""
    registry = ERC8004_REGISTRY.get(chain)
    if registry is None:
        raise WalletError(f"no ERC-8004 registry configured for {chain}")
    wallet = get_room_wallet(db, room_id)
    if wallet is None:
        raise WalletError(f"room {room_id} has no wallet")
    metadata = build_agent_metadata(db, room_id)
    uri = metadata_data_uri(metadata)
    tx = {
        "to": registry,
        "from": wallet["address"],
        "data": build_register_calldata(uri),
        "chain": chain,
    }
    if dry_run:
        return {"tx": tx, "metadata": metadata, "submitted": False}

    # live submission: sign the registration call and broadcast
    # (fail-closed: the nonce/fee RPC reads raise without network)
    from .ethtx import sign_eip1559
    from .wallet import _rpc, decrypt_wallet_key
    from .chains import CHAINS

    cfg = CHAINS[chain]
    nonce = int(_rpc(
        chain, "eth_getTransactionCount", [wallet["address"], "pending"]
    ), 16)
    base_fee = int(_rpc(chain, "eth_gasPrice", []), 16)
    priority = max(base_fee // 10, 1_000_000)
    signed = sign_eip1559(
        decrypt_wallet_key(wallet),
        chain_id=cfg.chain_id,
        nonce=nonce,
        max_priority_fee_per_gas=priority,
        max_fee_per_gas=base_fee * 2 + priority,
        gas_limit=300_000,
        to=registry,
        value=0,
        data=bytes.fromhex(tx["data"][2:]),
    )
    tx_hash = _rpc(chain, "eth_sendRawTransaction", [signed["raw"]])
    return {
        "tx": tx, "metadata": metadata, "submitted": True,
        "txHash": tx_hash,
    }


def record_registration(
    db: Database, room_id: int, agent_id: str
) -> None:
    db.execute(
        "UPDATE wallets SET erc8004_agent_id=? WHERE room_id=?",
        (agent_id, room_id),
    )


def get_identity(db: Database, room_id: int) -> Optional[dict]:
    w = get_room_wallet(db, room_id)
    if w is None:
        return None
    return {
        "address": w["address"],
        "chain": w["chain"],
        "erc8004_agent_id": w["erc8004_agent_id"],
        "registered": w["erc8004_agent_id"] is not None,
    }
