"""Worker and room templates (reference: src/shared/worker-templates.ts,
room-templates.ts): named presets a keeper (or the clerk) instantiates
with one call."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..db import Database
from . import rooms as rooms_mod, workers as workers_mod


@dataclass(frozen=True)
class WorkerTemplate:
    key: str
    name: str
    role: str
    description: str
    system_prompt: str


WORKER_TEMPLATES: dict[str, WorkerTemplate] = {
    t.key: t
    for t in (
        WorkerTemplate(
            "scout", "Scout", "researcher",
            "Finds and verifies information fast.",
            "You are Scout. Hunt down the information the room needs: "
            "search, cross-check at least two sources, store verified "
            "findings with remember(), and flag anything dubious.",
        ),
        WorkerTemplate(
            "forge", "Forge", "executor",
            "Builds whatever the queen delegates.",
            "You are Forge. Take delegated goals and produce concrete "
            "artifacts. Break work into steps, do the next step every "
            "cycle, and report progress on your goals honestly.",
        ),
        WorkerTemplate(
            "blaze", "Blaze", "executor",
            "Ships quickly and iterates.",
            "You are Blaze. Bias to shipping: prefer a rough working "
            "version now over a perfect one later. Close goals fast and "
            "note follow-ups in memory.",
        ),
        WorkerTemplate(
            "warden", "Warden", "guardian",
            "Reviews decisions and guards the room.",
            "You are Warden. Each cycle review announced decisions and "
            "recent activity for risk, waste, or scope creep. Object "
            "with a clear reason when warranted; stay silent otherwise.",
        ),
        WorkerTemplate(
            "scribe", "Scribe", "writer",
            "Turns the room's work into prose.",
            "You are Scribe. Maintain clear written artifacts: status "
            "summaries, documentation, reports. Pull from goals, memory "
            "and activity; store finished documents with remember().",
        ),
        WorkerTemplate(
            "ledger", "Ledger", "analyst",
            "Watches numbers and metrics.",
            "You are Ledger. Track the room's measurable outcomes, "
            "reconcile them against goals, and surface trends the queen "
            "should act on.",
        ),
        WorkerTemplate(
            "herald", "Herald", "writer",
            "Keeps the keeper and other rooms informed.",
            "You are Herald. Watch for milestones, blockers, and "
            "decisions that the keeper or peer rooms should hear about; "
            "send concise messages when they happen and answer incoming "
            "mail promptly.",
        ),
        WorkerTemplate(
            "probe", "Probe", "researcher",
            "Stress-tests the room's own plans.",
            "You are Probe. Each cycle pick one active goal or recent "
            "decision and try to break it: find the failure mode, the "
            "missing dependency, the untested assumption. File what you "
            "find as objections or memory notes.",
        ),
    )
}


@dataclass(frozen=True)
class RoomTemplate:
    key: str
    name: str
    goal: str
    description: str
    workers: tuple[str, ...] = field(default=())


ROOM_TEMPLATES: dict[str, RoomTemplate] = {
    t.key: t
    for t in (
        RoomTemplate(
            "saas-builder", "SaaS Builder",
            "Design, build, and launch a small SaaS product end to end.",
            "Queen + Forge/Blaze builders + Scout research + Warden "
            "review.",
            ("scout", "forge", "blaze", "warden"),
        ),
        RoomTemplate(
            "research-desk", "Research Desk",
            "Continuously research a topic and maintain a living brief.",
            "Scout-heavy room with a Scribe for synthesis.",
            ("scout", "scout", "scribe"),
        ),
        RoomTemplate(
            "ops-room", "Ops Room",
            "Keep scheduled jobs healthy and report anomalies.",
            "Executor + analyst + guardian for steady-state operations.",
            ("forge", "ledger", "warden"),
        ),
        RoomTemplate(
            "content-studio", "Content Studio",
            "Produce a steady stream of written artifacts on a theme.",
            "Research feeds writing; a herald publishes updates.",
            ("scout", "scribe", "scribe", "herald"),
        ),
        RoomTemplate(
            "red-team", "Red Team",
            "Adversarially probe a plan, product, or codebase and "
            "report weaknesses.",
            "Probes attack, a warden triages, a scribe writes it up.",
            ("probe", "probe", "warden", "scribe"),
        ),
    )
}


def instantiate_room_template(
    db: Database,
    template_key: str,
    name: Optional[str] = None,
    worker_model: str = "tpu",
) -> dict:
    tpl = ROOM_TEMPLATES.get(template_key)
    if tpl is None:
        raise KeyError(
            f"unknown room template {template_key!r}; known: "
            f"{sorted(ROOM_TEMPLATES)}"
        )
    room = rooms_mod.create_room(
        db, name or tpl.name, goal=tpl.goal, worker_model=worker_model
    )
    for wkey in tpl.workers:
        add_worker_from_template(db, room["id"], wkey, model=worker_model)
    return rooms_mod.get_room(db, room["id"])  # type: ignore[return-value]


def add_worker_from_template(
    db: Database, room_id: int, template_key: str,
    model: Optional[str] = None,
) -> int:
    tpl = WORKER_TEMPLATES.get(template_key)
    if tpl is None:
        raise KeyError(
            f"unknown worker template {template_key!r}; known: "
            f"{sorted(WORKER_TEMPLATES)}"
        )
    return workers_mod.create_worker(
        db, tpl.name, tpl.system_prompt, room_id=room_id, role=tpl.role,
        model=model, description=tpl.description,
    )
