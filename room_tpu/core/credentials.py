"""Encrypted per-room credential store + API-key resolution chain
(reference: src/shared/model-provider.ts:87-141 — this room's credential →
any room's credential → clerk setting → environment variable)."""

from __future__ import annotations

import os
from typing import Optional

from ..db import Database
from .messages import get_setting
from .secrets import decrypt_secret, encrypt_secret, is_encrypted


def store_credential(
    db: Database,
    room_id: int,
    name: str,
    value: str,
    type_: str = "other",
    provided_by: str = "keeper",
) -> int:
    db.execute(
        "INSERT INTO credentials(room_id, name, type, value_encrypted, "
        "provided_by) VALUES (?,?,?,?,?) "
        "ON CONFLICT(room_id, name) DO UPDATE SET "
        "value_encrypted=excluded.value_encrypted, type=excluded.type",
        (room_id, name, type_, encrypt_secret(value), provided_by),
    )
    row = db.query_one(
        "SELECT id FROM credentials WHERE room_id=? AND name=?",
        (room_id, name),
    )
    return int(row["id"])  # upserts can't trust lastrowid


def get_credential(db: Database, room_id: int, name: str) -> Optional[str]:
    row = db.query_one(
        "SELECT value_encrypted FROM credentials WHERE room_id=? AND name=?",
        (room_id, name),
    )
    if row is None:
        return None
    v = row["value_encrypted"]
    return decrypt_secret(v) if is_encrypted(v) else v


def list_credentials(db: Database, room_id: int) -> list[dict]:
    """Metadata only — values never leave the store unencrypted in bulk."""
    return db.query(
        "SELECT id, room_id, name, type, provided_by, created_at "
        "FROM credentials WHERE room_id=? ORDER BY id",
        (room_id,),
    )


def delete_credential(db: Database, room_id: int, name: str) -> bool:
    return db.execute(
        "DELETE FROM credentials WHERE room_id=? AND name=?", (room_id, name)
    ).rowcount > 0


def resolve_api_key(
    db: Database, key_name: str, room_id: Optional[int] = None
) -> Optional[str]:
    """Resolution chain: this room's credential → any room's credential →
    settings table → environment variable."""
    if room_id is not None:
        v = get_credential(db, room_id, key_name)
        if v:
            return v
    row = db.query_one(
        "SELECT value_encrypted FROM credentials WHERE name=? ORDER BY id "
        "LIMIT 1",
        (key_name,),
    )
    if row:
        v = row["value_encrypted"]
        return decrypt_secret(v) if is_encrypted(v) else v
    v = get_setting(db, key_name)
    if v:
        return v
    return os.environ.get(key_name)
