"""Qwen3/Qwen2-family decoder in functional JAX.

One implementation serves both flagship models (qwen3-coder-30B MoE and
Qwen2.5-72B dense) — the config toggles MoE, qk-norm, and qkv-bias.

TPU-first design choices:
- Layer parameters are *stacked* along a leading [L, ...] axis and the
  forward pass is a ``lax.scan`` over layers: one traced layer body
  regardless of depth, so the 48-layer model compiles as fast as the
  2-layer test model.
- Activations stay in bf16; norms/softmax/rope accumulate in fp32.
- The KV cache is a dense [L, B, Smax, Hkv, Dh] pair updated with
  per-batch scatter writes; the serving engine swaps in its paged cache
  by passing a custom ``attention_fn`` (same contract as
  ops.attention_ref).

Weights map 1:1 onto the upstream checkpoints' tensors (q/k/v/o, gate/up/
down, router, per-head q/k norms) so a converter can load the real 30B.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import (
    apply_rope, attention_ref, moe_ffn, moe_ffn_gshard, rms_norm,
    rope_angles, swiglu,
)
from ..ops.quant import QTensor, qeinsum
from .config import DecoderConfig

Params = dict[str, Any]


# ---- init ----

def _normal(key, shape, scale, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)


def init_params(cfg: DecoderConfig, key: jax.Array) -> Params:
    """Random-init parameter pytree (layer axes stacked at dim 0)."""
    dt = cfg.activation_dtype
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    scale = 1.0 / np.sqrt(cfg.hidden)
    lk = jax.random.split(k_layers, 12)
    L, D, Hq, Hkv, Dh = (
        cfg.n_layers, cfg.hidden, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    )

    layers: Params = {
        "wq": _normal(lk[0], (L, D, Hq * Dh), scale, dt),
        "wk": _normal(lk[1], (L, D, Hkv * Dh), scale, dt),
        "wv": _normal(lk[2], (L, D, Hkv * Dh), scale, dt),
        "wo": _normal(lk[3], (L, Hq * Dh, D), scale, dt),
        "ln1": jnp.ones((L, D), dt),
        "ln2": jnp.ones((L, D), dt),
    }
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, Hq * Dh), dt)
        layers["bk"] = jnp.zeros((L, Hkv * Dh), dt)
        layers["bv"] = jnp.zeros((L, Hkv * Dh), dt)
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, Dh), dt)
        layers["k_norm"] = jnp.ones((L, Dh), dt)
    if cfg.is_moe:
        E, F = cfg.n_experts, cfg.moe_intermediate
        layers["router"] = _normal(lk[4], (L, D, E), scale, jnp.float32)
        layers["w_gate"] = _normal(lk[5], (L, E, D, F), scale, dt)
        layers["w_up"] = _normal(lk[6], (L, E, D, F), scale, dt)
        layers["w_down"] = _normal(
            lk[7], (L, E, F, D), 1.0 / np.sqrt(F), dt
        )
    else:
        F = cfg.intermediate
        layers["w_gate"] = _normal(lk[5], (L, D, F), scale, dt)
        layers["w_up"] = _normal(lk[6], (L, D, F), scale, dt)
        layers["w_down"] = _normal(lk[7], (L, F, D), 1.0 / np.sqrt(F), dt)

    params: Params = {
        "embed": _normal(k_embed, (cfg.vocab_size, D), 1.0, dt),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _normal(k_head, (D, cfg.vocab_size), scale, dt)
    return params


# ---- KV cache ----

def init_kv_cache(
    cfg: DecoderConfig, batch: int, max_len: int, dtype=None
) -> Params:
    dt = dtype or cfg.activation_dtype
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dt),
        "v": jnp.zeros(shape, dt),
        "lengths": jnp.zeros((batch,), jnp.int32),
    }


# ---- forward ----

AttentionFn = Callable[..., jax.Array]


KvHook = Callable[..., tuple[jax.Array, Any]]


def _layer(
    cfg: DecoderConfig,
    attention_fn: AttentionFn,
    x: jax.Array,                 # [B, S, D]
    lp: Params,                   # this layer's params (leading axis removed)
    cos: jax.Array,
    sin: jax.Array,
    layer_cache: Optional[Params],  # {"k","v"} [B, Smax, Hkv, Dh] or None
    write_pos: Optional[jax.Array],  # [B, S] absolute positions to write
    kv_mask: Optional[jax.Array],
    q_positions: jax.Array,
    kv_hook: Optional[KvHook] = None,
) -> tuple[jax.Array, Optional[Params]]:
    b, s, d = x.shape
    h = rms_norm(x, lp["ln1"], cfg.rms_eps)
    q = qeinsum("bsd,de->bse", h, lp["wq"])
    k = qeinsum("bsd,de->bse", h, lp["wk"])
    v = qeinsum("bsd,de->bse", h, lp["wv"])
    if cfg.qkv_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    new_cache = None
    if kv_hook is not None:
        # serving-engine cache (e.g. paged KV): the hook owns both the
        # cache write and the attention read
        attn, new_cache = kv_hook(q, k, v, layer_cache)
    elif layer_cache is not None:
        # scatter this chunk into the cache at its absolute positions
        bidx = jnp.arange(b)[:, None]
        ck = layer_cache["k"].at[bidx, write_pos].set(k)
        cv = layer_cache["v"].at[bidx, write_pos].set(v)
        new_cache = {"k": ck, "v": cv}
        kv_len = ck.shape[1]
        kv_positions = jnp.broadcast_to(
            jnp.arange(kv_len)[None], (b, kv_len)
        )
        attn = attention_fn(
            q, ck, cv, causal=True, q_positions=q_positions,
            kv_positions=kv_positions, kv_mask=kv_mask,
        )
    else:
        attn = attention_fn(
            q, k, v, causal=True, q_positions=q_positions,
            kv_positions=q_positions, kv_mask=None,
        )

    attn = attn.reshape(b, s, cfg.n_heads * cfg.head_dim)
    x = x + qeinsum("bse,ed->bsd", attn, lp["wo"])

    h = rms_norm(x, lp["ln2"], cfg.rms_eps)
    if cfg.is_moe:
        if cfg.moe_impl not in ("ragged", "gshard", "shardmap"):
            raise ValueError(
                f"unknown moe_impl {cfg.moe_impl!r} "
                "(ragged|gshard|shardmap)"
            )
        if cfg.moe_impl == "shardmap":
            from ..ops.moe_shardmap import moe_ffn_shardmap_padded

            moe = partial(moe_ffn_shardmap_padded, mesh_key=cfg.name)
        else:
            moe = moe_ffn_gshard if cfg.moe_impl == "gshard" \
                else moe_ffn
        y = moe(
            h.reshape(b * s, d), lp["router"], lp["w_gate"], lp["w_up"],
            lp["w_down"],
            top_k=cfg.top_k, renormalize=cfg.norm_topk_prob,
        ).reshape(b, s, d)
    else:
        y = swiglu(h, lp["w_gate"], lp["w_up"], lp["w_down"])
    return x + y, new_cache


def forward(
    params: Params,
    cfg: DecoderConfig,
    tokens: jax.Array,                     # [B, S]
    positions: Optional[jax.Array] = None,  # [B, S] absolute positions
    kv_cache: Optional[Params] = None,
    attention_fn: AttentionFn = attention_ref,
    kv_hook: Optional[KvHook] = None,
    apply_head: bool = True,
) -> tuple[jax.Array, Optional[Params]]:
    """Run the decoder. Returns (logits [B, S, V], updated cache or None).

    Without a cache this is plain causal prefill/training. With a cache,
    ``positions`` gives each token's absolute slot; cached entries at
    positions < per-batch length are attended to (prefix continuation /
    single-token decode are the same code path). With ``kv_hook``, the
    hook owns cache write + attention and ``kv_cache`` is an opaque
    pytree whose leaves lead with the layer axis (scanned).

    ``apply_head=False`` returns the final hidden states [B, S, D]
    instead of logits — serving prefill samples only each row's last
    real position, and at a 151k vocab the full [B, S, V] head matmul
    dominates prefill FLOPs; callers slice then run ``_head`` on
    [B, 1, D].
    """
    b, s = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    emb = params["embed"]
    if isinstance(emb, QTensor):
        # per-row scale: gather + scale is exact dequantization
        x = (
            emb.q[tokens].astype(jnp.float32) * emb.s[tokens]
        ).astype(cfg.activation_dtype)
    else:
        x = emb[tokens]
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

    if kv_hook is not None:
        def body_hook(carry, xs):
            lp, layer_cache = xs
            y, new_layer_cache = _layer(
                cfg, attention_fn, carry, lp, cos, sin, layer_cache,
                None, None, positions, kv_hook,
            )
            return y, new_layer_cache

        x, new_cache = jax.lax.scan(
            body_hook, x, (params["layers"], kv_cache)
        )
        if not apply_head:
            return rms_norm(x, params["final_norm"], cfg.rms_eps), \
                new_cache
        return _head(params, cfg, x), new_cache

    kv_mask = None
    if kv_cache is not None:
        # Capacity is the caller's contract (the serving engine's admission
        # control never schedules past max_len). Inside jit we can't raise,
        # so out-of-range writes are dropped by scatter semantics and
        # lengths is clamped to stay bounded.
        max_len = kv_cache["k"].shape[2]
        new_lengths = jnp.minimum(
            jnp.maximum(kv_cache["lengths"], positions.max(axis=1) + 1),
            max_len,
        )
        kv_mask = (
            jnp.arange(max_len)[None] < new_lengths[:, None]
        )

    def body(carry, xs):
        x = carry
        lp, layer_cache = xs
        x, new_layer_cache = _layer(
            cfg, attention_fn, x, lp, cos, sin, layer_cache,
            positions if kv_cache is not None else None,
            kv_mask, positions,
        )
        return x, new_layer_cache

    if kv_cache is None:
        layer_fn = lambda c, lp: (body(c, (lp, None))[0], None)  # noqa: E731
        if cfg.remat:
            # recompute layer activations in backward: HBM usage drops
            # from O(L) live activation sets to O(1) at the cost of one
            # extra forward per layer (the standard TPU training trade)
            layer_fn = jax.checkpoint(layer_fn)
        x, _ = jax.lax.scan(layer_fn, x, params["layers"])
        new_cache = None
    else:
        x, new_kv = jax.lax.scan(
            body, x, (params["layers"], {"k": kv_cache["k"],
                                         "v": kv_cache["v"]}),
        )
        new_cache = {
            "k": new_kv["k"], "v": new_kv["v"], "lengths": new_lengths,
        }

    return _head(params, cfg, x), new_cache


def _head(params: Params, cfg: DecoderConfig, x: jax.Array) -> jax.Array:
    return lm_head(
        params, cfg, rms_norm(x, params["final_norm"], cfg.rms_eps)
    )


def lm_head(params: Params, cfg: DecoderConfig,
            normed: jax.Array) -> jax.Array:
    """Vocabulary projection over ALREADY-final-normed hidden states
    (what forward(apply_head=False) returns)."""
    head = params.get("lm_head")
    if head is None:
        emb = params["embed"]
        if isinstance(emb, QTensor):
            # tied head: per-row embed scale lands on the vocab axis
            y = jnp.einsum("bsd,vd->bsv", normed,
                           emb.q.astype(normed.dtype))
            return (
                y.astype(jnp.float32) * emb.s.reshape(-1)
            ).astype(normed.dtype)
        return jnp.einsum("bsd,dv->bsv", normed, emb.T)
    return qeinsum("bsd,dv->bsv", normed, head)


def decode_step(
    params: Params,
    cfg: DecoderConfig,
    tokens: jax.Array,          # [B] next token per sequence
    kv_cache: Params,
    attention_fn: AttentionFn = attention_ref,
) -> tuple[jax.Array, Params]:
    """One continuous-decode step: append each sequence's token at its
    current length. Returns (logits [B, V], cache)."""
    positions = kv_cache["lengths"][:, None]
    logits, new_cache = forward(
        params, cfg, tokens[:, None], positions, kv_cache, attention_fn
    )
    return logits[:, 0], new_cache


def param_count(params: Params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
