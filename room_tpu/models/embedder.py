"""384-d bidirectional text encoder (MiniLM-class) in functional JAX.

Replaces the reference's CPU ONNX all-MiniLM-L6-v2 pipeline (reference:
src/shared/embeddings.ts:33-100) with an XLA model that lives on the same
mesh as the LLM. Mean-pooled, L2-normalized sentence vectors; weights map
onto the upstream BERT-style checkpoint (word+position+type embeddings,
post-LN transformer, GELU FFN).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import attention_ref
from .config import EncoderConfig

Params = dict[str, Any]


def init_params(cfg: EncoderConfig, key: jax.Array) -> Params:
    dt = jnp.dtype(cfg.dtype)
    ks = jax.random.split(key, 9)
    D, L, F = cfg.hidden, cfg.n_layers, cfg.intermediate
    s = 1.0 / np.sqrt(D)

    def n(k, shape, scale=s):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    return {
        "word_embed": n(ks[0], (cfg.vocab_size, D), 0.02),
        "pos_embed": n(ks[1], (cfg.max_positions, D), 0.02),
        "type_embed": n(ks[2], (2, D), 0.02),
        "embed_ln_scale": jnp.ones((D,), dt),
        "embed_ln_bias": jnp.zeros((D,), dt),
        "layers": {
            "wq": n(ks[3], (L, D, D)),
            "bq": jnp.zeros((L, D), dt),
            "wk": n(ks[4], (L, D, D)),
            "bk": jnp.zeros((L, D), dt),
            "wv": n(ks[5], (L, D, D)),
            "bv": jnp.zeros((L, D), dt),
            "wo": n(ks[6], (L, D, D)),
            "bo": jnp.zeros((L, D), dt),
            "attn_ln_scale": jnp.ones((L, D), dt),
            "attn_ln_bias": jnp.zeros((L, D), dt),
            "w_in": n(ks[7], (L, D, F)),
            "b_in": jnp.zeros((L, F), dt),
            "w_out": n(ks[8], (L, F, D)),
            "b_out": jnp.zeros((L, D), dt),
            "ffn_ln_scale": jnp.ones((L, D), dt),
            "ffn_ln_bias": jnp.zeros((L, D), dt),
        },
    }


def _layer_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def encode(
    params: Params,
    cfg: EncoderConfig,
    tokens: jax.Array,      # [B, S] int32
    mask: jax.Array,        # [B, S] 1 for real tokens
) -> jax.Array:
    """Sentence embeddings [B, hidden]: mean-pool over valid tokens, then
    L2-normalize."""
    b, s = tokens.shape
    dh = cfg.hidden // cfg.n_heads
    x = (
        params["word_embed"][tokens]
        + params["pos_embed"][jnp.arange(s)][None]
        + params["type_embed"][0][None, None]
    )
    x = _layer_norm(
        x, params["embed_ln_scale"], params["embed_ln_bias"],
        cfg.layer_norm_eps,
    )
    kv_mask = mask.astype(bool)

    def body(x, lp):
        def proj(w, bias):
            return (jnp.einsum("bsd,de->bse", x, w) + bias).reshape(
                b, s, cfg.n_heads, dh
            )

        q, k, v = proj(lp["wq"], lp["bq"]), proj(lp["wk"], lp["bk"]), \
            proj(lp["wv"], lp["bv"])
        ctx = attention_ref(q, k, v, causal=False, kv_mask=kv_mask)
        ctx = ctx.reshape(b, s, cfg.hidden).astype(x.dtype)
        attn_out = jnp.einsum("bsd,de->bse", ctx, lp["wo"]) + lp["bo"]
        x = _layer_norm(
            x + attn_out, lp["attn_ln_scale"], lp["attn_ln_bias"],
            cfg.layer_norm_eps,
        )
        # exact (erf) GELU: BERT/MiniLM checkpoints are trained with it,
        # and the tanh approximation drifts the converted embeddings
        h = jax.nn.gelu(
            jnp.einsum("bsd,df->bsf", x, lp["w_in"]) + lp["b_in"],
            approximate=False,
        )
        h = jnp.einsum("bsf,fd->bsd", h, lp["w_out"]) + lp["b_out"]
        x = _layer_norm(
            x + h, lp["ffn_ln_scale"], lp["ffn_ln_bias"],
            cfg.layer_norm_eps,
        )
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])

    m = mask[..., None].astype(jnp.float32)
    pooled = (x.astype(jnp.float32) * m).sum(1) / jnp.maximum(m.sum(1), 1e-9)
    return pooled / jnp.maximum(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
    )
