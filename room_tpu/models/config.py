"""Model configurations.

Flagship serving targets (BASELINE.md): qwen3-coder-30B (MoE, the worker
model) and Qwen2.5-72B (dense, the hetero-swarm queen), plus a 384-d
MiniLM-class embedder for semantic memory. Tiny variants of each exist for
hermetic tests and the virtual-device dry runs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class DecoderConfig:
    name: str
    vocab_size: int
    hidden: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    head_dim: int
    intermediate: int               # dense FFN width (MoE: unused)
    rope_theta: float = 1e6
    rms_eps: float = 1e-6
    tie_embeddings: bool = False
    qkv_bias: bool = False          # Qwen2 yes, Qwen3 no
    qk_norm: bool = True            # Qwen3 per-head q/k RMSNorm
    # MoE (None => dense)
    n_experts: Optional[int] = None
    top_k: int = 8
    moe_intermediate: int = 0
    norm_topk_prob: bool = True
    # "ragged": sort + lax.ragged_dot (best single-chip / dp+tp).
    # "gshard": capacity-based dense dispatch — partitions expert compute
    # over the ep mesh axis with only activation psums.
    moe_impl: str = "ragged"
    dtype: str = "bfloat16"
    max_seq_len: int = 32768
    # rematerialize layer activations in the backward pass (training /
    # fine-tuning memory lever: trades one extra forward of FLOPs per
    # layer for not keeping every layer's activations in HBM)
    remat: bool = False

    @property
    def is_moe(self) -> bool:
        return self.n_experts is not None

    @property
    def activation_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim


def qwen3_coder_30b() -> DecoderConfig:
    """qwen3-coder-30B (30B-A3B MoE): the pinned worker model — the tpu:
    provider's default, replacing the reference's `qwen3-coder:30b` Ollama
    tag (reference: src/shared/local-model.ts:3-5)."""
    return DecoderConfig(
        name="qwen3-coder-30b",
        vocab_size=151_936,
        hidden=2048,
        n_layers=48,
        n_heads=32,
        n_kv_heads=4,
        head_dim=128,
        intermediate=0,
        rope_theta=1e7,
        qkv_bias=False,
        qk_norm=True,
        n_experts=128,
        top_k=8,
        moe_intermediate=768,
    )


def qwen2_72b() -> DecoderConfig:
    """Qwen2.5-72B dense: the hetero-swarm queen model (BASELINE.md
    config #5)."""
    return DecoderConfig(
        name="qwen2.5-72b",
        vocab_size=152_064,
        hidden=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        head_dim=128,
        intermediate=29_568,
        rope_theta=1e6,
        qkv_bias=True,
        qk_norm=False,
    )


def llama31_8b() -> DecoderConfig:
    """Llama-3.1-8B — third supported family: GQA without qk-norm or
    qkv-bias, 500k rope theta, 128k-token vocabulary. The decoder and
    the safetensors converter already cover this tensor layout (same
    q/k/v/o + gate/up/down naming, no extra tensors)."""
    return DecoderConfig(
        name="llama31-8b",
        vocab_size=128_256,
        hidden=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        head_dim=128,
        intermediate=14_336,
        rope_theta=5e5,
        qkv_bias=False,
        qk_norm=False,
    )


def tiny_llama(vocab_size: int = 512) -> DecoderConfig:
    """Hermetic stand-in with the llama family's shape (GQA, no
    qk-norm/bias, dense FFN, tied-free head)."""
    return DecoderConfig(
        name="tiny-llama",
        vocab_size=vocab_size,
        hidden=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        intermediate=128,
        rope_theta=5e5,
        qkv_bias=False,
        qk_norm=False,
        dtype="float32",
        max_seq_len=8192,
    )


def tiny_moe(vocab_size: int = 512) -> DecoderConfig:
    """Hermetic-test stand-in with the 30B's *shape* (MoE, GQA, qk-norm)."""
    return DecoderConfig(
        name="tiny-moe",
        vocab_size=vocab_size,
        hidden=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        intermediate=0,
        rope_theta=1e4,
        qk_norm=True,
        n_experts=8,
        top_k=2,
        moe_intermediate=32,
        dtype="float32",
        # generous context: agent prompts under the byte tokenizer run
        # thousands of tokens even for the tiny test model
        max_seq_len=8192,
    )


def tiny_dense(vocab_size: int = 512) -> DecoderConfig:
    return DecoderConfig(
        name="tiny-dense",
        vocab_size=vocab_size,
        hidden=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        intermediate=128,
        rope_theta=1e4,
        qkv_bias=True,
        qk_norm=False,
        dtype="float32",
        max_seq_len=8192,
    )


def qwen3_draft(vocab_size: int = 151_936) -> DecoderConfig:
    """Small qwen3-family draft decoder for on-mesh speculative
    decoding (docs/serving.md): rides the serving mesh next to the
    target (like the embedder) and proposes greedy draft tokens inside
    the dispatch window, where the target's batched forward verifies
    them. The shape is chosen so one draft forward over the
    ROOM_TPU_DRAFT_WINDOW tail costs well under one target decode
    step."""
    return DecoderConfig(
        name="qwen3-draft",
        vocab_size=vocab_size,
        hidden=512,
        n_layers=4,
        n_heads=8,
        n_kv_heads=2,
        head_dim=64,
        intermediate=1024,
        rope_theta=1e6,
        qkv_bias=False,
        qk_norm=True,
    )


def tiny_draft(vocab_size: int = 512) -> DecoderConfig:
    """Hermetic-test draft decoder (1 layer): drafting quality is
    irrelevant to correctness — every proposal is verified by the
    target — so tests only need the smallest thing that runs."""
    return DecoderConfig(
        name="tiny-draft",
        vocab_size=vocab_size,
        hidden=32,
        n_layers=1,
        n_heads=2,
        n_kv_heads=1,
        head_dim=16,
        intermediate=64,
        rope_theta=1e4,
        qk_norm=False,
        dtype="float32",
        max_seq_len=8192,
    )


DRAFT_PRESETS = {
    "qwen3-draft": qwen3_draft,
    "tiny-draft": tiny_draft,
}


def resolve_draft_config(name: str, vocab_size: int) -> DecoderConfig:
    """Resolve ``ROOM_TPU_DRAFT_MODEL`` to a draft config sharing the
    target's vocabulary (proposals are token ids the target's verify
    looks up — a mismatched vocab would index out of range). Unknown
    names raise so a typo'd deployment knob is loud."""
    fn = DRAFT_PRESETS.get(name)
    if fn is None:
        raise ValueError(
            f"unknown draft model {name!r}; known: "
            f"{sorted(DRAFT_PRESETS)}"
        )
    return fn(vocab_size=vocab_size)


@dataclass(frozen=True)
class EncoderConfig:
    """Bidirectional encoder for the 384-d memory embedder (the reference
    ran all-MiniLM-L6-v2 on CPU ONNX; here it is a JAX model on the mesh —
    reference: src/shared/embeddings.ts:33-69)."""
    name: str = "tpu-embed-384"
    vocab_size: int = 30_522
    hidden: int = 384
    n_layers: int = 6
    n_heads: int = 12
    intermediate: int = 1536
    max_positions: int = 512
    layer_norm_eps: float = 1e-12
    dtype: str = "float32"


def minilm_384() -> EncoderConfig:
    return EncoderConfig()


def tiny_encoder() -> EncoderConfig:
    return EncoderConfig(
        name="tiny-embed", vocab_size=256, hidden=32, n_layers=2,
        n_heads=4, intermediate=64, max_positions=128,
    )
