from . import config, embedder, qwen3
from .config import (
    DecoderConfig,
    EncoderConfig,
    minilm_384,
    qwen2_72b,
    qwen3_coder_30b,
    tiny_dense,
    tiny_encoder,
    tiny_moe,
)

__all__ = [
    "config",
    "embedder",
    "qwen3",
    "DecoderConfig",
    "EncoderConfig",
    "minilm_384",
    "qwen2_72b",
    "qwen3_coder_30b",
    "tiny_dense",
    "tiny_encoder",
    "tiny_moe",
]
