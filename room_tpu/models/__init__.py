from . import config, embedder, qwen3
from .config import (
    DecoderConfig,
    EncoderConfig,
    llama31_8b,
    minilm_384,
    qwen2_72b,
    qwen3_coder_30b,
    tiny_dense,
    tiny_encoder,
    tiny_llama,
    tiny_moe,
)

__all__ = [
    "config",
    "embedder",
    "qwen3",
    "DecoderConfig",
    "EncoderConfig",
    "llama31_8b",
    "minilm_384",
    "qwen2_72b",
    "qwen3_coder_30b",
    "tiny_dense",
    "tiny_encoder",
    "tiny_llama",
    "tiny_moe",
]
