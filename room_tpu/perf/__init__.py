"""Performance modeling: roofline predictions for the serving path."""

from room_tpu.perf.roofline import (  # noqa: F401
    V5E,
    ChipSpec,
    decode_flops_per_token,
    predict_decode,
    roofline_table,
    spec_expected_tokens,
)
