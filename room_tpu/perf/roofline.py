"""Roofline decode-performance model (VERDICT r4 #2).

Predicts decode tok/s + MFU from first principles so the perf story is
falsifiable before (and cross-checkable after) a hardware run. Per
decode step the chip must:

  (a) read every *active* weight byte once from HBM (batch rows share
      the read — this is why batching lifts decode throughput),
  (b) read each row's KV cache over its mean context,
  (c) compute ~2 FLOPs per active weight per token on the MXU.

Step time is the roofline max(bytes / HBM_BW, FLOPs / peak); decode on
a single chip is HBM-bandwidth-bound at every batch size this framework
serves (see the `bound` field), which is why the int8 levers (halving
weight or KV bytes) move the headline and extra MXU FLOPs are nearly
free — the basis for speculative decoding's uplift.

Speculative decoding is modeled as verify rounds: one forward over
(gamma+1) positions per row (weights read once per round, KV read once
per round per row — the verify pass is prefill-shaped), emitting
E[gamma, a] = sum_{i=0..gamma} a^i tokens per round at draft-acceptance
rate `a`. Draft generation itself is host-side n-gram lookup, ~free.

The FLOPs model here is the canonical one; bench.py imports it so the
measured MFU and the predicted MFU share arithmetic.

reference: BASELINE.md:34-35 (800 tok/s/chip, p50<4s) — the targets
these predictions are checked against; no reference-source counterpart
(the reference delegates serving perf to Ollama).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Peak numbers for one TPU chip (per-chip, not per-host)."""

    name: str
    peak_bf16_tflops: float   # dense bf16 matmul peak
    hbm_gbps: float           # HBM bandwidth, GB/s
    hbm_gib: float            # HBM capacity, GiB


# v5e: 197 bf16 TFLOP/s, 819 GB/s, 16 GiB — the chip BASELINE.md's
# 800 tok/s/chip target assumes.
V5E = ChipSpec("v5e", peak_bf16_tflops=197.0, hbm_gbps=819.0,
               hbm_gib=16.0)
# other generations the serving engine may land on (published peaks):
V4 = ChipSpec("v4", peak_bf16_tflops=275.0, hbm_gbps=1228.0,
              hbm_gib=32.0)
V5P = ChipSpec("v5p", peak_bf16_tflops=459.0, hbm_gbps=2765.0,
               hbm_gib=95.0)
V6E = ChipSpec("v6e", peak_bf16_tflops=918.0, hbm_gbps=1640.0,
               hbm_gib=32.0)

# substring of jax's device_kind (lowercased) -> spec; order matters
# ("v5p" must match before the bare "v5")
_KIND_TABLE = (
    ("v6e", V6E), ("trillium", V6E),
    ("v5p", V5P),
    ("v5e", V5E), ("v5 lite", V5E), ("v5litepod", V5E),
    ("v4", V4),
)


def detect_chip_spec(default: ChipSpec = V5E) -> ChipSpec:
    """ChipSpec for the device this process actually runs on, resolved
    from jax's device_kind (ADVICE r5: the engine's speculation gate
    must not assume V5E on every platform). CPU runs and unknown TPU
    generations fall back to ``default`` — V5E, the documented
    deployment target — which keeps gating behavior identical to the
    pre-detection code everywhere detection can't improve it."""
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return default
    for sub, spec in _KIND_TABLE:
        if sub in kind:
            return spec
    return default


def decode_flops_per_token(cfg, mean_ctx: float) -> float:
    """Forward FLOPs per decoded token: 2*active-params matmuls +
    attention score/value reads over the mean context."""
    d, dh = cfg.hidden, cfg.head_dim
    hq, hkv = cfg.n_heads, cfg.n_kv_heads
    attn_w = d * hq * dh + 2 * d * hkv * dh + hq * dh * d
    if cfg.is_moe:
        ffn_w = cfg.top_k * 3 * d * cfg.moe_intermediate
        ffn_w += d * cfg.n_experts  # router
    else:
        ffn_w = 3 * d * cfg.intermediate
    per_layer = 2 * (attn_w + ffn_w)
    # attention score+value FLOPs against the KV cache
    per_layer += 2 * 2 * mean_ctx * hq * dh
    head = 2 * d * cfg.vocab_size
    return cfg.n_layers * per_layer + head


def expected_experts_touched(cfg, tokens: int) -> float:
    """Expected distinct experts activated by `tokens` routed positions
    under uniform routing — the fraction of expert weight bytes a step
    actually reads. 1 - (1 - top_k/E)^tokens per expert."""
    if not cfg.is_moe:
        return 0.0
    p_miss = (1.0 - cfg.top_k / cfg.n_experts) ** tokens
    return cfg.n_experts * (1.0 - p_miss)


def step_weight_bytes(cfg, tokens: int, weight_bytes: float = 2.0) -> float:
    """HBM bytes of weights one forward step reads, shared across the
    `tokens` positions it processes (batch rows for plain decode,
    batch*(gamma+1) for a spec verify round). MoE expert bytes are
    scaled by the expected fraction of experts those tokens route to;
    embedding-table reads are row-gathers (negligible) but the LM head
    is a full matmul."""
    d, dh = cfg.hidden, cfg.head_dim
    hq, hkv = cfg.n_heads, cfg.n_kv_heads
    attn_p = d * hq * dh + 2 * d * hkv * dh + hq * dh * d
    if cfg.is_moe:
        per_expert = 3 * d * cfg.moe_intermediate
        ffn_p = expected_experts_touched(cfg, tokens) * per_expert
        ffn_p += d * cfg.n_experts
    else:
        ffn_p = 3 * d * cfg.intermediate
    head_p = d * cfg.vocab_size
    return (cfg.n_layers * (attn_p + ffn_p) + head_p) * weight_bytes


def kv_bytes_per_row(cfg, mean_ctx: float, kv_bytes: float = 2.0) -> float:
    """HBM bytes of KV cache one row's attention reads per step (K+V
    across all layers over the mean context)."""
    return cfg.n_layers * mean_ctx * 2 * cfg.kv_dim * kv_bytes


def step_components(
    cfg,
    chip: ChipSpec,
    batch: int,
    positions: int,
    mean_ctx: float,
    weight_bytes: float = 2.0,
    kv_bytes: float = 2.0,
) -> dict:
    """Bytes/FLOPs and HBM/MXU times of one forward step over batch
    rows x positions tokens each. The single source for
    predict_decode / predict_spec_class / spec_cost_ratio — the
    engine's throttle floor and the published tables must share this
    arithmetic."""
    b = (step_weight_bytes(cfg, batch * positions, weight_bytes)
         + batch * kv_bytes_per_row(cfg, mean_ctx, kv_bytes))
    f = batch * positions * decode_flops_per_token(cfg, mean_ctx)
    t_hbm = b / (chip.hbm_gbps * 1e9)
    t_mxu = f / (chip.peak_bf16_tflops * 1e12)
    return {"bytes": b, "flops": f, "t_hbm": t_hbm, "t_mxu": t_mxu,
            "t_step": max(t_hbm, t_mxu)}


def step_time_s(
    cfg,
    chip: ChipSpec,
    batch: int,
    positions: int,
    mean_ctx: float,
    weight_bytes: float = 2.0,
    kv_bytes: float = 2.0,
) -> float:
    """Roofline step time: max of HBM streaming and MXU compute."""
    return step_components(cfg, chip, batch, positions, mean_ctx,
                           weight_bytes, kv_bytes)["t_step"]


def spec_cost_ratio(
    cfg,
    batch: int,
    gamma: int,
    chip: ChipSpec = V5E,
    mean_ctx: float = 1024.0,
    weight_bytes: float = 2.0,
    kv_bytes: float = 2.0,
) -> float:
    """How much more a verify round costs than a plain decode step at
    the same (fixed) batch shape. >1 on MoE at small batch because the
    gamma+1 positions route through more distinct experts; ~1 for
    bandwidth-bound dense models (the extra FLOPs ride idle MXU)."""
    t_v = step_time_s(cfg, chip, batch, gamma + 1, mean_ctx,
                      weight_bytes, kv_bytes)
    t_p = step_time_s(cfg, chip, batch, 1, mean_ctx,
                      weight_bytes, kv_bytes)
    return t_v / t_p


def spec_expected_tokens(gamma: int, acceptance: float) -> float:
    """Expected tokens emitted per speculative verify round: the bonus
    token plus each draft token surviving with prob a^i —
    sum_{i=0..gamma} a^i."""
    if not 0.0 <= acceptance <= 1.0:
        raise ValueError(f"acceptance must be in [0,1], got {acceptance}")
    return sum(acceptance ** i for i in range(gamma + 1))


def predict_decode(
    cfg,
    chip: ChipSpec = V5E,
    batch: int = 8,
    mean_ctx: float = 2048.0,
    weight_bytes: float = 2.0,
    kv_bytes: float = 2.0,
    spec_gamma: int = 0,
    spec_acceptance: float = 0.0,
) -> dict:
    """Roofline prediction for one chip serving `batch` concurrent rows.

    Returns tok_s, per-step times, the binding resource, and MFU
    (achieved MXU FLOP/s over peak — decode MFU is inherently low
    because the workload is bandwidth-bound; that is the finding, not a
    bug)."""
    flops_tok = decode_flops_per_token(cfg, mean_ctx)
    positions = spec_gamma + 1  # verify round width (1 = plain decode)
    out_tokens = (batch * spec_expected_tokens(spec_gamma, spec_acceptance)
                  if spec_gamma else batch)

    # a verify round routes batch*(gamma+1) tokens through the MoE
    # router — it touches more distinct experts (more weight bytes)
    # than a plain decode step of the same batch
    c = step_components(cfg, chip, batch, positions, mean_ctx,
                        weight_bytes, kv_bytes)
    tok_s = out_tokens / c["t_step"]
    return {
        "tok_s": tok_s,
        "mfu": (c["flops"] / c["t_step"])
        / (chip.peak_bf16_tflops * 1e12),
        "bound": "hbm" if c["t_hbm"] >= c["t_mxu"] else "mxu",
        "t_hbm_us": c["t_hbm"] * 1e6,
        "t_mxu_us": c["t_mxu"] * 1e6,
        "step_bytes": c["bytes"],
        "step_flops": c["flops"],
        "flops_per_token": flops_tok,
    }


def predict_spec_class(
    cfg,
    chip: ChipSpec,
    batch: int,
    mean_ctx: float,
    gamma: int,
    rounds: int,
    plain_steps: int,
    emitted: int,
    weight_bytes: float = 2.0,
    kv_bytes: float = 2.0,
) -> dict:
    """Net TPU uplift of speculation for one traffic class, from
    replayed counters (room_tpu/serving/spec_replay.py): verify rounds
    pay the (gamma+1)-position step cost (more MoE experts touched),
    plain fallback rounds pay the 1-position cost, and the class emits
    `emitted` tokens over them. Uplift is vs all-plain sequential
    decode of the same tokens."""
    t_plain = step_time_s(cfg, chip, batch, 1, mean_ctx,
                          weight_bytes, kv_bytes)
    t_verify = step_time_s(cfg, chip, batch, gamma + 1, mean_ctx,
                           weight_bytes, kv_bytes)
    t_total = rounds * t_verify + plain_steps * t_plain
    tok_s = batch * emitted / t_total if t_total else 0.0
    baseline = batch / t_plain
    return {
        "tok_s": tok_s,
        "uplift": tok_s / baseline,
        "verify_cost_ratio": t_verify / t_plain,
    }


def spec_accept_floor(
    cfg,
    batch: int,
    gamma: int,
    chip: ChipSpec = V5E,
    mean_ctx: float = 1024.0,
    weight_bytes: float = 2.0,
    kv_bytes: float = 2.0,
) -> float:
    """Acceptance below which a verify round loses to plain decode on
    this model/batch shape: solves sum_{i<=gamma} a^i =
    t_verify/t_plain for a — the homogeneous-batch breakeven the
    published tables report, and the default per-class spec-off
    floor of the live gamma tuner (scheduler.SpecTuner;
    ROOM_TPU_SPEC_MIN_ACCEPT overrides)."""
    ratio = spec_cost_ratio(cfg, batch, gamma, chip, mean_ctx,
                            weight_bytes, kv_bytes)
    if ratio <= 1.0:
        return 0.0
    lo, hi = 0.0, 1.0
    for _ in range(40):
        mid = (lo + hi) / 2
        if sum(mid ** i for i in range(gamma + 1)) < ratio:
            lo = mid
        else:
            hi = mid
    return hi


# (label, weight_bytes, kv_bytes) — the serving engine's quant levers:
# ROOM_TPU_QUANT=int8 halves weight bytes, ROOM_TPU_KV_QUANT=int8
# halves KV bytes; both compute in bf16 on the MXU after dequant.
VARIANTS = (
    ("bf16", 2.0, 2.0),
    ("int8-weights", 1.0, 2.0),
    ("int8-kv", 2.0, 1.0),
    ("int8-w+kv", 1.0, 1.0),
)


def roofline_table(
    cfg,
    chip: ChipSpec = V5E,
    batches: Iterable[int] = (8, 32),
    mean_ctx: float = 2048.0,
    spec_gamma: int = 4,
    spec_acceptance: float = 0.8,
) -> list[dict]:
    """{bf16, int8-weights, int8-kv, int8-w+kv} x {spec off/on} x
    batches — the falsifiable prediction grid for the first green
    hardware window."""
    rows = []
    for label, wb, kb in VARIANTS:
        for batch in batches:
            for spec in (False, True):
                p = predict_decode(
                    cfg, chip, batch=batch, mean_ctx=mean_ctx,
                    weight_bytes=wb, kv_bytes=kb,
                    spec_gamma=spec_gamma if spec else 0,
                    spec_acceptance=spec_acceptance if spec else 0.0,
                )
                rows.append({
                    "variant": label,
                    "batch": batch,
                    "spec": (f"gamma{spec_gamma}@a={spec_acceptance}"
                             if spec else "off"),
                    "tok_s": round(p["tok_s"], 1),
                    "mfu": round(p["mfu"], 4),
                    "bound": p["bound"],
                })
    return rows


def format_markdown(rows: list[dict], chip: ChipSpec, cfg,
                    mean_ctx: float) -> str:
    head = (
        f"Roofline predictions — {cfg.name} on {chip.name} "
        f"({chip.peak_bf16_tflops:.0f} bf16 TFLOP/s, "
        f"{chip.hbm_gbps:.0f} GB/s HBM), mean ctx {mean_ctx:.0f}\n\n"
        "| variant | batch | spec | pred tok/s | pred MFU | bound |\n"
        "|---|---|---|---|---|---|\n"
    )
    body = "".join(
        f"| {r['variant']} | {r['batch']} | {r['spec']} | "
        f"{r['tok_s']} | {r['mfu']} | {r['bound']} |\n"
        for r in rows
    )
    return head + body
