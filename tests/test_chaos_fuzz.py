"""chaosfuzz self-test (docs/chaosfuzz.md): the invariant witness and
the seeded schedule fuzzer.

Witness half: each ``check_*`` is a pure reader over duck-typed state,
so every invariant gets a known-good and a known-bad fixture, plus the
strict-raise vs production-count contract and the snapshot surface.

Fuzzer half: the acceptance pins — same seed ⇒ byte-identical schedule
JSON and identical run outcome; a saved schedule replays to the same
outcome; the generator guarantees a kill event and a ≥2-point overlap;
a deliberately planted bug (``ROOM_TPU_CHAOSFUZZ_PLANT``) is detected
by the witness and auto-shrunk to ≤3 events; and the roomlint checker
keeps FUZZ_WEIGHTS ∪ FUZZ_EXCLUDED == faults.FAULT_POINTS.

Quick tier drives the SWARM workload (no model build, seconds); the
serving-workload determinism + kv_leak-plant runs live behind the
``slow`` marker — CI's chaosfuzz quick tier exercises the serving
workload through the CLI instead.
"""

import json
import threading
import time
from types import SimpleNamespace

import pytest

from room_tpu.chaos import fuzz, invariants
from room_tpu.serving import faults


@pytest.fixture(autouse=True)
def _clean_witness():
    faults.clear()
    invariants.reset()
    yield
    faults.clear()
    invariants.reset()


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("ROOM_TPU_INVARIANTS", "1")
    monkeypatch.setenv("ROOM_TPU_INVARIANTS_STRICT", "0")


@pytest.fixture
def armed_strict(monkeypatch):
    monkeypatch.setenv("ROOM_TPU_INVARIANTS", "1")
    monkeypatch.setenv("ROOM_TPU_INVARIANTS_STRICT", "1")


# ---- invariant checkers: good vs bad states ----

def test_kv_page_conservation_good_and_bad():
    from room_tpu.serving.kv_pages import PageTable

    pt = PageTable(n_pages=8, page_size=4)
    pt.ensure_capacity("s1", 8)
    assert invariants.check_kv_pages(pt) == []
    pt._free.pop()   # leak a page: free+owned < total
    bad = invariants.check_kv_pages(pt)
    assert bad and bad[0]["invariant"] == "kv_page_conservation"
    # double-ownership is a distinct corruption shape
    pt2 = PageTable(n_pages=8, page_size=4)
    pt2.ensure_capacity("a", 4)
    pt2._sessions["b"] = list(pt2._sessions["a"])
    bad2 = invariants.check_kv_pages(pt2)
    assert bad2 and bad2[0]["dupes"] >= 1


def test_slot_leak_good_and_bad():
    turn = SimpleNamespace(session_id="live")
    eng = SimpleNamespace(
        _active=[turn, None],
        sessions={"live": object()},
        _staged_sids=set(),
    )
    assert invariants.check_slots(eng) == []
    eng.sessions = {}   # session released, slot not reclaimed
    bad = invariants.check_slots(eng)
    assert bad and bad[0]["invariant"] == "slot_leak"
    # a mid-stage sid is NOT a leak
    eng._staged_sids = {"live"}
    assert invariants.check_slots(eng) == []


def test_fence_monotonic_good_and_bad():
    fleet = SimpleNamespace(
        _records={"s": SimpleNamespace(sid="s", fence=3)},
    )
    assert invariants.check_fences(fleet) == []
    fleet._records["s"].fence = 5   # forward: fine
    assert invariants.check_fences(fleet) == []
    fleet._records["s"].fence = 2   # rewind: the fork precursor
    bad = invariants.check_fences(fleet)
    assert bad and bad[0]["invariant"] == "fence_monotonic"
    assert bad[0]["seen"] == 5 and bad[0]["fence"] == 2


def _fake_fleet_for_ownership(sids_by_rid, inflight=(), records=None):
    replicas = [
        SimpleNamespace(
            rid=rid, state="serving",
            engine=SimpleNamespace(
                sessions={s: object() for s in sids}
            ),
        )
        for rid, sids in sids_by_rid.items()
    ]
    return SimpleNamespace(
        replicas=replicas,
        disagg=SimpleNamespace(_inflight={s: 1 for s in inflight}),
        _records=records or {},
    )


def test_single_ownership_good_and_bad():
    good = _fake_fleet_for_ownership(
        {"r0": ["a", "__null__"], "r1": ["b", "__null__"]},
    )
    assert invariants.check_ownership(good) == []
    bad_fleet = _fake_fleet_for_ownership(
        {"r0": ["a"], "r1": ["a"]},
    )
    bad = invariants.check_ownership(bad_fleet)
    assert bad and bad[0]["invariant"] == "single_ownership"
    # a tracked in-flight ship is the sanctioned two-owner window
    shipping = _fake_fleet_for_ownership(
        {"r0": ["a"], "r1": ["a"]}, inflight=["a"],
    )
    assert invariants.check_ownership(shipping) == []
    # ...as is a record mid-ship
    mid = _fake_fleet_for_ownership(
        {"r0": ["a"], "r1": ["a"]},
        records={"a": SimpleNamespace(ship_state="pushing")},
    )
    assert invariants.check_ownership(mid) == []


def _fake_fleet_for_mirror(pending, tokens, dropped=False):
    journal = SimpleNamespace(pending_snapshot=lambda: pending)
    shard = SimpleNamespace(
        journal=journal, shard_id=0,
        records={"s": SimpleNamespace(
            tokens=tokens, mirror_dropped=dropped,
        )},
    )
    return SimpleNamespace(_shards=[shard])


def test_mirror_offset_contiguity_good_and_bad():
    good = _fake_fleet_for_mirror({"s": (1, 2)}, tokens=[7, 7, 7])
    assert invariants.check_mirror_buffers(good) == []
    bad_fleet = _fake_fleet_for_mirror({"s": (2, 4)}, tokens=[7, 7, 7])
    bad = invariants.check_mirror_buffers(bad_fleet)
    assert bad and bad[0]["invariant"] == "mirror_offset_contiguity"
    # a capped-out (mirror_dropped) record is exempt by design
    capped = _fake_fleet_for_mirror(
        {"s": (2, 4)}, tokens=[7], dropped=True,
    )
    assert invariants.check_mirror_buffers(capped) == []


def test_thread_leak_good_and_bad():
    stop = threading.Event()
    th = threading.Thread(target=stop.wait, daemon=True)
    th.start()
    try:
        h = SimpleNamespace(
            rid="r0", state="dead", rehomed_done=True, thread=th,
        )
        fleet = SimpleNamespace(replicas=[h])
        bad = invariants.check_threads(fleet)
        assert bad and bad[0]["invariant"] == "thread_leak"
        h.state = "serving"   # alive thread on a live replica: fine
        assert invariants.check_threads(fleet) == []
        h.state, h.rehomed_done = "dead", False   # re-home pending
        assert invariants.check_threads(fleet) == []
    finally:
        stop.set()
        th.join(5)
    h.state, h.rehomed_done = "dead", True
    assert invariants.check_threads(fleet) == []   # thread exited


def test_xshard_idempotency_good_and_bad(tmp_path):
    from room_tpu.swarm.shard import SwarmRouter

    router = SwarmRouter(n_shards=2, db_dir=str(tmp_path), lease_s=0.0)
    try:
        r1 = router.create_room("a")["id"]
        router.create_room("b")
        assert invariants.check_xshard(router) == []
        # two committed effect rows under the SAME idem_key — the
        # double-commit the journal exists to prevent
        db = router.all_dbs()[0]
        for _ in range(2):
            db.execute(
                "INSERT INTO cycle_journal(kind, ref_id, room_id, "
                "worker_id, entry, status, idem_key, payload) "
                "VALUES ('xshard',0,?,0,'effect','committed',"
                "'dup-key','{}')",
                (r1,),
            )
        bad = invariants.check_xshard(router)
        assert bad and bad[0]["invariant"] == "xshard_idempotency"
        assert bad[0]["idem_key"] == "dup-key"
        assert bad[0]["committed"] == 2
    finally:
        router.close()


def test_drain_marker_good_and_bad():
    good = {"m": {"manifest_written": True}}
    assert invariants.check_drain(good) == []
    bad = invariants.check_drain(
        {"m": {"manifest_written": True},
         "n": {"manifest_written": False, "error": "disk full"}},
    )
    assert bad and bad[0]["invariant"] == "drain_marker"
    assert bad[0]["engine"] == "n"


# ---- strict vs count, snapshot, cadence ----

def test_strict_mode_raises_after_recording(armed_strict):
    with pytest.raises(invariants.InvariantViolation) as ei:
        invariants.probe_drain_marker(
            {"m": {"manifest_written": False}},
        )
    assert ei.value.problems[0]["invariant"] == "drain_marker"
    # the violation is on the books BEFORE the raise — a supervisor
    # swallowing the exception still leaves the count visible
    snap = invariants.snapshot()
    assert snap["violations"] == 1
    assert snap["by_invariant"] == {"drain_marker": 1}
    assert snap["evidence"][0]["invariant"] == "drain_marker"


def test_production_mode_counts_without_raising(armed):
    for _ in range(3):
        probs = invariants.probe_drain_marker(
            {"m": {"manifest_written": False}},
        )
        assert probs and probs[0]["invariant"] == "drain_marker"
    snap = invariants.snapshot()
    assert snap["violations"] == 3
    assert snap["probes"] == 3
    assert not snap["strict"]


def test_disarmed_probes_are_free(monkeypatch):
    monkeypatch.setenv("ROOM_TPU_INVARIANTS", "0")
    assert invariants.probe_drain_marker(
        {"m": {"manifest_written": False}},
    ) == []
    assert invariants.snapshot()["violations"] == 0


def test_probe_cadence(armed, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_INVARIANTS_EVERY", "4")
    from room_tpu.serving.kv_pages import PageTable

    eng = SimpleNamespace(
        page_table=PageTable(4, 4), _active=[], sessions={},
        _staged_sids=set(),
    )
    for _ in range(8):
        invariants.probe_engine(eng)
    assert invariants.snapshot()["probes"] == 2   # every 4th step


# ---- schedule generation ----

def test_schedule_generation_deterministic_and_versioned():
    for workload in ("serving", "swarm"):
        a = fuzz.generate_schedule(7, workload=workload, ticks=12)
        b = fuzz.generate_schedule(7, workload=workload, ticks=12)
        assert fuzz.schedule_json(a) == fuzz.schedule_json(b)
        assert a["version"] == fuzz.SCHEDULE_VERSION
        assert fuzz.schedule_id(a) == fuzz.schedule_id(b)
    assert fuzz.schedule_json(
        fuzz.generate_schedule(8, "swarm", 12)
    ) != fuzz.schedule_json(fuzz.generate_schedule(9, "swarm", 12))


def test_schedule_guarantees_kill_and_overlap():
    for seed in range(1, 16):
        for workload, points in (
            ("serving", fuzz.SERVING_POINTS),
            ("swarm", fuzz.SWARM_POINTS),
        ):
            s = fuzz.generate_schedule(seed, workload, ticks=12)
            evs = s["events"]
            kills = [e for e in evs if e["point"] in fuzz.KILL_POINTS]
            assert kills and kills[0]["times"] == 1
            assert fuzz._has_overlap(evs)
            assert {e["point"] for e in evs} <= set(points)
            # at most one window per point: overlapping windows on
            # one point would re-inject over a live spec
            pts = [e["point"] for e in evs]
            assert len(pts) == len(set(pts))


def test_schedule_version_pinned_on_load(tmp_path):
    s = fuzz.generate_schedule(3, "swarm", 8)
    path = str(tmp_path / "sched.json")
    fuzz.save_schedule(s, path)
    assert fuzz.load_schedule(path) == s
    stale = dict(s, version=99)
    with open(path, "w") as f:
        json.dump(stale, f)
    with pytest.raises(ValueError, match="version"):
        fuzz.load_schedule(path)


def test_weights_cover_fault_points_exactly():
    pts = set(faults.FAULT_POINTS)
    weighted = set(fuzz.FUZZ_WEIGHTS)
    excluded = set(fuzz.FUZZ_EXCLUDED)
    assert weighted | excluded == pts
    assert not (weighted & excluded)
    assert set(fuzz.SERVING_POINTS) | set(fuzz.SWARM_POINTS) \
        == weighted
    for reason in fuzz.FUZZ_EXCLUDED.values():
        assert reason.strip()


def test_roomlint_fuzz_checker_clean_on_repo(tmp_path):
    import os

    from room_tpu.analysis.chaosfuzz_checker import (
        check_fuzz_coverage,
    )

    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))
    assert check_fuzz_coverage(repo_root) == []
    # seeded-violation fixture: a point in neither table, a typo'd
    # weight, and a both-tables overlap must each get their rule
    os.makedirs(tmp_path / "room_tpu" / "serving")
    os.makedirs(tmp_path / "room_tpu" / "chaos")
    (tmp_path / "room_tpu" / "serving" / "faults.py").write_text(
        'FAULT_POINTS = ("a", "b", "c")\n'
    )
    (tmp_path / "room_tpu" / "chaos" / "fuzz.py").write_text(
        'FUZZ_WEIGHTS = {"a": 1, "typo": 2, "b": 1}\n'
        'FUZZ_EXCLUDED = {"b": "also weighted"}\n'
    )
    rules = sorted(
        v.rule for v in check_fuzz_coverage(str(tmp_path))
    )
    assert rules == [
        "fault-point-unfuzzed",      # "c" nowhere
        "fuzz-exclusion-overlap",    # "b" in both
        "fuzz-weight-unknown",       # "typo"
    ]


# ---- swarm workload: determinism, replay, plant, shrink ----

def _swarm_sched(seed=11, ticks=8):
    return fuzz.generate_schedule(seed, workload="swarm", ticks=ticks)


def test_swarm_run_deterministic(armed_strict):
    s = _swarm_sched(seed=11, ticks=10)
    out1 = fuzz.run_schedule(s)
    out2 = fuzz.run_schedule(s)
    assert out1 == out2
    assert out1["violations"] == 0
    assert out1["messages_lost"] == 0
    assert out1["messages_double"] == 0
    assert out1["sends_acked"] > 0
    assert out1["fired"].get("shard_crash") == 1   # kill + adoption


def test_swarm_replay_round_trip(armed_strict, tmp_path):
    s = _swarm_sched(seed=23)
    path = str(tmp_path / "schedule.json")
    fuzz.save_schedule(s, path)
    out_orig = fuzz.run_schedule(s)
    out_replay = fuzz.run_schedule(fuzz.load_schedule(path))
    assert out_orig == out_replay
    # the artifact itself is byte-stable
    fuzz.save_schedule(fuzz.load_schedule(path),
                       str(tmp_path / "again.json"))
    assert (tmp_path / "schedule.json").read_bytes() \
        == (tmp_path / "again.json").read_bytes()


def _seed_arming_db_io(ticks=8):
    """First seed whose swarm schedule arms db_io (the double_effect
    plant's trigger window) — deterministic, so no flake."""
    for seed in range(1, 64):
        s = fuzz.generate_schedule(seed, "swarm", ticks)
        if any(e["point"] == "db_io" for e in s["events"]):
            return s
    raise AssertionError("no seed arming db_io in range")


def test_planted_double_effect_found_and_shrunk(armed, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_CHAOSFUZZ_PLANT", "double_effect")
    s = _seed_arming_db_io()
    out = fuzz.run_schedule(s)
    assert out["violations"] > 0
    assert "xshard_idempotency" in out["by_invariant"]
    assert len(s["events"]) > 3   # something real to shrink
    small = fuzz.shrink_schedule(s)
    assert len(small["events"]) <= 3
    assert fuzz.outcome_failed(fuzz.run_schedule(small))
    # 1-minimality is local: the surviving events are all load-bearing
    assert any(e["point"] == "db_io" for e in small["events"])


def test_shrink_preserves_failure_with_custom_oracle():
    # pure-oracle shrink (no workload): fails iff a db_io event
    # survives — ddmin must strip everything else
    s = _seed_arming_db_io(ticks=10)
    calls = []

    def fails(sched):
        calls.append(1)
        return any(e["point"] == "db_io" for e in sched["events"])

    small = fuzz.shrink_schedule(s, fails=fails)
    assert [e["point"] for e in small["events"]] == ["db_io"]
    assert calls   # the oracle actually drove it


def test_outcome_records_schedule_id_and_active_info(armed):
    s = _swarm_sched(seed=5)
    seen = {}
    orig = fuzz._run_swarm

    def spy(sched):
        seen.update(fuzz.active_schedule_info() or {})
        return orig(sched)

    fuzz._run_swarm = spy
    try:
        out = fuzz.run_schedule(s)
    finally:
        fuzz._run_swarm = orig
    assert out["schedule_id"] == fuzz.schedule_id(s)
    # crash-report attachment surface: live during the run, id matches
    assert seen == {
        "id": fuzz.schedule_id(s), "seed": 5, "workload": "swarm",
    }
    assert fuzz.active_schedule_info() is None   # cleared after


def test_telemetry_attaches_chaos_schedule(armed):
    from room_tpu.core.telemetry import _active_chaos_schedule

    assert _active_chaos_schedule() is None
    fuzz._active_schedule = {"id": "abc", "seed": 1,
                             "workload": "swarm"}
    try:
        assert _active_chaos_schedule() == {
            "id": "abc", "seed": 1, "workload": "swarm",
        }
    finally:
        fuzz._active_schedule = None


# ---- slow soak: many seeds + the serving workload ----

@pytest.mark.slow
def test_swarm_soak_many_seeds(armed_strict):
    t0 = time.monotonic()
    for seed in range(50, 62):
        out = fuzz.run_schedule(_swarm_sched(seed=seed, ticks=16))
        assert out["violations"] == 0, (seed, out)
        assert out["messages_lost"] == 0, (seed, out)
        assert out["messages_double"] == 0, (seed, out)
        if time.monotonic() - t0 > 300:
            break


@pytest.mark.slow
def test_serving_run_deterministic_and_clean(armed_strict):
    s = fuzz.generate_schedule(23, workload="serving", ticks=8)
    out1 = fuzz.run_schedule(s)
    out2 = fuzz.run_schedule(s)
    assert out1 == out2
    assert out1["violations"] == 0
    assert out1["tokens"] > 0


@pytest.mark.slow
def test_planted_kv_leak_found(armed, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_CHAOSFUZZ_PLANT", "kv_leak")
    for seed in range(1, 64):
        s = fuzz.generate_schedule(seed, "serving", ticks=8)
        if any(e["point"] == "offload_io" for e in s["events"]):
            break
    out = fuzz.run_schedule(s)
    assert out["violations"] > 0
    assert "kv_page_conservation" in out["by_invariant"]
