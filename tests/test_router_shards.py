"""Sharded router tier suite (docs/podnet.md).

CI quick tier (lockdep-armed in the chaos job) for the room-id-
partitioned router: placement map + epoch fencing, shard crash +
journal adoption, and the interactions with the mirror cap and the
shard-count lifecycle:

- PlacementMap unit contract: stable hashing, redirect chains after a
  rehome, strictly-newer epoch applies, stale-epoch submit refusal.
- Kill one of two router shards MID-DECODE: zero durably-streamed
  token loss (every turn token-identical to an unkilled control), the
  bystander shard's room never stalls, the victim's rooms shed during
  the lease, and after the sibling adopts the journal a submit (or a
  replicated frame) carrying the pre-failover epoch is refused — one
  room, one owner, no fork after a heal.
- Journal adoption replay: a room whose engine side is gone re-parks
  from the dead shard's journal and resumes token-identically via
  re-prefill.
- Shard-count change N->M across a router crash: every journal is
  absorbed and sessions re-home onto their hash-current shard.
- Mirror-cap eviction tombstones are honored ACROSS adoption: the
  truncated prefix never resurrects, the live engine session still
  resumes exactly.
- Single-shard back-compat: flat journal dir, kill refused, the
  pre-shard surface unchanged.
- Chaos fault points: ``placement_io`` (dropped publish/apply costs
  staleness, never a fork) and ``router_shard_crash`` (supervisor
  kills the busiest shard; adoption heals it).
"""

import os
import time

import jax
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving import podnet
from room_tpu.serving.fleet import EngineFleet


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    podnet.reset_breakers()
    yield
    faults.clear()
    podnet.reset_breakers()


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


LONG_PROMPT = list(range(1, 20))
CONT = [7, 7, 7]


def _greedy(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


@pytest.fixture(scope="module")
def control(model):
    """Uninterrupted three-turn reference streams on one engine
    (greedy => sid-independent)."""
    cfg, params = model
    eng = ServingEngine(
        cfg, params, max_batch=4, page_size=8, n_pages=96,
        offload=False, stop_token_ids=[],
    )
    c1 = eng.submit(LONG_PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    c2 = eng.submit(CONT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    c3 = eng.submit(CONT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    return list(c1.new_tokens), list(c2.new_tokens), \
        list(c3.new_tokens)


@pytest.fixture()
def make_fleet(model, monkeypatch, tmp_path):
    """Fleet factory: sharded router tier armed, lease effectively
    infinite (tests expire it by hand for deterministic dead
    windows), journal batch 1 so every streamed token is on disk."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_DIR", str(tmp_path / "spool"))
    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", str(tmp_path / "lc"))
    monkeypatch.setenv("ROOM_TPU_WIRE_BACKOFF_S", "0.001")
    monkeypatch.setenv("ROOM_TPU_ROUTER_LEASE_S", "600")
    monkeypatch.setenv("ROOM_TPU_POD_MIRROR_BATCH", "1")
    cfg, params = model

    def build_engine(**kw):
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        kw.setdefault("offload", True)
        kw.setdefault("stop_token_ids", [])
        return ServingEngine(cfg, params, **kw)

    def build(n=2, shards=2, env=None, **kw):
        monkeypatch.setenv("ROOM_TPU_ROUTER_SHARDS", str(shards))
        for k, v in (env or {}).items():
            monkeypatch.setenv(k, v)
        return EngineFleet(
            "tiny-moe", lambda i: build_engine(**kw), n,
            auto_rebuild=False,
        )

    build.engine = build_engine
    return build


def _sids_on_shards(n_shards):
    """One room id per shard under the stable hash."""
    pm = podnet.PlacementMap(n_shards)
    out = {}
    for i in range(512):
        sid = f"room-{i}"
        out.setdefault(pm.shard_of(sid), sid)
        if len(out) == n_shards:
            return [out[k] for k in range(n_shards)]
    raise AssertionError("hash never covered every shard")


# ---- placement map unit contract ----

def test_placement_map_contract():
    pm = podnet.PlacementMap(4)
    sid = "room-x"
    assert pm.shard_of(sid) == pm.shard_of(sid)  # stable
    assert pm.epoch == 0
    dead = pm.shard_of(sid)
    adopter = (dead + 1) % 4
    assert pm.rehome(dead, adopter) == 1
    assert pm.shard_of(sid) == adopter
    # a second failover re-points chains INTO the newly dead shard
    adopter2 = (adopter + 1) % 4
    assert pm.rehome(adopter, adopter2) == 2
    assert pm.shard_of(sid) == adopter2
    # replication: strictly-newer applies, stale refused
    peer = podnet.PlacementMap(4)
    frame = pm.frame()
    assert peer.apply(frame) is True
    assert peer.epoch == 2
    assert peer.apply(frame) is False        # same epoch: refused
    assert peer.snapshot()["stale_applies_refused"] == 1
    # submit-side fencing
    assert peer.stale_epoch(None) is False   # pre-epoch submitter
    assert peer.stale_epoch(1) is True
    assert peer.stale_epoch(2) is False
    assert peer.stale_epoch("garbage") is True


# ---- shard crash + journal adoption ----

def test_kill_shard_mid_decode_zero_token_loss(make_fleet, control):
    """Acceptance: killing 1 of 2 router shards mid-decode loses zero
    durably-streamed tokens, never stalls the bystander shard's room,
    and refuses pre-failover placement epochs after the heal."""
    full, cont, cont2 = control
    fleet = make_fleet(n=2, shards=2)
    sa, sb = _sids_on_shards(2)
    t1a = fleet.submit(LONG_PROMPT, session_id=sa,
                       sampling=_greedy(len(full)))
    t1b = fleet.submit(LONG_PROMPT, session_id=sb,
                       sampling=_greedy(len(full)))
    fleet.run_until_idle()
    assert list(t1a.new_tokens) == full
    assert list(t1b.new_tokens) == full
    pre_frame = fleet.placement.frame()
    pre_epoch = fleet.placement.epoch
    # the victim shard dies at sa's SECOND streamed token of turn 2
    seen = {"n": 0}

    def killer(tok):
        seen["n"] += 1
        if seen["n"] == 2:
            assert fleet.kill_router_shard(0, reason="test")

    t2a = fleet.submit(CONT, session_id=sa, sampling=_greedy(len(cont)),
                       on_token=killer)
    fleet.run_until_idle()
    # the engine session was never touched: the in-flight turn streams
    # to completion token-identically
    assert list(t2a.new_tokens) == cont
    assert fleet._shards[0].state == "dead"
    # dead window: the victim's rooms shed with the 503 contract...
    probe = fleet.submit(CONT, session_id=sa, sampling=_greedy(3))
    assert probe.shed and "router shard down" in probe.error
    # ...while the bystander shard's room streams, unstalled
    t2b = fleet.submit(CONT, session_id=sb, sampling=_greedy(len(cont)))
    fleet.run_until_idle()
    assert not t2b.shed
    assert list(t2b.new_tokens) == cont
    # lease expires -> the sibling adopts the journal
    fleet.router_lease_s = 0.0
    fleet.supervise()
    rs = fleet.fleet_stats()["router_shards"]
    assert rs["adoptions"] == 1
    assert rs["epoch"] == pre_epoch + 1
    assert rs["shards"]["0"]["state"] == "retired"
    assert rs["shards"]["1"]["state"] == "serving"
    # a healed stale router: its replayed frame and its stale-epoch
    # submits are both refused — one room, one owner
    assert fleet.placement.apply(pre_frame) is False
    stale = fleet.submit(CONT, session_id=sa, sampling=_greedy(3),
                         placement_epoch=pre_epoch)
    assert stale.shed and "stale placement epoch" in stale.error
    assert fleet.fleet_stats()["router_shards"][
        "placement_refusals"] >= 1
    # both rooms resume token-identically after adoption
    t3a = fleet.submit(CONT, session_id=sa, sampling=_greedy(len(cont2)))
    t3b = fleet.submit(CONT, session_id=sb, sampling=_greedy(len(cont2)))
    fleet.run_until_idle()
    assert list(t3a.new_tokens) == cont2
    assert list(t3b.new_tokens) == cont2


def test_adoption_replays_journal_token_identical(make_fleet, control):
    """A room whose ENGINE side is gone too (the double failure)
    re-parks from the dead shard's journal and resumes via re-prefill,
    token-identical to the control."""
    full, cont, _ = control
    fleet = make_fleet(n=1, shards=2)
    sa = _sids_on_shards(2)[0]
    t1 = fleet.submit(LONG_PROMPT, session_id=sa,
                      sampling=_greedy(len(full)))
    fleet.run_until_idle()
    assert list(t1.new_tokens) == full
    handle = fleet._handle(fleet._records[sa].rid)
    # the engine loses the session (models the engine side of a dead
    # router PROCESS) without the router seeing a release
    handle.engine.release_session(sa)
    handle.engine.run_until_idle()
    assert sa not in handle.engine.sessions
    assert fleet.kill_router_shard(0, reason="test")
    fleet.router_lease_s = 0.0
    fleet.supervise()
    rec = fleet._records[sa]
    assert rec.shard == 1
    assert rec.rid == "" and rec.pending_entry is not None
    assert fleet.fleet_stats()["router_shards"][
        "sessions_adopted"] == 1
    # the adopting route re-prefills from the journal mirror; greedy
    # continuation is token-identical
    t2 = fleet.submit(CONT, session_id=sa, sampling=_greedy(len(cont)))
    fleet.run_until_idle()
    assert list(t2.new_tokens) == cont


def test_shard_count_change_absorbs_every_journal(make_fleet, control):
    """Router crash + restart with a DIFFERENT shard count (2 -> 3):
    every old journal is absorbed and each session re-homes onto its
    hash-current shard."""
    full, cont, _ = control
    fleet1 = make_fleet(n=1, shards=2)
    sa, sb = _sids_on_shards(2)
    for sid in (sa, sb):
        t = fleet1.submit(LONG_PROMPT, session_id=sid,
                          sampling=_greedy(len(full)))
        fleet1.run_until_idle()
        assert list(t.new_tokens) == full
    # router process crashes: no drain — the journals are all that
    # survive
    del fleet1
    fleet2 = make_fleet(n=1, shards=3)
    assert fleet2.fleet_stats()["mirror_restored"] == 2
    pm3 = podnet.PlacementMap(3)
    for sid in (sa, sb):
        rec = fleet2._records[sid]
        assert rec.shard == pm3.shard_of(sid)
        assert rec.rid == "" and rec.pending_entry is not None
    for sid in (sa, sb):
        t = fleet2.submit(CONT, session_id=sid,
                          sampling=_greedy(len(cont)))
        fleet2.run_until_idle()
        assert list(t.new_tokens) == cont


def test_eviction_tombstone_honored_across_adoption(
    make_fleet, control,
):
    """A cap-evicted mirror's journal tombstone survives adoption: the
    truncated prefix never resurrects as a history (warm-only), while
    the live engine session still resumes token-identically."""
    full, cont, _ = control
    fleet = make_fleet(
        n=2, shards=2,
        env={"ROOM_TPU_FLEET_MIRROR_TOKENS": "4"},
    )
    sa = _sids_on_shards(2)[0]
    t1 = fleet.submit(LONG_PROMPT, session_id=sa,
                      sampling=_greedy(len(full)))
    fleet.run_until_idle()
    assert list(t1.new_tokens) == full
    assert fleet.fleet_stats()["mirror"]["evictions"] >= 1
    assert fleet.kill_router_shard(0, reason="test")
    fleet.router_lease_s = 0.0
    fleet.supervise()
    rec = fleet._records[sa]
    assert rec.mirror_dropped and not rec.tokens
    assert rec.pending_entry is None and rec.rid
    # the adopter's journal carries the tombstone, not the prefix
    state = fleet._shards[1].journal.replay()
    assert sa not in state
    # the live engine session is the exact resume path
    t2 = fleet.submit(CONT, session_id=sa, sampling=_greedy(len(cont)))
    fleet.run_until_idle()
    assert list(t2.new_tokens) == cont
    # and a later router restart must NOT restore the evicted room
    del fleet
    fleet2 = make_fleet(n=2, shards=2)
    assert fleet2.fleet_stats()["mirror_restored"] == 0


def test_single_shard_back_compat(make_fleet, control):
    """ROOM_TPU_ROUTER_SHARDS=1 is the classic router: flat journal
    dir, kill refused (nobody to adopt), pre-shard stats intact."""
    full, _, _ = control
    fleet = make_fleet(
        n=2, shards=1, env={"ROOM_TPU_POD_MIRROR": "1"},
    )
    assert fleet.kill_router_shard(0) is False
    assert os.path.basename(fleet.mirror_journal.dir) == \
        "router-mirror"
    t1 = fleet.submit(LONG_PROMPT, session_id="s",
                      sampling=_greedy(len(full)))
    fleet.run_until_idle()
    assert list(t1.new_tokens) == full
    rs = fleet.fleet_stats()["router_shards"]
    assert rs["count"] == 1 and rs["serving"] == 1
    assert rs["epoch"] == 0


def test_shard_heartbeat_leases_gate_adoption(make_fleet, control):
    """ROOM_TPU_ROUTER_SHARD_HEARTBEATS: adoption waits for the
    membership detector's suspect -> dead -> lease-expired verdict on
    the dead shard's heartbeat silence, not the killer's died_at
    timestamp — and serving shards keep beating alive."""
    full, cont, _ = control
    fleet = make_fleet(
        n=1, shards=2,
        env={
            "ROOM_TPU_ROUTER_SHARD_HEARTBEATS": "1",
            "ROOM_TPU_POD_SUSPECT_S": "0.01",
            "ROOM_TPU_POD_DEAD_S": "0.02",
        },
    )
    assert fleet._shard_membership is not None
    sa, sb = _sids_on_shards(2)
    for sid in (sa, sb):
        t = fleet.submit(LONG_PROMPT, session_id=sid,
                         sampling=_greedy(len(full)))
        fleet.run_until_idle()
        assert list(t.new_tokens) == full
    fleet.supervise()
    hb = fleet.fleet_stats()["router_shards"]["heartbeats"]
    assert hb["shard-0"]["state"] == "alive"
    assert hb["shard-1"]["state"] == "alive"
    assert fleet.kill_router_shard(0, reason="test")
    # the in-process timer contract is OFF: even with the router lease
    # forced to zero, adoption waits for the detector's verdict
    fleet.router_lease_s = 0.0
    fleet.supervise()
    assert fleet.fleet_stats()["router_shards"]["adoptions"] == 0
    # silence runs the suspect -> dead -> lease course
    fleet._shard_membership.lease_s = 0.0
    deadline = time.monotonic() + 5.0
    while fleet.fleet_stats()["router_shards"]["adoptions"] < 1:
        time.sleep(0.02)
        fleet.supervise()
        assert time.monotonic() < deadline
    hb = fleet.fleet_stats()["router_shards"]["heartbeats"]
    assert hb["shard-0"]["state"] == "dead"
    assert hb["shard-0"]["lease_fired"] is True
    assert hb["shard-1"]["state"] == "alive"
    rs = fleet.fleet_stats()["router_shards"]
    assert rs["shards"]["0"]["state"] == "retired"
    # both rooms resume on the adopter
    for sid in (sa, sb):
        t = fleet.submit(CONT, session_id=sid,
                         sampling=_greedy(len(cont)))
        fleet.run_until_idle()
        assert list(t.new_tokens) == cont


# ---- chaos fault points ----

def test_placement_io_fault_costs_staleness_never_forks(make_fleet):
    fleet = make_fleet(n=1, shards=2)
    # publish side: the dropped frame is counted, peers stay behind
    faults.inject("placement_io", times=1)
    assert fleet.pod.publish_placement() == 0
    assert fleet.pod._stats["placement_publish_drops"] == 1
    assert faults.fired("placement_io") == 1
    # apply side: the dropped install refuses, state unchanged
    faults.inject("placement_io", times=1)
    frame = {"kind": "placement", "epoch": 5, "redirects": {}}
    assert fleet.placement.apply(frame) is False
    assert fleet.placement.epoch == 0
    faults.clear()
    # the retransmit (next publish/apply) heals the staleness
    reply = fleet.pod.handle_control(frame)
    assert reply["ok"] and reply["applied"]
    assert fleet.placement.epoch == 5


def test_router_shard_crash_fault_point_heals(make_fleet, control):
    """faults.inject("router_shard_crash") kills the busiest serving
    shard at the next supervise; the sibling adopts past the lease and
    every room resumes token-identically."""
    full, cont, _ = control
    fleet = make_fleet(n=2, shards=2)
    sa, sb = _sids_on_shards(2)
    for sid in (sa, sb):
        t = fleet.submit(LONG_PROMPT, session_id=sid,
                         sampling=_greedy(len(full)))
        fleet.run_until_idle()
        assert list(t.new_tokens) == full
    faults.inject("router_shard_crash", times=1)
    fleet.supervise()
    assert faults.fired("router_shard_crash") == 1
    rs = fleet.fleet_stats()["router_shards"]
    assert rs["crashes"] == 1 and rs["serving"] == 1
    dead = next(s for s in fleet._shards if s.state == "dead")
    victim_sid = sa if fleet.placement.shard_of(sa) == \
        dead.shard_id else sb
    probe = fleet.submit(CONT, session_id=victim_sid,
                         sampling=_greedy(3))
    assert probe.shed
    fleet.router_lease_s = 0.0
    deadline = time.monotonic() + 5.0
    while fleet.fleet_stats()["router_shards"]["adoptions"] < 1:
        fleet.supervise()
        assert time.monotonic() < deadline
    for sid in (sa, sb):
        t = fleet.submit(CONT, session_id=sid,
                         sampling=_greedy(len(cont)))
        fleet.run_until_idle()
        assert list(t.new_tokens) == cont
