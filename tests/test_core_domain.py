"""Domain-core tests: rooms, goals, quorum, skills, self-mod, memory,
escalations, messages, credentials, wallet (offline paths)."""

import numpy as np
import pytest

from room_tpu.core import (
    activity, credentials, escalations, goals, memory, messages, quorum,
    rooms, selfmod, skills, wallet, workers,
)
from room_tpu.core.constants import RoomConfig


@pytest.fixture()
def room(db):
    return rooms.create_room(db, "alpha", goal="ship the thing")


def test_create_room_builds_collective(db, room):
    assert room["queen_worker_id"] is not None
    queen = workers.get_worker(db, room["queen_worker_id"])
    assert queen["role"] == "queen"
    root = goals.get_root_goal(db, room["id"])
    assert root["description"] == "ship the thing"
    w = wallet.get_room_wallet(db, room["id"])
    assert w["address"].startswith("0x") and len(w["address"]) == 42


def test_room_status_aggregate(db, room):
    st = rooms.get_room_status(db, room["id"])
    assert st["worker_count"] == 1
    assert st["active_goals"] == 1


def test_delete_room_removes_workers(db, room):
    rooms.delete_room(db, room["id"])
    assert workers.list_room_workers(db, room["id"]) == []
    assert rooms.get_room(db, room["id"]) is None


# ---- goals ----

def test_goal_tree_and_progress_rollup(db, room):
    root = goals.get_root_goal(db, room["id"])
    a = goals.create_goal(db, room["id"], "a", parent_goal_id=root["id"])
    b = goals.create_goal(db, room["id"], "b", parent_goal_id=root["id"])
    goals.complete_goal(db, a)
    assert goals.get_goal(db, root["id"])["progress"] == pytest.approx(0.5)
    goals.set_goal_progress(db, b, 0.5)
    assert goals.get_goal(db, root["id"])["progress"] == pytest.approx(0.75)
    tree = goals.get_goal_tree(db, room["id"])
    assert len(tree) == 1 and len(tree[0]["children"]) == 2


def test_new_objective_abandons_old_root(db, room):
    old_root = goals.get_root_goal(db, room["id"])
    goals.set_room_objective(db, room["id"], "new direction")
    assert goals.get_goal(db, old_root["id"])["status"] == "abandoned"
    assert goals.get_root_goal(db, room["id"])["description"] == "new direction"


# ---- quorum ----

def test_announce_auto_approves_low_impact(db, room):
    d = quorum.announce(db, room["id"], None, "tidy the docs", "low_impact")
    assert d["status"] == "approved"


def test_announce_object_flow(db, room):
    d = quorum.announce(db, room["id"], None, "rewrite core", "high_impact")
    assert d["status"] == "announced"
    wid = workers.create_worker(db, "w", "p", room_id=room["id"])
    d2 = quorum.object_to(db, d["id"], wid, "too risky")
    assert d2["status"] == "objected"
    with pytest.raises(quorum.QuorumError):
        quorum.object_to(db, d["id"], wid, "again")


def test_announce_becomes_effective_after_deadline(db, room):
    d = quorum.announce(
        db, room["id"], None, "migrate db", "high_impact", delay_minutes=0
    )
    n = quorum.check_expired_decisions(db)
    assert n == 1
    assert quorum.get_decision(db, d["id"])["status"] == "effective"


def test_ballot_majority_resolves_early(db, room):
    w1 = workers.create_worker(db, "w1", "p", room_id=room["id"])
    w2 = workers.create_worker(db, "w2", "p", room_id=room["id"])
    d = quorum.open_ballot(db, room["id"], None, "buy domain")
    # electorate = queen + w1 + w2 = 3, majority needs 2
    quorum.vote(db, d["id"], w1, "yes")
    assert quorum.get_decision(db, d["id"])["status"] == "voting"
    quorum.vote(db, d["id"], w2, "yes")
    assert quorum.get_decision(db, d["id"])["status"] == "passed"


def test_keeper_vote_on_announcement(db, room):
    d = quorum.announce(db, room["id"], None, "risky", "high_impact")
    d2 = quorum.keeper_vote(db, d["id"], "no")
    assert d2["status"] == "objected"


def test_ballot_two_thirds_threshold(db, room):
    # electorate = queen + 2 workers = 3; two_thirds needs 3 (int(3*2/3)+1)
    w1 = workers.create_worker(db, "w1", "p", room_id=room["id"])
    w2 = workers.create_worker(db, "w2", "p", room_id=room["id"])
    d = quorum.open_ballot(db, room["id"], None, "migrate stack",
                           threshold="two_thirds")
    quorum.vote(db, d["id"], w1, "yes")
    quorum.vote(db, d["id"], w2, "yes")
    assert quorum.get_decision(db, d["id"])["status"] == "voting"
    quorum.vote(db, d["id"], room["queen_worker_id"], "yes")
    assert quorum.get_decision(db, d["id"])["status"] == "passed"


def test_ballot_unanimous_one_no_rejects(db, room):
    w1 = workers.create_worker(db, "w1", "p", room_id=room["id"])
    d = quorum.open_ballot(db, room["id"], None, "rewrite in cobol",
                           threshold="unanimous")
    quorum.vote(db, d["id"], w1, "no")
    # yes can never reach electorate once a no is in
    assert quorum.get_decision(db, d["id"])["status"] == "rejected"


def test_ballot_early_rejection_when_unreachable(db, room):
    w1 = workers.create_worker(db, "w1", "p", room_id=room["id"])
    w2 = workers.create_worker(db, "w2", "p", room_id=room["id"])
    d = quorum.open_ballot(db, room["id"], None, "p")   # majority of 3 = 2
    quorum.vote(db, d["id"], w1, "no")
    quorum.vote(db, d["id"], w2, "no")
    # 1 remaining voter can bring yes to at most 1 < 2
    assert quorum.get_decision(db, d["id"])["status"] == "rejected"


def test_ballot_min_voters_raises_bar(db, room):
    # electorate floor via min_voters: one room worker but min 3 voters
    d = quorum.open_ballot(db, room["id"], None, "p", min_voters=3)
    quorum.vote(db, d["id"], room["queen_worker_id"], "yes")
    # 1 yes < majority of 3 (=2); and 2 remaining seats exist, not decided
    assert quorum.get_decision(db, d["id"])["status"] == "voting"


def test_keeper_vote_counts_in_ballot_tally(db, room):
    w1 = workers.create_worker(db, "w1", "p", room_id=room["id"])
    w2 = workers.create_worker(db, "w2", "p", room_id=room["id"])
    d = quorum.open_ballot(db, room["id"], None, "p")   # majority of 3 = 2
    quorum.vote(db, d["id"], w1, "yes")
    assert quorum.get_decision(db, d["id"])["status"] == "voting"
    d2 = quorum.keeper_vote(db, d["id"], "yes")
    assert d2["status"] == "passed"
    assert w2  # silent voter never needed


def test_expired_ballot_with_undecided_tally_expires(db, room):
    workers.create_worker(db, "w1", "p", room_id=room["id"])
    d = quorum.open_ballot(db, room["id"], None, "p",
                           timeout_minutes=-1)   # already past deadline
    assert quorum.check_expired_decisions(db) == 1
    assert quorum.get_decision(db, d["id"])["status"] == "expired"


def test_object_rejected_after_effective(db, room):
    d = quorum.announce(db, room["id"], None, "p", "high_impact",
                        delay_minutes=-1)
    quorum.check_expired_decisions(db)
    assert quorum.get_decision(db, d["id"])["status"] == "effective"
    with pytest.raises(quorum.QuorumError):
        quorum.object_to(db, d["id"], 1, "too late")


def test_invalid_vote_value_rejected(db, room):
    d = quorum.open_ballot(db, room["id"], None, "p")
    with pytest.raises(quorum.QuorumError):
        quorum.vote(db, d["id"], room["queen_worker_id"], "maybe")


# ---- memory ----

def test_remember_and_fts_recall(db, room):
    memory.remember(
        db, "deploy runbook", "use blue-green on fridays",
        room_id=room["id"],
    )
    hits = memory.fts_search(db, "blue-green runbook", room_id=room["id"])
    assert hits and hits[0]["name"] == "deploy runbook"


def test_fts_handles_hostile_query(db, room):
    memory.remember(db, "x", "y", room_id=room["id"])
    assert memory.fts_search(db, '"unbalanced AND (', room_id=room["id"]) \
        is not None  # must not raise


def test_hybrid_search_rrf_merges(db, room):
    e1 = memory.remember(db, "tpu sharding", "mesh is 2x4", room_id=room["id"])
    e2 = memory.remember(db, "lunch spot", "tacos on 3rd", room_id=room["id"])
    memory.store_embedding(db, e1, "tpu sharding", np.ones(8))
    memory.store_embedding(db, e2, "lunch spot", -np.ones(8))
    out = memory.hybrid_search(
        db, "tpu sharding", query_vector=np.ones(8), room_id=room["id"]
    )
    assert out[0]["entity_id"] == e1
    assert out[0]["observations"] == ["mesh is 2x4"]


def test_embedding_room_scope_includes_global(db, room):
    eg = memory.remember(db, "global fact", "applies everywhere")
    memory.store_embedding(db, eg, "global fact", np.ones(4))
    mat, ids = memory.embedding_matrix(db, room_id=room["id"])
    assert eg in ids


def test_indexer_queue_tracks_staleness(db, room):
    e = memory.remember(db, "fresh", "one", room_id=room["id"])
    queue = memory.entities_needing_embedding(db)
    assert e in [q["id"] for q in queue]
    memory.store_embedding(db, e, "fresh one", np.ones(4))
    assert e not in [q["id"] for q in memory.entities_needing_embedding(db)]
    memory.add_observation(db, e, "two")  # re-dirty
    assert e in [q["id"] for q in memory.entities_needing_embedding(db)]


# ---- skills + self-mod ----

def test_skill_context_loader_caps(db, room):
    for i in range(12):
        skills.create_skill(
            db, f"s{i}", "x" * 400, room_id=room["id"], auto_activate=True
        )
    ctx = skills.load_skills_for_agent(db, room["id"])
    assert ctx.count("## Skill:") <= 8
    assert len(ctx) <= 6000


def test_selfmod_forbidden_and_ratelimit(db, room):
    wid = workers.create_worker(db, "w", "p", room_id=room["id"])
    ok, why = selfmod.can_modify(db, wid, "wallets/keys.json")
    assert not ok and "protected" in why
    sid = skills.create_skill(db, "s", "v1", room_id=room["id"])
    selfmod.perform_modification(
        db, room["id"], wid, "skill", sid, "skills/s", "v1", "v2", "improve"
    )
    assert skills.get_skill(db, sid)["content"] == "v2"
    with pytest.raises(selfmod.SelfModError):
        selfmod.perform_modification(
            db, room["id"], wid, "skill", sid, "skills/s", "v2", "v3", "again"
        )


def test_selfmod_revert_restores_snapshot(db, room):
    sid = skills.create_skill(db, "s", "v1", room_id=room["id"])
    aid = selfmod.perform_modification(
        db, room["id"], None, "skill", sid, "skills/s", "v1", "v2", "r"
    )
    assert selfmod.revert_modification(db, aid)
    assert skills.get_skill(db, sid)["content"] == "v1"
    assert not selfmod.revert_modification(db, aid)  # only once


# ---- escalations + messages + credentials ----

def test_escalation_lifecycle(db, room):
    eid = escalations.create_escalation(db, room["id"], "may I buy a domain?")
    assert len(escalations.pending_escalations(db, room["id"])) == 1
    escalations.answer_escalation(db, eid, "yes, under $20")
    assert escalations.pending_escalations(db, room["id"]) == []
    assert escalations.recently_answered(db, room["id"])[0]["answer"] \
        == "yes, under $20"


def test_inter_room_messaging(db, room):
    other = rooms.create_room(db, "beta", create_wallet=False)
    messages.send_room_message(
        db, room["id"], other["id"], "hello", "let's collaborate"
    )
    unread = messages.unread_messages(db, other["id"])
    assert len(unread) == 1 and unread[0]["subject"] == "hello"
    messages.mark_message_read(db, unread[0]["id"])
    assert messages.unread_messages(db, other["id"]) == []


def test_chat_inbox_poll(db, room):
    messages.add_chat_message(db, room["id"], "user", "status?")
    assert len(messages.unanswered_keeper_messages(db, room["id"])) == 1
    messages.add_chat_message(db, room["id"], "assistant", "all good")
    assert messages.unanswered_keeper_messages(db, room["id"]) == []


def test_credential_resolution_chain(db, room, monkeypatch):
    monkeypatch.setenv("SOME_API_KEY", "from-env")
    assert credentials.resolve_api_key(db, "SOME_API_KEY", room["id"]) \
        == "from-env"
    messages.set_setting(db, "SOME_API_KEY", "from-settings")
    assert credentials.resolve_api_key(db, "SOME_API_KEY", room["id"]) \
        == "from-settings"
    credentials.store_credential(db, room["id"], "SOME_API_KEY", "from-room")
    assert credentials.resolve_api_key(db, "SOME_API_KEY", room["id"]) \
        == "from-room"
    # stored values are encrypted at rest
    raw = db.query_one("SELECT value_encrypted FROM credentials")
    assert raw["value_encrypted"].startswith("enc:v1:")


# ---- wallet (offline) ----

def test_wallet_key_roundtrip_and_checksum(db, room):
    w = wallet.get_room_wallet(db, room["id"])
    key = wallet.decrypt_wallet_key(w)
    assert len(key) == 32
    assert wallet.private_key_to_address(key) == w["address"]
    # EIP-55 known vector
    assert wallet.to_checksum_address(
        "0x5aaeb6053f3e94c9b9a09f33669435e7ef1beaed"
    ) == "0x5aAeb6053F3E94C9b9A09f33669435E7Ef1BeAed"


def test_wallet_rpc_fails_closed(db, room, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_RPC_BASE", "http://127.0.0.1:1")
    with pytest.raises(wallet.WalletError, match="unreachable"):
        wallet.get_native_balance(db, room["id"])


def test_keccak_known_vectors():
    from room_tpu.core.keccak import keccak256
    assert keccak256(b"").hex() == (
        "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
    )
    assert keccak256(b"abc").hex() == (
        "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
    )


def test_public_feed_requires_public_room(db, room):
    activity.log_room_activity(db, room["id"], "note", "hi")
    assert activity.get_public_feed(db) == []
    rooms.update_room(db, room["id"], visibility="public")
    assert len(activity.get_public_feed(db)) >= 1


def test_vote_change_does_not_inflate_participation(db, room):
    w1 = workers.create_worker(db, "w1", "p", room_id=room["id"])
    for _ in range(2):
        workers.create_worker(db, "x", "p", room_id=room["id"])
    d = quorum.open_ballot(db, room["id"], None, "p")
    quorum.vote(db, d["id"], w1, "abstain")
    quorum.vote(db, d["id"], w1, "yes")
    assert workers.get_worker(db, w1)["votes_cast"] == 1


def test_keeper_vote_resolves_ballot(db, room):
    w1 = workers.create_worker(db, "w1", "p", room_id=room["id"])
    d = quorum.open_ballot(db, room["id"], None, "p")  # electorate 2, need 2
    quorum.vote(db, d["id"], w1, "yes")
    d2 = quorum.keeper_vote(db, d["id"], "yes")
    assert d2["status"] == "passed"


def test_upsert_returns_real_ids(db, room):
    cid1 = credentials.store_credential(db, room["id"], "K", "v1")
    db.insert("INSERT INTO rooms(name) VALUES ('decoy')")
    cid2 = credentials.store_credential(db, room["id"], "K", "v2")
    assert cid1 == cid2
    e = memory.remember(db, "e", "o", room_id=room["id"])
    r1 = memory.store_embedding(db, e, "t", np.ones(4))
    db.insert("INSERT INTO rooms(name) VALUES ('decoy2')")
    r2 = memory.store_embedding(db, e, "t2", np.zeros(4))
    assert r1 == r2


def test_explicit_zero_overrides_preset(db, room):
    wid = workers.create_worker(
        db, "e", "p", room_id=room["id"], role="executor",
        cycle_gap_ms=0, max_turns=0,
    )
    w = workers.get_worker(db, wid)
    assert w["cycle_gap_ms"] == 0 and w["max_turns"] == 0


# ---- queen tool dispatcher edges (density toward the reference's
# tool-surface coverage; ref: queen tool tests in agent-loop.test.ts) ----

class TestQueenToolDispatch:
    @staticmethod
    def _room(db):
        from room_tpu.core import rooms as rooms_mod

        room = rooms_mod.create_room(db, "qt", worker_model="echo",
                                     create_wallet=False)
        return room["id"], room["queen_worker_id"]

    def test_cross_room_worker_is_rejected(self, db):
        from room_tpu.core.queen_tools import execute_queen_tool
        from room_tpu.core import rooms as rooms_mod

        rid, qid = self._room(db)
        other = rooms_mod.create_room(db, "other", worker_model="echo",
                                      create_wallet=False)
        out = execute_queen_tool(
            db, rid, qid, "delegate",
            {"worker_id": other["queen_worker_id"],
             "description": "steal"},
        )
        assert "no worker" in out

    def test_cross_room_goal_is_rejected(self, db):
        from room_tpu.core import goals as goals_mod
        from room_tpu.core.queen_tools import execute_queen_tool

        rid, qid = self._room(db)
        rid2, _ = self._room(db)
        foreign = goals_mod.create_goal(db, rid2, "theirs")
        out = execute_queen_tool(
            db, rid, qid, "complete_goal", {"goal_id": foreign}
        )
        assert "no goal" in out

    def test_announce_decision_dedupes_open_proposal(self, db):
        from room_tpu.core.queen_tools import execute_queen_tool

        rid, qid = self._room(db)
        first = execute_queen_tool(
            db, rid, qid, "announce_decision",
            {"proposal": "buy a tpu", "decision_type": "high_impact"},
        )
        again = execute_queen_tool(
            db, rid, qid, "announce_decision",
            {"proposal": "buy a tpu", "decision_type": "high_impact"},
        )
        assert "already announced" in again
        assert first.split()[1] == again.split()[1]  # same #id

    def test_unknown_tool_and_bad_args_are_reported_not_raised(self, db):
        from room_tpu.core.queen_tools import execute_queen_tool

        rid, qid = self._room(db)
        out = execute_queen_tool(db, rid, qid, "no_such_tool", {})
        assert "unknown tool" in out or "tool error" in out
        # missing required arg -> tool error string, never an exception
        out = execute_queen_tool(db, rid, qid, "set_goal", {})
        assert out.startswith("tool error")

    def test_update_goal_progress_records_metric(self, db):
        from room_tpu.core import goals as goals_mod
        from room_tpu.core.queen_tools import execute_queen_tool

        rid, qid = self._room(db)
        gid = goals_mod.create_goal(db, rid, "measure me")
        out = execute_queen_tool(
            db, rid, qid, "update_goal_progress",
            {"goal_id": gid, "progress": 0.5, "observation": "half"},
        )
        assert "progress=0.5" in out
        rows = db.query(
            "SELECT metric_value FROM goal_updates WHERE goal_id=?",
            (gid,),
        )
        assert rows and rows[-1]["metric_value"] == 0.5

    def test_wallet_status_without_wallet(self, db):
        from room_tpu.core.queen_tools import execute_queen_tool

        rid, qid = self._room(db)
        assert "no wallet" in execute_queen_tool(
            db, rid, qid, "wallet_status", {}
        )

    def test_escalate_emits_event(self, db):
        from room_tpu.core.events import event_bus
        from room_tpu.core.queen_tools import execute_queen_tool

        rid, qid = self._room(db)
        got = []
        unsub = event_bus.subscribe(f"room:{rid}", got.append)
        try:
            out = execute_queen_tool(
                db, rid, qid, "escalate_to_keeper",
                {"question": "may I?"},
            )
            assert "sent to keeper" in out
            assert any(e.type == "escalation:created" for e in got)
        finally:
            unsub()


def test_room_config_min_voters_is_ballot_default(db, room):
    """The dashboard's min-voters knob (config.minVoters) must actually
    bind: open_ballot with no explicit arg inherits it."""
    import json

    db.execute(
        "UPDATE rooms SET config=? WHERE id=?",
        (json.dumps({"minVoters": 3}), room["id"]),
    )
    d = quorum.open_ballot(db, room["id"], None, "needs-three")
    assert d["min_voters"] == 3
    quorum.vote(db, d["id"], room["queen_worker_id"], "yes")
    # one yes against an electorate floor of 3 cannot resolve
    assert quorum.get_decision(db, d["id"])["status"] == "voting"
    # explicit argument still wins over the config default
    d2 = quorum.open_ballot(db, room["id"], None, "explicit",
                            min_voters=1)
    assert d2["min_voters"] == 1


def test_queen_open_ballot_tool(db, room):
    from room_tpu.core.queen_tools import execute_queen_tool

    out = execute_queen_tool(
        db, room["id"], room["queen_worker_id"], "open_ballot",
        {"proposal": "tooled-vote"},
    )
    assert "ballot #" in out
    open_ = quorum.pending_decisions(db, room["id"])
    assert any(d["proposal"] == "tooled-vote"
               and d["status"] == "voting" for d in open_)
    # dedupe: same proposal while open returns the existing ballot
    again = execute_queen_tool(
        db, room["id"], room["queen_worker_id"], "open_ballot",
        {"proposal": "tooled-vote"},
    )
    assert "already open" in again


def test_escalation_and_decision_events_reach_the_bus(db, room):
    """Desktop notifications ride these: EVERY escalation creation
    path and every open decision must emit on the room channel
    (create_escalation emits itself; quorum announce/open_ballot
    emit decision:announced)."""
    from room_tpu.core import escalations
    from room_tpu.core.events import event_bus

    rid = room["id"]
    got = []
    unsub = event_bus.subscribe(f"room:{rid}", got.append)
    try:
        eid = escalations.create_escalation(db, rid, "need keeper")
        d1 = quorum.announce(db, rid, None, "evt-prop",
                             decision_type="high_impact")
        d2 = quorum.open_ballot(db, rid, None, "evt-ballot")
        auto = quorum.announce(db, rid, None, "auto-ok")  # low impact
    finally:
        unsub()
    by_type = {}
    for e in got:
        by_type.setdefault(e.type, []).append(e.data)
    assert {"id": eid, "question": "need keeper"} in \
        by_type["escalation:created"]
    props = {d["proposal"]: d for d in by_type["decision:announced"]}
    assert props["evt-prop"]["id"] == d1["id"]
    assert props["evt-ballot"]["status"] == "voting"
    # auto-approved decisions don't ping the keeper
    assert "auto-ok" not in props
    assert auto["status"] == "approved"


def test_keeper_vote_rejects_unknown_vocabulary(db, room):
    d = quorum.open_ballot(db, room["id"], None, "strict-veto")
    with pytest.raises(quorum.QuorumError):
        quorum.keeper_vote(db, d["id"], "reject")   # UI word, not core
    # unchanged — the typo'd veto did NOT approve
    assert quorum.get_decision(db, d["id"])["status"] == "voting"
