"""Dashboard request-flow tests (no browser in the image): static
bundle serves, and every API call the panels make resolves against the
live router with a 2xx on seeded data — so panel drift against the
REST surface fails CI (reference analogue: src/ui/ integration tests).
"""

import json
import os
import re
import urllib.request

import pytest

from room_tpu.db import Database
from room_tpu.server.http import ApiServer

UI_DIR = os.path.join(os.path.dirname(__file__), "..", "ui")


@pytest.fixture
def server(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path / "data"))
    monkeypatch.setenv("ROOM_TPU_EMAIL_OUTBOX", str(tmp_path / "outbox"))
    db = Database(":memory:")
    srv = ApiServer(db, static_dir=UI_DIR)
    srv.start()
    yield srv
    srv.stop()


def fetch(server, path, token=None):
    headers = {}
    if token:
        headers["Authorization"] = f"Bearer {server.tokens['user']}"
    r = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}", headers=headers
    )
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            return resp.status, resp.headers, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def test_static_bundle_serves(server):
    for path, ctype in [
        ("/", "text/html"),
        ("/app.js", "text/javascript"),
        ("/panels.js", "text/javascript"),
        ("/style.css", "text/css"),
    ]:
        status, headers, body = fetch(server, path)
        assert status == 200, path
        assert ctype in headers["Content-Type"], (path, headers)
        assert len(body) > 200, path
    # SPA fallback: unknown path serves index.html
    status, headers, body = fetch(server, "/some/spa/route")
    assert status == 200 and b"room_tpu" in body


def _strip_js(src: str) -> str:
    """Remove strings/template literals/comments so delimiter counting
    sees only code (no JS engine in the image)."""
    out = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in "'\"`":
            q = c
            i += 1
            while i < n and src[i] != q:
                if src[i] == "\\":
                    i += 1
                elif q == "`" and src.startswith("${", i):
                    # template interpolations contain code: keep them
                    depth = 0
                    j = i + 2
                    while j < n:
                        if src[j] == "{":
                            depth += 1
                        elif src[j] == "}":
                            if depth == 0:
                                break
                            depth -= 1
                        j += 1
                    out.append(" " + _strip_js(src[i + 2:j]) + " ")
                    i = j
                i += 1
        elif src.startswith("//", i):
            while i < n and src[i] != "\n":
                i += 1
            continue
        elif src.startswith("/*", i):
            j = src.find("*/", i + 2)
            i = n if j < 0 else j + 2
            continue
        else:
            out.append(c)
        i += 1
    return "".join(out)


@pytest.mark.parametrize("fname", ["app.js", "panels.js"])
def test_js_delimiters_balanced(fname):
    code = _strip_js(open(os.path.join(UI_DIR, fname)).read())
    for o, c in ("()", "[]", "{}"):
        assert code.count(o) == code.count(c), (
            f"{fname}: unbalanced {o}{c} "
            f"({code.count(o)} vs {code.count(c)})"
        )


def test_onclick_handlers_defined():
    """Every inline onclick/onkeydown handler resolves to a function
    defined in the bundle (catches typo'd handler names)."""
    js = open(os.path.join(UI_DIR, "app.js")).read()
    js += open(os.path.join(UI_DIR, "panels.js")).read()
    html = open(os.path.join(UI_DIR, "index.html")).read()
    defined = set(re.findall(r"(?:async\s+)?function\s+(\w+)", js))
    defined |= set(re.findall(r"const\s+(\w+)\s*=", js))
    used = set()
    for m in re.finditer(r'on(?:click|keydown)="([^"]+)"', js + html):
        for name in re.findall(r"(\w+)\s*\(", m.group(1)):
            if name not in ("if", "JSON"):
                used.add(name)
    missing = used - defined - {"event"}
    assert not missing, f"handlers not defined: {missing}"


def _panel_api_calls() -> list[tuple[str, str]]:
    src = open(os.path.join(UI_DIR, "panels.js")).read()
    src += open(os.path.join(UI_DIR, "app.js")).read()
    # dynamic `${action}` segments expand to the concrete verbs the
    # panel can pass
    actions = {
        "/api/goals/1/@A@": ("complete", "abandon"),
        "/api/rooms/1/@A@": ("start", "stop", "pause"),
        "/api/tasks/1/@A@": ("run", "pause", "resume"),
        "/api/escalations/1/@A@": ("answer", "dismiss"),
        "/api/providers/@A@/sessions/1": ("auth", "install"),
        "/api/providers/@A@/sessions/1/cancel": ("auth", "install"),
    }
    calls = set()
    for m in re.finditer(
        r'api\(\s*"(GET|POST|PUT|DELETE)",\s*[`"]([^`"?]+)', src
    ):
        method, path = m.group(1), m.group(2)
        path = path.replace("${action}", "@A@")
        # normalize remaining template interpolations to a concrete id
        path = re.sub(r"\$\{[^}]+\}", "1", path)
        if "@A@" in path:
            for verb in actions.get(path, ()):
                calls.add((method, path.replace("@A@", verb)))
            continue
        calls.add((method, path))
    assert len(calls) > 30, "extraction regression"
    return sorted(calls)


def test_every_panel_call_resolves(server):
    """Seed one of everything, then hit each (method, path) the panels
    use. 2xx/4xx-with-known-reason allowed; 404-route or 405 = drift."""
    from room_tpu.core import (
        escalations as esc_mod, goals as goals_mod,
        memory as memory_mod, messages as messages_mod,
        quorum as quorum_mod, rooms as rooms_mod, skills as skills_mod,
        task_runner, workers as workers_mod,
    )

    db = server.db
    room = rooms_mod.create_room(db, "ui", worker_model="echo")
    rid = room["id"]
    task_runner.create_task(db, "t", "do", trigger_type="manual")
    goals_mod.create_goal(db, rid, "g")
    quorum_mod.announce(db, rid, None, "p")
    esc_mod.create_escalation(db, rid, "q")
    messages_mod.send_room_message(db, rid, rid, "subj", "m")
    memory_mod.remember(db, "ui-fact", "fact")
    skills_mod.create_skill(db, "s", "how-to")
    assert workers_mod  # queen auto-created with the room
    from room_tpu.core import credentials as credentials_mod
    from room_tpu.core import watches as watches_mod

    # the extractor substitutes interpolations with "1": store matching
    # fixtures so parameterized DELETEs resolve
    credentials_mod.store_credential(db, rid, "1", "v")
    watches_mod.create_watch(db, "/tmp/ui-watch", "check")
    # a finished run so the runs panel's detail/log calls resolve
    db.insert("INSERT INTO task_runs(task_id, status) VALUES (1, 'ok')")

    bodies = {
        ("POST", "/api/rooms"): {"name": "x"},
        ("POST", "/api/rooms/1/chat"): {"content": "hi"},
        ("POST", "/api/rooms/1/goals"): {"description": "g2"},
        ("POST", "/api/rooms/1/workers"): {"name": "w2"},
        ("POST", "/api/rooms/1/wallet/withdraw"):
            {"to": "0x" + "11" * 20, "amount": "5"},
        ("POST", "/api/rooms/1/credentials"):
            {"name": "k2", "value": "v2"},
        ("PUT", "/api/rooms/1"): {"goal": "edited"},
        ("POST", "/api/watches"):
            {"path": "/tmp/ui-watch2", "actionPrompt": "a"},
        ("POST", "/api/update/check"): {},
        ("POST", "/api/self-mod/1/revert"): {},
        ("POST", "/api/memory"): {"name": "f2", "content": "f2"},
        ("POST", "/api/skills"): {"name": "s2", "content": "c"},
        ("POST", "/api/escalations/1/answer"): {"answer": "a"},
        ("POST", "/api/messages/1/reply"): {"body": "r"},
        ("POST", "/api/decisions/1/vote"): {"vote": "approve"},
        ("POST", "/api/decisions/1/keeper-vote"): {"vote": "reject"},
        ("POST", "/api/clerk/message"): {"content": "hello"},
        ("POST", "/api/contacts/email/start"):
            {"email": "k@example.com"},
        ("POST", "/api/contacts/email/verify"): {"code": "000000"},
        ("POST", "/api/templates/instantiate"):
            {"template": "research-desk", "workerModel": "echo"},
        ("PUT", "/api/settings"): {"ui_test": "1"},
        ("POST", "/api/rooms/1/messages"):
            {"toRoomId": 1, "subject": "s", "body": "b"},
        ("POST", "/api/goals/1/updates"): {"update": "progress note"},
        ("POST", "/api/memory/entities/1/observations"):
            {"content": "seen in the ui sweep"},
        ("POST", "/api/memory/relations"):
            {"fromId": 1, "toId": 1, "relationType": "relates_to"},
    }
    # endpoints whose 4xx is data-dependent, not drift
    allowed_4xx = {
        ("POST", "/api/contacts/email/verify"),   # wrong code
        ("POST", "/api/rooms/1/wallet/withdraw"), # no chain RPC (503)
        ("POST", "/api/providers/1/auth/start"),  # mock id, no CLI
        ("GET", "/api/providers/1/auth"),         # no active session
        ("GET", "/api/providers/auth/sessions/1"),  # unknown session
        ("GET", "/api/providers/install/sessions/1"),   # unknown session
        ("POST", "/api/providers/auth/sessions/1/cancel"),
        ("POST", "/api/providers/install/sessions/1/cancel"),
        ("POST", "/api/providers/1/install/start"),  # mock provider id
        ("POST", "/api/invites"),                 # no JWT secret (503)
        ("GET", "/api/tpu/provision/1"),          # unknown session
        ("POST", "/api/tpu/provision"),           # spawns a load thread
        ("POST", "/api/rooms/1/start"),           # provider not ready
        ("POST", "/api/workers/1/start"),         # provider not ready
        ("POST", "/api/decisions/1/keeper-vote"), # already resolved (409)
        ("POST", "/api/self-mod/1/revert"),       # no audit entry (409)
        ("POST", "/api/decisions/1/vote"),        # quorum state (409)
        ("POST", "/api/tasks/1/run"),             # no runtime thread (503)
        ("GET", "/api/rooms/1/wallet/balance"),   # no chain RPC (503)
    }
    # destructive calls go last so a DELETE doesn't remove the row a
    # later POST/GET in the sorted sweep targets; among DELETEs,
    # children before parents (deepest path first) so archiving
    # /api/rooms/1 doesn't cascade-404 /api/rooms/1/credentials/1
    ordered = sorted(
        _panel_api_calls(),
        key=lambda mp: (
            mp[0] == "DELETE",
            -len(mp[1]) if mp[0] == "DELETE" else 0,
            mp,
        ),
    )
    for method, path in ordered:
        body = bodies.get((method, path))
        headers = {
            "Authorization": f"Bearer {server.tokens['user']}",
            "Content-Type": "application/json",
        }
        r = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode() if body is not None
            else (b"{}" if method in ("POST", "PUT") else None),
            headers=headers, method=method,
        )
        try:
            with urllib.request.urlopen(r, timeout=10) as resp:
                status = resp.status
        except urllib.error.HTTPError as e:
            status = e.code
        if (method, path) in allowed_4xx:
            assert status != 404 or "providers" in path or \
                "sessions" in path or "provision" in path, \
                (method, path, status)
            continue
        assert 200 <= status < 300, (
            f"{method} {path} -> {status} (panel/API drift)"
        )


def test_panel_payload_shapes(server):
    """Beyond 2xx: the exact fields the panels RENDER exist in the
    responses (VERDICT r2 #7 — the drift test must catch a renamed
    column, not just a dead route). Field lists mirror panels.js
    render functions."""
    from room_tpu.core import (
        escalations as esc_mod, goals as goals_mod,
        memory as memory_mod, quorum as quorum_mod,
        rooms as rooms_mod, skills as skills_mod, task_runner,
    )

    db = server.db
    room = rooms_mod.create_room(db, "shapes", worker_model="echo")
    rid = room["id"]
    goals_mod.create_goal(db, rid, "a goal")
    task_runner.create_task(db, "t", "do", trigger_type="manual")
    memory_mod.remember(db, "shape-fact", "fact body")
    skills_mod.create_skill(db, "s", "how-to")
    quorum_mod.announce(db, rid, None, "proposal text")
    esc_mod.create_escalation(db, rid, "question?")

    def get(path):
        status, _, body = fetch(server, path, token=True)
        assert status == 200, (path, status, body)
        return json.loads(body)["data"]

    # app.js statusline
    st = get("/api/status")
    assert {"version", "platform", "devices", "activeRooms"} <= set(st)

    # renderSwarm / renderRooms: r.id/name/launched; workers feed
    # swarmCard: id/name/role/room_id/is_default
    rooms = get("/api/rooms")
    assert rooms and {"id", "name", "launched"} <= set(rooms[0])
    workers = get(f"/api/rooms/{rid}/workers")
    assert workers and \
        {"id", "name", "role", "room_id", "is_default"} <= \
        set(workers[0])
    # the queen carries is_default so the swarm graph can hub on her
    assert any(w["is_default"] for w in workers)

    # renderTasks: id/name/prompt/trigger_type/run_count/status
    tasks = get("/api/tasks")
    assert tasks and {
        "id", "name", "prompt", "trigger_type", "run_count",
        "status",
    } <= set(tasks[0])

    # renderSkills: id/name/content
    skills = get("/api/skills")
    assert skills and {"id", "name", "content"} <= set(skills[0])

    # memSearch: entity_id/name/observations/category/score
    mem = get("/api/memory/search?q=fact")
    assert mem and {
        "entity_id", "name", "observations", "category", "score",
    } <= set(mem[0])

    # renderVotes: id/proposal/status/created_at
    ds = get(f"/api/rooms/{rid}/decisions")
    assert ds and {"id", "proposal", "status", "created_at"} <= \
        set(ds[0])

    # renderGoals tree: id/description/status
    goals = get(f"/api/rooms/{rid}/goals")
    assert goals and {"id", "description", "status"} <= set(goals[0])

    # renderInbox escalations: id/question/status
    escs = get("/api/escalations")
    assert escs and {"id", "question", "status"} <= set(escs[0])


def test_tour_steps_reference_real_panels():
    """Every guided-walkthrough step targets a registered panel, and
    the help panel itself is registered (the tour switches views by
    key, so a renamed panel must fail CI, not no-op at runtime)."""
    js = open(os.path.join(UI_DIR, "panels.js")).read()
    steps = re.findall(r'\{view: "(\w+)"', js)
    assert len(steps) >= 5
    m = re.search(r"const PANELS = \{(.*?)\n\};", js, re.S)
    assert m, "PANELS registry not found"
    panels = set(re.findall(r"(\w+): \{title", m.group(1)))
    assert set(steps) <= panels, set(steps) - panels
    assert "help" in panels


def test_dom_ids_referenced_exist_in_templates():
    """DOM-level drift check (no browser in the image — the jsdom-style
    stand-in): every element id a panel reads via $("id") must be
    PRODUCED somewhere in the bundle — an id="..." in a template
    literal/HTML, or a createElement+.id assignment. A typo'd id means
    a runtime null deref in the panel."""
    js = open(os.path.join(UI_DIR, "app.js")).read()
    js += open(os.path.join(UI_DIR, "panels.js")).read()
    html = open(os.path.join(UI_DIR, "index.html")).read()
    bundle = js + html

    read = set(re.findall(r'\$\("([\w-]+)"\)', js))
    # ids produced statically...
    produced = set(re.findall(r'id="([\w-]+)"', bundle))
    # ...or assigned programmatically (el.id = "toast")
    produced |= set(re.findall(r'\.id\s*=\s*"([\w-]+)"', js))
    # ...or through the sel("id", ...) select-builder helper, whose
    # template emits id="${id_}"
    produced |= set(re.findall(r'sel\("([\w-]+)"', js))
    # ...or templated with a dynamic suffix (id="view-${key}")
    dynamic_prefixes = [
        m.split("${", 1)[0]
        for m in re.findall(r'id="([^"]*\$\{[^"]*)"', bundle)
    ]
    # $("view-" + k) style reads resolve against dynamic templates
    dyn_reads = set(re.findall(r'\$\("([\w-]+)"\s*\+', js))

    missing = {
        i for i in read
        if i not in produced
        and not any(i.startswith(p) for p in dynamic_prefixes if p)
    }
    assert not missing, f"$() reads with no produced id: {missing}"
    for r in dyn_reads:
        assert any(p == r for p in dynamic_prefixes), (
            f'dynamic read $("{r}" + ...) has no id="{r}${{...}}" '
            "template"
        )


def test_notifications_subscribe_all_rooms_on_ws_open():
    """Desktop notifications (ADVICE r5): the client must subscribe to
    every room channel on boot and on every WS (re)open, independent of
    which panel renders — a keeper parked on another view still gets
    escalation/decision alerts. Pinned at the source level: onopen
    re-subscribes the wildcard AND fetches /api/rooms to subscribe each
    room:{id} channel explicitly."""
    js = open(os.path.join(UI_DIR, "app.js")).read()
    onopen = js.split("ws.onopen", 1)[1].split("};", 1)[0]
    assert "subscribed.clear()" in onopen
    assert "subscribe" in onopen and '"*"' in onopen
    assert "subscribeRoomChannels()" in onopen
    fn = js.split("async function subscribeRoomChannels", 1)[1] \
        .split("\n}", 1)[0]
    assert '"/api/rooms"' in fn
    assert "subscribe(`room:${r.id}`)" in fn
    # the notify handler stays registered at module level, not inside
    # any panel render
    assert "wsHandlers.notify" in js


def test_pwa_assets_serve(server):
    """manifest + service worker + icon serve with usable types, and
    the bundle registers the worker (reference: the SPA's PWA layer)."""
    for path, frag in [
        ("/manifest.json", b'"start_url"'),
        ("/sw.js", b"addEventListener"),
        ("/icon.svg", b"<svg"),
    ]:
        status, headers, body = fetch(server, path)
        assert status == 200 and frag in body, path
    html = open(os.path.join(UI_DIR, "index.html")).read()
    assert 'rel="manifest"' in html
    js = open(os.path.join(UI_DIR, "app.js")).read()
    assert "serviceWorker" in js and 'register("/sw.js")' in js
    # sw.js never caches live surfaces or foreign origins — assert on
    # the actual guards, not comments
    sw = open(os.path.join(UI_DIR, "sw.js")).read()
    assert 'url.pathname.startsWith("/api")' in sw
    assert "url.origin !== self.location.origin" in sw
    # version state is persisted, not an in-memory global the browser
    # can reap with the idle worker
    assert 'match("/__version")' in sw
