"""claude/codex CLI provider tests against mock binaries: stream-JSON
parsing, session capture, timeout/abort, auth-probe + login sessions
(reference behaviors: src/shared/claude-code.ts, agent-executor.ts
executeCodex, src/server/provider-auth.ts)."""

import json
import os
import stat
import threading
import time

import pytest

from room_tpu.providers import get_model_provider, reset_provider_cache
from room_tpu.providers.base import ExecutionRequest
from room_tpu.providers.cli import (
    ClaudeCliProvider, CodexCliProvider, StreamEvents, parse_claude_line,
    parse_codex_line, probe_connected, probe_installed, stream_cli,
)
from room_tpu.providers.registry import provider_kind


def _write_script(path, body: str) -> str:
    # -E -S keeps the mock's startup instant: the ambient PYTHONPATH
    # sitecustomize imports jax (seconds, and it may probe the TPU
    # tunnel), which would blow the 1.5s --version probe budget
    path.write_text(f"#!/usr/bin/env -S python3 -E -S\n{body}")
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


MOCK_CLAUDE = r'''
import json, sys, time
args = sys.argv[1:]
if "--version" in args:
    print("9.9.9 (Claude Code)"); sys.exit(0)
if "--sleep" in __import__("os").environ.get("MOCK_MODE", ""):
    time.sleep(60)
prompt = args[args.index("-p") + 1]
assert "--output-format" in args and "stream-json" in args
print(json.dumps({"type": "system", "subtype": "init"}))
print(json.dumps({"type": "assistant", "message": {"content": [
    {"type": "text", "text": f"echo:{prompt}"},
    {"type": "tool_use", "name": "Bash", "input": {"command": "ls"}},
]}}))
print(json.dumps({"type": "result", "result": f"final:{prompt}",
                  "session_id": "sess-abc123"}))
'''

MOCK_CODEX = r'''
import json, sys, time, os
args = sys.argv[1:]
if "--version" in args:
    print("codex-cli 0.5"); sys.exit(0)
if "--sleep" in os.environ.get("MOCK_MODE", ""):
    time.sleep(60)
assert args[0] == "exec" and "--json" in args
prompt = args[-1]
resumed = "resume" in args
print(json.dumps({"type": "thread.started",
                  "thread_id": "resumed-1" if resumed else "thread-1"}))
print(json.dumps({"type": "item.completed", "item": {
    "type": "agent_message", "text": f"codex:{prompt}"}}))
print(json.dumps({"type": "item.completed", "item": {
    "type": "mcp_tool_call", "tool": "search",
    "arguments": {"q": "x"}}}))
'''


@pytest.fixture
def mock_clis(tmp_path, monkeypatch):
    claude = _write_script(tmp_path / "mock_claude.py", MOCK_CLAUDE)
    codex = _write_script(tmp_path / "mock_codex.py", MOCK_CODEX)
    monkeypatch.setenv("ROOM_TPU_CLAUDE_CLI", claude)
    monkeypatch.setenv("ROOM_TPU_CODEX_CLI", codex)
    monkeypatch.delenv("MOCK_MODE", raising=False)
    reset_provider_cache()
    yield {"claude": claude, "codex": codex}
    reset_provider_cache()


# ---- probes ----

def test_probe_installed_and_missing(mock_clis, monkeypatch):
    assert probe_installed("claude") == {
        "installed": True, "version": "9.9.9 (Claude Code)",
    }
    monkeypatch.setenv("ROOM_TPU_CLAUDE_CLI", "/nonexistent/claude")
    assert probe_installed("claude") == {"installed": False}
    assert probe_connected("claude") is None  # not installed


def test_probe_connected_api_key(mock_clis, monkeypatch):
    monkeypatch.setenv("ANTHROPIC_API_KEY", "sk-test")
    assert probe_connected("claude") is True
    monkeypatch.delenv("ANTHROPIC_API_KEY")
    monkeypatch.setenv("HOME", "/nonexistent-home")
    assert probe_connected("claude") is False


# ---- execution ----

def test_claude_execute_parses_stream(mock_clis):
    texts = []
    p = ClaudeCliProvider()
    res = p.execute(ExecutionRequest(
        prompt="hello", timeout_s=30, on_text=texts.append,
    ))
    assert res.success, res.error
    assert res.text == "final:hello"     # result event wins
    assert res.session_id == "sess-abc123"
    assert res.tool_calls == [
        {"name": "Bash", "arguments": {"command": "ls"}},
    ]
    assert texts == ["echo:hello"]


def test_codex_execute_parses_jsonl(mock_clis):
    p = CodexCliProvider()
    res = p.execute(ExecutionRequest(prompt="task", timeout_s=30))
    assert res.success, res.error
    assert res.text == "codex:task"
    assert res.session_id == "thread-1"
    assert res.tool_calls == [{"name": "search", "arguments": {"q": "x"}}]
    # resume passes the session id through
    res2 = p.execute(ExecutionRequest(
        prompt="more", timeout_s=30, session_id="thread-1",
    ))
    assert res2.session_id == "resumed-1"


def test_claude_timeout_kills_process(mock_clis, monkeypatch):
    monkeypatch.setenv("MOCK_MODE", "--sleep")
    p = ClaudeCliProvider()
    t0 = time.monotonic()
    res = p.execute(ExecutionRequest(prompt="x", timeout_s=0.5))
    assert time.monotonic() - t0 < 10
    assert not res.success and "timeout" in res.error


def test_stream_cli_abort(mock_clis, monkeypatch):
    monkeypatch.setenv("MOCK_MODE", "--sleep")
    abort = threading.Event()
    threading.Timer(0.3, abort.set).start()
    t0 = time.monotonic()
    run = stream_cli(
        [mock_clis["claude"], "-p", "x", "--output-format",
         "stream-json"],
        lambda line: None, timeout_s=60, abort_event=abort,
    )
    assert run.aborted and run.exit_code == 130
    assert time.monotonic() - t0 < 10


def test_missing_cli_fails_closed(monkeypatch):
    monkeypatch.setenv("ROOM_TPU_CLAUDE_CLI", "/nonexistent/claude")
    p = ClaudeCliProvider()
    ready, why = p.is_ready()
    assert not ready and "not found" in why
    res = p.execute(ExecutionRequest(prompt="x"))
    assert not res.success


# ---- parsers (unit) ----

def test_parse_claude_line_ignores_garbage():
    ev = StreamEvents()
    parse_claude_line("not json", ev)
    parse_claude_line(json.dumps({"type": "unknown"}), ev)
    assert ev.texts == [] and ev.session_id is None


def test_parse_codex_line_shapes():
    ev = StreamEvents()
    parse_codex_line(
        json.dumps({"type": "thread.started", "thread_id": "t9"}), ev
    )
    parse_codex_line(
        json.dumps({"type": "item.completed",
                    "item": {"type": "agent_message", "text": "hi"}}), ev
    )
    assert ev.session_id == "t9" and ev.texts == ["hi"]


# ---- registry ----

def test_registry_accepts_cli_prefixes(mock_clis):
    assert provider_kind("claude") == "claude"
    assert provider_kind("claude:opus") == "claude"
    assert provider_kind("codex:gpt-5") == "codex"
    p = get_model_provider("claude:opus")
    assert isinstance(p, ClaudeCliProvider) and p.model == "opus"
    c = get_model_provider("codex")
    assert isinstance(c, CodexCliProvider)
    ready, detail = p.is_ready()
    # mock binary is "installed"; connection probe depends on HOME
    assert isinstance(ready, bool) and detail


# ---- auth sessions ----

MOCK_LOGIN_OK = r'''
import sys, time
if "--version" in sys.argv:
    print("9.9.9"); sys.exit(0)
assert sys.argv[1] == "login"
print("Visit https://auth.example.com/device?user=1 to authenticate")
print("Your code: ABCD-1234")
sys.exit(0)
'''

MOCK_LOGIN_HANG = r'''
import sys, time
if "--version" in sys.argv:
    print("9.9.9"); sys.exit(0)
print("Visit https://auth.example.com/device to authenticate", flush=True)
time.sleep(60)
'''


def test_auth_session_completes(tmp_path, monkeypatch):
    from room_tpu.server.provider_auth import ProviderAuthManager

    cli = _write_script(tmp_path / "login_ok.py", MOCK_LOGIN_OK)
    monkeypatch.setenv("ROOM_TPU_CLAUDE_CLI", cli)
    mgr = ProviderAuthManager()
    view = mgr.start("claude")
    sid = view["sessionId"]
    for _ in range(100):
        view = mgr.get(sid)
        if view["status"] not in ("starting", "running"):
            break
        time.sleep(0.05)
    assert view["status"] == "completed"
    assert view["verificationUrl"] == \
        "https://auth.example.com/device?user=1"
    assert view["deviceCode"] == "ABCD-1234"
    assert view["exitCode"] == 0
    assert not view["active"]


def test_auth_session_cancel_and_single_active(tmp_path, monkeypatch):
    from room_tpu.server.provider_auth import ProviderAuthManager

    cli = _write_script(tmp_path / "login_hang.py", MOCK_LOGIN_HANG)
    monkeypatch.setenv("ROOM_TPU_CLAUDE_CLI", cli)
    mgr = ProviderAuthManager()
    view = mgr.start("claude")
    sid = view["sessionId"]
    # second start returns the same active session
    again = mgr.start("claude")
    assert again["sessionId"] == sid
    # URL shows up from the stream
    for _ in range(100):
        view = mgr.get(sid)
        if view["verificationUrl"]:
            break
        time.sleep(0.05)
    assert view["verificationUrl"] == "https://auth.example.com/device"
    mgr.cancel(sid)
    for _ in range(100):
        view = mgr.get(sid)
        if view["status"] == "canceled":
            break
        time.sleep(0.05)
    assert view["status"] == "canceled"
    # a new session can start once the old one is gone
    view2 = mgr.start("claude")
    assert view2["sessionId"] != sid
    mgr.shutdown()


def test_auth_unknown_provider(tmp_path):
    from room_tpu.server.provider_auth import ProviderAuthManager

    with pytest.raises(ValueError):
        ProviderAuthManager().start("evil")


def test_provider_routes(tmp_path, monkeypatch):
    """REST surface: /api/providers probe + auth session lifecycle."""
    from tests.test_server import req  # reuse the HTTP helper

    from room_tpu.db import Database
    from room_tpu.server.http import ApiServer

    cli = _write_script(tmp_path / "login_ok.py", MOCK_LOGIN_OK)
    monkeypatch.setenv("ROOM_TPU_CLAUDE_CLI", cli)
    monkeypatch.setenv("ROOM_TPU_CODEX_CLI", "/nonexistent")
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path / "data"))

    db = Database(":memory:")
    server = ApiServer(db)
    server.start()
    try:
        status, out = req(server, "GET", "/api/providers")
        assert status == 200
        assert out["data"]["claude"]["installed"] is True
        assert out["data"]["codex"]["installed"] is False

        status, out = req(
            server, "POST", "/api/providers/claude/auth/start", {}
        )
        assert status == 201
        sid = out["data"]["sessionId"]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            status, out = req(
                server, "GET", f"/api/providers/auth/sessions/{sid}"
            )
            if out["data"]["status"] not in ("starting", "running"):
                break
            time.sleep(0.05)
        assert out["data"]["status"] == "completed"

        status, out = req(
            server, "POST", "/api/providers/codex/auth/start", {}
        )
        assert status == 409  # CLI not installed
    finally:
        server.stop()


# ---- install sessions ----

MOCK_NPM = r'''
import sys
assert sys.argv[1:4] == ["install", "-g", "@anthropic-ai/claude-code"]
print("added 120 packages in 4s")
sys.exit(0)
'''


def test_install_session_with_mock_npm(tmp_path, monkeypatch):
    from room_tpu.server.provider_auth import ProviderInstallManager

    npm = _write_script(tmp_path / "npm.py", MOCK_NPM)
    monkeypatch.setenv("ROOM_TPU_NPM", npm)
    mgr = ProviderInstallManager()
    view = mgr.start("claude")
    assert "npm install -g @anthropic-ai/claude-code" == view["command"]
    sid = view["sessionId"]
    for _ in range(100):
        view = mgr.get(sid)
        if view["status"] not in ("starting", "running"):
            break
        time.sleep(0.05)
    assert view["status"] == "completed"
    assert any("120 packages" in l["text"] for l in view["lines"])


def test_install_session_requires_npm(monkeypatch):
    from room_tpu.server.provider_auth import ProviderInstallManager

    monkeypatch.setenv("ROOM_TPU_NPM", "")
    monkeypatch.setenv("PATH", "/nonexistent")
    with pytest.raises(FileNotFoundError, match="npm"):
        ProviderInstallManager().start("codex")


# ---- shell path ----

def test_inherit_shell_path(tmp_path, monkeypatch):
    from room_tpu.server.shell_path import inherit_shell_path

    fake_shell = tmp_path / "shell.sh"
    fake_shell.write_text(
        "#!/bin/sh\n"
        '[ "$1" = "-l" ] || exit 1\n'
        'printf "/opt/extra/bin:/usr/bin"\n'
    )
    fake_shell.chmod(0o755)
    monkeypatch.setenv("SHELL", str(fake_shell))
    monkeypatch.setenv("PATH", "/usr/bin:/bin")
    assert inherit_shell_path() is True
    assert "/opt/extra/bin" in os.environ["PATH"].split(":")
    # idempotent: nothing new the second time
    assert inherit_shell_path() is False


def test_inherit_shell_path_broken_shell(monkeypatch):
    from room_tpu.server.shell_path import inherit_shell_path

    monkeypatch.setenv("SHELL", "/nonexistent/zsh")
    assert inherit_shell_path() is False


# ---- port reclamation ----

def test_port_conflict_kill_retry(tmp_path, monkeypatch):
    import socket
    import subprocess
    import sys

    from room_tpu.db import Database
    from room_tpu.server.http import ApiServer
    from room_tpu.server.shell_path import find_pid_listening_on

    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    # a sacrificial child occupies a port
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    child = subprocess.Popen(
        [sys.executable, "-E", "-S", "-c",
         "import socket,time\n"
         "s=socket.socket()\n"
         "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
         f"s.bind(('127.0.0.1',{port}))\n"
         "s.listen()\n"
         "print('up',flush=True)\n"
         "time.sleep(60)"],
        stdout=subprocess.PIPE, text=True,
    )
    assert child.stdout.readline().strip() == "up"
    assert find_pid_listening_on(port) == child.pid

    srv = ApiServer(Database(":memory:"), port=port)
    srv.start()
    try:
        assert srv.port == port  # reclaimed from the stale process
        import urllib.request

        r = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/auth/handshake"
        )
        with urllib.request.urlopen(r, timeout=5) as resp:
            assert resp.status == 200
    finally:
        srv.stop()
        child.wait(timeout=10)
